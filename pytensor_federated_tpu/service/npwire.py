"""Binary ndarray wire format for the host-federation transport.

Re-design of the reference's ``npproto`` protobuf wire format
(reference: npproto/__init__.py:13-22, npproto/utils.py:9-24,
protobufs/npproto/ndarray.proto): any buffer-protocol NumPy array
round-trips as raw data bytes + dtype string + shape.  Differences from
the reference, on purpose:

- Simple length-prefixed framing instead of protobuf — no codegen, no
  betterproto dependency, and the payload bytes are written with a
  single memcpy per array.
- Non-contiguous arrays are made contiguous at encode time instead of
  shipping strides (the reference serializes strides; every consumer
  immediately reshapes anyway, and contiguous payloads are what the
  device wants).
- ``dtype=object`` is rejected loudly.  The reference's README admits
  object dtype "doesn't work" while its test serializes pointers that
  only round-trip in-process (reference: README.md:30,
  test_npproto.py:20) — here it is a hard error.

A message frames N arrays plus a 16-byte correlation uuid (parity with
the reference's uuid field, reference: rpc.py:37-39), an optional
error string, an optional 16-byte telemetry trace id (flag bit 2)
that correlates driver-side and node-side spans of the same call
(:mod:`..telemetry.spans`), and an optional trailing SPANS block (flag
bit 4): a JSON list of completed node-side span trees, piggybacked on
REPLIES so the node's half of a correlated trace travels home on the
very RPC it describes (:mod:`..telemetry.reunion`).  The spans block
sits at the TAIL — after the arrays — so a server can attach it to an
already-encoded reply with :func:`append_spans` (one flag-byte patch +
one append) instead of re-encoding array payloads.  Absent all three,
the frame is byte-identical to the pre-telemetry format; PRESENT, they
require a decoder that knows the flag — npwire peers all live in this
repo and ship in lockstep (a pre-telemetry build would reject a
flagged frame as corrupt, which is this format's loud-failure
contract, not silent skipping).  Cross-implementation forward
compatibility is the npproto codec's job (its field-15 trace id and
field-16 spans ARE skipped by unknown-field rules).

BATCH frames (flag bit 8): one wire message carrying K complete
sub-frames, so a pipelined window pays one transport message and one
syscall each way instead of K (:mod:`.batching` is the server half).
The outer header is the SAME layout as a plain frame — the count field
holds ``n_items`` instead of ``n_arrays`` and the body is
``item_len(u32) + item_bytes`` per item, each item a full npwire frame
with its own uuid/arrays/error.  Error isolation is per item: a
poisoned request fails only its own reply frame.  The outer uuid
correlates the window; the outer trace id (flag 2) is the
AUTHORITATIVE one for the node's span context (items are complete
frames and may redundantly carry their own trace block — this repo's
clients reuse their per-call encodings — which decoders simply
consume and drop); the spans tail (flag 4) attaches to the outer
frame exactly as on a plain reply.  A batch frame is only
ever sent to a peer that advertised the capability (GetLoad ``batch``
field / the TCP probe), so the loud :class:`WireError` a pre-batch
decoder raises on flag 8 is a negotiation bug surfacing, not a
compatibility hazard.  Plain frames are byte-identical with or without
this feature compiled in.

DEADLINE frames (flag bit 16): an 8-byte little-endian float64 after
the trace block carrying the request's REMAINING deadline budget in
seconds (relative, never an absolute timestamp — peer clocks are not
ours; :mod:`.deadline` is the contextvar source and the enforcement
vocabulary).  Servers enforce it at admission: an expired budget is
answered with a :data:`~.deadline.DEADLINE_ERROR_PREFIX` in-band error
and never computed.  Absent a bound deadline the flag stays clear and
the frame is byte-identical to the pre-deadline wire (property-tested).

TENANT frames (flag bit 32): a u16-length-prefixed utf8 tenant id
after the deadline block — the per-tenant identity the gateway tier
(:mod:`..gateway`) meters quotas and weighted-fair service by.  Like
the deadline, it is OPTIONAL metadata: absent a tenant the flag stays
clear and the frame is byte-identical to the pre-tenant wire
(property-tested); servers that do not meter tenancy consume and drop
the block.  :func:`peek_tenant` is the admission-side reader (the
gateway classifies BEFORE paying any decode).

Layout (little-endian):
  message: MAGIC(4s) version(u8) flags(u8) uuid(16s) n_arrays(u32)
           [flags&1 error: len(u32) utf8]
           [flags&2 trace: trace_id(16s)]
           [flags&16 deadline: budget_s(f64)]
           [flags&32 tenant: len(u16) utf8]
           [flags&64 partition: index(u32) count(u32) offset(u64)
                     length(u64) total(u64)]
           [flags&128 version: step_version(u64)]  then per array:
  array:   dtype_len(u16) dtype_str shape_ndim(u8) shape(u64*ndim)
           data_len(u64) data_bytes
  tail:    [flags&4 spans: len(u32) utf8-JSON]
  batch:   same header with flags&8; count = n_items; body is
           item_len(u32) + item_bytes per item (each a full frame);
           same optional error/trace/deadline/tenant blocks and
           spans tail
"""

from __future__ import annotations

import itertools
import json
import os
import struct
import threading
import uuid as uuid_mod
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np
from numpy.lib.format import descr_to_dtype, dtype_to_descr

from ..faultinject import runtime as _fi
from ..telemetry import metrics as _metrics

#: One buffer of a scatter/gather frame: header/metadata bytes, or a
#: zero-copy view of a source array's payload.
Buffer = Union[bytes, memoryview]

#: Payload bytes the transport stack memcpy's, by lane and stage — the
#: instrument behind docs/performance.md's "Zero-copy budget" table.
#: Stages: ``encode_layout`` (non-contiguous input normalized),
#: ``encode_join`` (payload flattened into one contiguous frame),
#: ``decode_copy`` (frame bytes copied out into result arrays),
#: ``arena_write`` (bytes written into a shared-memory arena slot —
#: the shm lane's single copy).  Zero-copy paths (sendmsg vectors,
#: ``copy=False`` decode, arena read views) inc nothing.
WIRE_BYTES_COPIED = _metrics.counter(
    "pftpu_wire_bytes_copied_total",
    "Payload bytes memcpy'd by the transport stack, by lane and stage",
    ("lane", "stage"),
)
_LAYOUT_COPIED = WIRE_BYTES_COPIED.labels(lane="npwire", stage="encode_layout")
_JOIN_COPIED = WIRE_BYTES_COPIED.labels(lane="npwire", stage="encode_join")
_DECODE_COPIED = WIRE_BYTES_COPIED.labels(lane="npwire", stage="decode_copy")

MAGIC = b"NPW1"
_FLAG_ERROR = 1
_FLAG_TRACE = 2
_FLAG_SPANS = 4
_FLAG_BATCH = 8
_FLAG_DEADLINE = 16
_FLAG_TENANT = 32
_FLAG_PARTITION = 64
_FLAG_VERSION = 128
# Every known flag bit, mirrored from service/wire_registry.py (the
# declared source; the graftlint wire-registry rule cross-checks the
# two).  Decoders REJECT any bit outside this mask: an unknown flag
# means the frame carries blocks this build cannot place, and parsing
# around them would be silent mis-parsing — the exact version-skew
# hazard the module docstring's loud-failure contract forbids.
_KNOWN_FLAGS = (
    _FLAG_ERROR | _FLAG_TRACE | _FLAG_SPANS | _FLAG_BATCH
    | _FLAG_DEADLINE | _FLAG_TENANT | _FLAG_PARTITION | _FLAG_VERSION
)
# flags byte offset in the header ("<4sBB...": magic, version, flags)
_FLAGS_OFF = 5
# Header and partition-block structs, preserialized at module level —
# a struct.pack/calcsize with a literal format re-parses the format
# string per call in the hot send path (ISSUE-13 satellite: the
# PR-10-review bug class, swept from the client lanes too).
_HEADER_STRUCT = struct.Struct("<4sBB16sI")
_HEADER_SIZE = _HEADER_STRUCT.size
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")
#: The gradient-partition index block (flag bit 64):
#: index(u32) count(u32) offset(u64) length(u64) total(u64) — layout
#: declared in service/wire_registry.py PARTITION_STRUCT; the
#: semantics (head/tail slice rule, reduction, reassembly) live in
#: routing/partition.py.
_PARTITION_STRUCT = struct.Struct("<IIQQQ")
#: The step-version stamp block (flag bit 128): one u64 after the
#: partition block — layout declared in service/wire_registry.py
#: VERSION_STRUCT; the semantics (monotonic optimizer-step version,
#: stale-shard refusal) live in optim/sharded.py.  Zero is meaningful
#: (the init handshake), so presence rides the flag bit, not the value.
_VERSION_STRUCT = struct.Struct("<Q")


class WireError(ValueError):
    """Malformed or unsupported wire payload."""


# Correlation ids need per-process uniqueness, not cryptographic
# randomness — but ``uuid4()`` draws 16 bytes of real entropy, a
# getrandom(2) syscall that costs tens of microseconds on some hosts
# (measured 37 us in the round-9 container: 38% of the shm lane's hot
# path).  A random 10-byte process prefix + pid + 4-byte counter keeps
# ids unique across processes, connections, and 4 billion calls.
_UUID_PREFIX = os.urandom(10) + struct.pack("<H", os.getpid() & 0xFFFF)
_uuid_counter = itertools.count()
_uuid_lock = threading.Lock()


def _reseed_uuid_prefix() -> None:
    """Fork hook: a fork-started worker inherits the parent's prefix
    AND counter, so without reseeding every child would emit the
    parent's exact id stream — re-derive both in the child."""
    global _UUID_PREFIX, _uuid_counter
    _UUID_PREFIX = os.urandom(10) + struct.pack("<H", os.getpid() & 0xFFFF)
    _uuid_counter = itertools.count()


if hasattr(os, "register_at_fork"):  # POSIX only; spawn needs nothing
    os.register_at_fork(after_in_child=_reseed_uuid_prefix)


def fast_uuid() -> bytes:
    """A 16-byte correlation id without the per-call entropy syscall
    (module comment above).  Wire-compatible with ``uuid4().bytes`` —
    every peer treats uuids as opaque 16-byte tokens."""
    with _uuid_lock:
        n = next(_uuid_counter)
    return _UUID_PREFIX + struct.pack("<I", n & 0xFFFFFFFF)


def _check_flags(flags: int) -> None:
    """Reject undeclared flag bits loudly (loud-failure contract)."""
    unknown = flags & ~_KNOWN_FLAGS
    if unknown:
        raise WireError(
            f"unknown flag bits 0x{unknown:02x} "
            f"(known mask 0x{_KNOWN_FLAGS:02x}) — version-skewed peer? "
            "npwire peers must ship in lockstep"
        )


def _encode_tenant(tenant: str) -> bytes:
    """The tenant block (flag bit 32): u16 length + utf8 id.  Loud on
    the shapes that cannot round-trip — the empty id (absent and empty
    must stay distinguishable: absent means "no tenancy metering") and
    ids past the u16 length prefix."""
    raw = tenant.encode("utf-8")
    if not raw:
        raise WireError("tenant id must be non-empty (omit it instead)")
    if len(raw) > 0xFFFF:
        raise WireError(
            f"tenant id too long ({len(raw)} utf8 bytes > 65535)"
        )
    return struct.pack("<H", len(raw)) + raw


def _encode_partition(partition: Sequence[int]) -> bytes:
    """The partition block (flag bit 64): a 5-int sequence in
    ``wire_registry.PARTITION_FIELD_ORDER`` (index, count, offset,
    length, total — ``routing.partition.GradPartition`` is one).  Loud
    on shapes that cannot describe a shard; the SEMANTIC validation
    (plan consistency, reassembly) is routing/partition.py's."""
    try:
        index, count, offset, length, total = (
            int(v) for v in partition
        )
    except (TypeError, ValueError) as e:
        raise WireError(f"partition must be 5 ints: {e}") from None
    if not 0 <= index < count:
        raise WireError(
            f"partition index {index} outside 0..{count - 1}"
        )
    if min(offset, length, total) < 0 or offset + length > total:
        raise WireError(
            f"partition slice [{offset}, {offset + length}) cannot "
            f"cover total {total}"
        )
    try:
        return _PARTITION_STRUCT.pack(index, count, offset, length, total)
    except struct.error as e:
        raise WireError(f"partition out of wire range: {e}") from None


def _decode_partition(buf: bytes, off: int) -> Tuple[tuple, int]:
    """Parse a partition block at ``off`` -> ((5 ints), new_offset)."""
    try:
        fields = _PARTITION_STRUCT.unpack_from(buf, off)
    except struct.error as e:
        raise WireError(f"truncated partition block: {e}") from None
    return fields, off + _PARTITION_STRUCT.size


def _encode_version(version: int) -> bytes:
    """The step-version block (flag bit 128): one u64 stamp.  Loud on
    values the wire cannot carry; the SEMANTICS (monotonicity,
    stale-shard refusal) are optim/sharded.py's."""
    try:
        v = int(version)
    except (TypeError, ValueError) as e:
        raise WireError(f"version must be an int: {e}") from None
    if not 0 <= v < (1 << 64):
        raise WireError(f"version {v} outside u64 range")
    return _VERSION_STRUCT.pack(v)


def _decode_version(buf: bytes, off: int) -> Tuple[int, int]:
    """Parse a version block at ``off`` -> (version, new_offset)."""
    try:
        (version,) = _VERSION_STRUCT.unpack_from(buf, off)
    except struct.error as e:
        raise WireError(f"truncated version block: {e}") from None
    return version, off + _VERSION_STRUCT.size


def _tupleize(descr: object) -> object:
    """JSON round-trip turns descr tuples into lists; restore them
    recursively (field entries are tuples, nested shapes too)."""
    if isinstance(descr, list):
        if descr and isinstance(descr[0], (list, tuple)):
            return [tuple(_tupleize(x) for x in f) for f in descr]
        return tuple(_tupleize(x) for x in descr)
    return descr


@lru_cache(maxsize=256)
def _parse_dtype(dt_bytes: bytes) -> np.dtype:
    # Pure bytes -> dtype, cached: a window of same-typed arrays pays
    # one parse, not one per array (failures are not cached, so every
    # corrupt descriptor stays loud).
    try:
        dt_str = dt_bytes.decode("utf-8")
        if dt_str.startswith("["):
            # JSON-array descr = structured dtype; plain string otherwise.
            return descr_to_dtype(_tupleize(json.loads(dt_str)))
        return np.dtype(dt_str)
    except (ValueError, TypeError, KeyError, SyntaxError) as e:
        # ValueError covers UnicodeDecodeError and json errors too —
        # every corrupt-descriptor shape must surface as WireError.
        # SyntaxError: numpy parses some malformed dtype strings as
        # Python literals (e.g. b"08f" -> "leading zeros..."), found
        # by the ISSUE-9 descriptor fuzz — without this arm a flipped
        # dtype byte escaped the loud-failure classification.
        raise WireError(f"bad dtype descriptor {dt_bytes!r}: {e}") from None


def normalize_arrays(arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Layout normalization, ONCE at encode entry (shared by the
    contiguous and scatter/gather encoders): every array comes out
    C-contiguous with a wire-legal dtype, so the payload bytes are a
    straight memory image and the scatter/gather path can ship a view
    instead of ``a.tobytes()``.  Fortran-ordered and sliced inputs pay
    exactly one copy here (counted under ``encode_layout``);
    already-contiguous inputs pay none."""
    out: List[np.ndarray] = []
    for a in arrays:
        a = np.asarray(a)
        if a.dtype == object:
            raise WireError(
                "dtype=object arrays cannot cross the wire (they serialize "
                "pointers); use a structured or numeric dtype"
            )
        if not a.flags["C_CONTIGUOUS"]:
            # NB: np.ascontiguousarray promotes 0-d to 1-d, so only call
            # it when actually needed (0-d is always contiguous).
            a = np.ascontiguousarray(a)
            _LAYOUT_COPIED.inc(a.nbytes)
        out.append(a)
    return out


def payload_view(a: np.ndarray) -> Buffer:
    """A zero-copy byte view of a (C-contiguous) array's payload, or a
    ``tobytes()`` copy for the few dtypes that refuse the buffer
    protocol (datetime64/timedelta64) — counted as a layout copy."""
    try:
        mv = memoryview(a)
        return mv if mv.format == "B" and mv.ndim == 1 else mv.cast("B")
    except (ValueError, TypeError, BufferError):
        data = a.tobytes()
        _LAYOUT_COPIED.inc(len(data))
        return data


@lru_cache(maxsize=256)
def _encode_dtype(dtype: np.dtype) -> bytes:
    # dtype_to_descr/descr_to_dtype are the official npy-format
    # helpers: plain dtypes serialize as their ".str" (e.g. "<f4"),
    # structured dtypes as their field descr (JSON-encoded here) —
    # ".str" alone collapses records to opaque void ("|V15").
    # Cached: dtypes are hashable and a workload reuses a handful.
    descr = dtype_to_descr(dtype)
    return (
        descr.encode("ascii")
        if isinstance(descr, str)
        else json.dumps(descr).encode("utf-8")
    )


def encode_arrays_sg(
    arrays: Sequence[np.ndarray],
    *,
    uuid: Optional[bytes] = None,
    error: Optional[str] = None,
    trace_id: Optional[bytes] = None,
    deadline_s: Optional[float] = None,
    tenant: Optional[str] = None,
    partition: Optional[Sequence[int]] = None,
    version: Optional[int] = None,
) -> List[Buffer]:
    """Scatter/gather encode: the same frame as :func:`encode_arrays`
    as a BUFFER VECTOR — header/metadata ``bytes`` interleaved with
    zero-copy ``memoryview`` s of the (normalized) source arrays'
    payloads.  ``b"".join(vector)`` is byte-identical to the
    contiguous encoder's output; a vectored send
    (``socket.sendmsg``, :func:`..service.tcp._sendmsg_all`) skips
    that join entirely, so array bytes go source → kernel with no
    intermediate frame copy.  The caller must keep the source arrays
    alive until the vector is consumed (the views borrow their
    memory).  With a fault plan installed the vector collapses to one
    filtered contiguous buffer — byte-lane chaos needs the whole
    frame in hand."""
    if uuid is None:
        uuid = uuid_mod.uuid4().bytes
    if len(uuid) != 16:
        raise WireError(f"uuid must be 16 bytes, got {len(uuid)}")
    arrays = normalize_arrays(arrays)
    flags = 0
    if error is not None:
        flags |= _FLAG_ERROR
    if trace_id is not None:
        if len(trace_id) != 16:
            raise WireError(
                f"trace_id must be 16 bytes, got {len(trace_id)}"
            )
        flags |= _FLAG_TRACE
    if deadline_s is not None:
        flags |= _FLAG_DEADLINE
    tenant_block = None
    if tenant is not None:
        tenant_block = _encode_tenant(tenant)
        flags |= _FLAG_TENANT
    partition_block = None
    if partition is not None:
        partition_block = _encode_partition(partition)
        flags |= _FLAG_PARTITION
    version_block = None
    if version is not None:
        version_block = _encode_version(version)
        flags |= _FLAG_VERSION
    parts: List[Buffer] = [
        _HEADER_STRUCT.pack(MAGIC, 1, flags, uuid, len(arrays))
    ]
    if error is not None:
        err = error.encode("utf-8")
        parts.append(_U32.pack(len(err)))
        parts.append(err)
    if trace_id is not None:
        parts.append(trace_id)
    if deadline_s is not None:
        parts.append(_F64.pack(float(deadline_s)))
    if tenant_block is not None:
        parts.append(tenant_block)
    if partition_block is not None:
        parts.append(partition_block)
    if version_block is not None:
        parts.append(version_block)
    for a in arrays:
        dt = _encode_dtype(a.dtype)
        parts.append(_U16.pack(len(dt)))
        parts.append(dt)
        parts.append(struct.pack("<B", a.ndim))
        parts.append(struct.pack(f"<{a.ndim}Q", *a.shape))
        parts.append(_U64.pack(a.nbytes))
        if a.nbytes:
            parts.append(payload_view(a))
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        return [_fi.filter_bytes("npwire.encode", b"".join(parts))]
    return parts


def sg_nbytes(parts: Sequence[Buffer]) -> int:
    """Total byte length of a scatter/gather buffer vector."""
    return sum(
        p.nbytes if isinstance(p, memoryview) else len(p) for p in parts
    )


def encode_arrays(
    arrays: Sequence[np.ndarray],
    *,
    uuid: Optional[bytes] = None,
    error: Optional[str] = None,
    trace_id: Optional[bytes] = None,
    deadline_s: Optional[float] = None,
    tenant: Optional[str] = None,
    partition: Optional[Sequence[int]] = None,
    version: Optional[int] = None,
) -> bytes:
    """Encode arrays (+uuid, +optional error/trace_id/deadline_s/
    tenant/partition/version) into one framed message.  ``trace_id``
    (16 bytes) is the telemetry correlation id; ``deadline_s`` the
    remaining deadline budget (flag bit 16); ``tenant`` the gateway
    tier's per-tenant identity (flag bit 32); ``partition`` the
    gradient-partition index block (flag bit 64, a 5-int sequence —
    routing/partition.py owns the semantics); ``version`` the u64
    step-version stamp (flag bit 128 — optim/sharded.py owns the
    semantics; zero is a meaningful stamp); every optional ``None``
    emits the exact pre-feature frame.  The contiguous form of
    :func:`encode_arrays_sg` — one flattening join, counted under the
    ``encode_join`` copy stage."""
    parts = encode_arrays_sg(
        arrays, uuid=uuid, error=error, trace_id=trace_id,
        deadline_s=deadline_s, tenant=tenant, partition=partition,
        version=version,
    )
    if len(parts) == 1 and isinstance(parts[0], bytes):
        return parts[0]  # chaos path: already joined and filtered
    _JOIN_COPIED.inc(
        sum(p.nbytes for p in parts if isinstance(p, memoryview))
    )
    return b"".join(parts)


def encode_batch(
    items: Sequence[bytes],
    *,
    uuid: Optional[bytes] = None,
    error: Optional[str] = None,
    trace_id: Optional[bytes] = None,
    deadline_s: Optional[float] = None,
    tenant: Optional[str] = None,
    partition: Optional[Sequence[int]] = None,
    version: Optional[int] = None,
) -> bytes:
    """Frame K already-encoded npwire messages as ONE batch message
    (flag bit 8).  ``items`` are complete frames — each keeps its own
    uuid/arrays/error, so replies stay correlated and error-isolated
    per item.  The outer ``uuid`` correlates the window as a whole;
    the outer ``trace_id`` is the authoritative span-context id for
    the batch (an item's own trace block, if present, is consumed and
    dropped by the server); a zero-item batch is legal — it is the
    TCP capability probe.  An OUTER ``partition`` block (flag bit 64)
    turns the window into a REDUCE request: the server sums its items'
    replies and answers ``count`` partition-indexed slices
    (routing/partition.py owns the rule; tcp.py/shm.py serve it).
    The result accepts :func:`append_spans` like any reply frame."""
    if uuid is None:
        uuid = uuid_mod.uuid4().bytes
    if len(uuid) != 16:
        raise WireError(f"uuid must be 16 bytes, got {len(uuid)}")
    flags = _FLAG_BATCH
    if error is not None:
        flags |= _FLAG_ERROR
    if trace_id is not None:
        if len(trace_id) != 16:
            raise WireError(
                f"trace_id must be 16 bytes, got {len(trace_id)}"
            )
        flags |= _FLAG_TRACE
    if deadline_s is not None:
        flags |= _FLAG_DEADLINE
    tenant_block = None
    if tenant is not None:
        tenant_block = _encode_tenant(tenant)
        flags |= _FLAG_TENANT
    partition_block = None
    if partition is not None:
        partition_block = _encode_partition(partition)
        flags |= _FLAG_PARTITION
    version_block = None
    if version is not None:
        version_block = _encode_version(version)
        flags |= _FLAG_VERSION
    parts: List[bytes] = [
        _HEADER_STRUCT.pack(MAGIC, 1, flags, uuid, len(items))
    ]
    if error is not None:
        err = error.encode("utf-8")
        parts.append(_U32.pack(len(err)))
        parts.append(err)
    if trace_id is not None:
        parts.append(trace_id)
    if deadline_s is not None:
        parts.append(_F64.pack(float(deadline_s)))
    if tenant_block is not None:
        parts.append(tenant_block)
    if partition_block is not None:
        parts.append(partition_block)
    if version_block is not None:
        parts.append(version_block)
    for item in items:
        if item[:4] != MAGIC:
            raise WireError("batch items must be complete npwire frames")
        parts.append(_U32.pack(len(item)))
        parts.append(item)
    out = b"".join(parts)
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        out = _fi.filter_bytes("npwire.encode_batch", out)
    return out


def is_batch_frame(buf: bytes) -> bool:
    """Whether ``buf`` leads with an npwire batch header (flag bit 8).
    A cheap dispatch predicate — full validation happens in
    :func:`decode_batch`."""
    return (
        len(buf) > _FLAGS_OFF
        and buf[:4] == MAGIC
        and bool(buf[_FLAGS_OFF] & _FLAG_BATCH)
    )


def frame_uuid(buf: bytes) -> bytes:
    """The 16-byte correlation uuid at its fixed header offset — the
    cheap read admission rejections need to answer in-band without
    paying a full decode.  Raises :class:`WireError` on a frame too
    short to carry one."""
    if len(buf) < 22 or buf[:4] != MAGIC:
        raise WireError("not an npwire frame")
    return buf[6:22]


def peek_deadline(buf: bytes) -> Optional[float]:
    """The frame's remaining-deadline budget (flag bit 16) in seconds,
    or ``None`` when the flag is clear — WITHOUT decoding arrays.  The
    server-side admission reader: an expired budget must be rejected
    before any decode/compute cost is paid.  Walks only the fixed-
    offset blocks in front of the payload (error, trace), so the cost
    is a handful of bounds checks.  Raises :class:`WireError` on a
    frame whose leading blocks are truncated (the full decoder would
    reject it identically)."""
    try:
        magic, version, flags = struct.unpack_from("<4sBB", buf, 0)
    except struct.error as e:
        raise WireError(f"truncated header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    _check_flags(flags)
    if not flags & _FLAG_DEADLINE:
        return None
    off = _HEADER_SIZE
    if flags & _FLAG_ERROR:
        try:
            (elen,) = struct.unpack_from("<I", buf, off)
        except struct.error as e:
            raise WireError(f"truncated error block: {e}") from None
        off += 4 + elen
    if flags & _FLAG_TRACE:
        off += 16
    try:
        (budget,) = struct.unpack_from("<d", buf, off)
    except struct.error as e:
        raise WireError(f"truncated deadline block: {e}") from None
    return budget


def peek_tenant(buf: bytes) -> Optional[str]:
    """The frame's tenant id (flag bit 32), or ``None`` when the flag
    is clear — WITHOUT decoding arrays.  The gateway's admission
    reader: quota and fair-queue classification happen before any
    decode cost is paid (the :func:`peek_deadline` posture).  Raises
    :class:`WireError` on a frame whose leading blocks are truncated
    (the full decoder would reject it identically)."""
    try:
        magic, version, flags = struct.unpack_from("<4sBB", buf, 0)
    except struct.error as e:
        raise WireError(f"truncated header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    _check_flags(flags)
    if not flags & _FLAG_TENANT:
        return None
    off = _HEADER_SIZE
    if flags & _FLAG_ERROR:
        try:
            (elen,) = struct.unpack_from("<I", buf, off)
        except struct.error as e:
            raise WireError(f"truncated error block: {e}") from None
        off += 4 + elen
    if flags & _FLAG_TRACE:
        off += 16
    if flags & _FLAG_DEADLINE:
        off += 8
    try:
        (tlen,) = struct.unpack_from("<H", buf, off)
        off += 2
        if off + tlen > len(buf):
            raise WireError("truncated tenant block")
        return buf[off : off + tlen].decode("utf-8")
    except (struct.error, UnicodeDecodeError) as e:
        raise WireError(f"corrupt tenant block: {e}") from None


def peek_partition(buf: bytes) -> Optional[tuple]:
    """The frame's partition block (flag bit 64) as a 5-int tuple, or
    ``None`` when the flag is clear — WITHOUT decoding arrays, and for
    BOTH plain and batch frames (the block sits in front of the body
    either way).  The server-side dispatch reader: a reduce window
    must be recognized before items are decoded.  Raises
    :class:`WireError` on a frame whose leading blocks are truncated
    (the full decoder would reject it identically)."""
    try:
        magic, version, flags = struct.unpack_from("<4sBB", buf, 0)
    except struct.error as e:
        raise WireError(f"truncated header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    _check_flags(flags)
    if not flags & _FLAG_PARTITION:
        return None
    off = _HEADER_SIZE
    if flags & _FLAG_ERROR:
        try:
            (elen,) = struct.unpack_from("<I", buf, off)
        except struct.error as e:
            raise WireError(f"truncated error block: {e}") from None
        off += 4 + elen
    if flags & _FLAG_TRACE:
        off += 16
    if flags & _FLAG_DEADLINE:
        off += 8
    if flags & _FLAG_TENANT:
        off = _skip_tenant_block(buf, off)
    part, _off = _decode_partition(buf, off)
    return part


def peek_version(buf: bytes) -> Optional[int]:
    """The frame's step-version stamp (flag bit 128) as an int, or
    ``None`` when the flag is clear — WITHOUT decoding arrays, and for
    BOTH plain and batch frames.  The server-side dispatch reader: a
    versioned update/refresh request must be recognized before arrays
    are decoded (optim/sharded.py owns the semantics; zero is a
    meaningful stamp, which is why absence is ``None``, never 0).
    Raises :class:`WireError` on a frame whose leading blocks are
    truncated (the full decoder would reject it identically)."""
    try:
        magic, version, flags = struct.unpack_from("<4sBB", buf, 0)
    except struct.error as e:
        raise WireError(f"truncated header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    _check_flags(flags)
    if not flags & _FLAG_VERSION:
        return None
    off = _HEADER_SIZE
    if flags & _FLAG_ERROR:
        try:
            (elen,) = struct.unpack_from("<I", buf, off)
        except struct.error as e:
            raise WireError(f"truncated error block: {e}") from None
        off += 4 + elen
    if flags & _FLAG_TRACE:
        off += 16
    if flags & _FLAG_DEADLINE:
        off += 8
    if flags & _FLAG_TENANT:
        off = _skip_tenant_block(buf, off)
    if flags & _FLAG_PARTITION:
        off += _PARTITION_STRUCT.size
    stamp, _off = _decode_version(buf, off)
    return stamp


def _skip_tenant_block(buf: bytes, off: int) -> int:
    """Consume a tenant block at ``off`` (decoders keep their
    historical tuple shapes; :func:`peek_tenant` is the reader)."""
    try:
        (tlen,) = struct.unpack_from("<H", buf, off)
    except struct.error as e:
        raise WireError(f"truncated tenant block: {e}") from None
    off += 2
    if off + tlen > len(buf):
        raise WireError("truncated tenant block")
    return off + tlen


def decode_batch(
    buf: bytes,
) -> Tuple[List[bytes], bytes, Optional[str], Optional[bytes], Optional[list]]:
    """Decode a batch message -> (items, uuid, error, trace_id, spans).
    ``items`` are the K framed sub-messages, still encoded — decode
    each with :func:`decode_arrays_all` (they may individually carry
    error blocks: per-item failure isolation).  An outer partition
    block (flag bit 64) is consumed and dropped — the reduce-window
    server path reads it with :func:`decode_batch_part`."""
    items, uuid, error, trace_id, spans, _part, _ver = decode_batch_part(
        buf
    )
    return items, uuid, error, trace_id, spans


def decode_batch_part(
    buf: bytes,
) -> Tuple[
    List[bytes],
    bytes,
    Optional[str],
    Optional[bytes],
    Optional[list],
    Optional[tuple],
    Optional[int],
]:
    """Full batch decode -> (items, uuid, error, trace_id, spans,
    partition, version) where ``partition`` is the outer partition
    block's 5-int tuple (flag bit 64; ``None`` when clear) — the
    reduce-window request/reply marker (routing/partition.py) — and
    ``version`` the u64 step-version stamp (flag bit 128; ``None``
    when clear — zero is a meaningful stamp; optim/sharded.py)."""
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        buf = _fi.filter_bytes("npwire.decode_batch", buf)
    try:
        magic, version, flags, uuid, n = _HEADER_STRUCT.unpack_from(buf, 0)
    except struct.error as e:
        raise WireError(f"truncated header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != 1:
        raise WireError(f"unsupported version {version}")
    _check_flags(flags)
    if not flags & _FLAG_BATCH:
        raise WireError("not a batch frame (flag bit 8 unset)")
    off = _HEADER_SIZE
    error = None
    if flags & _FLAG_ERROR:
        try:
            (elen,) = struct.unpack_from("<I", buf, off)
            off += 4
            if off + elen > len(buf):
                raise WireError("truncated error block")
            error = buf[off : off + elen].decode("utf-8")
            off += elen
        except (struct.error, UnicodeDecodeError) as e:
            raise WireError(f"truncated error block: {e}") from None
    trace_id = None
    if flags & _FLAG_TRACE:
        if off + 16 > len(buf):
            raise WireError("truncated trace block")
        trace_id = buf[off : off + 16]
        off += 16
    if flags & _FLAG_DEADLINE:
        # Consumed and dropped here: admission reads it pre-decode via
        # peek_deadline (the enforcement point), so the tuple shapes
        # every existing caller depends on stay stable.
        if off + 8 > len(buf):
            raise WireError("truncated deadline block")
        off += 8
    if flags & _FLAG_TENANT:
        # Consumed and dropped (peek_tenant is the gateway-side reader).
        off = _skip_tenant_block(buf, off)
    partition = None
    if flags & _FLAG_PARTITION:
        partition, off = _decode_partition(buf, off)
    step_version = None
    if flags & _FLAG_VERSION:
        step_version, off = _decode_version(buf, off)
    items: List[bytes] = []
    for _ in range(n):
        try:
            (ilen,) = struct.unpack_from("<I", buf, off)
        except struct.error as e:
            raise WireError(f"truncated batch item length: {e}") from None
        off += 4
        item = buf[off : off + ilen]
        if len(item) != ilen:
            raise WireError("truncated batch item")
        if item[:4] != MAGIC:
            raise WireError("batch item is not an npwire frame")
        items.append(item)
        off += ilen
    spans = None
    if flags & _FLAG_SPANS:
        try:
            (slen,) = struct.unpack_from("<I", buf, off)
            off += 4
            if off + slen > len(buf):
                raise WireError("truncated spans block")
            spans = json.loads(buf[off : off + slen].decode("utf-8"))
            off += slen
        except (struct.error, UnicodeDecodeError, ValueError) as e:
            raise WireError(f"corrupt spans block: {e}") from None
        if not isinstance(spans, list):
            raise WireError(
                f"spans block must be a JSON list, got {type(spans).__name__}"
            )
    return items, uuid, error, trace_id, spans, partition, step_version


def append_spans(frame: bytes, spans: Sequence[dict]) -> bytes:
    """Attach a spans tail to an ALREADY-ENCODED frame (flag bit 4).

    The node-side piggyback path: the ``node.evaluate`` span tree only
    finishes after the reply's arrays are encoded (encoding is itself a
    timed stage), so the tree is appended post-hoc — one flag-byte
    patch plus one tail append, no array re-encode.  ``spans`` is a
    list of JSON-friendly span-tree dicts (``Span.to_dict`` shape).
    Raises :class:`WireError` on a frame that is not a bare header or
    already carries a spans tail."""
    if frame[:4] != MAGIC or len(frame) < _FLAGS_OFF + 1:
        raise WireError("append_spans: not an npwire frame")
    flags = frame[_FLAGS_OFF]
    if flags & _FLAG_SPANS:
        raise WireError("append_spans: frame already carries a spans tail")
    # default=str: span ATTRS are free-form user values (numpy scalars
    # included) — a non-JSON-native attr must degrade to its repr, not
    # fail the reply that carries real results.
    payload = json.dumps(list(spans), default=str).encode("utf-8")
    return (
        frame[:_FLAGS_OFF]
        + bytes([flags | _FLAG_SPANS])
        + frame[_FLAGS_OFF + 1 :]
        + struct.pack("<I", len(payload))
        + payload
    )


def decode_arrays(
    buf: bytes, *, copy: bool = True
) -> Tuple[List[np.ndarray], bytes, Optional[str]]:
    """Decode a framed message -> (arrays, uuid, error).

    The historical 3-tuple shape; a frame carrying a trace id or spans
    tail decodes fine (both consumed and dropped).  Use
    :func:`decode_arrays_ex` / :func:`decode_arrays_all` to read them."""
    arrays, uuid, error, _ = decode_arrays_ex(buf, copy=copy)
    return arrays, uuid, error


def decode_arrays_ex(
    buf: bytes, *, copy: bool = True
) -> Tuple[List[np.ndarray], bytes, Optional[str], Optional[bytes]]:
    """Decode a framed message -> (arrays, uuid, error, trace_id); a
    spans tail (flag bit 4) is consumed and dropped."""
    arrays, uuid, error, trace_id, _ = decode_arrays_all(buf, copy=copy)
    return arrays, uuid, error, trace_id


def decode_arrays_all(
    buf: bytes,
    *,
    copy: bool = True,
) -> Tuple[
    List[np.ndarray],
    bytes,
    Optional[str],
    Optional[bytes],
    Optional[list],
]:
    """Full decode -> (arrays, uuid, error, trace_id, spans) where
    ``spans`` is the piggybacked span-tree list (``None`` when the flag
    is unset).  A partition block (flag bit 64) is consumed and
    dropped — partitioned lanes read it with
    :func:`decode_arrays_part`.

    ``copy=True`` (the default, and the historical behavior) returns
    owned writable arrays.  ``copy=False`` returns READ-ONLY
    ``frombuffer`` views into ``buf`` itself — zero payload copies;
    the views keep the whole frame alive, so opt in where the frame is
    short-lived anyway (a server decoding a request it computes on and
    drops) rather than where results are retained."""
    arrays, uuid, error, trace_id, spans, _part, _ver = (
        decode_arrays_part(buf, copy=copy)
    )
    return arrays, uuid, error, trace_id, spans


def decode_arrays_part(
    buf: bytes,
    *,
    copy: bool = True,
) -> Tuple[
    List[np.ndarray],
    bytes,
    Optional[str],
    Optional[bytes],
    Optional[list],
    Optional[tuple],
    Optional[int],
]:
    """:func:`decode_arrays_all` plus the frame's partition block as a
    5-int tuple (flag bit 64; ``None`` when clear) — what the
    partitioned client/server lanes decode replies with
    (routing/partition.py owns the semantics) — and the u64
    step-version stamp (flag bit 128; ``None`` when clear — zero is a
    meaningful stamp; optim/sharded.py owns the semantics)."""
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        buf = _fi.filter_bytes("npwire.decode", buf)
    try:
        magic, version, flags, uuid, n = _HEADER_STRUCT.unpack_from(buf, 0)
    except struct.error as e:
        raise WireError(f"truncated header: {e}") from None
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    if version != 1:
        raise WireError(f"unsupported version {version}")
    _check_flags(flags)
    if flags & _FLAG_BATCH:
        # Loud, not silent: parsing K framed items as arrays would
        # yield garbage.  Batch frames only reach negotiated peers
        # (module docstring), so landing here is a dispatch bug.
        raise WireError(
            "batch frame (flag bit 8); decode with decode_batch"
        )
    off = _HEADER_SIZE
    error = None
    if flags & _FLAG_ERROR:
        try:
            (elen,) = struct.unpack_from("<I", buf, off)
            off += 4
            if off + elen > len(buf):
                raise WireError("truncated error block")
            error = buf[off : off + elen].decode("utf-8")
            off += elen
        except (struct.error, UnicodeDecodeError) as e:
            raise WireError(f"truncated error block: {e}") from None
    trace_id = None
    if flags & _FLAG_TRACE:
        if off + 16 > len(buf):
            raise WireError("truncated trace block")
        trace_id = buf[off : off + 16]
        off += 16
    if flags & _FLAG_DEADLINE:
        # Consumed and dropped (peek_deadline is the admission-side
        # reader; see decode_batch for the rationale).
        if off + 8 > len(buf):
            raise WireError("truncated deadline block")
        off += 8
    if flags & _FLAG_TENANT:
        # Consumed and dropped (peek_tenant is the gateway-side reader).
        off = _skip_tenant_block(buf, off)
    partition = None
    if flags & _FLAG_PARTITION:
        partition, off = _decode_partition(buf, off)
    step_version = None
    if flags & _FLAG_VERSION:
        step_version, off = _decode_version(buf, off)
    arrays: List[np.ndarray] = []
    for _ in range(n):
        try:
            (dtlen,) = struct.unpack_from("<H", buf, off)
            off += 2
            dt = _parse_dtype(buf[off : off + dtlen])
            off += dtlen
            (ndim,) = struct.unpack_from("<B", buf, off)
            off += 1
            shape = struct.unpack_from(f"<{ndim}Q", buf, off)
            off += 8 * ndim
            (dlen,) = struct.unpack_from("<Q", buf, off)
            off += 8
            data_off = off
            if data_off + dlen > len(buf):
                raise WireError("truncated array payload")
            off += dlen
        except struct.error as e:
            raise WireError(f"truncated message: {e}") from None
        try:
            # frombuffer with an explicit offset/count reads the frame
            # in place — no slice copy; ``copy=True`` then pays exactly
            # ONE copy (the historical path paid two: slice + .copy()).
            if dt.itemsize == 0 or dlen % dt.itemsize:
                raise ValueError(
                    f"payload length {dlen} is not a multiple of "
                    f"itemsize {dt.itemsize}"
                )
            arr = np.frombuffer(
                buf, dtype=dt, count=dlen // dt.itemsize, offset=data_off
            ).reshape(shape)
            if copy:
                arr = arr.copy()
                _DECODE_COPIED.inc(dlen)
            arrays.append(arr)
        except ValueError as e:
            # e.g. data_len inconsistent with shape * itemsize
            raise WireError(f"corrupt array payload: {e}") from None
    spans = None
    if flags & _FLAG_SPANS:
        try:
            (slen,) = struct.unpack_from("<I", buf, off)
            off += 4
            if off + slen > len(buf):
                raise WireError("truncated spans block")
            spans = json.loads(buf[off : off + slen].decode("utf-8"))
            off += slen
        except (struct.error, UnicodeDecodeError, ValueError) as e:
            raise WireError(f"corrupt spans block: {e}") from None
        if not isinstance(spans, list):
            raise WireError(
                f"spans block must be a JSON list, got {type(spans).__name__}"
            )
    return arrays, uuid, error, trace_id, spans, partition, step_version
