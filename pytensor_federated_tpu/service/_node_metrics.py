"""Node-side RPC metric families, shared by every serving lane.

One declaration site for the ``pftpu_server_*`` instruments the gRPC
service (server.py), the TCP template node (tcp.py ``serve_tcp_once``)
and — through the shared ``serve_npwire_payload`` path — the shm
doorbell node all record into.  The registry would dedupe identical
re-declarations, but a single source means the help text and bucket
ladders cannot drift between lanes, and every lane's histograms merge
bucket-wise in the fleet view (:mod:`..telemetry.collector`).  Metric
catalog: docs/observability.md.
"""

from __future__ import annotations

from ..telemetry import metrics as _metrics

REQUESTS = _metrics.counter(
    "pftpu_server_requests_total",
    "RPCs served by the node, by method",
    ("method",),
)
ERRORS = _metrics.counter(
    "pftpu_server_errors_total",
    "Node-side failures, by kind (decode or compute)",
    ("kind",),
)
INFLIGHT = _metrics.gauge(
    "pftpu_server_inflight_requests",
    "Evaluate RPCs currently being served",
)
DECODE_S = _metrics.histogram(
    "pftpu_server_decode_seconds", "Request wire-decode latency"
)
QUEUE_S = _metrics.histogram(
    "pftpu_server_queue_wait_seconds",
    "Wait between RPC decode and compute start (thread-executor queue)",
)
COMPUTE_S = _metrics.histogram(
    "pftpu_server_compute_seconds", "compute_fn latency"
)
ENCODE_S = _metrics.histogram(
    "pftpu_server_encode_seconds", "Reply wire-encode latency"
)
ADMISSION_SHED = _metrics.counter(
    "pftpu_admission_shed_total",
    "Requests shed by server-side admission control, by reason",
    ("reason",),
)

__all__ = [
    "REQUESTS",
    "ERRORS",
    "INFLIGHT",
    "DECODE_S",
    "QUEUE_S",
    "COMPUTE_S",
    "ENCODE_S",
    "ADMISSION_SHED",
]
