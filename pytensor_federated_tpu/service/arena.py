"""Generation-counted mmap ring arena — the zero-copy payload plane.

One :class:`Arena` is a shared-memory file mapped by BOTH sides of a
(driver, replica) pair: the owning side allocates slots and writes
array bytes into them EXACTLY ONCE; the peer reads the same physical
pages through descriptors ``(slot, delta, length, generation)`` that
ride the lightweight doorbell channel (:mod:`.shm`) instead of the
payload.  The arena itself is transport-agnostic: it knows nothing
about sockets, frames, or numpy dtypes — only aligned slots, a ring
allocator, and the generation protocol below.

Slot layout (all little-endian, at arena offset ``slot``)::

    head:    generation(u64) payload_length(u64)
    payload: payload_length bytes (arrays packed 8-aligned at deltas)
    tail:    generation(u64)

The generation protocol is what makes recycled and torn slots LOUD
instead of silently wrong (CLAUDE.md wire invariant):

- the writer stamps head (generation, length), copies the payload,
  then stamps the tail generation — so a slot whose write never
  finished (process death, chaos ``truncate_slot``) has a mismatched
  tail and every read of it raises :class:`~.npwire.WireError`;
- generations increase monotonically per arena, so a descriptor held
  across a slot recycle (a late reader, chaos ``stale_generation``)
  sees a head generation that no longer matches and fails loudly —
  never torn data;
- :meth:`Arena.read_bytes` re-validates head AND tail after copying,
  so even a recycle that lands mid-copy is detected before the bytes
  are believed.

Allocation is two regions in one mapping: a FIFO ring for transient
request/reply slots (freed strictly in allocation order — the doorbell
protocol is lock-step FIFO, so replies release request slots in
order), and a pinned region growing down from the top for arrays the
owner writes once and references forever (the driver's per-node data
constants — "same-host replicas shouldn't move bytes at all").  The
two watermarks colliding is an explicit :class:`~.npwire.WireError`,
never an overwrite.

Version-2 arenas (ISSUE 18) reserve a RING REGION between the file
header and the slot space: a 64-byte ring header plus ``ring_slots``
fixed-size seqlock'd records — the zero-syscall descriptor ring
(:mod:`.ring`) that replaces the TCP doorbell round-trip for colocated
pairs.  The arena knows only the geometry (it shifts the slot floor
and validates bounds); :mod:`.ring` owns the record protocol.
Version-1 files (``ring_slots == 0``) attach unchanged.

The backing file lives in ``/dev/shm`` when available (tmpfs — the
bytes never touch a disk) and the server unlinks it as soon as the
peer has mapped it, so a SIGKILL'd process leaks nothing.
"""

from __future__ import annotations

import mmap
import os
import struct
import tempfile
import threading
from collections import deque
from typing import Deque, List, Optional, Sequence, Tuple, Union

from .npwire import WIRE_BYTES_COPIED, WireError

__all__ = ["Arena", "ARENA_MAGIC", "DEFAULT_ARENA_BYTES"]

ARENA_MAGIC = b"PFA1"
#: Default per-direction arena capacity.  Generous relative to any
#: pipelined window so the ring never wraps onto live slots in normal
#: operation; tmpfs pages are allocated lazily, so an idle arena costs
#: only what was actually written.
DEFAULT_ARENA_BYTES = 64 * 1024 * 1024

_FILE_HEADER = struct.Struct("<4sBxxxQ")  # magic, version, capacity
#: Version-2 header: v1 fields + the ring geometry (ISSUE 18).  The
#: ring region layout itself (header words, record seqlocks) is
#: declared in service/wire_registry.py and owned by service/ring.py.
_FILE_HEADER_V2 = struct.Struct("<4sBxxxQII")  # + ring_slots, record_bytes
_HEADER_SIZE = 64  # file header, padded to one alignment unit
_RING_HEADER_BYTES = 64  # ring header, padded to one alignment unit
_SLOT_HEAD = struct.Struct("<QQ")  # generation, payload_length
_SLOT_TAIL = struct.Struct("<Q")  # generation (truncation/torn guard)
_ALIGN = 64

_ARENA_WRITE = WIRE_BYTES_COPIED.labels(lane="shm", stage="arena_write")
_ARENA_READ = WIRE_BYTES_COPIED.labels(lane="shm", stage="decode_copy")


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _bytes_view(buf: Union[bytes, bytearray, memoryview]) -> memoryview:
    """A flat unsigned-byte view of any C-contiguous buffer (numpy
    arrays included) — what ``mmap`` slice assignment needs."""
    mv = memoryview(buf)
    if mv.format == "B" and mv.ndim == 1:
        return mv
    return mv.cast("B")


def arena_dir() -> str:
    """Directory for arena backing files: tmpfs when available."""
    if os.path.isdir("/dev/shm") and os.access("/dev/shm", os.W_OK):
        return "/dev/shm"
    return tempfile.gettempdir()


class Arena:
    """One mapped arena (module docstring for the slot/generation
    protocol).  Construct with :meth:`create` (the allocating owner)
    or :meth:`attach` (the reading peer); both sides may read, only
    the owner allocates and writes."""

    def __init__(
        self,
        path: str,
        mm: mmap.mmap,
        capacity: int,
        *,
        owner: bool,
        ring_slots: int = 0,
        ring_record_bytes: int = 0,
    ) -> None:
        self.path = path
        self.mm = mm
        self.capacity = capacity
        self.owner = owner
        self.ring_slots = ring_slots
        self.ring_record_bytes = ring_record_bytes
        # Slot space starts past the file header AND the ring region
        # (v1 arenas: ring_slots == 0, the floor is the header alone).
        self.data_floor = _HEADER_SIZE + (
            _RING_HEADER_BYTES + ring_slots * ring_record_bytes
            if ring_slots
            else 0
        )
        # One long-lived view: read_view slices this instead of
        # re-exporting the mmap's buffer per call (hot-path cost).
        self._mv = memoryview(mm)
        self._lock = threading.Lock()
        self._next_gen = 1  # 0 is reserved: fresh pages read as gen 0
        # Transient FIFO ring over [data_floor, _pin_floor).
        self._head = self.data_floor
        self._tail = self.data_floor
        self._live: Deque[Tuple[int, int]] = deque()  # (slot, total)
        self._pin_floor = capacity  # pinned region grows DOWN from here

    # -- construction -----------------------------------------------------

    @classmethod
    def create(
        cls,
        capacity: int = DEFAULT_ARENA_BYTES,
        *,
        path: Optional[str] = None,
        writer: bool = True,
        ring_slots: int = 0,
        ring_record_bytes: int = 4096,
    ) -> "Arena":
        """Create and map a fresh arena file of ``capacity`` data
        bytes.  ``writer=False`` creates the file but leaves slot
        allocation to the peer (the server creates BOTH arenas of a
        pair; the client allocates in the request one).
        ``ring_slots > 0`` reserves the version-2 descriptor-ring
        region (:mod:`.ring`); ``ring_record_bytes`` must be a
        positive multiple of the 64-byte alignment unit so the slot
        floor stays aligned."""
        if ring_slots:
            if ring_record_bytes <= 0 or ring_record_bytes % _ALIGN:
                raise WireError(
                    f"ring_record_bytes {ring_record_bytes} must be a "
                    f"positive multiple of {_ALIGN}"
                )
            floor = _HEADER_SIZE + _RING_HEADER_BYTES + (
                ring_slots * ring_record_bytes
            )
        else:
            floor = _HEADER_SIZE
        if capacity < floor + _ALIGN:
            raise WireError(f"arena capacity {capacity} is below one slot")
        if path is None:
            fd, path = tempfile.mkstemp(
                prefix="pftpu-arena-", suffix=".shm", dir=arena_dir()
            )
        else:
            fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
        try:
            os.ftruncate(fd, capacity)
            mm = mmap.mmap(fd, capacity)
        finally:
            os.close(fd)
        if ring_slots:
            mm[: _FILE_HEADER_V2.size] = _FILE_HEADER_V2.pack(
                ARENA_MAGIC, 2, capacity, ring_slots, ring_record_bytes
            )
        else:
            mm[: _FILE_HEADER.size] = _FILE_HEADER.pack(
                ARENA_MAGIC, 1, capacity
            )
        return cls(
            path, mm, capacity, owner=writer,
            ring_slots=ring_slots,
            ring_record_bytes=ring_record_bytes if ring_slots else 0,
        )

    @classmethod
    def attach(cls, path: str, *, writer: bool = False) -> "Arena":
        """Map an existing arena file created by the peer.
        ``writer=True`` takes the allocation role (exactly one side of
        a pair may hold it — the doorbell protocol assigns the request
        arena's to the client, the reply arena's to the server)."""
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            if size < _HEADER_SIZE:
                raise WireError(f"arena file {path!r} is truncated")
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        magic, version, capacity = _FILE_HEADER.unpack_from(mm, 0)
        ring_slots = 0
        ring_record_bytes = 0
        if magic != ARENA_MAGIC:
            mm.close()
            raise WireError(f"bad arena magic {magic!r} in {path!r}")
        if version == 2:
            (
                _magic, _ver, capacity, ring_slots, ring_record_bytes,
            ) = _FILE_HEADER_V2.unpack_from(mm, 0)
            floor = _HEADER_SIZE + _RING_HEADER_BYTES + (
                ring_slots * ring_record_bytes
            )
            if (
                ring_slots <= 0
                or ring_record_bytes <= 0
                or ring_record_bytes % _ALIGN
                or floor + _ALIGN > size
            ):
                mm.close()
                raise WireError(
                    f"corrupt arena ring geometry in {path!r}: "
                    f"{ring_slots} x {ring_record_bytes}-byte records "
                    f"do not fit {size} bytes"
                )
        elif version != 1:
            mm.close()
            raise WireError(f"unsupported arena version {version}")
        if capacity != size:
            mm.close()
            raise WireError(
                f"arena header declares {capacity} bytes but the file "
                f"holds {size}"
            )
        return cls(
            path, mm, capacity, owner=writer,
            ring_slots=ring_slots, ring_record_bytes=ring_record_bytes,
        )

    def close(self, *, unlink: bool = False) -> None:
        """Drop the mapping (and optionally the file).  If zero-copy
        views into the arena are still alive the OS mapping survives
        until they die — close never invalidates handed-out views."""
        try:
            self._mv.release()
        except BufferError:
            pass  # exported sub-views keep it alive; gc releases it
        try:
            self.mm.close()
        except BufferError:
            # numpy views exported from the mapping are still alive;
            # the mapping is released when the last view is collected.
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    # -- allocation (owner side) ------------------------------------------

    def _alloc(self, total: int, *, pinned: bool) -> int:
        """One aligned region of ``total`` bytes; raises WireError when
        the arena cannot hold it (LOUD — never overwrite live slots)."""
        if pinned:
            floor = self._pin_floor - _align(total)
            # The floor must clear the HIGHEST live byte, not just the
            # ring pointers: in a wrapped ring the slot starting at
            # ``tail`` extends past it, and head/tail alone let the
            # pinned region land inside an in-flight slot (round-9
            # review finding, reproduced: a pinned promotion
            # mid-window corrupted request bytes the node was
            # computing on).
            limit = self._head
            for s, t in self._live:
                if s + t > limit:
                    limit = s + t
            if floor < limit or floor < self.data_floor:
                raise WireError(
                    f"arena exhausted: pinned region cannot grow by "
                    f"{total} bytes (capacity {self.capacity})"
                )
            self._pin_floor = floor
            return floor
        total = _align(total)
        if not self._live:
            self._head = self._tail = self.data_floor
        elif self._head == self._tail:
            # head == tail is ambiguous: empty OR exactly full.  Live
            # slots resolve it — the ring is FULL (an exact-fit
            # allocation landed flush against the oldest live slot),
            # and the branch below would otherwise hand out the live
            # region again and overwrite in-flight payloads.
            raise WireError(
                f"arena exhausted: ring exactly full "
                f"({len(self._live)} live slots) — the in-flight "
                "window outran reclamation"
            )
        if self._tail <= self._head:
            if self._head + total <= self._pin_floor:
                slot = self._head
                self._head += total
            elif self._live and self.data_floor + total <= self._tail:
                slot = self.data_floor  # wrap
                self._head = self.data_floor + total
            else:
                raise WireError(
                    f"arena exhausted: {total} bytes do not fit "
                    f"(capacity {self.capacity}, "
                    f"{len(self._live)} live slots) — the in-flight "
                    "window outran reclamation"
                )
        else:
            if self._head + total <= self._tail:
                slot = self._head
                self._head += total
            else:
                raise WireError(
                    f"arena exhausted: {total} bytes do not fit "
                    f"(capacity {self.capacity}, "
                    f"{len(self._live)} live slots) — the in-flight "
                    "window outran reclamation"
                )
        self._live.append((slot, total))
        return slot

    def write_many(
        self,
        buffers: Sequence[Union[bytes, bytearray, memoryview]],
        *,
        pinned: bool = False,
    ) -> Tuple[int, int, List[int]]:
        """Pack ``buffers`` 8-aligned into ONE freshly allocated slot;
        returns ``(slot, generation, deltas)`` where ``deltas[i]`` is
        buffer *i*'s offset inside the slot payload.  Each byte is
        copied exactly once — from the source buffer into the shared
        pages the peer will read in place."""
        if not self.owner:
            raise WireError("only the arena owner allocates slots")
        views = [_bytes_view(b) for b in buffers]
        deltas: List[int] = []
        length = 0
        for v in views:
            deltas.append(length)
            length += (v.nbytes + 7) & ~7  # 8-align every array start
        total = _SLOT_HEAD.size + length + _SLOT_TAIL.size
        with self._lock:
            slot = self._alloc(total, pinned=pinned)
            gen = self._next_gen
            self._next_gen += 1
        mm = self.mm
        _SLOT_HEAD.pack_into(mm, slot, gen, length)
        base = slot + _SLOT_HEAD.size
        copied = 0
        for v, delta in zip(views, deltas):
            if v.nbytes:
                mm[base + delta : base + delta + v.nbytes] = v
                copied += v.nbytes
        _SLOT_TAIL.pack_into(mm, base + length, gen)
        if copied:
            _ARENA_WRITE.inc(copied)
        return slot, gen, deltas

    def free(self, slot: int) -> None:
        """Release the OLDEST live transient slot (FIFO — the doorbell
        protocol replies in order, so out-of-order release is a
        protocol bug and raises)."""
        with self._lock:
            if not self._live or self._live[0][0] != slot:
                raise WireError(
                    f"arena free out of order: slot {slot} is not the "
                    "oldest live slot"
                )
            _, total = self._live.popleft()
            self._tail = self._live[0][0] if self._live else self._head
        # The slot's pages stay intact until recycled by a later
        # allocation — a late reader sees its (still matching)
        # generation until then, and a LOUD mismatch after.

    def live_slots(self) -> int:
        with self._lock:
            return len(self._live)

    def transient_bytes_free(self) -> int:
        """Largest transient allocation currently guaranteed to fit —
        the client's in-flight byte-cap input."""
        with self._lock:
            if not self._live:
                return max(
                    0, self._pin_floor - self.data_floor - 2 * _ALIGN
                )
            if self._head == self._tail:
                return 0  # exactly full (live slots resolve the tie)
            if self._tail < self._head:
                return max(
                    0,
                    max(
                        self._pin_floor - self._head,
                        self._tail - self.data_floor,
                    ) - 2 * _ALIGN,
                )
            return max(0, self._tail - self._head - 2 * _ALIGN)

    # -- reading (either side) --------------------------------------------

    def _validate(self, slot: int, delta: int, length: int, gen: int) -> int:
        """Bounds + generation checks; returns the payload base offset."""
        if slot < self.data_floor or slot + _SLOT_HEAD.size > self.capacity:
            raise WireError(
                f"descriptor slot {slot} out of arena bounds "
                f"(slot space starts at {self.data_floor})"
            )
        if slot % 8 or delta % 8:
            raise WireError(
                f"descriptor misaligned (slot {slot}, delta {delta})"
            )
        head_gen, payload_len = _SLOT_HEAD.unpack_from(self.mm, slot)
        base = slot + _SLOT_HEAD.size
        if base + payload_len + _SLOT_TAIL.size > self.capacity:
            raise WireError(
                f"slot {slot} declares {payload_len} payload bytes past "
                "the arena end"
            )
        if head_gen != gen:
            raise WireError(
                f"stale descriptor: slot {slot} is generation {head_gen}, "
                f"descriptor expects {gen} (slot recycled?)"
            )
        if delta + length > payload_len:
            raise WireError(
                f"descriptor range [{delta}, {delta + length}) exceeds "
                f"slot {slot}'s {payload_len}-byte payload"
            )
        (tail_gen,) = _SLOT_TAIL.unpack_from(self.mm, base + payload_len)
        if tail_gen != gen:
            raise WireError(
                f"torn slot {slot}: tail generation {tail_gen} != "
                f"{gen} — the write never completed"
            )
        return base

    def read_view(
        self, slot: int, delta: int, length: int, gen: int
    ) -> memoryview:
        """Zero-copy view of a descriptor's bytes, validated (head AND
        tail generation) before return.  Valid until the slot is
        recycled — under the doorbell protocol, until the reply for
        the frame that carried the descriptor is sent."""
        base = self._validate(slot, delta, length, gen)
        return self._mv[base + delta : base + delta + length]

    def read_bytes(self, slot: int, delta: int, length: int, gen: int) -> bytes:
        """Copy a descriptor's bytes out, with the generation
        RE-validated after the copy so a recycle landing mid-copy is
        detected before the bytes are believed."""
        base = self._validate(slot, delta, length, gen)
        data = bytes(self.mm[base + delta : base + delta + length])
        self._validate(slot, delta, length, gen)  # no recycle mid-copy
        if length:
            _ARENA_READ.inc(length)
        return data

    # -- chaos hooks (fault injection / tests only) ------------------------

    def scribble_tail(self, slot: int) -> None:
        """Corrupt a slot's tail generation — the ``truncate_slot``
        chaos fault: the slot now reads as a write that never
        finished.  Test/fault-injection use only."""
        _, payload_len = _SLOT_HEAD.unpack_from(self.mm, slot)
        off = slot + _SLOT_HEAD.size + payload_len
        (tail_gen,) = _SLOT_TAIL.unpack_from(self.mm, off)
        _SLOT_TAIL.pack_into(self.mm, off, tail_gen ^ 0xDEAD)
