"""Host-federation client: load balancing, connection cache, failover.

Re-design of the reference's client core (reference: service.py:161-423).
All the behavioral contracts survive:

- **GetLoad polling**: all candidate servers queried concurrently with a
  timeout; unresponsive servers map to ``None``
  (reference: get_loads_async, service.py:161-211).
- **Balanced connect**: shuffle + small de-sync sleep, then pick the
  server with the fewest active clients
  (reference: ClientPrivates.connect_balanced, service.py:240-263) via
  :func:`..utils.argmin_none_or_func`.  Ports stay ``int`` s — the
  reference's numpy-shuffle turned them into strings (SURVEY §5 quirks);
  here the shuffle uses ``random.sample`` on the tuple list.
- **Connection cache**: gRPC objects are not picklable, so they live in
  a module-global dict keyed ``(id(client), pid, thread_id)`` and are
  re-created lazily after the client is pickled into worker processes
  (reference: _privates, service.py:214-275).
- **uuid correlation** on every evaluation
  (reference: service.py:321-322).
- **Failover**: on a dead connection the cached channel is dropped and
  the retry loop rebalances onto a surviving server
  (reference: service.py:407-416); all servers dead raises
  ``TimeoutError`` (reference: service.py:257-260).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import json
import logging
import os
import random
import threading
import time
import uuid as uuid_mod
from typing import Dict, List, Optional, Sequence, Tuple

import grpc
import numpy as np

from ..faultinject import runtime as _fi
from ..telemetry import flightrec as _flightrec
from ..telemetry import reunion as _reunion
from ..telemetry import spans as _spans
from ..telemetry import watchdog as _watchdog
from ..utils import argmin_none_or_func, get_event_loop
from . import _rpc_metrics
from . import deadline as _deadline
from . import npproto_codec
from .npproto_codec import decode_get_load_result
from .npwire import (
    WireError,
    decode_arrays_all,
    decode_batch,
    encode_arrays,
    encode_batch,
    fast_uuid,
)
from .server import EVALUATE, EVALUATE_STREAM, GET_LOAD

_log = logging.getLogger(__name__)

HostPort = Tuple[str, int]
_identity = lambda b: b  # noqa: E731

# Driver-side RPC instrumentation, shared with the TCP lane
# (transport="grpc" here, "tcp" in .tcp) so dashboards aggregate
# across lanes (metric catalog: docs/observability.md).
_CALL_S = _rpc_metrics.CALL_S
_RETRIES = _rpc_metrics.RETRIES
_DROPS = _rpc_metrics.DROPS
_BATCH_S = _rpc_metrics.BATCH_S
_WINDOW_DEPTH = _rpc_metrics.WINDOW_DEPTH
_FRAME_REQS = _rpc_metrics.BATCH_FRAME_REQS


# gRPC status codes that mark a DETERMINISTIC server-side failure: the
# npproto path has no in-band error field, so a compute error surfaces
# as a stream abort — re-running it retries+1 times would re-execute
# the whole batch into the same exception (ADVICE r5 #2).  Transport
# trouble (UNAVAILABLE, ...) stays retryable.  DEADLINE_EXCEEDED is in
# the NO-RETRY set since ISSUE 10: a spent deadline is spent on every
# replica at once, so a retry can only add load for a caller that
# already gave up — the retry-storm amplification the deadline
# machinery exists to remove (it is also the status the server aborts
# with for an npproto request whose wire budget expired).
_NO_RETRY_STATUS = frozenset(
    {
        grpc.StatusCode.UNKNOWN,  # server handler raised
        grpc.StatusCode.INVALID_ARGUMENT,
        grpc.StatusCode.OUT_OF_RANGE,
        grpc.StatusCode.FAILED_PRECONDITION,
        grpc.StatusCode.UNIMPLEMENTED,
        grpc.StatusCode.DEADLINE_EXCEEDED,
    }
)


def _is_retryable(exc: BaseException) -> bool:
    """Whether the retry-and-rebalance loop should re-attempt after
    ``exc`` — AioRpcError is classified by status code; raw socket
    trouble (ConnectionError/OSError) is always transport."""
    if isinstance(exc, grpc.aio.AioRpcError):
        return exc.code() not in _NO_RETRY_STATUS
    return True


async def _stream_write(stream, payload: bytes) -> None:
    """``stream.write`` with dead-stream translation: writing to an RPC
    the server already aborted raises ``asyncio.InvalidStateError``
    ("RPC already finished"), which is TRANSPORT trouble — without the
    translation it would escape the retry/failover classification and
    surface as an unclassified crash (found by tools/chaos_run.py:
    a server aborting mid-window left the next write unclassified)."""
    try:
        await stream.write(payload)
    except asyncio.InvalidStateError as e:
        raise ConnectionError(f"stream already finished: {e}") from e


async def _stream_read(stream):
    """``stream.read`` with the same dead-stream translation, bounded
    by the ambient deadline when one is set: a server that accepted
    the write but never replies must fail the call inside the caller's
    budget, not block until the watchdog fires.  The timeout cancels
    the read, desynchronizing the lock-step stream — the TimeoutError
    (an OSError since 3.10) lands in the callers' transport-error
    handlers, which drop the cached connection."""
    remaining = _deadline.remaining_s()
    try:
        if remaining is None:
            return await stream.read()
        if remaining <= 0:
            _deadline.DEADLINE_EXPIRED.labels(stage="client").inc()
            # The request was already written (lock-step): raising
            # without reading leaves the cached stream one reply
            # ahead, failing the NEXT healthy call with a uuid
            # mismatch.  DeadlineExceeded is a RuntimeError, so the
            # callers' transport handlers never drop the connection —
            # cancel the RPC here so the next use raises
            # InvalidStateError -> ConnectionError and reconnects.
            with contextlib.suppress(Exception):
                stream.cancel()
            raise _deadline.DeadlineExceeded(
                _deadline.deadline_error("budget spent awaiting reply")
            )
        return await asyncio.wait_for(stream.read(), timeout=remaining)
    except asyncio.CancelledError:
        # grpc.aio raises CancelledError from read() on an RPC that
        # was itself cancelled (e.g. by a previous timed-out read
        # tearing the call down) — that is a DEAD STREAM, transport
        # trouble, not our task being cancelled.  A genuine task
        # cancellation leaves the RPC alive and must propagate.
        done = getattr(stream, "done", None)
        if done is not None and done():
            raise ConnectionError("stream cancelled mid-read") from None
        raise
    except asyncio.TimeoutError:
        # Translate to the transport classification (asyncio's
        # TimeoutError is not an OSError on 3.10): the callers drop
        # the now-desynchronized connection and fail over; the next
        # attempt's own deadline check then raises DeadlineExceeded.
        _deadline.DEADLINE_EXPIRED.labels(stage="client").inc()
        raise ConnectionError(
            "reply deadline elapsed on the lock-step stream"
        ) from None
    except asyncio.InvalidStateError as e:
        raise ConnectionError(f"stream already finished: {e}") from e


async def get_load_async(
    host: str, port: int, *, timeout: float = 5.0
) -> Optional[dict]:
    """Query one server's load; ``None`` if unreachable/slow/garbled
    (reference: get_load_async, service.py:161-186).

    The reply format is AUTO-DETECTED: this package's nodes answer
    JSON (always starts with ``{``); an unmodified reference node —
    or a node started with ``getload_wire="npproto"`` — answers the
    reference's protobuf ``GetLoadResult`` (service.proto:24-31),
    which can never start with ``{`` (0x7B = field 15 with illegal
    wire type 3).  Either way the same dict comes back, so ANY client
    can balance over ANY pool.
    """
    try:
        async with grpc.aio.insecure_channel(f"{host}:{port}") as channel:
            method = channel.unary_unary(
                GET_LOAD, request_serializer=_identity, response_deserializer=_identity
            )
            reply = await asyncio.wait_for(method(b""), timeout=timeout)
            if reply[:1] == b"{":
                return json.loads(reply.decode("utf-8"))
            # The decoder accepts b"" (the legitimate all-defaults
            # encoding an idle proto-wire server sends) and schema-
            # evolved replies, but raises WireError on garbage that
            # proto3 leniency would otherwise decode to the all-zero —
            # i.e. maximally attractive — load (unknown-fields-only
            # buffers).
            try:
                return decode_get_load_result(reply)
            # A garbled load reply is a failed PROBE, not a failed call:
            # None feeds the balancer's "replica unknown" path, which is
            # the loud in-band verdict for this lane.
            except WireError:  # graftlint: disable=wire-loudness -- probe verdict lane
                return None
    except (  # graftlint: disable=wire-loudness -- probe verdict lane (None = failed probe)
        asyncio.TimeoutError,
        grpc.aio.AioRpcError,
        OSError,
        ConnectionError,
        ValueError,  # garbled JSON / undecodable bytes
    ):
        return None


async def get_loads_async(
    hosts_and_ports: Sequence[HostPort], *, timeout: float = 5.0
) -> List[Optional[dict]]:
    """Concurrent load query over the pool (reference: service.py:189-211)."""
    return list(
        await asyncio.gather(
            *(get_load_async(h, p, timeout=timeout) for h, p in hosts_and_ports)
        )
    )


async def get_node_traces_async(
    host: str, port: int, *, timeout: float = 5.0
) -> List[dict]:
    """PULL a node's recent completed span trees over the enriched
    GetLoad lane (request payload ``b"traces"``; server.py get_load)
    and ingest them into the trace-reunion store.  Returns the trees.

    The forensics complement to the reply piggyback: spans whose own
    reply never arrived (the call that wedged or died) are still in
    the node's ring — if the node survives, this fetches them.
    npwire-JSON nodes only; an npproto-wire or unreachable node yields
    ``[]`` (the fixed reference GetLoad schema has no room for traces).
    """
    try:
        async with grpc.aio.insecure_channel(f"{host}:{port}") as channel:
            method = channel.unary_unary(
                GET_LOAD,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            reply = await asyncio.wait_for(method(b"traces"), timeout=timeout)
            if reply[:1] != b"{":
                return []
            traces = json.loads(reply.decode("utf-8")).get("traces") or []
    except (
        asyncio.TimeoutError,
        grpc.aio.AioRpcError,
        OSError,
        ConnectionError,
        ValueError,
    ):
        return []
    if isinstance(traces, list):
        _reunion.ingest(traces)
        return traces
    return []


def get_node_traces(
    host: str, port: int, *, timeout: float = 5.0
) -> List[dict]:
    """Sync wrapper over :func:`get_node_traces_async`."""
    loop = get_event_loop()
    return loop.run_until_complete(
        get_node_traces_async(host, port, timeout=timeout)
    )


async def get_node_telemetry_async(
    host: str, port: int, *, timeout: float = 5.0
) -> Optional[dict]:
    """PULL a node's full telemetry snapshot over the enriched GetLoad
    lane (request payload ``b"telemetry"``, declared in
    :data:`.wire_registry.GETLOAD_PAYLOADS`; server.py ``get_load``).
    Returns the whole load dict — whose ``"telemetry"`` key carries the
    node's metric families, recent span trees, flight-record tail, and
    wall-clock ``ts`` — or ``None`` if the node is unreachable, slow,
    garbled, or answers without the key (an npproto-wire or
    pre-telemetry node).  The fleet collector
    (:mod:`...telemetry.collector`) is the consumer; unlike
    :func:`get_node_traces_async` nothing is ingested here — the
    collector owns merge/staleness semantics.
    """
    try:
        async with grpc.aio.insecure_channel(f"{host}:{port}") as channel:
            method = channel.unary_unary(
                GET_LOAD,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            reply = await asyncio.wait_for(
                method(b"telemetry"), timeout=timeout
            )
            if reply[:1] != b"{":
                return None
            load = json.loads(reply.decode("utf-8"))
    except (  # graftlint: disable=wire-loudness -- probe verdict lane (None = failed scrape)
        asyncio.TimeoutError,
        grpc.aio.AioRpcError,
        OSError,
        ConnectionError,
        ValueError,
    ):
        return None
    if not isinstance(load, dict) or not isinstance(
        load.get("telemetry"), dict
    ):
        return None
    return load


def get_node_telemetry(
    host: str, port: int, *, timeout: float = 5.0
) -> Optional[dict]:
    """Sync wrapper over :func:`get_node_telemetry_async`."""
    loop = get_event_loop()
    return loop.run_until_complete(
        get_node_telemetry_async(host, port, timeout=timeout)
    )


@dataclasses.dataclass
class ClientPrivates:
    """Non-picklable per-(client,process,thread,loop) connection state
    (reference: ClientPrivates, service.py:214-263).  ``loop`` records
    the aio loop the channel is bound to, so a cache hit can verify the
    entry really belongs to the currently running loop (id(loop) in the
    cache key can collide after a dead loop's address is recycled)."""

    host: str
    port: int
    channel: grpc.aio.Channel
    stream: Optional[grpc.aio.StreamStreamCall] = None
    loop: Optional[asyncio.AbstractEventLoop] = None
    # Per-connection batch capability: None = not yet probed; {} = the
    # server does not advertise wire batch frames; a dict with
    # "max_batch" = it does (GetLoad "batch" field, server.py).
    batch_caps: Optional[dict] = None

    @staticmethod
    async def connect(host: str, port: int, *, use_stream: bool) -> "ClientPrivates":
        channel = grpc.aio.insecure_channel(f"{host}:{port}")
        privates = ClientPrivates(
            host=host,
            port=port,
            channel=channel,
            loop=asyncio.get_running_loop(),
        )
        if use_stream:
            method = channel.stream_stream(
                EVALUATE_STREAM,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            privates.stream = method()
        _log.info("connected to %s:%d (stream=%s)", host, port, use_stream)
        return privates

    @staticmethod
    async def connect_balanced(
        hosts_and_ports: Sequence[HostPort],
        *,
        use_stream: bool,
        timeout: float = 5.0,
        desync: Tuple[float, float] = (0.0, 0.05),
    ) -> "ClientPrivates":
        """Pick the least-loaded healthy server
        (reference: connect_balanced, service.py:240-263)."""
        candidates = random.sample(list(hosts_and_ports), k=len(hosts_and_ports))
        # De-sync concurrent clients so they don't all pick the same
        # server (the reference sleeps U[0.2, 2] s; that dominates
        # connect latency, so the window here is 50 ms).
        await asyncio.sleep(random.uniform(*desync))
        loads = await get_loads_async(candidates, timeout=timeout)
        best = argmin_none_or_func(loads, lambda l: l["n_clients"])
        if best is None:
            raise TimeoutError(
                f"none of {len(candidates)} servers responded to GetLoad"
            )
        host, port = candidates[best]
        return await ClientPrivates.connect(host, port, use_stream=use_stream)

    async def close(self) -> None:
        if self.stream is not None:
            try:
                self.stream.cancel()
            except Exception:
                pass
            self.stream = None
        await self.channel.close()


# Module-global cache so client objects survive pickling into worker
# processes and reconnect lazily per process/thread/loop
# (reference: _privates + thread_pid_id, service.py:266-275).
# Keyed by a per-instance token rather than id(obj): CPython recycles
# object addresses, so an id-keyed cache could hand a new client a dead
# client's connection.  The token survives pickling, so a client copied
# into a worker process keys the same logical identity there.
# The key ALSO includes the driving event loop: a grpc.aio channel is
# bound to the loop it was created on, and one thread can legally run
# several loops over its lifetime (sync wrapper's cached loop, then
# asyncio.run(...)) — reusing a channel across loops errors or hangs,
# so each (client, process, thread, loop) owns its own connection.
_privates: Dict[Tuple[str, int, int, int], ClientPrivates] = {}


def thread_pid_id(obj) -> Tuple[str, int, int]:
    token = getattr(obj, "_cache_token", None) or str(id(obj))
    return (token, os.getpid(), threading.get_ident())


def _conn_key(obj) -> Tuple[str, int, int, int]:
    """Full cache key; must be computed inside the driving loop."""
    loop_id = id(asyncio.get_running_loop())
    return (*thread_pid_id(obj), loop_id)


def _cancel_stream(privates: Optional[ClientPrivates]) -> None:
    """Best-effort teardown usable from any context: stream.cancel() is
    loop-safe-ish; channel close must run on its own (possibly dead)
    loop, so the channel is left to GC."""
    if privates is not None and privates.stream is not None:
        try:
            privates.stream.cancel()
        except Exception:
            pass


def _purge_dead_loop_entries() -> None:
    """Evict entries whose loop has closed — each asyncio.run() leaves
    its connections behind, and unbounded entries both leak channels
    and set up id(loop) collisions.  Snapshot keys first (list() is
    C-atomic) so concurrent threads mutating the dict can't break the
    sweep."""
    for cid in list(_privates):
        privates = _privates.get(cid)
        if (
            privates is not None
            and privates.loop is not None
            and privates.loop.is_closed()
        ):
            _privates.pop(cid, None)
            _cancel_stream(privates)


class ArraysToArraysServiceClient:
    """Sync+async evaluation client with balancing and failover
    (reference: ArraysToArraysServiceClient, service.py:326-423)."""

    def __init__(
        self,
        host: Optional[str] = None,
        port: Optional[int] = None,
        *,
        hosts_and_ports: Optional[Sequence[HostPort]] = None,
        use_stream: bool = True,
        retries: int = 2,
        codec: str = "npwire",
    ):
        """``codec``: "npwire" (this package's native framing, default)
        or "npproto" — the REFERENCE's protobuf wire
        (protobufs/service.proto:6-19), letting this client talk to an
        unmodified reference node pool.  Method paths are identical in
        both stacks (``/ArraysToArraysService/...``), so only Evaluate
        payload bytes differ; GetLoad balancing auto-detects the reply
        format and needs no codec choice.
        """
        if codec not in ("npwire", "npproto"):
            raise ValueError(
                f"codec must be 'npwire' or 'npproto', got {codec!r}"
            )
        if hosts_and_ports is None:
            if host is None or port is None:
                raise ValueError("pass host+port or hosts_and_ports")
            hosts_and_ports = [(host, int(port))]
        elif host is not None or port is not None:
            raise ValueError("pass either host+port or hosts_and_ports, not both")
        self.hosts_and_ports: List[HostPort] = [
            (h, int(p)) for h, p in hosts_and_ports
        ]
        self.use_stream = use_stream
        self.retries = retries
        self.codec = codec
        self._cache_token = uuid_mod.uuid4().hex

    # -- connection management -------------------------------------------

    async def _get_privates(self) -> ClientPrivates:
        _purge_dead_loop_entries()
        cid = _conn_key(self)
        privates = _privates.get(cid)
        if privates is not None and privates.loop is not asyncio.get_running_loop():
            # id(loop) collision: a recycled address matched a dead
            # loop's entry.  Never drive that channel from this loop.
            _privates.pop(cid, None)
            _cancel_stream(privates)
            privates = None
        if privates is None:
            privates = await ClientPrivates.connect_balanced(
                self.hosts_and_ports, use_stream=self.use_stream
            )
            _privates[cid] = privates
        return privates

    async def _batch_caps(self, privates: ClientPrivates) -> dict:
        """Read (once per connection) whether the peer advertises wire
        batch frames via its GetLoad ``batch`` field.  A reference
        node answers protobuf GetLoad (no such field) and an
        unreachable/garbled reply degrades to {} — either way the
        client never coalesces toward a peer that did not opt in, which
        is the negotiation contract batch frames depend on."""
        if privates.batch_caps is None:
            caps: dict = {}
            try:
                method = privates.channel.unary_unary(
                    GET_LOAD,
                    request_serializer=_identity,
                    response_deserializer=_identity,
                )
                reply = await asyncio.wait_for(method(b""), timeout=5.0)
                if reply[:1] == b"{":
                    b = json.loads(reply.decode("utf-8")).get("batch")
                    if isinstance(b, dict) and int(b.get("max_batch", 0)) > 1:
                        caps = {"max_batch": int(b["max_batch"])}
            except (
                asyncio.TimeoutError,
                grpc.aio.AioRpcError,
                OSError,
                ConnectionError,
                ValueError,
                TypeError,
            ):
                caps = {}
            privates.batch_caps = caps
        return privates.batch_caps

    async def _drop_privates(self) -> None:
        cid = _conn_key(self)
        privates = _privates.pop(cid, None)
        if privates is not None:
            _DROPS.labels(transport="grpc").inc()
            _flightrec.record(
                "rpc.drop", transport="grpc",
                peer=f"{privates.host}:{privates.port}",
            )
            _log.warning(
                "dropping connection to %s:%d", privates.host, privates.port
            )
            await privates.close()

    def __del__(self):
        # Best-effort stream teardown (reference: service.py:355-365).
        # No loop is running here, so sweep every loop's entry for this
        # (client, process, thread) identity.  Snapshot keys first:
        # other threads may be inserting concurrently, and iterating
        # the live dict from __del__ could raise mid-sweep.
        prefix = thread_pid_id(self)
        for cid in list(_privates):
            if cid[:3] == prefix:
                _cancel_stream(_privates.pop(cid, None))

    # -- evaluation -------------------------------------------------------

    async def _evaluate_once(self, request: bytes) -> bytes:
        privates = await self._get_privates()
        peer = f"{privates.host}:{privates.port}"
        if _fi.active_plan is not None:  # chaos seam (faultinject)
            request = await _fi.filter_bytes_async("grpc.send", request, peer)
        if privates.stream is not None:
            # Lock-step bidi hot loop (reference: _streamed_evaluate,
            # service.py:150-158).
            await _stream_write(privates.stream, request)
            reply = await _stream_read(privates.stream)
            if reply is grpc.aio.EOF:
                raise ConnectionError("stream closed by server")
            if _fi.active_plan is not None:  # chaos seam
                reply = await _fi.filter_bytes_async("grpc.recv", reply, peer)
            return reply
        method = privates.channel.unary_unary(
            EVALUATE, request_serializer=_identity, response_deserializer=_identity
        )
        # The ambient deadline bounds the RPC itself too, via OUR
        # timer rather than grpc's ``timeout=``: grpc.aio's client-side
        # deadline can race into a local cancellation that surfaces as
        # a bare CancelledError instead of DEADLINE_EXCEEDED (observed
        # under the overload chaos lane), while wait_for converts the
        # same cancellation into a deterministic TimeoutError here.
        remaining = _deadline.remaining_s()
        if remaining is None:
            reply = await method(request)
        else:
            try:
                reply = await asyncio.wait_for(
                    method(request), timeout=max(remaining, 1e-3)
                )
            except asyncio.TimeoutError:
                _deadline.DEADLINE_EXPIRED.labels(stage="client").inc()
                raise _deadline.DeadlineExceeded(
                    _deadline.deadline_error("budget spent awaiting reply")
                ) from None
        if _fi.active_plan is not None:  # chaos seam
            reply = await _fi.filter_bytes_async("grpc.recv", reply, peer)
        return reply

    def _encode_request(self, arrays):
        """(request_bytes, uuid, decode) for one call under the active
        codec; ``decode`` returns ``(outputs, uuid, error)``.

        The ACTIVE telemetry trace id (if any) is embedded in the
        request — npwire flag block or npproto field 15 — so the node's
        span tree correlates with the driver's.  npproto field 15 is
        genuinely ignorable by peers that predate it (proto3 skips
        unknown fields; property-tested against the official runtime) —
        use that codec toward reference nodes.  The npwire flag block
        is only understood by this package's own nodes (which ship in
        lockstep with this client); a PRE-telemetry npwire node would
        reject a flagged frame, so toward one either disable telemetry
        or upgrade the node.  With telemetry disabled the request is
        byte-identical to the uninstrumented wire either way.

        Both decoders also harvest the reply's piggybacked node-side
        span trees (npwire flag 4 / npproto field 16) into the trace-
        reunion store (:mod:`..telemetry.reunion`) — how the driver
        gets the other half of a correlated trace."""
        arrays = [np.asarray(a) for a in arrays]
        trace_id = _spans.current_trace_id() if _spans.enabled() else None
        # Deadline propagation: the remaining budget rides the request
        # (npwire flag 16 / npproto field 18); None — the default —
        # keeps the frame byte-identical to the deadline-free wire.
        deadline_s = _deadline.wire_budget()
        if self.codec == "npproto":
            uuid = str(uuid_mod.uuid4())
            request = npproto_codec.encode_arrays_msg(
                arrays, uuid=uuid, trace_id=trace_id,
                deadline_s=deadline_s,
            )

            def decode(reply):
                outputs, ruuid, _tid, spans = (
                    npproto_codec.decode_arrays_msg_all(reply)
                )
                if spans:
                    _reunion.ingest(spans)
                return outputs, ruuid, None

        else:
            uuid = fast_uuid()
            request = encode_arrays(
                arrays, uuid=uuid, trace_id=trace_id,
                deadline_s=deadline_s,
            )

            def decode(reply):
                outputs, ruuid, error, _tid, spans = decode_arrays_all(reply)
                if spans:
                    _reunion.ingest(spans)
                return outputs, ruuid, error

        return request, uuid, decode

    async def _validate_reply(self, reply, uuid, decode):
        """Single-sourced reply validation: returns ``(outputs,
        error_msg)``.  The error check runs FIRST (error replies carry a
        zero uuid); a uuid mismatch — a desynchronized lock-step stream
        (e.g. a previous call cancelled between write and read) stays
        off-by-one forever — drops the connection so the next call
        reconnects cleanly, then raises."""
        # Off-loop when chaos is active: the decoder holds sync
        # byte-lane seams whose delay kinds sleep (graftflow
        # async-blocking; the PR-5 bug class).
        outputs, reply_uuid, error = await _fi.call_shimmed_async(
            decode, reply
        )
        if error is None and reply_uuid != uuid:
            await self._drop_privates()
            raise RuntimeError(
                "uuid mismatch: response does not correlate with request"
            )
        return outputs, error

    async def evaluate_async(self, *arrays: np.ndarray) -> List[np.ndarray]:
        """Evaluate with retry-and-rebalance failover
        (reference: evaluate_async, service.py:376-423).

        Deterministic server failures do not burn retries: in-band
        error replies (npwire) and non-retryable gRPC status codes
        (npproto compute errors abort the RPC as UNKNOWN) raise
        immediately; only transport trouble rebalances."""
        with _spans.span(
            "rpc.evaluate", transport="grpc", codec=self.codec
        ) as root:
            # The span (entered above) binds the trace id the encode
            # step stamps into the request.
            with _spans.span("encode"):
                # Fail fast on a spent budget BEFORE paying encode or
                # transport: the pool's failover loop re-enters here,
                # so this is also what stops failover once the
                # caller's deadline is gone.
                _deadline.check_remaining("grpc evaluate")
                request, uuid, decode = await _fi.call_shimmed_async(
                    self._encode_request, arrays
                )
            mode = "stream" if self.use_stream else "unary"
            last_exc: Optional[BaseException] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="grpc").inc()
                    _flightrec.record(
                        "rpc.retry", transport="grpc", attempt=attempt
                    )
                    # A spent budget stops the rebalance loop: the
                    # retry would arrive at a replica only to be shed
                    # at its admission check.
                    _deadline.check_remaining("grpc retry")
                    # Restamp the REMAINING budget: re-sending the
                    # attempt-0 request would advertise the budget as
                    # it stood before the failed attempts burned wall
                    # time, so the replica would admit work whose
                    # caller is closer to giving up than the wire
                    # claims.  (A fresh uuid per attempt is fine: each
                    # attempt is its own RPC, validated against its
                    # own decode closure.)
                    if _deadline.current_deadline() is not None:
                        request, uuid, decode = await _fi.call_shimmed_async(
                            self._encode_request, arrays
                        )
                t0 = time.perf_counter()
                try:
                    with _spans.span("call"):
                        reply = await self._evaluate_once(request)
                except (grpc.aio.AioRpcError, ConnectionError, OSError) as e:
                    last_exc = e
                    await self._drop_privates()
                    if not _is_retryable(e):
                        root.set_attr("error", "server")
                        raise
                    continue
                with _spans.span("decode"):
                    outputs, error = await self._validate_reply(
                        reply, uuid, decode
                    )
                _CALL_S.labels(transport="grpc", mode=mode).observe(
                    time.perf_counter() - t0
                )
                if error is not None:
                    root.set_attr("error", "server")
                    _flightrec.record(
                        "rpc.error", transport="grpc", error=error[:200]
                    )
                    if _deadline.is_deadline_error(error):
                        raise _deadline.DeadlineExceeded(error)
                    raise RuntimeError(f"server error: {error}")
                return outputs
            root.set_attr("error", "transport")
            raise (
                last_exc
                if last_exc is not None
                else ConnectionError("evaluation failed")
            )

    def evaluate(self, *arrays: np.ndarray) -> List[np.ndarray]:
        """Sync wrapper (reference: evaluate, service.py:371-374)."""
        loop = get_event_loop()
        return loop.run_until_complete(self.evaluate_async(*arrays))

    # -- pipelined batch evaluation --------------------------------------

    async def _evaluate_many_once(
        self, encoded, window: int, out: Optional[list] = None
    ) -> List[List[np.ndarray]]:
        """One pipelined pass over the current connection.

        Stream mode: keep up to ``window`` requests in flight on the
        lock-step stream and read replies in order — the server
        guarantees FIFO (one reply per request, in order,
        server.py:evaluate_stream), so client serialize, both network
        legs, and server decode/compute overlap instead of paying the
        full round-trip per call.  Unary mode: ``window``-sized
        ``asyncio.gather`` chunks over HTTP/2 multiplexing.

        A SERVER-SIDE error reply must not poison the stream for later
        calls: the remaining in-flight replies are drained (count-only)
        before the error raises, so the lock-step correlation survives.

        ``out`` (optional, len(encoded) of ``None``) is filled IN
        PLACE as replies validate, so a caller supplying it observes
        the partial results of a pass that died mid-window — the
        replica-pool failover lane (routing/) re-queues exactly the
        still-``None`` tail.
        """
        privates = await self._get_privates()
        peer = f"{privates.host}:{privates.port}"
        n = len(encoded)
        results: List[Optional[List[np.ndarray]]] = (
            out if out is not None else [None] * n
        )
        if privates.stream is None:
            method = privates.channel.unary_unary(
                EVALUATE,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            for start in range(0, n, window):
                chunk = encoded[start : start + window]
                reqs = [req for req, _u, _d in chunk]
                if _fi.active_plan is not None:  # chaos seam
                    reqs = [
                        await _fi.filter_bytes_async("grpc.send", r, peer)
                        for r in reqs
                    ]
                # return_exceptions: every sibling RPC settles before we
                # raise, so a failing chunk never leaves orphan tasks
                # whose channel _drop_privates then closes under them
                # ("Task exception was never retrieved" spam).
                replies = await asyncio.gather(
                    *(method(req) for req in reqs),
                    return_exceptions=True,
                )
                for reply in replies:
                    if isinstance(reply, BaseException):
                        raise reply
                for k, (reply, (_req, uuid, decode)) in enumerate(
                    zip(replies, chunk)
                ):
                    outputs, error = await self._validate_reply(
                        reply, uuid, decode
                    )
                    if error is not None:
                        if _deadline.is_deadline_error(error):
                            raise _deadline.DeadlineExceeded(error)
                        raise RuntimeError(f"server error: {error}")
                    results[start + k] = outputs
            return results  # type: ignore[return-value]

        stream = privates.stream
        # Flow-control guard: a client that keeps WRITING while never
        # reading can deadlock against HTTP/2 stream windows when the
        # in-flight bytes exceed the transport's credit (client stuck
        # in write -> never reads -> server's replies never drain ->
        # server never reads the next request).  Capping in-flight
        # REQUEST bytes well under the 64 KiB minimum initial stream
        # window keeps every write completable, so the loop always
        # reaches read(); a single oversized request still proceeds
        # alone (the write_idx == read_idx disjunct) in plain lock-step,
        # which is the proven-safe per-call mode.
        max_inflight_bytes = 32 * 1024
        write_idx = read_idx = 0
        inflight_bytes = 0
        try:
            while read_idx < n:
                while write_idx < n and (
                    write_idx == read_idx
                    or (
                        write_idx - read_idx < window
                        and inflight_bytes + len(encoded[write_idx][0])
                        <= max_inflight_bytes
                    )
                ):
                    payload = encoded[write_idx][0]
                    if _fi.active_plan is not None:  # chaos seam
                        payload = await _fi.filter_bytes_async(
                            "grpc.send", payload, peer
                        )
                    await _stream_write(stream, payload)
                    inflight_bytes += len(encoded[write_idx][0])
                    write_idx += 1
                _WINDOW_DEPTH.labels(transport="grpc").observe(
                    write_idx - read_idx
                )
                reply = await _stream_read(stream)
                if reply is grpc.aio.EOF:
                    raise ConnectionError("stream closed by server")
                if _fi.active_plan is not None:  # chaos seam
                    reply = await _fi.filter_bytes_async("grpc.recv", reply, peer)
                _req, uuid, decode = encoded[read_idx]
                inflight_bytes -= len(_req)
                try:
                    outputs, error = await self._validate_reply(
                        reply, uuid, decode
                    )
                except (grpc.aio.AioRpcError, ConnectionError, OSError):
                    raise  # transport trouble: the outer except drops
                except RuntimeError:
                    raise  # uuid mismatch: _validate_reply already dropped
                except BaseException:
                    # Corrupt reply (e.g. WireError) with replies still
                    # in flight: the lock-step correlation cannot be
                    # trusted any more — drop the cached connection so
                    # the NEXT call reconnects cleanly, mirroring the
                    # TCP lane (tcp.py _evaluate_many_once), then let
                    # the decode error surface loudly (ADVICE r5 #1).
                    await self._drop_privates()
                    raise
                if error is not None:
                    # Drain in-flight replies so the stream stays
                    # correlated for the NEXT call, then surface the
                    # deterministic server error (no retry — same
                    # policy as evaluate_async).
                    for _ in range(write_idx - read_idx - 1):
                        drained = await _stream_read(stream)
                        if drained is grpc.aio.EOF:
                            break
                    if _deadline.is_deadline_error(error):
                        raise _deadline.DeadlineExceeded(error)
                    raise RuntimeError(f"server error: {error}")
                results[read_idx] = outputs
                read_idx += 1
        except (grpc.aio.AioRpcError, ConnectionError, OSError):
            await self._drop_privates()
            raise
        return results  # type: ignore[return-value]

    def _decode_batch_item(self, item: bytes):
        """Decode one reply item out of a wire batch frame under the
        active codec -> (outputs, uuid, error); piggybacked node spans
        are harvested like any reply's."""
        if self.codec == "npproto":
            outputs, ruuid, error, _tid, spans = (
                npproto_codec.decode_arrays_msg_full(item)
            )
        else:
            outputs, ruuid, error, _tid, spans = decode_arrays_all(item)
        if spans:
            _reunion.ingest(spans)
        return outputs, ruuid, error

    def _encode_batch_frame(self, part, trace_id):
        """One outer batch frame for a window slice of encoded
        requests -> (frame_bytes, outer_uuid)."""
        deadline_s = _deadline.wire_budget()
        if self.codec == "npproto":
            outer_uuid = str(uuid_mod.uuid4())
            frame = npproto_codec.encode_batch_msg(
                [req for req, _u, _d in part],
                uuid=outer_uuid,
                trace_id=trace_id,
                deadline_s=deadline_s,
            )
        else:
            outer_uuid = fast_uuid()
            frame = encode_batch(
                [req for req, _u, _d in part],
                uuid=outer_uuid,
                trace_id=trace_id,
                deadline_s=deadline_s,
            )
        return frame, outer_uuid

    def _decode_batch_frame(self, reply: bytes):
        """Outer batch reply -> (items, outer_uuid, outer_error);
        outer spans (the node's whole-window tree) are harvested."""
        if self.codec == "npproto":
            items, ruuid, _tid, spans = npproto_codec.decode_batch_msg(
                reply
            )
            error = None
        else:
            items, ruuid, error, _tid, spans = decode_batch(reply)
        if spans:
            _reunion.ingest(spans)
        return items, ruuid, error

    async def _evaluate_many_batched_once(
        self, encoded, window: int, max_batch: int,
        out: Optional[list] = None,
    ) -> List[List[np.ndarray]]:
        """One pipelined pass using WIRE BATCH FRAMES: the window is
        packed ``min(window, max_batch)`` requests per frame, so K
        requests pay one transport message, one server decode loop and
        one (vmapped) dispatch per frame instead of per call.  Frames
        pipeline on the stream under the same in-flight byte cap as
        the unbatched path; per-item uuids still correlate inside each
        frame and the outer uuid correlates the frame itself.  Error
        semantics match the unbatched pass: the first item error
        drains the in-flight frames and raises without retry.
        ``out`` is the same in-place partial-results channel as
        :meth:`_evaluate_many_once` (frame-granular here: a frame's
        items land together when its reply validates)."""
        privates = await self._get_privates()
        peer = f"{privates.host}:{privates.port}"
        n = len(encoded)
        chunk = max(1, min(window, max_batch))
        trace_id = _spans.current_trace_id() if _spans.enabled() else None
        frames = []  # (frame_bytes, outer_uuid, start, part)
        for start in range(0, n, chunk):
            part = encoded[start : start + chunk]
            frame, outer_uuid = await _fi.call_shimmed_async(
                self._encode_batch_frame, part, trace_id
            )
            _FRAME_REQS.labels(transport="grpc").observe(len(part))
            frames.append((frame, outer_uuid, start, part))
        results: List[Optional[List[np.ndarray]]] = (
            out if out is not None else [None] * n
        )

        async def consume(reply, frame_idx, *, inflight_after: int):
            """Validate one outer reply; fills results or raises.
            ``inflight_after`` = frames still undrained after this one
            (for the error-drain path)."""
            _frame, outer_uuid, start, part = frames[frame_idx]
            try:
                items, ruuid, outer_error = await _fi.call_shimmed_async(
                    self._decode_batch_frame, reply
                )
            except (grpc.aio.AioRpcError, ConnectionError, OSError):
                raise
            except BaseException:
                # Corrupt reply mid-pipeline: correlation is gone —
                # drop so the NEXT call reconnects cleanly (same
                # posture as the unbatched pass).
                await self._drop_privates()
                raise
            # Outer error FIRST: an outer-level batch failure is
            # encoded with a zeroed uuid (server.py / cpp_node), so
            # checking correlation first would mask the real error as
            # a phantom uuid mismatch.
            if outer_error is not None:
                await self._drain_frames(inflight_after)
                if _deadline.is_deadline_error(outer_error):
                    raise _deadline.DeadlineExceeded(outer_error)
                raise RuntimeError(f"server error: {outer_error}")
            if ruuid != outer_uuid:
                await self._drop_privates()
                raise RuntimeError(
                    "uuid mismatch: batch reply does not correlate "
                    "with its frame"
                )
            if len(items) != len(part):
                await self._drop_privates()
                raise RuntimeError(
                    f"batch reply carries {len(items)} items for a "
                    f"{len(part)}-request frame"
                )
            for j, (item, (_req, uuid, _dec)) in enumerate(
                zip(items, part)
            ):
                try:
                    outputs, ruuid_j, error_j = await _fi.call_shimmed_async(
                        self._decode_batch_item, item
                    )
                except (grpc.aio.AioRpcError, ConnectionError, OSError):
                    raise
                except BaseException:
                    # Corrupt nested item with frames still in flight:
                    # the stream's undrained replies would poison the
                    # NEXT call — drop, like the unbatched pass does
                    # for a corrupt reply.
                    await self._drop_privates()
                    raise
                if error_j is not None:
                    await self._drain_frames(inflight_after)
                    if _deadline.is_deadline_error(error_j):
                        raise _deadline.DeadlineExceeded(error_j)
                    raise RuntimeError(f"server error: {error_j}")
                if ruuid_j != uuid:
                    await self._drop_privates()
                    raise RuntimeError(
                        "uuid mismatch: batch item does not correlate "
                        "with its request"
                    )
                results[start + j] = outputs

        if privates.stream is None:
            method = privates.channel.unary_unary(
                EVALUATE,
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            # Bounded like the unbatched unary pass: ~window REQUESTS
            # in flight, i.e. window//chunk frames per gather — a huge
            # request list must not explode into thousands of
            # simultaneous RPCs just because frames are big.
            frames_per_gather = max(1, window // chunk)
            for start_f in range(0, len(frames), frames_per_gather):
                part_f = frames[start_f : start_f + frames_per_gather]
                payloads = [frame for frame, _u, _s, _p in part_f]
                if _fi.active_plan is not None:  # chaos seam
                    payloads = [
                        await _fi.filter_bytes_async("grpc.send", p, peer)
                        for p in payloads
                    ]
                replies = await asyncio.gather(
                    *(method(frame) for frame in payloads),
                    return_exceptions=True,
                )
                for reply in replies:
                    if isinstance(reply, BaseException):
                        raise reply
                for k, reply in enumerate(replies):
                    await consume(reply, start_f + k, inflight_after=0)
            return results  # type: ignore[return-value]

        stream = privates.stream
        # Same flow-control geometry as the unbatched pass: cap
        # in-flight frame bytes under the HTTP/2 stream window, with
        # the lone-frame disjunct for oversized frames.
        max_inflight_bytes = 32 * 1024
        nf = len(frames)
        write_idx = read_idx = 0
        inflight_bytes = 0
        try:
            while read_idx < nf:
                while write_idx < nf and (
                    write_idx == read_idx
                    or inflight_bytes + len(frames[write_idx][0])
                    <= max_inflight_bytes
                ):
                    payload = frames[write_idx][0]
                    if _fi.active_plan is not None:  # chaos seam
                        payload = await _fi.filter_bytes_async(
                            "grpc.send", payload, peer
                        )
                    await _stream_write(stream, payload)
                    inflight_bytes += len(frames[write_idx][0])
                    write_idx += 1
                _WINDOW_DEPTH.labels(transport="grpc").observe(
                    write_idx - read_idx
                )
                reply = await _stream_read(stream)
                if reply is grpc.aio.EOF:
                    raise ConnectionError("stream closed by server")
                if _fi.active_plan is not None:  # chaos seam
                    reply = await _fi.filter_bytes_async("grpc.recv", reply, peer)
                inflight_bytes -= len(frames[read_idx][0])
                await consume(
                    reply,
                    read_idx,
                    inflight_after=write_idx - read_idx - 1,
                )
                read_idx += 1
        except (grpc.aio.AioRpcError, ConnectionError, OSError):
            await self._drop_privates()
            raise
        return results  # type: ignore[return-value]

    async def _drain_frames(self, n_frames: int) -> None:
        """Count-only drain of in-flight stream replies so the
        lock-step correlation survives a deterministic server error
        (mirror of the unbatched drain)."""
        if n_frames <= 0:
            return
        privates = await self._get_privates()
        if privates.stream is None:
            return
        for _ in range(n_frames):
            drained = await _stream_read(privates.stream)
            if drained is grpc.aio.EOF:
                break

    async def evaluate_many_async(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[List[np.ndarray]]:
        """Pipelined evaluation of MANY argument tuples on one node.

        The reference's stream protocol is strictly one-in-flight
        (lock-step write/read per call, reference: service.py:150-158),
        which prices every call at a full round-trip.  The wire itself
        is FIFO, so this client keeps ``window`` requests in flight and
        overlaps the pipeline stages — a throughput mode the
        reference's design cannot express, measured 1.7-3x the per-call
        rate on the localhost lane depending on machine throttle state
        (the suite artifact and an idle-machine sweep; docs/
        performance.md "Host lane budget").

        ``batch``: "auto" (default) additionally packs the window into
        WIRE BATCH FRAMES — ``min(window, server max_batch)`` requests
        per transport message — when the connected server advertises
        the capability in its GetLoad reply, so the whole window pays
        one encode/decode and one syscall each way and the server can
        execute it as one vmapped call (docs/performance.md "Host lane
        budget", batched rows).  ``False`` forces the plain pipelined
        pass (per-call frames); ``True`` requires batch support and
        raises if the server does not advertise it.  Reference-wire
        peers never advertise, so "auto" degrades to the plain pass —
        a reference runtime never sees a batch frame.

        All-or-nothing TRANSPORT failover: on connection failure the
        whole batch retries on a freshly balanced connection
        (per-result partial retry would reorder effects on a stateful
        node).  Server-side compute errors raise without retry, like
        :meth:`evaluate_async`, and leave the connection usable: as
        in-band error replies with ``codec="npwire"``, and as
        non-retryable gRPC status aborts with ``codec="npproto"`` (the
        reference schema has no error field, so the server re-raises
        into the RPC layer — classified by status code here so a
        deterministic compute error is NOT re-executed retries+1
        times; npproto stream aborts do tear down that connection).
        In batched mode both codecs carry per-item in-band errors
        (npwire item error block / npproto field 14), same no-retry
        raise.
        """
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        # Identity checks, not equality: 0/1 would pass an `in` test
        # (0 == False) yet route down the WRONG branch below, so they
        # are rejected outright.
        if batch != "auto" and batch is not True and batch is not False:
            raise ValueError(
                f"batch must be 'auto', True or False, got {batch!r}"
            )
        with _spans.span(
            "rpc.evaluate_many",
            transport="grpc",
            n=len(requests),
            window=window,
        ) as root:
            with _spans.span("encode"):
                encoded = await _fi.call_shimmed_async(
                    lambda: [
                        self._encode_request(args) for args in requests
                    ]
                )
            if not encoded:
                return []
            t0 = time.perf_counter()
            last_exc: Optional[BaseException] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="grpc").inc()
                    _flightrec.record(
                        "rpc.retry", transport="grpc", attempt=attempt,
                        batch=len(encoded),
                    )
                try:
                    # Capability is per CONNECTION (a retry may land on
                    # a different pool member): read it after connect,
                    # before deciding how to pack the window.
                    max_batch = 0
                    if batch is not False:
                        privates = await self._get_privates()
                        caps = await self._batch_caps(privates)
                        max_batch = int(caps.get("max_batch", 0))
                        if batch is True and max_batch < 2:
                            raise RuntimeError(
                                f"server {privates.host}:{privates.port} "
                                "does not advertise wire batch frames "
                                "(GetLoad carries no usable 'batch' field)"
                            )
                    # Known wedge point (CLAUDE.md): an HTTP/2 batch
                    # window can deadlock against flow control — armed
                    # so a hang leaves an incident bundle, not a blank.
                    with _watchdog.armed(
                        "grpc.batch_window",
                        n=len(encoded), window=window,
                    ):
                        if max_batch >= 2:
                            root.set_attr("batched", True)
                            results = await self._evaluate_many_batched_once(
                                encoded, window, max_batch
                            )
                        else:
                            results = await self._evaluate_many_once(
                                encoded, window
                            )
                except (grpc.aio.AioRpcError, ConnectionError, OSError) as e:
                    last_exc = e
                    await self._drop_privates()
                    if not _is_retryable(e):
                        raise
                    continue
                _BATCH_S.labels(transport="grpc").observe(
                    time.perf_counter() - t0
                )
                return results
            raise (
                last_exc
                if last_exc is not None
                else ConnectionError("batch evaluation failed")
            )

    def evaluate_many(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[List[np.ndarray]]:
        """Sync wrapper over :meth:`evaluate_many_async`."""
        loop = get_event_loop()
        return loop.run_until_complete(
            self.evaluate_many_async(requests, window=window, batch=batch)
        )

    async def evaluate_many_partial_async(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> Tuple[List[Optional[List[np.ndarray]]], Optional[BaseException]]:
        """ONE pipelined pass with no internal retry, surfacing partial
        progress: returns ``(results, transport_exc)`` where
        ``results`` holds each request's outputs in order with ``None``
        for every request whose reply never arrived, and
        ``transport_exc`` is the connection failure that ended the
        pass (``None`` on a complete pass).

        This is the failover primitive the replica pool
        (:mod:`pytensor_federated_tpu.routing`) builds on: the caller
        re-queues exactly the ``None`` tail onto another replica
        instead of re-running the whole batch (the all-or-nothing
        contract :meth:`evaluate_many_async` keeps for single-node
        callers).  Batch-frame packing, the in-flight byte cap, and
        the capability negotiation all behave exactly as in
        :meth:`evaluate_many_async`; deterministic server errors
        (in-band error replies, non-retryable status codes, corrupt
        frames) RAISE instead of being returned — the same inputs
        would fail identically on any replica, so failover must not
        swallow them.
        """
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if batch != "auto" and batch is not True and batch is not False:
            raise ValueError(
                f"batch must be 'auto', True or False, got {batch!r}"
            )
        with _spans.span(
            "rpc.evaluate_many",
            transport="grpc",
            n=len(requests),
            window=window,
            partial=True,
        ):
            with _spans.span("encode"):
                encoded = await _fi.call_shimmed_async(
                    lambda: [
                        self._encode_request(args) for args in requests
                    ]
                )
            if not encoded:
                return [], None
            out: List[Optional[List[np.ndarray]]] = [None] * len(encoded)
            t0 = time.perf_counter()
            try:
                max_batch = 0
                if batch is not False:
                    privates = await self._get_privates()
                    caps = await self._batch_caps(privates)
                    max_batch = int(caps.get("max_batch", 0))
                    if batch is True and max_batch < 2:
                        raise RuntimeError(
                            f"server {privates.host}:{privates.port} "
                            "does not advertise wire batch frames "
                            "(GetLoad carries no usable 'batch' field)"
                        )
                with _watchdog.armed(
                    "grpc.batch_window", n=len(encoded), window=window
                ):
                    if max_batch >= 2:
                        await self._evaluate_many_batched_once(
                            encoded, window, max_batch, out=out
                        )
                    else:
                        await self._evaluate_many_once(
                            encoded, window, out=out
                        )
            except (grpc.aio.AioRpcError, ConnectionError, OSError) as e:
                # Drop the connection (idempotent when the *_once pass
                # already did) and classify like the retry loop does —
                # only transport trouble is failover-worthy.
                await self._drop_privates()
                if not _is_retryable(e):
                    raise
                return out, e
            _BATCH_S.labels(transport="grpc").observe(
                time.perf_counter() - t0
            )
            return out, None
