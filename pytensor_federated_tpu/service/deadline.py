"""End-to-end deadline budgets for the serving stack.

The reference runtime's driver waits forever: every RPC blocks until
the peer answers and every failure is retried blindly — exactly what
melts down first under overload (ROADMAP item 3's front-door tier
needs the *protection* half before any accept path can scale).  This
module is the budget that threads through the whole stack:

- the DRIVER binds a deadline with :func:`deadline_scope`; it lives in
  a :mod:`contextvars` var, so it crosses ``await`` points, executor
  hops made with ``contextvars.copy_context`` (the repo convention),
  and nested calls without any plumbing;
- CLIENTS stamp the REMAINING budget into each request as a wire field
  — npwire flag bit 16, npproto extension field 18, shm doorbell flag
  bit 4, all declared in :mod:`.wire_registry` first — as *relative
  seconds*, never an absolute timestamp: peer clocks are not ours;
- SERVERS enforce it at admission (an already-expired request is
  answered with a :data:`DEADLINE_ERROR_PREFIX` in-band error and
  never computed), in the micro-batcher queue (expired entries are
  shed before compute, never vmap'd in), and across the compute
  handoff (:func:`budget_scope` re-binds the budget node-side so
  nested work inherits it);
- CLIENTS classify the reply: an in-band error carrying the prefix
  raises :class:`DeadlineExceeded` — deliberately a ``RuntimeError``
  subclass, because every lane already treats ``RuntimeError`` as a
  DETERMINISTIC, non-retryable verdict (re-sending work whose deadline
  is spent would multiply load for a caller that already gave up: the
  retry-storm amplification this PR exists to remove).

No deadline bound (the shipping default) costs one contextvar read on
the encode path — bench.py's ``deadline_overhead`` gate holds that
line — and produces BYTE-IDENTICAL frames on every codec
(property-tested), so deadline-free peers interoperate unchanged.
"""

from __future__ import annotations

import contextlib
import contextvars
import socket
import time
from typing import IO, Callable, Iterator, Optional

from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics

__all__ = [
    "DEADLINE_ERROR_PREFIX",
    "DeadlineExceeded",
    "bounded_reader",
    "budget_scope",
    "check_remaining",
    "current_deadline",
    "deadline_error",
    "deadline_scope",
    "expired",
    "is_deadline_error",
    "recv_budget_s",
    "remaining_s",
    "shed_expired_admission",
    "wire_budget",
]

#: The in-band error classification marker.  Every server that rejects
#: or sheds expired work builds its error string with
#: :func:`deadline_error`; every client maps a reply error containing
#: the marker to :class:`DeadlineExceeded` via :func:`is_deadline_error`
#: (substring, not prefix: servers may wrap the message in their own
#: stage prefixes, e.g. ``"compute error: deadline exceeded: …"``).
DEADLINE_ERROR_PREFIX = "deadline exceeded"

#: Deadline instrumentation (catalog: docs/observability.md).
DEADLINE_EXPIRED = _metrics.counter(
    "pftpu_deadline_expired_total",
    "Work abandoned because its deadline budget was spent, by stage",
    ("stage",),
)
DEADLINE_BUDGET_S = _metrics.histogram(
    "pftpu_deadline_budget_seconds",
    "Remaining deadline budget observed at server admission",
)
#: Same family as the server/batcher declarations (the metrics registry
#: is get-or-create): admission sheds are ONE counter across lanes.
ADMISSION_SHED = _metrics.counter(
    "pftpu_admission_shed_total",
    "Requests shed by server-side admission control, by reason",
    ("reason",),
)


class DeadlineExceeded(RuntimeError):
    """A call's deadline budget was spent — before send, at server
    admission, in a shedding queue, or waiting for the reply.

    A ``RuntimeError`` on purpose: the transports, the replica pool,
    and the chaos harness all classify ``RuntimeError`` as a
    deterministic (non-transient, non-retryable) failure, which is the
    correct posture — the caller's budget is gone everywhere at once,
    so failover or retry can only add load, never an answer in time.
    """


def deadline_error(detail: str) -> str:
    """The in-band error string for a deadline rejection/shed."""
    return f"{DEADLINE_ERROR_PREFIX}: {detail}"


def is_deadline_error(error: Optional[str]) -> bool:
    """Whether a reply's in-band error string is the deadline
    classification (clients raise :class:`DeadlineExceeded` for it)."""
    return error is not None and DEADLINE_ERROR_PREFIX in error


#: The ambient deadline: an ABSOLUTE ``time.monotonic()`` instant, or
#: ``None`` (unbounded — the shipping default).  Monotonic on purpose:
#: wall clocks jump; only the wire form is relative.
_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "pftpu_deadline", default=None
)


def current_deadline() -> Optional[float]:
    """The ambient absolute deadline (``time.monotonic()`` units), or
    ``None`` when the context is unbounded."""
    return _DEADLINE.get()


def remaining_s() -> Optional[float]:
    """Seconds of budget left in this context (possibly negative once
    spent), or ``None`` when unbounded."""
    d = _DEADLINE.get()
    return None if d is None else d - time.monotonic()


def expired() -> bool:
    """Whether the ambient deadline has been spent."""
    d = _DEADLINE.get()
    return d is not None and time.monotonic() >= d


def check_remaining(where: str) -> Optional[float]:
    """Remaining budget, raising :class:`DeadlineExceeded` (and booking
    the ``client`` expiry metric) when it is already spent — the
    fail-fast guard clients run before paying for an attempt."""
    r = remaining_s()
    if r is not None and r <= 0.0:
        DEADLINE_EXPIRED.labels(stage="client").inc()
        raise DeadlineExceeded(
            deadline_error(f"budget spent before {where}")
        )
    return r


@contextlib.contextmanager
def deadline_scope(timeout_s: Optional[float]) -> Iterator[None]:
    """Bind a deadline of ``timeout_s`` seconds from now for the
    calling context.  Nested scopes only ever TIGHTEN (the effective
    deadline is the min of the ambient one and the new one), so an
    inner retry loop cannot mint itself fresh budget.  ``None`` is a
    no-op, keeping call sites unconditional."""
    if timeout_s is None:
        yield
        return
    new = time.monotonic() + float(timeout_s)
    cur = _DEADLINE.get()
    if cur is not None:
        new = min(new, cur)
    token = _DEADLINE.set(new)
    try:
        yield
    finally:
        _DEADLINE.reset(token)


@contextlib.contextmanager
def budget_scope(budget_s: Optional[float]) -> Iterator[None]:
    """Server-side twin of :func:`deadline_scope`: adopt a budget that
    arrived OFF THE WIRE (relative seconds) as this context's deadline,
    so the compute handoff, the micro-batcher, and any nested outbound
    calls inherit the caller's remaining time."""
    with deadline_scope(budget_s):
        yield


def shed_expired_admission(
    budget: Optional[float], *, transport: str
) -> Optional[str]:
    """Admission enforcement shared by EVERY serving lane (grpc
    handler, tcp accept loop, shm doorbell), so their shed semantics
    and telemetry cannot diverge: observe the advertised budget, and
    when it is already spent emit the full shed record —
    ``pftpu_admission_shed_total{reason=expired}``,
    ``pftpu_deadline_expired_total{stage=admission}``, flightrec
    ``admission.shed`` — and return the in-band deadline error text
    for the lane to wrap in its own reply shape (or raise, on the
    error-field-free npproto wire).  ``None`` means admit."""
    if budget is None:
        return None
    DEADLINE_BUDGET_S.observe(budget)
    if budget > 0.0:
        return None
    ADMISSION_SHED.labels(reason="expired").inc()
    DEADLINE_EXPIRED.labels(stage="admission").inc()
    _flightrec.record(
        "admission.shed", transport=transport, reason="expired"
    )
    return deadline_error("budget spent before admission")


def wire_budget() -> Optional[float]:
    """The remaining budget to stamp into an outgoing request, or
    ``None`` when the context is unbounded (the frame then stays
    byte-identical to the deadline-free wire).  Clamped at a small
    positive floor: callers fail fast on a spent budget via
    :func:`check_remaining` BEFORE encoding, so a non-positive value
    here only happens in the race between check and encode — ship the
    floor and let the server's admission check be the judge."""
    r = remaining_s()
    if r is None:
        return None
    return max(r, 1e-6)


def recv_budget_s(timeout_s: Optional[float]) -> Optional[float]:
    """Effective bound for one reply read: the explicit per-call
    ``timeout_s`` knob and the ambient deadline's remaining budget,
    whichever is tighter; ``None`` keeps the historical blocking read
    (bounded only by the connect-era socket timeout)."""
    r = remaining_s()
    cands = [t for t in (timeout_s, r) if t is not None]
    return min(cands) if cands else None


#: One bounded chunk = at most ONE underlying ``recv`` (``read1``), so
#: the remaining budget is re-armed between kernel reads — a socket
#: timeout is PER RECV, and a peer dripping bytes just under it would
#: otherwise stretch a multi-recv frame read far past the budget.
_BOUNDED_CHUNK = 1 << 16


@contextlib.contextmanager
def bounded_reader(
    sock: socket.socket,
    rfile: IO[bytes],
    timeout_s: Optional[float],
    close: Callable[[], None],
) -> Iterator[Callable[[int], bytes]]:
    """Yield ``read_exact(n) -> bytes`` whose TOTAL wall time across
    every read in the ``with`` body is bounded by ``timeout_s`` (from
    :func:`recv_budget_s`) — the shared bounded-read posture the TCP
    socket lane and the shm doorbell both delegate to, so their
    deadline semantics cannot diverge:

    - an already-spent budget (``timeout_s <= 0``): the reply is
      unread and the connection desynchronized — ``close()`` so the
      next call reconnects cleanly, and classify as deadline;
    - the budget exhausted mid-frame, or one chunk's recv timing out:
      the connection cannot be trusted to stay correlated —
      ``close()``, and raise ``TimeoutError`` (an OSError: the
      transient classification drives retry/failover);
    - a short read: ``ConnectionError`` (peer closed mid-frame);
    - ``None`` keeps the historical blocking read (bounded only by
      the connect-era socket timeout);
    - the socket's connect-era timeout is restored on exit.

    Bounded reads go through ``rfile.read1`` — buffer-first, at most
    one underlying ``recv`` per chunk — with the REMAINING budget
    re-armed before each chunk, so a slowly-dripping peer cannot
    evade the bound the way a per-recv ``settimeout`` alone allows.
    """
    if timeout_s is None:

        def read_blocking(n: int) -> bytes:
            buf = rfile.read(n)
            if buf is None or len(buf) < n:
                raise ConnectionError("peer closed mid-frame")
            return buf

        yield read_blocking
        return
    if timeout_s <= 0:
        close()
        DEADLINE_EXPIRED.labels(stage="client").inc()
        raise DeadlineExceeded(
            deadline_error("budget spent awaiting reply")
        )
    deadline = time.monotonic() + timeout_s
    prev = sock.gettimeout()

    def read_bounded(n: int) -> bytes:
        got = bytearray()
        while len(got) < n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                close()
                raise TimeoutError(
                    "reply read exceeded the deadline budget"
                )
            sock.settimeout(remaining)
            try:
                chunk = rfile.read1(  # type: ignore[attr-defined]
                    min(n - len(got), _BOUNDED_CHUNK)
                )
            except TimeoutError:
                close()
                raise
            if not chunk:
                raise ConnectionError("peer closed mid-frame")
            got += chunk
        return bytes(got)

    try:
        yield read_bounded
    finally:
        try:
            sock.settimeout(prev)
        except OSError:
            # close() above already tore the socket down.
            pass
