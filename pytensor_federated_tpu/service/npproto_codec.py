"""Hand-rolled proto3 codec for the REFERENCE wire format.

The reference speaks protobuf over gRPC (reference:
protobufs/npproto/ndarray.proto:7-12, protobufs/service.proto:6-19,
rpc.py:31-72 via betterproto/grpclib).  npwire (this package's native
framing) is deliberately different — but a migrating user must be able
to point THIS client at an unmodified reference node pool, and a
reference client at this package's nodes.  This module implements
exactly the four message types those two .proto files define, as plain
proto3 wire-format encode/decode with no codegen and no protobuf
dependency:

    npproto.ndarray   data(1: bytes) dtype(2: string)
                      shape(3: repeated int64) strides(4: repeated int64)
    InputArrays       items(1: repeated ndarray) uuid(2: string)
    OutputArrays      items(1: repeated ndarray) uuid(2: string)

plus FOUR extension fields this package emits and understands:
``trace_id(15: bytes)`` on InputArrays — the 16-byte telemetry
correlation id (:mod:`..telemetry.spans`); ``spans(16: bytes)`` on
OutputArrays — a JSON list of the node's completed span trees for
that call, piggybacked on the reply so the driver can reunite both
halves of the trace (:mod:`..telemetry.reunion`);
``batch_items(17: repeated bytes)`` — K nested InputArrays/
OutputArrays messages making the message a BATCH frame (one RPC
message per pipelined window, the npproto twin of npwire flag bit 8;
:func:`encode_batch_msg`); ``error(14: string)`` — a per-item
compute/decode error INSIDE a batch reply item, the isolation channel
the reference schema lacks (outside batches npproto errors still
surface as gRPC aborts, unchanged); and ``deadline_s(18: double)`` —
the request's remaining deadline budget in relative seconds
(:mod:`.deadline`; the npproto twin of npwire flag bit 16, enforced at
server admission); and ``tenant_id(19: string)`` — the gateway tier's
per-tenant identity (:mod:`..gateway.fairness`; the npproto twin of
npwire flag bit 32); and ``partition(20: message)`` — the
gradient-partition index block (``routing/partition.py``; the npproto
twin of npwire flag bit 64): a nested message of varint sub-fields
``index(1) count(2) offset(3) length(4) total(5)``
(``wire_registry.NPPROTO_PARTITION_FIELDS``).  Fields 14-20 are
unknown to the
reference schema, so an unmodified reference peer skips them by wire
type (the standard proto3 forward-compatibility rule, property-tested
against the official runtime); they cost nothing when absent — and a
reference peer never RECEIVES a batch frame at all: clients only
coalesce toward a server whose GetLoad advertised the capability.
    GetLoadParams     (empty)
    GetLoadResult     n_clients(1: int32) percent_cpu(2: float)
                      percent_ram(3: float)

Wire-format notes (proto3 spec):

- varints are little-endian base-128; int32/int64 negatives are
  10-byte two's-complement varints (NOT zigzag — that is sint*).
- repeated int64 accepts BOTH packed (len-delimited, the proto3
  default emitted here) and unpacked (one varint per element) forms on
  decode, as the spec requires of parsers.
- unknown fields are skipped by wire type (forward compatibility);
  truncated/overlong/invalid payloads raise :class:`~.npwire.WireError`
  loudly — same failure contract as npwire (property-tested).
- encoding is canonical: fields in ascending number order, packed
  repeats, nothing emitted for empty/default scalars — byte-identical
  to the official protobuf encoder for these messages (cross-checked
  against the google.protobuf runtime in tests when available).

ndarray conversion semantics match the reference helpers
(reference: npproto/utils.py:9-24): ``dtype=str(arr.dtype)``,
``data=bytes(arr.data)``, shape and strides in element/byte units; on
decode the array is materialized from (buffer, dtype, shape, strides).
``dtype=object`` is rejected loudly — the reference ships pointers that
only round-trip in-process (reference: README.md:30, test_npproto.py:20);
here it is the same hard error npwire raises.
"""

from __future__ import annotations

import json
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..faultinject import runtime as _fi
from .npwire import WireError

__all__ = [
    "encode_ndarray",
    "decode_ndarray",
    "encode_arrays_msg",
    "decode_arrays_msg",
    "decode_arrays_msg_ex",
    "decode_arrays_msg_all",
    "decode_arrays_msg_full",
    "encode_batch_msg",
    "decode_batch_msg",
    "has_batch_items",
    "peek_deadline_msg",
    "peek_tenant_msg",
    "peek_partition_msg",
    "peek_version_msg",
    "append_spans_msg",
    "encode_get_load_result",
    "decode_get_load_result",
    "GETLOAD_PARAMS",
]

# GetLoadParams has no fields: its canonical encoding is empty.
GETLOAD_PARAMS = b""

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def _encode_varint(value: int) -> bytes:
    """Unsigned base-128 varint (callers pre-map negatives)."""
    if value < 0:
        raise WireError(f"varint must be non-negative, got {value}")
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode_int64(value: int) -> bytes:
    """int32/int64 field encoding: negatives as 64-bit two's complement."""
    if not -(1 << 63) <= value < (1 << 64):
        raise WireError(f"int64 out of range: {value}")
    return _encode_varint(value & 0xFFFFFFFFFFFFFFFF)


def _decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    start = pos
    while True:
        if pos >= len(buf):
            raise WireError(f"truncated varint at byte {start}")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise WireError(f"overlong varint at byte {start}")


def _to_int64(raw: int) -> int:
    """Interpret a decoded varint as a signed 64-bit value."""
    return raw - (1 << 64) if raw >= (1 << 63) else raw


def _tag(field: int, wire_type: int) -> bytes:
    return _encode_varint((field << 3) | wire_type)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, _WT_LEN) + _encode_varint(len(payload)) + payload


def _decode_tag(buf: bytes, pos: int) -> Tuple[int, int, int]:
    raw, pos = _decode_varint(buf, pos)
    field, wire_type = raw >> 3, raw & 0x7
    if field == 0:
        raise WireError(f"illegal field number 0 at byte {pos}")
    return field, wire_type, pos


def _decode_len(buf: bytes, pos: int) -> Tuple[bytes, int]:
    n, pos = _decode_varint(buf, pos)
    end = pos + n
    if end > len(buf):
        raise WireError(
            f"length-delimited field overruns buffer ({end} > {len(buf)})"
        )
    return buf[pos:end], end


def _skip(buf: bytes, pos: int, wire_type: int) -> int:
    """Skip an unknown field's payload (forward compatibility)."""
    if wire_type == _WT_VARINT:
        _, pos = _decode_varint(buf, pos)
        return pos
    if wire_type == _WT_I64:
        if pos + 8 > len(buf):
            raise WireError("truncated fixed64 field")
        return pos + 8
    if wire_type == _WT_LEN:
        _, pos = _decode_len(buf, pos)
        return pos
    if wire_type == _WT_I32:
        if pos + 4 > len(buf):
            raise WireError("truncated fixed32 field")
        return pos + 4
    raise WireError(f"unsupported wire type {wire_type}")


def _decode_repeated_int64(
    buf: bytes, pos: int, wire_type: int, into: List[int]
) -> int:
    """One occurrence of a repeated int64 field: packed or unpacked."""
    if wire_type == _WT_LEN:  # packed
        payload, pos = _decode_len(buf, pos)
        p = 0
        while p < len(payload):
            raw, p = _decode_varint(payload, p)
            into.append(_to_int64(raw))
        return pos
    if wire_type == _WT_VARINT:  # unpacked
        raw, pos = _decode_varint(buf, pos)
        into.append(_to_int64(raw))
        return pos
    raise WireError(f"repeated int64 field with wire type {wire_type}")


# ---------------------------------------------------------------------------
# partition sub-message (extension field 20)
# ---------------------------------------------------------------------------


def _encode_partition_msg(partition: Sequence[int]) -> bytes:
    """The nested partition message: varint sub-fields in
    ``wire_registry.NPPROTO_PARTITION_FIELDS`` order (index=1,
    count=2, offset=3, length=4, total=5); proto3-canonical — zero
    values are omitted."""
    try:
        index, count, offset, length, total = (
            int(v) for v in partition
        )
    except (TypeError, ValueError) as e:
        raise WireError(f"partition must be 5 ints: {e}") from None
    if not 0 <= index < count:
        raise WireError(
            f"partition index {index} outside 0..{count - 1}"
        )
    if min(offset, length, total) < 0 or offset + length > total:
        raise WireError(
            f"partition slice [{offset}, {offset + length}) cannot "
            f"cover total {total}"
        )
    out = bytearray()
    for num, val in enumerate((index, count, offset, length, total), 1):
        if val:
            out += _tag(num, _WT_VARINT) + _encode_varint(val)
    return bytes(out)


def _decode_partition_msg(raw: bytes) -> Tuple[int, int, int, int, int]:
    """Inverse of :func:`_encode_partition_msg`; unknown sub-fields
    are skipped (proto3 posture), absent ones default to zero."""
    vals = [0, 0, 0, 0, 0]
    pos = 0
    while pos < len(raw):
        field, wt, pos = _decode_tag(raw, pos)
        if 1 <= field <= 5 and wt == _WT_VARINT:
            v, pos = _decode_varint(raw, pos)
            vals[field - 1] = v
        else:
            pos = _skip(raw, pos, wt)
    return (vals[0], vals[1], vals[2], vals[3], vals[4])


# ---------------------------------------------------------------------------
# npproto.ndarray
# ---------------------------------------------------------------------------


def encode_ndarray(arr: np.ndarray) -> bytes:
    """numpy -> npproto.ndarray bytes (reference: npproto/utils.py:9-16)."""
    arr = np.asarray(arr)
    if arr.dtype.hasobject:
        raise WireError(
            "dtype=object cannot cross the wire (the reference serializes "
            "in-process pointers here; this codec rejects it loudly)"
        )
    # The reference wire carries dtype as str(dtype) and reconstructs
    # with np.dtype(s) (reference: npproto/utils.py:12,22) — structured
    # dtypes don't survive that round trip (str() gives a repr np.dtype
    # rejects), on EITHER end.  Fail here, loudly, not remotely.
    try:
        if np.dtype(str(arr.dtype)) != arr.dtype:
            raise TypeError("round-trip changed the dtype")
    except TypeError as e:
        raise WireError(
            f"dtype {arr.dtype!r} does not survive the reference wire's "
            f"str()/np.dtype() round trip ({e}); the native npwire codec "
            "ships structured dtypes via their full descr instead"
        ) from None
    out = bytearray()
    # NOT np.ascontiguousarray: that promotes 0-d arrays to 1-d, and
    # the strides field must stay consistent with the true shape.
    contig = arr if arr.flags.c_contiguous else arr.copy(order="C")
    data = contig.tobytes()
    # proto3 canonical: default-valued (empty) scalar fields are not
    # serialized — matches the official encoder byte for byte.
    if data:
        out += _len_field(1, data)
    out += _len_field(2, str(arr.dtype).encode("utf-8"))
    # contiguous data => contiguous strides, consistent with the shape
    if arr.shape:
        packed = b"".join(_encode_int64(s) for s in arr.shape)
        out += _len_field(3, packed)
    if contig.strides:
        packed = b"".join(_encode_int64(s) for s in contig.strides)
        out += _len_field(4, packed)
    return bytes(out)


def decode_ndarray(buf: bytes) -> np.ndarray:
    """npproto.ndarray bytes -> numpy (reference: npproto/utils.py:19-24)."""
    data: Optional[bytes] = None
    dtype_str = ""
    shape: List[int] = []
    strides: List[int] = []
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 1 and wt == _WT_LEN:
            data, pos = _decode_len(buf, pos)
        elif field == 2 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            try:
                dtype_str = raw.decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireError(f"bad dtype string: {e}") from None
        elif field == 3:
            pos = _decode_repeated_int64(buf, pos, wt, shape)
        elif field == 4:
            pos = _decode_repeated_int64(buf, pos, wt, strides)
        else:
            pos = _skip(buf, pos, wt)
    try:
        dtype = np.dtype(dtype_str or "float64")
    except TypeError as e:
        raise WireError(f"bad dtype {dtype_str!r}: {e}") from None
    if dtype.hasobject:
        raise WireError("dtype=object cannot cross the wire")
    if any(s < 0 for s in shape):
        raise WireError(f"negative dimension in shape {shape}")
    try:
        return np.ndarray(
            buffer=data if data is not None else b"",
            shape=shape,
            dtype=dtype,
            strides=strides or None,
        ).copy()  # own the memory; the input buffer may be reused
    except (ValueError, TypeError) as e:
        raise WireError(
            f"inconsistent ndarray (shape={shape}, dtype={dtype_str!r}, "
            f"strides={strides}, {len(data or b'')} data bytes): {e}"
        ) from None


# ---------------------------------------------------------------------------
# InputArrays / OutputArrays (identical layout)
# ---------------------------------------------------------------------------


def encode_arrays_msg(
    arrays: Sequence[np.ndarray],
    uuid: str,
    *,
    trace_id: Optional[bytes] = None,
    error: Optional[str] = None,
    deadline_s: Optional[float] = None,
    tenant: Optional[str] = None,
    partition: Optional[Sequence[int]] = None,
    version: Optional[int] = None,
) -> bytes:
    """InputArrays/OutputArrays: repeated ndarray items + string uuid
    (reference: service.proto:6-19; uuid is the correlation id the
    reference's client checks, rpc.py:37-39).  ``trace_id`` emits the
    telemetry extension field 15 (module docstring); ``error`` emits
    the per-item error extension field 14 — only used on items INSIDE
    a batch reply, where the gRPC-abort channel cannot isolate one
    poisoned request; ``deadline_s`` emits the remaining-deadline
    extension field 18 (fixed64 double, relative seconds); ``tenant``
    emits the gateway tier's tenant-id extension field 19 (utf8
    string, non-empty); ``partition`` emits the gradient-partition
    extension field 20 (nested message — routing/partition.py owns the
    semantics); ``version`` emits the step-version extension field 21
    (varint u64 — optim/sharded.py owns the semantics; emitted even
    at 0, because field PRESENCE marks a versioned message and the
    zero stamp is the init handshake).  All ``None`` keeps the message
    byte-identical to the official encoder's output."""
    out = bytearray()
    for a in arrays:
        out += _len_field(1, encode_ndarray(a))
    if uuid:
        out += _len_field(2, uuid.encode("utf-8"))
    if error is not None:
        out += _len_field(14, error.encode("utf-8"))
    if trace_id is not None:
        if len(trace_id) != 16:
            raise WireError(
                f"trace_id must be 16 bytes, got {len(trace_id)}"
            )
        out += _len_field(15, trace_id)
    if deadline_s is not None:
        out += _tag(18, _WT_I64) + struct.pack("<d", float(deadline_s))
    if tenant is not None:
        if not tenant:
            raise WireError(
                "tenant id must be non-empty (omit it instead)"
            )
        out += _len_field(19, tenant.encode("utf-8"))
    if partition is not None:
        out += _len_field(20, _encode_partition_msg(partition))
    if version is not None:
        out += _tag(21, _WT_VARINT) + _encode_varint(
            _check_version(version)
        )
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        return _fi.filter_bytes("npproto.encode", bytes(out))
    return bytes(out)


def _check_version(version: int) -> int:
    """Validate a step-version stamp for field 21 (varint u64)."""
    try:
        v = int(version)
    except (TypeError, ValueError) as e:
        raise WireError(f"version must be an int: {e}") from None
    if not 0 <= v < (1 << 64):
        raise WireError(f"version {v} outside u64 range")
    return v


def encode_batch_msg(
    items: Sequence[bytes],
    uuid: str,
    *,
    trace_id: Optional[bytes] = None,
    deadline_s: Optional[float] = None,
    tenant: Optional[str] = None,
    partition: Optional[Sequence[int]] = None,
    version: Optional[int] = None,
) -> bytes:
    """Frame K already-encoded InputArrays/OutputArrays messages as ONE
    batch message (extension field 17) — the npproto twin of
    :func:`..npwire.encode_batch`.  The outer uuid correlates the
    window; each nested item keeps its own uuid (and, on replies, its
    own field-14 error), so failure isolation is per item.  Only sent
    to peers that advertised the capability via GetLoad — a reference
    runtime would skip field 17 and see an empty message, which is why
    negotiation gates the send."""
    out = bytearray()
    if uuid:
        out += _len_field(2, uuid.encode("utf-8"))
    if trace_id is not None:
        if len(trace_id) != 16:
            raise WireError(
                f"trace_id must be 16 bytes, got {len(trace_id)}"
            )
        out += _len_field(15, trace_id)
    if deadline_s is not None:
        out += _tag(18, _WT_I64) + struct.pack("<d", float(deadline_s))
    if tenant is not None:
        if not tenant:
            raise WireError(
                "tenant id must be non-empty (omit it instead)"
            )
        out += _len_field(19, tenant.encode("utf-8"))
    if partition is not None:
        out += _len_field(20, _encode_partition_msg(partition))
    if version is not None:
        out += _tag(21, _WT_VARINT) + _encode_varint(
            _check_version(version)
        )
    for item in items:
        out += _len_field(17, item)
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        return _fi.filter_bytes("npproto.encode_batch", bytes(out))
    return bytes(out)


def has_batch_items(buf: bytes) -> bool:
    """Whether a message carries batch items (field 17) at top level —
    the server's cheap batch-vs-plain dispatch (tags are skipped, no
    ndarray decode happens)."""
    pos = 0
    try:
        while pos < len(buf):
            field, wt, pos = _decode_tag(buf, pos)
            if field == 17 and wt == _WT_LEN:
                return True
            pos = _skip(buf, pos, wt)
    except WireError:
        return False
    return False


def peek_deadline_msg(buf: bytes) -> Optional[float]:
    """The message's remaining-deadline budget (field 18, fixed64
    double, relative seconds), or ``None`` when absent — a skip-walk
    like :func:`has_batch_items`, so server admission can enforce the
    deadline before paying any ndarray decode.  Raises
    :class:`~.npwire.WireError` on structurally broken messages (the
    full decoder would reject them identically)."""
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 18 and wt == _WT_I64:
            if pos + 8 > len(buf):
                raise WireError("truncated deadline_s field")
            (budget,) = struct.unpack_from("<d", buf, pos)
            return budget
        pos = _skip(buf, pos, wt)
    return None


def peek_tenant_msg(buf: bytes) -> Optional[str]:
    """The message's tenant id (field 19, utf8 string), or ``None``
    when absent — a skip-walk like :func:`peek_deadline_msg`, so the
    gateway can meter quotas before paying any ndarray decode.  Raises
    :class:`~.npwire.WireError` on structurally broken messages."""
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 19 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            try:
                return raw.decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireError(f"bad tenant id string: {e}") from None
        pos = _skip(buf, pos, wt)
    return None


def peek_partition_msg(buf: bytes) -> Optional[Tuple[int, int, int, int, int]]:
    """The message's partition block (field 20) as a 5-int tuple, or
    ``None`` when absent — a skip-walk like :func:`peek_deadline_msg`,
    so the partitioned server lanes can dispatch before any ndarray
    decode.  Raises :class:`~.npwire.WireError` on structurally broken
    messages."""
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 20 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            return _decode_partition_msg(raw)
        pos = _skip(buf, pos, wt)
    return None


def peek_version_msg(buf: bytes) -> Optional[int]:
    """The message's step-version stamp (field 21, varint u64) as an
    int, or ``None`` when absent — a skip-walk like
    :func:`peek_deadline_msg`, so the versioned sharded-optimizer lane
    (optim/sharded.py) can dispatch before any ndarray decode.  Zero
    is a meaningful stamp, which is why absence is ``None``, never 0.
    Raises :class:`~.npwire.WireError` on structurally broken
    messages."""
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 21 and wt == _WT_VARINT:
            raw, pos = _decode_varint(buf, pos)
            return raw
        pos = _skip(buf, pos, wt)
    return None


def decode_batch_msg(
    buf: bytes,
) -> Tuple[List[bytes], str, Optional[bytes], Optional[list]]:
    """Decode a batch message -> (items, uuid, trace_id, spans);
    ``items`` are the nested messages still encoded (decode each with
    :func:`decode_arrays_msg_full`)."""
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        buf = _fi.filter_bytes("npproto.decode_batch", buf)
    items: List[bytes] = []
    uuid = ""
    trace_id: Optional[bytes] = None
    spans: Optional[list] = None
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 17 and wt == _WT_LEN:
            item, pos = _decode_len(buf, pos)
            items.append(item)
        elif field == 2 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            try:
                uuid = raw.decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireError(f"bad uuid string: {e}") from None
        elif field == 15 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            trace_id = raw if len(raw) == 16 else None
        elif field == 16 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            try:
                parsed = json.loads(raw.decode("utf-8"))
            except (UnicodeDecodeError, ValueError):
                parsed = None  # tolerant: sidecar only, never the payload
            spans = parsed if isinstance(parsed, list) else None
        elif field == 18 and wt == _WT_I64:
            # deadline_s: consumed and dropped here — admission reads
            # it pre-decode via peek_deadline_msg, keeping this tuple
            # shape stable for every existing caller.
            if pos + 8 > len(buf):
                raise WireError("truncated deadline_s field")
            pos += 8
        elif field == 19 and wt == _WT_LEN:
            # tenant_id: consumed and dropped (peek_tenant_msg is the
            # gateway-side reader; same posture as deadline_s).
            _raw, pos = _decode_len(buf, pos)
        elif field == 20 and wt == _WT_LEN:
            # partition: consumed and dropped (peek_partition_msg is
            # the partition-lane reader; same posture as deadline_s).
            _raw, pos = _decode_len(buf, pos)
        elif field == 21 and wt == _WT_VARINT:
            # version: consumed and dropped (peek_version_msg is the
            # sharded-optimizer-lane reader; same posture as deadline_s).
            _raw, pos = _decode_varint(buf, pos)
        else:
            pos = _skip(buf, pos, wt)
    return items, uuid, trace_id, spans


def append_spans_msg(buf: bytes, spans: Sequence[dict]) -> bytes:
    """Attach the spans extension (field 16, JSON) to an already-encoded
    OutputArrays message.  Proto3 fields may appear in any order, so
    appending a length-delimited field to valid message bytes yields a
    valid message — the node-side piggyback needs no re-encode (mirror
    of :func:`..npwire.append_spans`)."""
    # default=str: free-form span attrs (numpy scalars included) must
    # degrade to their repr, never fail the reply (npwire.append_spans
    # has the same posture).
    return buf + _len_field(
        16, json.dumps(list(spans), default=str).encode("utf-8")
    )


def decode_arrays_msg(buf: bytes) -> Tuple[List[np.ndarray], str]:
    """The historical 2-tuple shape — a trace id (field 15) or spans
    (field 16) is skipped like any unknown field.  Use
    :func:`decode_arrays_msg_ex` / :func:`decode_arrays_msg_all`."""
    arrays, uuid, _ = decode_arrays_msg_ex(buf)
    return arrays, uuid


def decode_arrays_msg_ex(
    buf: bytes,
) -> Tuple[List[np.ndarray], str, Optional[bytes]]:
    """Decode InputArrays/OutputArrays -> (arrays, uuid, trace_id);
    a spans field is consumed and dropped."""
    arrays, uuid, trace_id, _ = decode_arrays_msg_all(buf)
    return arrays, uuid, trace_id


def decode_arrays_msg_all(
    buf: bytes,
) -> Tuple[List[np.ndarray], str, Optional[bytes], Optional[list]]:
    """The historical 4-tuple -> (arrays, uuid, trace_id, spans); a
    per-item error field (14, batch items only) is dropped."""
    arrays, uuid, _error, trace_id, spans = decode_arrays_msg_full(buf)
    return arrays, uuid, trace_id, spans


def decode_arrays_msg_full(
    buf: bytes,
) -> Tuple[List[np.ndarray], str, Optional[str], Optional[bytes], Optional[list]]:
    """Full decode -> (arrays, uuid, error, trace_id, spans): ``spans``
    is the piggybacked span-tree list (field 16; ``None`` when absent
    or unparseable — a garbled instrumentation sidecar must not fail
    the RPC that carried real results); ``error`` is the per-item
    failure channel (field 14) batch reply items carry."""
    if _fi.active_plan is not None:  # chaos seam (faultinject.runtime)
        buf = _fi.filter_bytes("npproto.decode", buf)
    arrays: List[np.ndarray] = []
    uuid = ""
    error: Optional[str] = None
    trace_id: Optional[bytes] = None
    spans: Optional[list] = None
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 1 and wt == _WT_LEN:
            item, pos = _decode_len(buf, pos)
            arrays.append(decode_ndarray(item))
        elif field == 2 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            try:
                uuid = raw.decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireError(f"bad uuid string: {e}") from None
        elif field == 14 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            try:
                error = raw.decode("utf-8")
            except UnicodeDecodeError as e:
                raise WireError(f"bad error string: {e}") from None
        elif field == 15 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            # Tolerant on length: a future sender might widen the id;
            # only the exact 16-byte form correlates spans here.
            trace_id = raw if len(raw) == 16 else None
        elif field == 16 and wt == _WT_LEN:
            raw, pos = _decode_len(buf, pos)
            try:
                parsed = json.loads(raw.decode("utf-8"))
            except (UnicodeDecodeError, ValueError):
                parsed = None  # tolerant: sidecar only, never the payload
            spans = parsed if isinstance(parsed, list) else None
        elif field == 18 and wt == _WT_I64:
            # deadline_s: consumed and dropped (peek_deadline_msg is
            # the admission-side reader; see decode_batch_msg).
            if pos + 8 > len(buf):
                raise WireError("truncated deadline_s field")
            pos += 8
        elif field == 19 and wt == _WT_LEN:
            # tenant_id: consumed and dropped (peek_tenant_msg is the
            # gateway-side reader; see decode_batch_msg).
            _raw, pos = _decode_len(buf, pos)
        elif field == 20 and wt == _WT_LEN:
            # partition: consumed and dropped (peek_partition_msg is
            # the partition-lane reader; see decode_batch_msg).
            _raw, pos = _decode_len(buf, pos)
        elif field == 21 and wt == _WT_VARINT:
            # version: consumed and dropped (peek_version_msg is the
            # sharded-optimizer-lane reader; see decode_batch_msg).
            _raw, pos = _decode_varint(buf, pos)
        else:
            pos = _skip(buf, pos, wt)
    return arrays, uuid, error, trace_id, spans


# ---------------------------------------------------------------------------
# GetLoadResult
# ---------------------------------------------------------------------------


# GetLoad chaos injects at the LANE point (server.getload via
# getload_filter, which swaps the whole reply for GETLOAD_GARBAGE) —
# this pair deliberately carries no byte seam of its own.
# graftlint: disable=fault-shim-coverage -- GetLoad lane injects via getload_filter
def encode_get_load_result(
    n_clients: int, percent_cpu: float, percent_ram: float
) -> bytes:
    out = bytearray()
    if n_clients:
        out += _tag(1, _WT_VARINT) + _encode_int64(n_clients)
    if percent_cpu:
        out += _tag(2, _WT_I32) + struct.pack("<f", percent_cpu)
    if percent_ram:
        out += _tag(3, _WT_I32) + struct.pack("<f", percent_ram)
    return bytes(out)


# graftlint: disable=fault-shim-coverage -- GetLoad lane injects via getload_filter
def decode_get_load_result(buf: bytes) -> dict:
    """Decode a ``GetLoadResult`` (service.proto:24-31).

    The empty buffer is the legitimate all-defaults encoding (proto3
    writers omit default fields) and decodes to the zero load.  A
    NON-empty buffer containing no known field, however, is rejected as
    :class:`WireError`: proto3's unknown-field leniency would otherwise
    decode arbitrary garbage to the all-zero — i.e. maximally
    attractive — load and silently skew pool balancing.  Schema-evolved
    replies (new fields alongside at least one known field, at any byte
    position) still decode fine.
    """
    n_clients, percent_cpu, percent_ram = 0, 0.0, 0.0
    known = False
    pos = 0
    while pos < len(buf):
        field, wt, pos = _decode_tag(buf, pos)
        if field == 1 and wt == _WT_VARINT:
            raw, pos = _decode_varint(buf, pos)
            val = _to_int64(raw)
            if not -(1 << 31) <= val < (1 << 31):
                raise WireError(f"n_clients out of int32 range: {val}")
            n_clients = val
            known = True
        elif field == 2 and wt == _WT_I32:
            if pos + 4 > len(buf):
                raise WireError("truncated percent_cpu")
            (percent_cpu,) = struct.unpack_from("<f", buf, pos)
            pos += 4
            known = True
        elif field == 3 and wt == _WT_I32:
            if pos + 4 > len(buf):
                raise WireError("truncated percent_ram")
            (percent_ram,) = struct.unpack_from("<f", buf, pos)
            pos += 4
            known = True
        else:
            pos = _skip(buf, pos, wt)
    if buf and not known:
        raise WireError("GetLoadResult decoded to unknown fields only")
    return {
        "n_clients": n_clients,
        "percent_cpu": percent_cpu,
        "percent_ram": percent_ram,
    }
