"""Host-federation transport (reference L1-L2 analog; off the hot path)."""

from .client import (
    ArraysToArraysServiceClient,
    ClientPrivates,
    get_load_async,
    get_loads_async,
    get_node_traces,
    get_node_traces_async,
    thread_pid_id,
)
from .clients import LogpGradServiceClient, LogpServiceClient
from .npwire import WireError, decode_arrays, encode_arrays
from .tcp import RemoteComputeError, TcpArraysClient, serve_tcp_once
from .server import (
    ArraysToArraysService,
    device_compute_fn,
    run_node,
    serve,
)

__all__ = [
    "ArraysToArraysService",
    "ArraysToArraysServiceClient",
    "ClientPrivates",
    "LogpGradServiceClient",
    "LogpServiceClient",
    "WireError",
    "decode_arrays",
    "device_compute_fn",
    "encode_arrays",
    "RemoteComputeError",
    "TcpArraysClient",
    "get_load_async",
    "get_loads_async",
    "get_node_traces",
    "get_node_traces_async",
    "run_node",
    "serve",
    "serve_tcp_once",
    "thread_pid_id",
]
