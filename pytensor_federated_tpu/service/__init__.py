"""Host-federation transport (reference L1-L2 analog; off the hot path)."""

from .client import (
    ArraysToArraysServiceClient,
    ClientPrivates,
    get_load_async,
    get_loads_async,
    get_node_telemetry,
    get_node_telemetry_async,
    get_node_traces,
    get_node_traces_async,
    thread_pid_id,
)
from .batching import MicroBatcher, batched_compute_fn
from .clients import LogpGradServiceClient, LogpServiceClient
from .npwire import (
    WireError,
    decode_arrays,
    decode_batch,
    encode_arrays,
    encode_batch,
)
from .ring import RingArraysClient, serve_ring
from .shm import ShmArraysClient, serve_shm
from .tcp import RemoteComputeError, TcpArraysClient, serve_tcp_once
from .server import (
    ArraysToArraysService,
    device_compute_fn,
    run_node,
    serve,
)

__all__ = [
    "ArraysToArraysService",
    "ArraysToArraysServiceClient",
    "ClientPrivates",
    "LogpGradServiceClient",
    "LogpServiceClient",
    "MicroBatcher",
    "WireError",
    "batched_compute_fn",
    "decode_arrays",
    "decode_batch",
    "device_compute_fn",
    "encode_arrays",
    "encode_batch",
    "RemoteComputeError",
    "RingArraysClient",
    "ShmArraysClient",
    "TcpArraysClient",
    "get_load_async",
    "get_loads_async",
    "get_node_telemetry",
    "get_node_telemetry_async",
    "get_node_traces",
    "get_node_traces_async",
    "run_node",
    "serve",
    "serve_ring",
    "serve_shm",
    "serve_tcp_once",
    "thread_pid_id",
]
