"""Host-federation server: arrays-in/arrays-out compute behind gRPC.

Re-design of the reference's service core (reference: service.py:45-115)
for the one capability that cannot collapse onto the mesh: *true*
federation across trust domains, where a node's private data may never
leave its machine (reference: README.md:6-11).  This path is explicitly
off the TPU hot loop (SURVEY §7 step 6); on-pod sharding lives in
:mod:`pytensor_federated_tpu.parallel`.

Differences from the reference, on purpose:

- grpc.aio (C-core) with raw-bytes methods + the npwire codec instead of
  grpclib + betterproto: no codegen step, and HTTP/2 flow control is
  handled by the C core.
- Compute runs in a thread executor, so one slow evaluation does not
  block the event loop (the reference computes on the loop thread and
  notes per-node concurrency only across streams,
  reference: service.py:66, SURVEY §3.2).
- ``n_clients`` decrements in a ``finally`` — an abruptly killed client
  cannot leak the counter (the reference leaks it, SURVEY §5 quirks).
- A node can pin its compute to a JAX device (each federated node owning
  one accelerator), via :func:`device_compute_fn`.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable, Optional, Sequence

import grpc
import numpy as np

from ..signatures import ComputeFn
from .npwire import decode_arrays, encode_arrays

_log = logging.getLogger(__name__)

SERVICE_NAME = "ArraysToArraysService"
EVALUATE = f"/{SERVICE_NAME}/Evaluate"
EVALUATE_STREAM = f"/{SERVICE_NAME}/EvaluateStream"
GET_LOAD = f"/{SERVICE_NAME}/GetLoad"

_identity = lambda b: b  # noqa: E731  (raw-bytes (de)serializer)


def device_compute_fn(fn: ComputeFn, *, jit: bool = True) -> Callable:
    """Adapt a JAX function into the host compute contract.

    The node-side analog of the reference compiling its model with
    PyTensor before serving it (reference: demo_node.py:39-42): ``fn``
    is jitted once, inputs arrive as NumPy, outputs return as NumPy.
    """
    import jax

    jfn = jax.jit(fn) if jit else fn

    def compute(*arrays: np.ndarray) -> Sequence[np.ndarray]:
        out = jfn(*arrays)
        return [np.asarray(o) for o in out]

    return compute


class ArraysToArraysService:
    """The gRPC service implementation (reference: service.py:75-115).

    ``compute_fn`` takes/returns NumPy arrays.  Three methods, same
    contract as the reference schema (reference: service.proto:6-19):
    unary ``Evaluate``, lock-step bidi ``EvaluateStream``, and the
    ``GetLoad`` control-plane query.
    """

    def __init__(
        self,
        compute_fn: Callable[..., Sequence[np.ndarray]],
        *,
        getload_wire: str = "npwire",
        inline_compute: bool = False,
    ):
        """``getload_wire``: "npwire" (JSON reply, this package's
        native clients) or "npproto" (reference ``GetLoadResult``
        protobuf, for serving unmodified reference clients).  Evaluate
        and the stream need no such switch — their request payload
        identifies the wire and the reply mirrors it — but GetLoad's
        request is EMPTY in both schemas, so the reply format is a
        node-level choice.

        ``inline_compute``: run ``compute_fn`` directly on the event
        loop instead of in a thread executor.  The executor exists so
        a SLOW compute cannot stall GetLoad and other streams (the
        reference pays the same structure via its event loop +
        ``run_in_executor``-free design, but it is single-stream); for
        a sub-millisecond compute the two thread handoffs cost more
        than the compute — measured ~1.4x sync-client and up to ~2x
        async-client round-trip throughput on the localhost lane
        (docs/performance.md "Host lane budget") — so nodes serving
        fast jitted evals should pass True.  A compute that blocks for
        long stretches must keep the default."""
        if getload_wire not in ("npwire", "npproto"):
            raise ValueError(
                f"getload_wire must be 'npwire' or 'npproto', "
                f"got {getload_wire!r}"
            )
        self.getload_wire = getload_wire
        self.inline_compute = bool(inline_compute)
        self.compute_fn = compute_fn
        self._n_clients = 0
        # Start psutil's interval-based CPU accounting early so the
        # first real query is meaningful (reference: service.py:84-85).
        try:
            import psutil

            psutil.cpu_percent()
        except Exception:
            pass

    # -- compute plumbing -------------------------------------------------

    async def _run_compute(self, request: bytes) -> bytes:
        """decode -> compute (in executor) -> encode, echoing the uuid.

        Errors are encoded into the reply instead of tearing down the
        stream (reference: _run_compute_func, service.py:45-72).

        WIRE AUTO-DETECTION: a request starting with the npwire magic
        is npwire (this package's native client); anything else is
        decoded as the reference's protobuf ``InputArrays``
        (npproto_codec — an npwire frame can never parse as proto:
        ``N`` = tag with illegal wire type 6, and a proto payload can
        never carry the magic).  The reply uses the SAME format, so an
        unmodified reference client gets reference-wire replies.  The
        reference schema has NO error field — its server re-raises into
        the gRPC layer (reference: service.py:45-72) — so npproto
        decode/compute errors raise here too and surface to the peer as
        a gRPC error, exactly what a reference client expects.
        """
        from . import npproto_codec
        from .npwire import MAGIC

        is_npwire = request[:4] == MAGIC
        if is_npwire:
            try:
                inputs, uuid, _ = decode_arrays(request)
            except Exception as e:
                return encode_arrays(
                    [], uuid=b"\0" * 16, error=f"decode error: {e}"
                )
        else:
            inputs, proto_uuid = npproto_codec.decode_arrays_msg(request)
        try:
            if self.inline_compute:
                # Fast-compute path: the two thread handoffs of the
                # executor dominate a sub-ms compute (docs/performance.md).
                outputs = list(self.compute_fn(*inputs))
            else:
                loop = asyncio.get_running_loop()
                outputs = await loop.run_in_executor(
                    None, lambda: list(self.compute_fn(*inputs))
                )
            outputs = [np.asarray(o) for o in outputs]
        except Exception as e:
            _log.exception("compute_fn failed")
            if is_npwire:
                return encode_arrays(
                    [], uuid=uuid, error=f"compute error: {e}"
                )
            raise
        if is_npwire:
            return encode_arrays(outputs, uuid=uuid)
        return npproto_codec.encode_arrays_msg(outputs, uuid=proto_uuid)

    # -- RPC methods ------------------------------------------------------

    async def evaluate(self, request: bytes, context) -> bytes:
        return await self._run_compute(request)

    async def evaluate_stream(self, request_iterator, context):
        """Lock-step bidi stream: one reply per request, in order
        (reference: service.py:104-112)."""
        self._n_clients += 1
        _log.info("stream opened (n_clients=%d)", self._n_clients)
        try:
            async for request in request_iterator:
                yield await self._run_compute(request)
        finally:
            self._n_clients -= 1
            _log.info("stream closed (n_clients=%d)", self._n_clients)

    def determine_load(self) -> dict:
        """Load snapshot (reference: service.py:88-96 GetLoadResult)."""
        try:
            import psutil

            percent_cpu = psutil.cpu_percent()
            percent_ram = psutil.virtual_memory().percent
        except Exception:
            percent_cpu = percent_ram = -1.0
        return {
            "n_clients": self._n_clients,
            "percent_cpu": percent_cpu,
            "percent_ram": percent_ram,
        }

    async def get_load(self, request: bytes, context) -> bytes:
        load = self.determine_load()
        if self.getload_wire == "npproto":
            from . import npproto_codec

            return npproto_codec.encode_get_load_result(
                load["n_clients"], load["percent_cpu"], load["percent_ram"]
            )
        return json.dumps(load).encode("utf-8")

    # -- wiring -----------------------------------------------------------

    def generic_handler(self) -> grpc.GenericRpcHandler:
        handlers = {
            "Evaluate": grpc.unary_unary_rpc_method_handler(
                self.evaluate,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "EvaluateStream": grpc.stream_stream_rpc_method_handler(
                self.evaluate_stream,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "GetLoad": grpc.unary_unary_rpc_method_handler(
                self.get_load,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


async def serve(
    compute_fn: Optional[Callable[..., Sequence[np.ndarray]]],
    bind: str = "127.0.0.1",
    port: int = 50000,
    *,
    getload_wire: str = "npwire",
    inline_compute: bool = False,
    service: Optional[ArraysToArraysService] = None,
) -> grpc.aio.Server:
    """Start a node server (reference: demo_node.py:76-79).  Returns the
    started ``grpc.aio.Server``; await ``server.wait_for_termination()``.

    Pass EITHER ``compute_fn`` (+ optional ``getload_wire``) — the
    service is constructed here — or a pre-built ``service`` with
    ``compute_fn=None``; both at once would be two sources of truth for
    what the node computes."""
    if service is None:
        if compute_fn is None:
            raise ValueError("pass compute_fn or a pre-built service")
        service = ArraysToArraysService(
            compute_fn,
            getload_wire=getload_wire,
            inline_compute=inline_compute,
        )
    elif compute_fn is not None:
        raise ValueError(
            "pass either compute_fn or a pre-built service, not both "
            "(the service already owns its compute_fn)"
        )
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((service.generic_handler(),))
    server.add_insecure_port(f"{bind}:{port}")
    await server.start()
    _log.info("node listening on %s:%d", bind, port)
    return server


def run_node(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    bind: str = "127.0.0.1",
    port: int = 50000,
    *,
    getload_wire: str = "npwire",
    inline_compute: bool = False,
) -> None:
    """Blocking single-node entry point (reference: demo_node.py:83-95).

    ``getload_wire="npproto"`` serves reference-format GetLoad replies
    so UNMODIFIED reference clients can balance over this node
    (Evaluate/EvaluateStream auto-detect per request either way).
    ``inline_compute=True`` skips the per-call thread-executor handoff
    for sub-ms compute fns (see ArraysToArraysService)."""

    async def main():
        server = await serve(
            compute_fn, bind, port,
            getload_wire=getload_wire,
            inline_compute=inline_compute,
        )
        await server.wait_for_termination()

    asyncio.run(main())
