"""Host-federation server: arrays-in/arrays-out compute behind gRPC.

Re-design of the reference's service core (reference: service.py:45-115)
for the one capability that cannot collapse onto the mesh: *true*
federation across trust domains, where a node's private data may never
leave its machine (reference: README.md:6-11).  This path is explicitly
off the TPU hot loop (SURVEY §7 step 6); on-pod sharding lives in
:mod:`pytensor_federated_tpu.parallel`.

Differences from the reference, on purpose:

- grpc.aio (C-core) with raw-bytes methods + the npwire codec instead of
  grpclib + betterproto: no codegen step, and HTTP/2 flow control is
  handled by the C core.
- Compute runs in a thread executor, so one slow evaluation does not
  block the event loop (the reference computes on the loop thread and
  notes per-node concurrency only across streams,
  reference: service.py:66, SURVEY §3.2).
- ``n_clients`` decrements in a ``finally`` — an abruptly killed client
  cannot leak the counter (the reference leaks it, SURVEY §5 quirks).
- A node can pin its compute to a JAX device (each federated node owning
  one accelerator), via :func:`device_compute_fn`.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import time
from typing import Callable, Optional, Sequence

import grpc
import numpy as np

from ..faultinject import runtime as _fi
from ..signatures import ComputeFn
from ..telemetry import flightrec as _flightrec
from ..telemetry import spans as _spans
from . import deadline as _deadline
from . import npproto_codec
from .batching import MicroBatcher, batched_compute_fn
from .npwire import (
    MAGIC,
    WireError,
    append_spans,
    decode_arrays_ex,
    decode_batch,
    encode_arrays,
    encode_batch,
    frame_uuid,
    is_batch_frame,
    peek_deadline,
    peek_partition,
)

_log = logging.getLogger(__name__)

# Node-side RPC instrumentation (metric catalog: docs/observability.md).
# Declared at import time in the shared ``_node_metrics`` module — the
# TCP/shm template nodes record into the SAME families, so every lane
# aggregates in the fleet view; every mutator is a no-op while
# telemetry is disabled, so an uninstrumented deployment pays one
# branch per call.
from ._node_metrics import (
    ADMISSION_SHED as _ADMISSION_SHED,
    COMPUTE_S as _COMPUTE_S,
    DECODE_S as _DECODE_S,
    ENCODE_S as _ENCODE_S,
    ERRORS as _ERRORS,
    INFLIGHT as _INFLIGHT,
    QUEUE_S as _QUEUE_S,
    REQUESTS as _REQUESTS,
)

SERVICE_NAME = "ArraysToArraysService"
EVALUATE = f"/{SERVICE_NAME}/Evaluate"
EVALUATE_STREAM = f"/{SERVICE_NAME}/EvaluateStream"
GET_LOAD = f"/{SERVICE_NAME}/GetLoad"

_identity = lambda b: b  # noqa: E731  (raw-bytes (de)serializer)


async def _fi_reply_filter(reply: bytes, context, *, unary: bool = False) -> tuple:
    """``grpc.server.reply`` chaos seam -> ``(reply_bytes, n_copies)``.

    Async on purpose: delay/stall are awaited so a chaos-slowed reply
    behaves like a genuinely slow node (GetLoad and sibling streams
    keep serving).  ``drop``/``disconnect`` abort the RPC with
    UNAVAILABLE — the transient classification, so a pooled client
    fails over instead of burning a no-retry error.  ``duplicate_reply``
    returns ``n_copies=2`` for the stream lane to yield twice; on the
    unary lane (one reply per RPC by construction) it is a plan-
    authoring bug and raises, rather than booking a fire that injected
    nothing."""
    rule = _fi.decide("grpc.server.reply")
    if rule is None:
        return reply, 1
    kind = rule.kind
    if kind in ("delay", "stall"):
        await asyncio.sleep(rule.delay_s if kind == "delay" else rule.stall_s)
        return reply, 1
    if kind in ("drop", "disconnect"):
        if context is not None:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"faultinject[{kind}]: reply withheld",
            )
        raise ConnectionError(f"faultinject[{kind}] at grpc.server.reply")
    if kind == "duplicate_reply":
        if unary:
            raise _fi.FaultPlanError(
                "duplicate_reply cannot be expressed on the unary lane"
            )
        return reply, 2
    # truncate_frame / corrupt_bytes / kill_process share the byte-lane
    # semantics (an inapplicable kind raises FaultPlanError, loudly);
    # transform_bytes is the sleep-free half, safe on the loop.
    return _fi.transform_bytes(rule, reply, "grpc.server.reply"), 1


def device_compute_fn(
    fn: ComputeFn,
    *,
    jit: bool = True,
    batched: bool = False,
    max_batch: int = 32,
) -> Callable:
    """Adapt a JAX function into the host compute contract.

    The node-side analog of the reference compiling its model with
    PyTensor before serving it (reference: demo_node.py:39-42): ``fn``
    is jitted once, inputs arrive as NumPy, outputs return as NumPy.

    ``batched=True`` additionally attaches a ``.batch`` attribute — a
    ``jax.vmap``-vectorized variant with a padded-bucket jit cache
    (:func:`.batching.batched_compute_fn`) — which the service's
    micro-batcher uses to execute a coalesced window of same-signature
    requests as ONE device call instead of K.  ``max_batch`` bounds the
    bucket ladder; keep it in sync with the service's ``max_batch``.
    """
    import jax

    jfn = jax.jit(fn) if jit else fn

    def compute(*arrays: np.ndarray) -> Sequence[np.ndarray]:
        out = jfn(*arrays)
        return [np.asarray(o) for o in out]

    if batched:
        compute.batch = batched_compute_fn(fn, jit=jit, max_batch=max_batch)
    return compute


class ArraysToArraysService:
    """The gRPC service implementation (reference: service.py:75-115).

    ``compute_fn`` takes/returns NumPy arrays.  Three methods, same
    contract as the reference schema (reference: service.proto:6-19):
    unary ``Evaluate``, lock-step bidi ``EvaluateStream``, and the
    ``GetLoad`` control-plane query.
    """

    def __init__(
        self,
        compute_fn: Callable[..., Sequence[np.ndarray]],
        *,
        getload_wire: str = "npwire",
        inline_compute: bool = False,
        ship_spans: bool = True,
        max_batch: int = 32,
        max_wait_us: float = 200.0,
        batch_fn: Optional[Callable] = None,
        max_queue: Optional[int] = None,
        max_inflight_bytes: Optional[int] = None,
    ):
        """``getload_wire``: "npwire" (JSON reply, this package's
        native clients) or "npproto" (reference ``GetLoadResult``
        protobuf, for serving unmodified reference clients).  Evaluate
        and the stream need no such switch — their request payload
        identifies the wire and the reply mirrors it — but GetLoad's
        request is EMPTY in both schemas, so the reply format is a
        node-level choice.

        ``inline_compute``: run ``compute_fn`` directly on the event
        loop instead of in a thread executor.  The executor exists so
        a SLOW compute cannot stall GetLoad and other streams (the
        reference pays the same structure via its event loop +
        ``run_in_executor``-free design, but it is single-stream); for
        a sub-millisecond compute the two thread handoffs cost more
        than the compute — measured ~1.4x sync-client and up to ~2x
        async-client round-trip throughput on the localhost lane
        (docs/performance.md "Host lane budget") — so nodes serving
        fast jitted evals should pass True.  A compute that blocks for
        long stretches must keep the default.

        ``ship_spans``: piggyback this node's completed span tree on
        each reply whose request carried a trace id (npwire flag 4 /
        npproto field 16), so the driver reunites both halves of the
        trace (:mod:`..telemetry.reunion`).  Costs a few hundred bytes
        of JSON per traced reply; False keeps replies span-free (the
        driver can still pull via GetLoad ``b"traces"``).

        ``max_batch``/``max_wait_us``: the micro-batching engine
        (:mod:`.batching`).  Requests that arrive while a device call
        is in flight — concurrent RPCs, concurrent streams, or the K
        items of one wire batch frame — coalesce and execute together
        as one ``jax.vmap``-batched call when the compute exposes a
        vectorized variant (``batch_fn`` here, or the ``.batch``
        attribute ``device_compute_fn(..., batched=True)`` attaches).
        A lone request on an idle node dispatches immediately (zero
        added latency); ``max_wait_us`` is only ever paid while the
        queue is non-empty.  The coalescing queue serializes dispatch
        (that is what creates the batches), so it only ENGAGES where
        that trade wins: a vectorized compute, or an inline (sub-ms)
        one.  A slow executor-mode compute WITHOUT a vectorized
        variant keeps the classic per-request executor concurrency —
        wire batch frames are still served (decoded once, executed
        concurrently, replied as one frame) and the capability is
        still advertised, since the frame itself is a transport win
        regardless.  ``max_batch=1`` disables batch frames and the
        engine entirely.

        ``max_queue``/``max_inflight_bytes``: ADMISSION CONTROL — the
        overload-protection half of ROADMAP item 3.  ``max_queue``
        bounds the node's backlog (the larger of in-flight RPCs and
        the micro-batcher's coalescing queue — a queued request is
        also an in-flight RPC, counted once); ``max_inflight_bytes``
        bounds the request bytes being served at once.  A full node
        first sheds queued work whose deadline is already spent
        (oldest-past-deadline first — those callers stopped waiting,
        so computing them is pure load), then refuses the NEW request
        with a retryable UNAVAILABLE so pinned clients rebalance and
        pools fail over, composing with the graceful-drain rejection
        below.  ``None`` (the default) keeps the historical unbounded
        queues."""
        if getload_wire not in ("npwire", "npproto"):
            raise ValueError(
                f"getload_wire must be 'npwire' or 'npproto', "
                f"got {getload_wire!r}"
            )
        self.getload_wire = getload_wire
        self.inline_compute = bool(inline_compute)
        self.ship_spans = bool(ship_spans)
        self.compute_fn = compute_fn
        self.max_batch = int(max_batch)
        batch_fn = batch_fn or getattr(compute_fn, "batch", None)
        self._batcher: Optional[MicroBatcher] = None
        if max_batch > 1 and (batch_fn is not None or inline_compute):
            self._batcher = MicroBatcher(
                compute_fn,
                batch_fn,
                max_batch=max_batch,
                max_wait_us=max_wait_us,
                inline=inline_compute,
            )
        self._n_clients = 0
        # Graceful-drain state: while draining, NEW work is rejected
        # with a retryable UNAVAILABLE (the pool fails over cleanly)
        # and :meth:`drain` waits for in-flight work to settle.
        self._draining = False
        self._inflight_rpcs = 0
        # Admission-control state (constructor docstring).
        self.max_queue = None if max_queue is None else int(max_queue)
        self.max_inflight_bytes = (
            None if max_inflight_bytes is None else int(max_inflight_bytes)
        )
        self._inflight_bytes = 0
        # Start psutil's interval-based CPU accounting early so the
        # first real query is meaningful (reference: service.py:84-85).
        try:
            import psutil

            psutil.cpu_percent()
        except Exception:
            pass

    # -- compute plumbing -------------------------------------------------

    async def _run_compute(self, request: bytes) -> bytes:
        """Deadline admission, then dispatch (:meth:`_run_compute_inner`).

        The request's remaining-budget field (npwire flag 16 / npproto
        field 18, :mod:`.deadline`) is peeked BEFORE any decode cost:
        an expired budget is answered with the in-band deadline
        classification (npwire) or raised as
        :class:`~.deadline.DeadlineExceeded` (npproto — the caller
        aborts the RPC as DEADLINE_EXCEEDED, the status the reference
        schema's error-field-free wire must use); a live one is bound
        as the handler's ambient deadline so the micro-batcher queue
        and the compute handoff inherit it."""
        is_npwire = request[:4] == MAGIC
        try:
            budget = (
                peek_deadline(request)
                if is_npwire
                else npproto_codec.peek_deadline_msg(request)
            )
        except WireError:
            budget = None  # the codec path below rejects it loudly
        err = _deadline.shed_expired_admission(budget, transport="grpc")
        if err is not None:
            if not is_npwire:
                raise _deadline.DeadlineExceeded(err)
            uid = frame_uuid(request)
            # call_shimmed_async: the encoders hold sync chaos
            # seams whose delay kinds sleep (the PR-5 bug class).
            if is_batch_frame(request):
                return await _fi.call_shimmed_async(
                    encode_batch, [], uuid=uid, error=err
                )
            return await _fi.call_shimmed_async(
                encode_arrays, [], uuid=uid, error=err
            )
        with _deadline.budget_scope(budget):
            return await self._run_compute_inner(request)

    async def _run_compute_inner(self, request: bytes) -> bytes:
        """decode -> compute (in executor) -> encode, echoing the uuid.

        Errors are encoded into the reply instead of tearing down the
        stream (reference: _run_compute_func, service.py:45-72).

        WIRE AUTO-DETECTION: a request starting with the npwire magic
        is npwire (this package's native client); anything else is
        decoded as the reference's protobuf ``InputArrays``
        (npproto_codec — an npwire frame can never parse as proto:
        ``N`` = tag with illegal wire type 6, and a proto payload can
        never carry the magic).  The reply uses the SAME format, so an
        unmodified reference client gets reference-wire replies.  The
        reference schema has NO error field — its server re-raises into
        the gRPC layer (reference: service.py:45-72) — so npproto
        decode/compute errors raise here too and surface to the peer as
        a gRPC error, exactly what a reference client expects.
        """
        t_arrive = time.perf_counter()
        is_npwire = request[:4] == MAGIC
        # Wire batch frames (npwire flag bit 8 / npproto field 17): one
        # message carrying a whole pipelined window; handled on their
        # own path so error isolation stays per item.
        if is_npwire and is_batch_frame(request):
            return await self._run_batch_npwire(request, t_arrive)
        if not is_npwire and npproto_codec.has_batch_items(request):
            return await self._run_batch_npproto(request, t_arrive)
        trace_id = None
        # Codec calls go through _fi.call_shimmed_async: the codecs
        # hold sync byte-lane chaos seams whose delay kinds sleep, so
        # with a fault plan active they run in the executor instead of
        # on the loop (graftflow async-blocking; the PR-5 bug class).
        if is_npwire:
            try:
                inputs, uuid, _, trace_id = await _fi.call_shimmed_async(
                    decode_arrays_ex, request
                )
            except Exception as e:
                _ERRORS.labels(kind="decode").inc()
                _flightrec.record(
                    "server.error", stage="decode", wire="npwire",
                    error=str(e)[:200],
                )
                return await _fi.call_shimmed_async(
                    encode_arrays,
                    [], uuid=b"\0" * 16, error=f"decode error: {e}",
                )
        else:
            try:
                inputs, proto_uuid, trace_id = await _fi.call_shimmed_async(
                    npproto_codec.decode_arrays_msg_ex, request
                )
            except Exception as e:
                _ERRORS.labels(kind="decode").inc()
                _flightrec.record(
                    "server.error", stage="decode", wire="npproto",
                    error=str(e)[:200],
                )
                raise
        t_decoded = time.perf_counter()
        _DECODE_S.observe(t_decoded - t_arrive)
        # Adopt the DRIVER's trace id off the wire (None is a no-op):
        # the node-side span tree lands in this process's telemetry
        # under the same 16-byte id as the driver-side tree.  The reply
        # is BUILT inside the span (encode is a timed stage) and the
        # finished tree attached after the span closes — the tree's
        # duration only exists then (npwire.append_spans docstring).
        with _spans.trace_context(trace_id), _spans.span(
            "node.evaluate",
            wire="npwire" if is_npwire else "npproto",
            n_inputs=len(inputs),
        ) as root:
            root.set_attr("decode_s", t_decoded - t_arrive)
            err_reply = None
            try:
                with _spans.span("compute") as c_span:
                    if _fi.active_plan is not None:  # chaos seam
                        await _fi.compute_filter_async()
                    if self._batcher is not None:
                        # Micro-batching engine: this request coalesces
                        # with any concurrently in-flight siblings (the
                        # batcher records queue-wait/compute metrics).
                        outputs = await self._batcher.submit(inputs)
                        c_span.set_attr(
                            "queue_depth", self._batcher.queue_depth
                        )
                    elif self.inline_compute:
                        # Fast-compute path: the two thread handoffs of
                        # the executor dominate a sub-ms compute
                        # (docs/performance.md).
                        t_c0 = time.perf_counter()
                        outputs = list(self.compute_fn(*inputs))
                        t_c1 = time.perf_counter()
                        queue_wait = max(0.0, t_c0 - t_decoded)
                        _QUEUE_S.observe(queue_wait)
                        _COMPUTE_S.observe(t_c1 - t_c0)
                        c_span.set_attr("queue_wait_s", queue_wait)
                    else:
                        loop = asyncio.get_running_loop()

                        def timed_compute():
                            t0 = time.perf_counter()
                            out = list(self.compute_fn(*inputs))
                            return out, t0, time.perf_counter()

                        outputs, t_c0, t_c1 = await loop.run_in_executor(
                            None, timed_compute
                        )
                        queue_wait = max(0.0, t_c0 - t_decoded)
                        _QUEUE_S.observe(queue_wait)
                        _COMPUTE_S.observe(t_c1 - t_c0)
                        c_span.set_attr("queue_wait_s", queue_wait)
                    outputs = [np.asarray(o) for o in outputs]
            except _deadline.DeadlineExceeded as e:
                # Shed, not failed: the batcher (or a nested client)
                # abandoned work whose budget was spent — answer with
                # the bare deadline classification (no "compute error"
                # wrap, no traceback noise); npproto aborts the RPC as
                # DEADLINE_EXCEEDED via the handler's catch.
                if not is_npwire:
                    raise
                err_reply = await _fi.call_shimmed_async(
                    encode_arrays, [], uuid=uuid, error=str(e)
                )
            except Exception as e:
                _log.exception("compute_fn failed")
                _ERRORS.labels(kind="compute").inc()
                _flightrec.record(
                    "server.error", stage="compute",
                    wire="npwire" if is_npwire else "npproto",
                    error=str(e)[:200],
                )
                if not is_npwire:
                    raise
                err_reply = await _fi.call_shimmed_async(
                    encode_arrays,
                    [], uuid=uuid, error=f"compute error: {e}",
                )
            if err_reply is not None:
                reply = err_reply
            else:
                with _spans.span("encode"):
                    t_e0 = time.perf_counter()
                    if is_npwire:
                        reply = await _fi.call_shimmed_async(
                            encode_arrays, outputs, uuid=uuid
                        )
                    else:
                        reply = await _fi.call_shimmed_async(
                            npproto_codec.encode_arrays_msg,
                            outputs, uuid=proto_uuid,
                        )
                    _ENCODE_S.observe(time.perf_counter() - t_e0)
        # Trace reunion piggyback: the request carried a trace id, so
        # the driver is correlating — ship the node's half home on this
        # very reply.  Untraced requests get the PR-1 byte-identical
        # frame (the acceptance invariant).
        if (
            self.ship_spans
            and trace_id is not None
            and root.span is not None
        ):
            tree = root.span.to_dict()
            if is_npwire:
                reply = append_spans(reply, [tree])
            else:
                reply = npproto_codec.append_spans_msg(reply, [tree])
        return reply

    async def _compute_window(
        self, to_compute: Sequence[Sequence[np.ndarray]]
    ) -> list:
        """Execute a decoded wire-batch window; one outcome (output
        list or exception) per request — per-item error isolation,
        whether or not the batching engine is engaged.  Without the
        engine (slow executor compute, no vectorized variant) the
        window fans out over the executor's workers, preserving the
        concurrency the per-RPC path has."""
        if _fi.active_plan is not None:  # chaos seam: compute path
            try:
                await _fi.compute_filter_async()
            except _fi.FaultPlanError:
                raise  # a plan-authoring bug stays LOUD, never in-band
            except RuntimeError as e:
                # Injected compute failure covers the whole window,
                # per item and in-band — exactly like a real pre-
                # dispatch failure would.
                return [e for _ in to_compute]
        if self._batcher is not None:
            return await self._batcher.submit_many(to_compute)

        def one(inputs) -> object:
            try:
                return [np.asarray(o) for o in self.compute_fn(*inputs)]
            except Exception as e:
                return e

        if self.inline_compute:
            return [one(inputs) for inputs in to_compute]
        loop = asyncio.get_running_loop()
        return list(
            await asyncio.gather(
                *(
                    loop.run_in_executor(None, one, inputs)
                    for inputs in to_compute
                )
            )
        )

    async def _run_batch_npwire(
        self, request: bytes, t_arrive: float
    ) -> bytes:
        """One npwire batch frame in -> one batch frame out, item
        replies in item order, each with its own uuid and its own
        error channel (a poisoned item fails only its own reply)."""
        try:
            items, outer_uuid, _err, trace_id, _spans_in = (
                await _fi.call_shimmed_async(decode_batch, request)
            )
        except Exception as e:
            _ERRORS.labels(kind="decode").inc()
            _flightrec.record(
                "server.error", stage="decode", wire="npwire-batch",
                error=str(e)[:200],
            )
            return await _fi.call_shimmed_async(
                encode_batch,
                [], uuid=b"\0" * 16, error=f"decode error: {e}",
            )
        try:
            reduce_part = peek_partition(request)
        except WireError:
            reduce_part = None
        if reduce_part is not None:
            # A REDUCE window (outer partition block, ISSUE 13): the
            # gRPC lane does not serve reduce windows — answering
            # per-item replies to a caller that asked for a partial
            # sum would be a silent contract break, so the refusal is
            # loud and in-band (the tcp/shm lanes, and aggregator
            # trees over them, are the reduce transports; this repo's
            # pooled client reduces grpc replicas driver-side).
            return await _fi.call_shimmed_async(
                encode_batch,
                [],
                uuid=outer_uuid,
                error=(
                    "partition reduce windows are not served on the "
                    "grpc lane (use tcp/shm, or the pooled client's "
                    "driver-side reduction)"
                ),
            )
        _DECODE_S.observe(time.perf_counter() - t_arrive)
        with _spans.trace_context(trace_id), _spans.span(
            "node.evaluate_batch", wire="npwire", n_items=len(items)
        ) as root:
            replies: list = [None] * len(items)
            to_compute = []  # (slot, inputs, uuid)
            for i, item in enumerate(items):
                try:
                    inputs, uuid, _, _ = await _fi.call_shimmed_async(
                        decode_arrays_ex, item
                    )
                except Exception as e:
                    _ERRORS.labels(kind="decode").inc()
                    _flightrec.record(
                        "server.error", stage="decode", wire="npwire",
                        error=str(e)[:200],
                    )
                    replies[i] = await _fi.call_shimmed_async(
                        encode_arrays,
                        [], uuid=b"\0" * 16, error=f"decode error: {e}",
                    )
                    continue
                to_compute.append((i, inputs, uuid))
            outcomes = await self._compute_window(
                [inputs for _, inputs, _ in to_compute]
            )
            with _spans.span("encode"):
                t_e0 = time.perf_counter()
                for (i, _inputs, uuid), res in zip(to_compute, outcomes):
                    if isinstance(res, BaseException):
                        _ERRORS.labels(kind="compute").inc()
                        _flightrec.record(
                            "server.error", stage="compute", wire="npwire",
                            error=str(res)[:200],
                        )
                        replies[i] = await _fi.call_shimmed_async(
                            encode_arrays,
                            [], uuid=uuid, error=f"compute error: {res}",
                        )
                    else:
                        replies[i] = await _fi.call_shimmed_async(
                            encode_arrays, res, uuid=uuid
                        )
                reply = await _fi.call_shimmed_async(
                    encode_batch, replies, uuid=outer_uuid
                )
                _ENCODE_S.observe(time.perf_counter() - t_e0)
        if (
            self.ship_spans
            and trace_id is not None
            and root.span is not None
        ):
            reply = append_spans(reply, [root.span.to_dict()])
        return reply

    async def _run_batch_npproto(
        self, request: bytes, t_arrive: float
    ) -> bytes:
        """npproto batch message (field 17) in -> batch message out.
        Per-item failures use the field-14 error extension — the
        isolation channel the reference schema lacks; only this
        package's clients send batch messages (capability-gated), so
        no reference peer ever sees field 14/17."""
        # Outer decode errors raise -> gRPC abort, exactly like a
        # malformed plain npproto request (reference contract).
        items, outer_uuid, trace_id, _spans_in = (
            await _fi.call_shimmed_async(
                npproto_codec.decode_batch_msg, request
            )
        )
        _DECODE_S.observe(time.perf_counter() - t_arrive)
        with _spans.trace_context(trace_id), _spans.span(
            "node.evaluate_batch", wire="npproto", n_items=len(items)
        ) as root:
            replies: list = [None] * len(items)
            to_compute = []
            for i, item in enumerate(items):
                try:
                    inputs, uuid, _ = await _fi.call_shimmed_async(
                        npproto_codec.decode_arrays_msg_ex, item
                    )
                except Exception as e:
                    _ERRORS.labels(kind="decode").inc()
                    _flightrec.record(
                        "server.error", stage="decode", wire="npproto",
                        error=str(e)[:200],
                    )
                    replies[i] = await _fi.call_shimmed_async(
                        npproto_codec.encode_arrays_msg,
                        [], uuid="", error=f"decode error: {e}",
                    )
                    continue
                to_compute.append((i, inputs, uuid))
            outcomes = await self._compute_window(
                [inputs for _, inputs, _ in to_compute]
            )
            with _spans.span("encode"):
                t_e0 = time.perf_counter()
                for (i, _inputs, uuid), res in zip(to_compute, outcomes):
                    if isinstance(res, BaseException):
                        _ERRORS.labels(kind="compute").inc()
                        _flightrec.record(
                            "server.error", stage="compute",
                            wire="npproto", error=str(res)[:200],
                        )
                        replies[i] = await _fi.call_shimmed_async(
                            npproto_codec.encode_arrays_msg,
                            [], uuid=uuid, error=f"compute error: {res}",
                        )
                    else:
                        replies[i] = await _fi.call_shimmed_async(
                            npproto_codec.encode_arrays_msg, res, uuid=uuid
                        )
                reply = await _fi.call_shimmed_async(
                    npproto_codec.encode_batch_msg,
                    replies, uuid=outer_uuid,
                )
                _ENCODE_S.observe(time.perf_counter() - t_e0)
        if (
            self.ship_spans
            and trace_id is not None
            and root.span is not None
        ):
            reply = npproto_codec.append_spans_msg(
                reply, [root.span.to_dict()]
            )
        return reply

    # -- graceful drain ---------------------------------------------------

    async def _reject_if_draining(self, context) -> None:
        """While draining, NEW work is refused with a retryable status:
        UNAVAILABLE is outside the client's no-retry set (client.py
        ``_NO_RETRY_STATUS``), so pinned clients retry-and-rebalance and
        the replica pool books a transient failure and fails the work
        over — the clean half of a rolling restart."""
        if self._draining:
            _flightrec.record("server.drain_reject")
            if context is not None:
                await context.abort(
                    grpc.StatusCode.UNAVAILABLE, "node draining"
                )
            raise ConnectionError("node draining")

    async def drain(self, timeout_s: float = 30.0) -> bool:
        """Begin a graceful drain: reject new work (see
        :meth:`_reject_if_draining`), then wait for every in-flight RPC
        — including requests parked in the micro-batcher's coalescing
        queue — to finish.  Returns ``True`` when the node went idle
        within ``timeout_s`` (``False`` = timed out with work still in
        flight; the caller may stop the server anyway or keep waiting).
        Idempotent; :meth:`undrain` re-opens the node."""
        self._draining = True
        _flightrec.record("server.drain_begin", inflight=self._inflight_rpcs)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s

        def busy() -> bool:
            if self._inflight_rpcs > 0:
                return True
            b = self._batcher
            return b is not None and (
                b.queue_depth > 0 or b._worker is not None
            )

        while busy() and loop.time() < deadline:
            await asyncio.sleep(0.01)
        clean = not busy()
        _flightrec.record(
            "server.drained", clean=clean, inflight=self._inflight_rpcs
        )
        return clean

    def undrain(self) -> None:
        """Re-open a draining/drained node for new work."""
        self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    # -- admission control ------------------------------------------------

    async def _reject_overloaded(self, context, reason: str) -> None:
        """Refuse one request at the door with a RETRYABLE status —
        UNAVAILABLE is outside the clients' no-retry set, so a pinned
        client rebalances and a pool books a transient failure and
        fails over, exactly like the drain rejection.  The refusal is
        the cheap outcome by design: under overload the work a node
        does NOT accept is what keeps the work it did accept inside
        its SLO."""
        _ADMISSION_SHED.labels(reason=reason).inc()
        _flightrec.record(
            "admission.shed", transport="grpc", reason=reason
        )
        if context is not None:
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"node overloaded ({reason})",
            )
        raise ConnectionError(f"node overloaded ({reason})")

    async def _admit(self, request: bytes, context) -> None:
        """Bounded-queue admission (constructor docstring): drain
        check, then queue-depth and in-flight-byte caps, shedding
        already-expired batcher entries before refusing new work."""
        await self._reject_if_draining(context)
        if self.max_queue is not None:
            def depth() -> int:
                # A queued request is ALSO an in-flight RPC (its
                # handler awaits the batcher), so summing the two
                # would double-count every queued single and halve
                # the effective cap.  max() counts each waiting
                # request once and still sees a one-RPC batch window
                # whose items outnumber its RPC.
                b = self._batcher
                return max(
                    self._inflight_rpcs,
                    b.queue_depth if b is not None else 0,
                )

            shed = 0
            if depth() >= self.max_queue and self._batcher is not None:
                # Shed oldest-past-deadline first: dead queue entries
                # must not crowd out live callers.
                shed = self._batcher.shed_expired()
            # A shed entry's handler is still counted by
            # _inflight_rpcs until its loop tick delivers the failed
            # future through the RPC's finally block, so recheck
            # against the depth the shed actually freed: exact for
            # unary traffic (one queued entry == one RPC); batch
            # windows already show the drop synchronously through
            # queue_depth, which stays the floor of the max().
            b = self._batcher
            if max(
                self._inflight_rpcs - shed,
                b.queue_depth if b is not None else 0,
            ) >= self.max_queue:
                await self._reject_overloaded(context, "queue_full")
        if (
            self.max_inflight_bytes is not None
            and self._inflight_rpcs > 0
            and self._inflight_bytes + len(request)
            > self.max_inflight_bytes
        ):
            # The idle-node exemption (_inflight_rpcs > 0): one
            # request larger than the cap must degrade to serial
            # service, not be refused forever.
            await self._reject_overloaded(context, "inflight_bytes")

    # -- RPC methods ------------------------------------------------------

    async def evaluate(self, request: bytes, context) -> bytes:
        await self._admit(request, context)
        _REQUESTS.labels(method="evaluate").inc()
        _INFLIGHT.inc()
        self._inflight_rpcs += 1
        self._inflight_bytes += len(request)
        try:
            reply = await self._run_compute(request)
        except _deadline.DeadlineExceeded as e:
            # npproto lane (no in-band error field): the RPC aborts as
            # DEADLINE_EXCEEDED — non-retryable in the client table,
            # because the budget is spent everywhere at once.
            if context is not None:
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED, str(e)
                )
            raise
        finally:
            _INFLIGHT.dec()
            self._inflight_rpcs -= 1
            self._inflight_bytes -= len(request)
        if _fi.active_plan is not None:  # chaos seam: reply lane
            reply, _n = await _fi_reply_filter(reply, context, unary=True)
        return reply

    async def evaluate_stream(self, request_iterator, context):
        """Lock-step bidi stream: one reply per request, in order
        (reference: service.py:104-112)."""
        self._n_clients += 1
        _log.info("stream opened (n_clients=%d)", self._n_clients)
        try:
            async for request in request_iterator:
                # Per request, not per stream: a drain (or overload)
                # beginning mid-stream rejects the stream's NEXT
                # request (retryable), while requests already being
                # served run to completion.
                await self._admit(request, context)
                _REQUESTS.labels(method="evaluate_stream").inc()
                _INFLIGHT.inc()
                self._inflight_rpcs += 1
                self._inflight_bytes += len(request)
                try:
                    reply = await self._run_compute(request)
                except _deadline.DeadlineExceeded as e:
                    if context is not None:
                        await context.abort(
                            grpc.StatusCode.DEADLINE_EXCEEDED, str(e)
                        )
                    raise
                finally:
                    _INFLIGHT.dec()
                    self._inflight_rpcs -= 1
                    self._inflight_bytes -= len(request)
                if _fi.active_plan is not None:  # chaos seam: reply lane
                    reply, n_copies = await _fi_reply_filter(reply, context)
                    for _ in range(n_copies):
                        yield reply
                else:
                    yield reply
        finally:
            self._n_clients -= 1
            _log.info("stream closed (n_clients=%d)", self._n_clients)

    def determine_load(self) -> dict:
        """Load snapshot (reference: service.py:88-96 GetLoadResult).

        With telemetry enabled, an ``"rpc"`` sub-dict folds the node's
        live RPC picture into the reply — request counts, in-flight
        depth, and compute/queue latency quantiles from the server
        histograms — so a driver polling GetLoad sees WHY a node is
        slow, not just that it is busy.  The three reference fields
        stay top-level, so balancing (and the npproto reply, which has
        no room for more) is unaffected.

        With the micro-batching engine enabled, a ``"batch"`` sub-dict
        carries BOTH the capability advertisement clients key on
        before sending wire batch frames (``max_batch`` > 1 is the
        signal) AND the live batcher picture: queue depth, dispatch
        tallies, and — telemetry on — batch-size/coalesce-wait
        quantiles.  npwire-JSON lane only; the reference-format
        GetLoad reply is fixed at its three fields, which is exactly
        why a reference peer can never be lured into batch frames.
        """
        try:
            import psutil

            percent_cpu = psutil.cpu_percent()
            percent_ram = psutil.virtual_memory().percent
        except Exception:
            percent_cpu = percent_ram = -1.0
        load = {
            "n_clients": self._n_clients,
            "percent_cpu": percent_cpu,
            "percent_ram": percent_ram,
        }
        if _spans.enabled():

            def _q(hist, q):
                v = hist.approx_quantile(q)
                return None if math.isnan(v) or math.isinf(v) else v

            load["rpc"] = {
                "requests_total": sum(
                    v for _n, _l, v in _REQUESTS.samples()
                ),
                "inflight": _INFLIGHT.value,
                "compute_p50_s": _q(_COMPUTE_S, 0.5),
                "compute_p99_s": _q(_COMPUTE_S, 0.99),
                "queue_p99_s": _q(_QUEUE_S, 0.99),
            }
        if self.max_batch > 1:
            # Capability advertisement: batch frames are served (and a
            # transport win) even when the coalescing engine itself is
            # not engaged for this compute, so max_batch>1 is the
            # signal; live engine stats ride along when it is.
            load["batch"] = (
                self._batcher.stats()
                if self._batcher is not None
                else {"max_batch": self.max_batch}
            )
        return load

    async def get_load(self, request: bytes, context) -> bytes:
        """GetLoad; the npwire-JSON reply doubles as the telemetry
        PULL lanes: a request payload of ``b"traces"`` adds this
        node's recent completed span trees (``"traces"`` key) to the
        reply — the reunion path for spans whose own reply never made
        it back (:func:`.client.get_node_traces`) — and ``b"telemetry"``
        adds the FULL telemetry snapshot (``"telemetry"`` key: metric
        families, recent traces, the flight-record tail, and the
        node's wall-clock ``ts`` for Cristian-style clock alignment)
        — the fleet-collector scrape lane
        (:mod:`..telemetry.collector`).  Both schemas define an EMPTY
        GetLoad request, so any non-empty payload is an in-repo
        extension (the recognized payloads are declared in
        :data:`.wire_registry.GETLOAD_PAYLOADS`); unknown payloads are
        ignored (plain load reply).  The npproto reply schema is fixed
        — no room for traces or telemetry there.
        """
        _REQUESTS.labels(method="get_load").inc()
        if _fi.active_plan is not None:  # chaos seam: probe lane
            # The async twin: a delay rule must not block the event
            # loop (graftlint async-blocking, the PR-5 bug class).
            garbage = await _fi.getload_filter_async()
            if garbage is not None:
                return garbage
        load = self.determine_load()
        if self.getload_wire == "npproto":
            return npproto_codec.encode_get_load_result(
                load["n_clients"], load["percent_cpu"], load["percent_ram"]
            )
        if request == b"traces" and _spans.enabled():
            load["traces"] = _spans.recent_traces(16)
        if request == b"telemetry" and _spans.enabled():
            from ..telemetry import export as _export

            load["telemetry"] = {
                **_export.snapshot(),
                "flightrec": _flightrec.events(128),
            }
        # default=str: the traces lane carries free-form span attrs
        # (numpy scalars included) — degrade, never fail the query.
        return json.dumps(load, default=str).encode("utf-8")

    # -- wiring -----------------------------------------------------------

    def generic_handler(self) -> grpc.GenericRpcHandler:
        handlers = {
            "Evaluate": grpc.unary_unary_rpc_method_handler(
                self.evaluate,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "EvaluateStream": grpc.stream_stream_rpc_method_handler(
                self.evaluate_stream,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "GetLoad": grpc.unary_unary_rpc_method_handler(
                self.get_load,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


async def serve(
    compute_fn: Optional[Callable[..., Sequence[np.ndarray]]],
    bind: str = "127.0.0.1",
    port: int = 50000,
    *,
    getload_wire: str = "npwire",
    inline_compute: bool = False,
    ship_spans: bool = True,
    max_batch: int = 32,
    max_wait_us: float = 200.0,
    max_queue: Optional[int] = None,
    max_inflight_bytes: Optional[int] = None,
    service: Optional[ArraysToArraysService] = None,
    metrics_port: Optional[int] = None,
    metrics_host: str = "127.0.0.1",
) -> grpc.aio.Server:
    """Start a node server (reference: demo_node.py:76-79).  Returns the
    started ``grpc.aio.Server``; await ``server.wait_for_termination()``.

    Pass EITHER ``compute_fn`` (+ optional ``getload_wire``) — the
    service is constructed here — or a pre-built ``service`` with
    ``compute_fn=None``; both at once would be two sources of truth for
    what the node computes.

    ``metrics_port`` (opt-in) starts a Prometheus-style exposition
    endpoint (:mod:`..telemetry.export`) alongside the node — ``0``
    binds an ephemeral port.  Loopback-bound by default: a node's RPC
    telemetry can leak workload shape, so scraping across hosts is an
    explicit ``metrics_host`` decision.  The running exporter hangs off
    the returned server as ``server.metrics_exporter`` (``.port``,
    ``.close()``); it stops with the daemon thread at process exit."""
    if service is None:
        if compute_fn is None:
            raise ValueError("pass compute_fn or a pre-built service")
        service = ArraysToArraysService(
            compute_fn,
            getload_wire=getload_wire,
            inline_compute=inline_compute,
            ship_spans=ship_spans,
            max_batch=max_batch,
            max_wait_us=max_wait_us,
            max_queue=max_queue,
            max_inflight_bytes=max_inflight_bytes,
        )
    elif compute_fn is not None:
        raise ValueError(
            "pass either compute_fn or a pre-built service, not both "
            "(the service already owns its compute_fn)"
        )
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((service.generic_handler(),))
    server.add_insecure_port(f"{bind}:{port}")
    server.metrics_exporter = None
    if metrics_port is not None:
        from ..telemetry.export import start_exporter

        # Before server.start(): if the exposition port is taken, this
        # raises while nothing is listening yet, instead of leaking a
        # started gRPC server the caller never received a handle to.
        server.metrics_exporter = start_exporter(metrics_host, metrics_port)
    await server.start()
    _log.info("node listening on %s:%d", bind, port)
    return server


def run_node(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    bind: str = "127.0.0.1",
    port: int = 50000,
    *,
    getload_wire: str = "npwire",
    inline_compute: bool = False,
    max_batch: int = 32,
    max_wait_us: float = 200.0,
    metrics_port: Optional[int] = None,
    metrics_host: str = "127.0.0.1",
) -> None:
    """Blocking single-node entry point (reference: demo_node.py:83-95).

    ``getload_wire="npproto"`` serves reference-format GetLoad replies
    so UNMODIFIED reference clients can balance over this node
    (Evaluate/EvaluateStream auto-detect per request either way).
    ``inline_compute=True`` skips the per-call thread-executor handoff
    for sub-ms compute fns (see ArraysToArraysService).
    ``max_batch``/``max_wait_us`` tune the micro-batching engine — a
    ``compute_fn`` with a ``.batch`` attribute (see
    :func:`device_compute_fn` ``batched=True``) executes coalesced
    windows as one vmapped call (``max_batch=1`` disables).
    ``metrics_port`` opts into the telemetry exposition endpoint
    (see :func:`serve`)."""

    async def main():
        server = await serve(
            compute_fn, bind, port,
            getload_wire=getload_wire,
            inline_compute=inline_compute,
            max_batch=max_batch,
            max_wait_us=max_wait_us,
            metrics_port=metrics_port,
            metrics_host=metrics_host,
        )
        await server.wait_for_termination()

    asyncio.run(main())
