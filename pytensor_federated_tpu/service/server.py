"""Host-federation server: arrays-in/arrays-out compute behind gRPC.

Re-design of the reference's service core (reference: service.py:45-115)
for the one capability that cannot collapse onto the mesh: *true*
federation across trust domains, where a node's private data may never
leave its machine (reference: README.md:6-11).  This path is explicitly
off the TPU hot loop (SURVEY §7 step 6); on-pod sharding lives in
:mod:`pytensor_federated_tpu.parallel`.

Differences from the reference, on purpose:

- grpc.aio (C-core) with raw-bytes methods + the npwire codec instead of
  grpclib + betterproto: no codegen step, and HTTP/2 flow control is
  handled by the C core.
- Compute runs in a thread executor, so one slow evaluation does not
  block the event loop (the reference computes on the loop thread and
  notes per-node concurrency only across streams,
  reference: service.py:66, SURVEY §3.2).
- ``n_clients`` decrements in a ``finally`` — an abruptly killed client
  cannot leak the counter (the reference leaks it, SURVEY §5 quirks).
- A node can pin its compute to a JAX device (each federated node owning
  one accelerator), via :func:`device_compute_fn`.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Callable, Optional, Sequence

import grpc
import numpy as np

from ..signatures import ComputeFn
from .npwire import decode_arrays, encode_arrays

_log = logging.getLogger(__name__)

SERVICE_NAME = "ArraysToArraysService"
EVALUATE = f"/{SERVICE_NAME}/Evaluate"
EVALUATE_STREAM = f"/{SERVICE_NAME}/EvaluateStream"
GET_LOAD = f"/{SERVICE_NAME}/GetLoad"

_identity = lambda b: b  # noqa: E731  (raw-bytes (de)serializer)


def device_compute_fn(fn: ComputeFn, *, jit: bool = True) -> Callable:
    """Adapt a JAX function into the host compute contract.

    The node-side analog of the reference compiling its model with
    PyTensor before serving it (reference: demo_node.py:39-42): ``fn``
    is jitted once, inputs arrive as NumPy, outputs return as NumPy.
    """
    import jax

    jfn = jax.jit(fn) if jit else fn

    def compute(*arrays: np.ndarray) -> Sequence[np.ndarray]:
        out = jfn(*arrays)
        return [np.asarray(o) for o in out]

    return compute


class ArraysToArraysService:
    """The gRPC service implementation (reference: service.py:75-115).

    ``compute_fn`` takes/returns NumPy arrays.  Three methods, same
    contract as the reference schema (reference: service.proto:6-19):
    unary ``Evaluate``, lock-step bidi ``EvaluateStream``, and the
    ``GetLoad`` control-plane query.
    """

    def __init__(self, compute_fn: Callable[..., Sequence[np.ndarray]]):
        self.compute_fn = compute_fn
        self._n_clients = 0
        # Start psutil's interval-based CPU accounting early so the
        # first real query is meaningful (reference: service.py:84-85).
        try:
            import psutil

            psutil.cpu_percent()
        except Exception:
            pass

    # -- compute plumbing -------------------------------------------------

    async def _run_compute(self, request: bytes) -> bytes:
        """decode -> compute (in executor) -> encode, echoing the uuid.

        Errors are encoded into the reply instead of tearing down the
        stream (reference: _run_compute_func, service.py:45-72).
        """
        try:
            inputs, uuid, _ = decode_arrays(request)
        except Exception as e:
            return encode_arrays([], uuid=b"\0" * 16, error=f"decode error: {e}")
        try:
            loop = asyncio.get_running_loop()
            outputs = await loop.run_in_executor(
                None, lambda: list(self.compute_fn(*inputs))
            )
            return encode_arrays(
                [np.asarray(o) for o in outputs], uuid=uuid
            )
        except Exception as e:
            _log.exception("compute_fn failed")
            return encode_arrays([], uuid=uuid, error=f"compute error: {e}")

    # -- RPC methods ------------------------------------------------------

    async def evaluate(self, request: bytes, context) -> bytes:
        return await self._run_compute(request)

    async def evaluate_stream(self, request_iterator, context):
        """Lock-step bidi stream: one reply per request, in order
        (reference: service.py:104-112)."""
        self._n_clients += 1
        _log.info("stream opened (n_clients=%d)", self._n_clients)
        try:
            async for request in request_iterator:
                yield await self._run_compute(request)
        finally:
            self._n_clients -= 1
            _log.info("stream closed (n_clients=%d)", self._n_clients)

    def determine_load(self) -> dict:
        """Load snapshot (reference: service.py:88-96 GetLoadResult)."""
        try:
            import psutil

            percent_cpu = psutil.cpu_percent()
            percent_ram = psutil.virtual_memory().percent
        except Exception:
            percent_cpu = percent_ram = -1.0
        return {
            "n_clients": self._n_clients,
            "percent_cpu": percent_cpu,
            "percent_ram": percent_ram,
        }

    async def get_load(self, request: bytes, context) -> bytes:
        return json.dumps(self.determine_load()).encode("utf-8")

    # -- wiring -----------------------------------------------------------

    def generic_handler(self) -> grpc.GenericRpcHandler:
        handlers = {
            "Evaluate": grpc.unary_unary_rpc_method_handler(
                self.evaluate,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "EvaluateStream": grpc.stream_stream_rpc_method_handler(
                self.evaluate_stream,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "GetLoad": grpc.unary_unary_rpc_method_handler(
                self.get_load,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
        }
        return grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)


async def serve(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    bind: str = "127.0.0.1",
    port: int = 50000,
    *,
    service: Optional[ArraysToArraysService] = None,
) -> grpc.aio.Server:
    """Start a node server (reference: demo_node.py:76-79).  Returns the
    started ``grpc.aio.Server``; await ``server.wait_for_termination()``."""
    service = service or ArraysToArraysService(compute_fn)
    server = grpc.aio.server()
    server.add_generic_rpc_handlers((service.generic_handler(),))
    server.add_insecure_port(f"{bind}:{port}")
    await server.start()
    _log.info("node listening on %s:%d", bind, port)
    return server


def run_node(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    bind: str = "127.0.0.1",
    port: int = 50000,
) -> None:
    """Blocking single-node entry point (reference: demo_node.py:83-95)."""

    async def main():
        server = await serve(compute_fn, bind, port)
        await server.wait_for_termination()

    asyncio.run(main())
