"""Driver-side RPC metric families, shared by both transport lanes.

One declaration site for the instruments the gRPC lane (client.py,
``transport="grpc"``) and the TCP lane (tcp.py, ``transport="tcp"``)
both record into — the registry would dedupe identical re-declarations,
but a single source means the help text and bucket ladders cannot
drift between lanes (metric catalog: docs/observability.md).
"""

from __future__ import annotations

from ..telemetry import metrics as _metrics

CALL_S = _metrics.histogram(
    "pftpu_client_call_seconds",
    "One RPC attempt, driver-observed (write -> validated reply)",
    ("transport", "mode"),
)
RETRIES = _metrics.counter(
    "pftpu_client_retries_total",
    "Failed attempts that triggered the retry/rebalance loop",
    ("transport",),
)
DROPS = _metrics.counter(
    "pftpu_client_connection_drops_total",
    "Cached connections dropped (failover, desync, decode failure)",
    ("transport",),
)
BATCH_S = _metrics.histogram(
    "pftpu_client_batch_seconds",
    "evaluate_many wall time per batch",
    ("transport",),
)
WINDOW_DEPTH = _metrics.histogram(
    "pftpu_client_window_depth",
    "In-flight pipeline depth observed at each evaluate_many reply",
    ("transport",),
    buckets=_metrics.DEFAULT_COUNT_BUCKETS,
)
BATCH_FRAME_REQS = _metrics.histogram(
    "pftpu_client_batch_frame_requests",
    "Requests coalesced into each wire batch frame sent by evaluate_many",
    ("transport",),
    buckets=_metrics.DEFAULT_COUNT_BUCKETS,
)

__all__ = [
    "CALL_S",
    "RETRIES",
    "DROPS",
    "BATCH_S",
    "WINDOW_DEPTH",
    "BATCH_FRAME_REQS",
]
