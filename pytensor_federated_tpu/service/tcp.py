"""Plain-TCP transport for the npwire format — the cross-language lane.

The gRPC service (:mod:`.server`/:mod:`.client`) is the batteries-
included host-federation transport; this module is the *minimal* one: a
u32-length-prefixed npwire frame over a TCP socket.  Its purpose is the
capability the reference only gestures at — "the model implementation
could be C++" (reference: README.md:34-35): ``native/cpp_node.cpp``
implements this exact protocol with zero Python, and
:class:`TcpArraysClient` drives it from the driver process.

Frame layout: ``u32 little-endian payload length`` + npwire payload
(see :mod:`.npwire` for the payload layout).  Requests and replies are
lock-step per connection — the same one-in-flight pattern the reference
uses on its bidirectional streams (reference: service.py:150-158).
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..faultinject import runtime as _fi
from ..telemetry import flightrec as _flightrec
from ..telemetry import reunion as _reunion
from ..telemetry import spans as _spans
from ..telemetry import watchdog as _watchdog
from . import _node_metrics
from . import _rpc_metrics
from . import deadline as _deadline
from .batching import execute_window_sync as _execute_window_sync
from .npwire import (
    WireError,
    append_spans,
    fast_uuid,
    decode_arrays_all,
    decode_arrays_ex,
    decode_arrays_part,
    decode_batch,
    decode_batch_part,
    encode_arrays,
    encode_arrays_sg,
    encode_batch,
    frame_uuid,
    is_batch_frame,
    peek_deadline,
    peek_partition,
    sg_nbytes,
)

# The partition lane (ISSUE 13): shard math + loud reassembly rules.
# routing/ deliberately never imports service/ at module level, so this
# upward import cannot cycle (the same direction wire_registry rides).
from ..routing import partition as _partition

__all__ = ["TcpArraysClient", "serve_tcp_once", "RemoteComputeError"]

# Same metric families as the gRPC lane (client.py), labeled
# transport="tcp" so both lanes aggregate on one dashboard
# (metric catalog: docs/observability.md).
_CALL_S = _rpc_metrics.CALL_S
_RETRIES = _rpc_metrics.RETRIES
_DROPS = _rpc_metrics.DROPS
_BATCH_S = _rpc_metrics.BATCH_S
_WINDOW_DEPTH = _rpc_metrics.WINDOW_DEPTH
_FRAME_REQS = _rpc_metrics.BATCH_FRAME_REQS


class RemoteComputeError(RuntimeError):
    """The remote node replied with an error payload."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        # graftlint: disable=unbounded-wait -- server frame loop: waiting for the NEXT request is the node's idle state, bounded only by the peer disconnecting
        b = sock.recv(n)
        if not b:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


# Preserialized header packers (ISSUE-13 satellite): the u32 length
# prefix rides every frame each way, and struct.pack with a literal
# format re-parses the format string per call in the hot send path —
# the PR-10-review _run_compute class, swept from the client lanes.
_U32 = struct.Struct("<I")

# Linux IOV_MAX is 1024; stay under it so one sendmsg never fails
# with EMSGSIZE however many frames a burst coalesces.
_IOV_CHUNK = 512


def _sendmsg_all(sock: socket.socket, parts) -> None:
    """Send a buffer vector with ``socket.sendmsg`` — the scatter/
    gather syscall: the kernel gathers the views directly, so nothing
    is concatenated in userspace (the copy ``b"".join`` used to pay).
    Handles partial sends (a filled send buffer can accept any byte
    count) and chunks the vector under IOV_MAX."""
    mvs = []
    for p in parts:
        mv = p if isinstance(p, memoryview) else memoryview(p)
        if mv.format != "B" or mv.ndim != 1:
            # Byte-format views only: the partial-send arithmetic below
            # slices by BYTES, and a typed view slices by elements.
            mv = mv.cast("B")
        mvs.append(mv)
    start = 0
    while start < len(mvs):
        chunk = mvs[start : start + _IOV_CHUNK]
        while chunk:
            sent = sock.sendmsg(chunk)
            while chunk and sent >= chunk[0].nbytes:
                sent -= chunk[0].nbytes
                chunk.pop(0)
            if sent:
                chunk[0] = chunk[0][sent:]
        start += _IOV_CHUNK


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    # Header + payload as one sendmsg vector: no copy-to-prepend.
    _sendmsg_all(sock, (_U32.pack(len(payload)), payload))


def _send_frame_vec(sock: socket.socket, parts, nbytes: int) -> None:
    """One length-prefixed frame from a scatter/gather buffer vector
    (``encode_arrays_sg`` output): the u32 header and every piece ride
    a single ``sendmsg``, so array payloads go source → kernel with no
    intermediate frame copy."""
    _sendmsg_all(sock, [_U32.pack(nbytes), *parts])


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _U32.unpack(_recv_exact(sock, 4))
    return _recv_exact(sock, n)


def _serve_send(conn: socket.socket, payload: bytes) -> None:
    """Server-side frame send, routed through the chaos seam
    (``tcp.server.send``) when a fault plan is installed."""
    if _fi.active_plan is not None:
        _fi.send_frame_through("tcp.server.send", conn.sendall, payload)
    else:
        _send_frame(conn, payload)


class TcpArraysClient:
    """Arrays-in → arrays-out over one persistent TCP connection.

    API parity with :class:`.client.ArraysToArraysServiceClient`'s sync
    surface: ``evaluate(*arrays) -> [arrays]`` with uuid correlation
    checking and lazy (re)connection.  ``retries`` reconnects on a dead
    socket — the failover analog for a single fixed peer (reference:
    service.py:408-416 rebalances across a pool; a TCP peer is pinned).
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        retries: int = 2,
        max_inflight_bytes: Optional[int] = None,
        connect_timeout_s: float = 30.0,
        connect_retries: int = 1,
        connect_backoff_s: float = 0.05,
        timeout_s: Optional[float] = None,
        tenant: Optional[str] = None,
    ):
        """``max_inflight_bytes`` caps the pipelined window's in-flight
        REQUEST bytes (deadlock guard, see ``evaluate_many``).  The
        default (None) is ADAPTIVE: at least the classic 32 KiB, grown
        to fit a few copies of the first encoded request — so a
        workload whose single request exceeds 32 KiB does not silently
        degrade to lock-step — and clamped to the socket's send-buffer
        size (the actual deadlock boundary).

        ``connect_timeout_s`` bounds each initial-connect attempt (the
        old hard-coded 30 s, now a knob: a pool sweeping replicas wants
        sub-second verdicts); ``connect_retries`` re-attempts a failed
        connect with a ``connect_backoff_s`` pause between tries —
        exhaustion raises :class:`ConnectionError`, which every caller
        (the retry loop here, the replica pool's ``is_transient``)
        classifies as transport trouble, so failover proceeds cleanly.

        ``timeout_s`` bounds each reply read; with an ambient deadline
        bound (:mod:`.deadline`) the read is ALSO capped at the
        remaining budget, so a node that accepts then never replies
        fails over within the caller's deadline instead of blocking
        until the watchdog fires.  A fired bound closes the
        (desynchronized) connection and surfaces as ``TimeoutError`` —
        an ``OSError``, i.e. the transient classification every retry
        loop and pool already fails over on.

        ``tenant`` stamps every request with a tenant id (npwire flag
        bit 32) — the identity the gateway tier meters quotas and
        weighted-fair service by; ``None`` (the default) keeps every
        frame byte-identical to the pre-tenant wire."""
        self.host = host
        self.port = int(port)
        self.retries = retries
        self.tenant = tenant
        self.max_inflight_bytes = max_inflight_bytes
        self.timeout_s = None if timeout_s is None else float(timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.connect_retries = int(connect_retries)
        self.connect_backoff_s = float(connect_backoff_s)
        self._sock: Optional[socket.socket] = None
        self._rfile = None  # buffered reader over _sock
        # Per-connection batch-frame capability (None = not probed).
        self._batch_ok: Optional[bool] = None

    @property
    def _peer(self) -> str:
        return f"{self.host}:{self.port}"

    def _connect(self) -> socket.socket:
        if self._sock is None:
            last_err: Optional[Exception] = None
            for attempt in range(self.connect_retries + 1):
                if attempt:
                    time.sleep(self.connect_backoff_s)
                try:
                    s = socket.create_connection(
                        (self.host, self.port),
                        timeout=self.connect_timeout_s,
                    )
                    break
                except (ConnectionError, OSError) as e:
                    last_err = e
            else:
                raise ConnectionError(
                    f"connect to {self._peer} failed after "
                    f"{self.connect_retries + 1} attempts "
                    f"(timeout {self.connect_timeout_s}s)"
                ) from last_err
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            # Buffered reads: a frame costs one length + one payload
            # read from the buffer instead of 2+ raw recv syscalls.
            self._rfile = s.makefile("rb")
        return self._sock

    def _read_frame(self) -> bytes:
        # Bounded read: the per-call timeout_s knob and the ambient
        # deadline, whichever is tighter, as a TOTAL bound across the
        # header+payload chunks; posture (expired-budget close,
        # TimeoutError close, socket-timeout restore) is the shared
        # _deadline.bounded_reader so the shm doorbell cannot diverge.
        assert self._sock is not None and self._rfile is not None
        with _deadline.bounded_reader(
            self._sock,
            self._rfile,
            _deadline.recv_budget_s(self.timeout_s),
            self.close,
        ) as read_exact:
            (n,) = _U32.unpack(read_exact(4))
            return read_exact(n)

    def close(self) -> None:
        if self._sock is not None:
            try:
                if self._rfile is not None:
                    try:
                        self._rfile.close()
                    except OSError:
                        pass
                self._sock.close()
            finally:
                self._sock = None
                self._rfile = None
                # Re-probe after reconnect: the peer may have been
                # replaced by a build without (or with) batch support.
                self._batch_ok = None

    def __del__(self):  # best-effort, mirrors client.py teardown
        try:
            self.close()
        except Exception:
            pass

    def evaluate(
        self,
        *arrays: np.ndarray,
        partition: Optional[Sequence[int]] = None,
    ) -> List[np.ndarray]:
        """One lock-step evaluation.  ``partition`` (keyword-only, a
        5-int sequence) requests the head/tail SLICED reply — the
        reply is ``[head, slice]`` with the block echoed; geometry
        disagreement surfaces as :class:`RemoteComputeError`
        (routing/partition.py owns the rule)."""
        outputs, _ver = self._evaluate_inner(arrays, partition, None)
        return outputs

    def evaluate_versioned(
        self,
        *arrays: np.ndarray,
        partition: Optional[Sequence[int]] = None,
        version: int,
    ) -> Tuple[List[np.ndarray], Optional[int]]:
        """One VERSIONED round trip (the sharded-optimizer lane,
        ISSUE 16) -> ``(outputs, reply_version)``.  The request
        carries ``version`` as its u64 step stamp (flag bit 128;
        zero is meaningful — the init handshake) and ``partition``
        as the owned-shard geometry; the node's ``versioned_update``
        handler answers shard-shaped outputs stamped with the NEW
        version.  A stale stamp surfaces as
        :class:`RemoteComputeError` carrying the node's loud
        refusal (optim/sharded.py classifies it)."""
        return self._evaluate_inner(arrays, partition, version)

    def _evaluate_inner(
        self,
        arrays: Sequence[np.ndarray],
        partition: Optional[Sequence[int]],
        version: Optional[int],
    ) -> Tuple[List[np.ndarray], Optional[int]]:
        with _spans.span("rpc.evaluate", transport="tcp"):
            with _spans.span("encode"):
                uid = fast_uuid()
                trace_id = (
                    _spans.current_trace_id() if _spans.enabled() else None
                )
                _deadline.check_remaining("tcp evaluate")
                # Scatter/gather encode: the frame stays a buffer
                # vector (header bytes + views of the input arrays)
                # until sendmsg hands the pieces to the kernel — no
                # contiguous-frame copy.  ``norm`` outlives the send,
                # so the views stay valid across retries.
                norm = [np.asarray(a) for a in arrays]
                request = encode_arrays_sg(
                    norm,
                    uuid=uid,
                    trace_id=trace_id,
                    deadline_s=_deadline.wire_budget(),
                    tenant=self.tenant,
                    partition=partition,
                    version=version,
                )
                request_len = sg_nbytes(request)
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.retry", transport="tcp", attempt=attempt
                    )
                    # A spent budget must stop the reconnect loop: a
                    # retry past it can only add load, never an answer
                    # the caller is still waiting for.
                    _deadline.check_remaining("tcp retry")
                    # Restamp the REMAINING budget: re-sending the
                    # attempt-0 frame would advertise the budget as it
                    # stood before the failed attempts burned wall
                    # time, so the server would admit (and the batcher
                    # keep) work whose caller is closer to giving up
                    # than the wire claims.
                    budget = _deadline.wire_budget()
                    if budget is not None:
                        request = encode_arrays_sg(
                            norm,
                            uuid=uid,
                            trace_id=trace_id,
                            deadline_s=budget,
                            tenant=self.tenant,
                            partition=partition,
                            version=version,
                        )
                        request_len = sg_nbytes(request)
                t0 = time.perf_counter()
                try:
                    with _spans.span("call"):
                        sock = self._connect()
                        if _fi.active_plan is not None:  # chaos seam
                            _fi.send_frame_through(
                                "tcp.send", sock.sendall,
                                b"".join(request),
                                peer=self._peer,
                            )
                        else:
                            _send_frame_vec(sock, request, request_len)
                        reply = self._read_frame()
                        if _fi.active_plan is not None:  # chaos seam
                            reply = _fi.filter_bytes(
                                "tcp.recv", reply, self._peer
                            )
                    break
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.drop", transport="tcp",
                        peer=f"{self.host}:{self.port}",
                    )
                    self.close()
            else:
                raise ConnectionError(
                    f"node {self.host}:{self.port} unreachable after "
                    f"{self.retries + 1} attempts"
                ) from last_err
            with _spans.span("decode"):
                try:
                    (
                        outputs, reply_uid, error, _tid, node_spans,
                        _rpart, reply_version,
                    ) = decode_arrays_part(reply)
                except Exception:
                    # Corrupt reply: close so the NEXT call reconnects
                    # cleanly instead of trusting a connection whose
                    # framing already lied once — same posture as the
                    # pipelined pass; the WireError surfaces loudly.
                    _DROPS.labels(transport="tcp").inc()
                    self.close()
                    raise
                if node_spans:
                    _reunion.ingest(node_spans)
            _CALL_S.labels(transport="tcp", mode="lockstep").observe(
                time.perf_counter() - t0
            )
            if error is not None:
                _flightrec.record(
                    "rpc.error", transport="tcp", error=error[:200]
                )
                if _deadline.is_deadline_error(error):
                    raise _deadline.DeadlineExceeded(error)
                raise RemoteComputeError(error)
            if reply_uid != uid:
                # A mismatched reply means this connection is
                # desynchronized (e.g. stale frames left by an aborted
                # batch) — close it so the NEXT call reconnects cleanly
                # instead of reading stale frames forever, matching
                # _evaluate_many_once (ADVICE r5 #3).
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise RuntimeError(
                    "uuid mismatch: reply does not match request"
                )
            return outputs, reply_version

    __call__ = evaluate

    # Default in-flight REQUEST bytes cap: keeps every sendall
    # completable so the pipelining loop always reaches its read —
    # without it, a write-only burst can fill both sockets' buffers
    # against a server blocked sending replies nobody reads (the same
    # deadlock geometry as HTTP/2 flow control on the gRPC lane,
    # client.py).  The EFFECTIVE cap is _inflight_cap(): constructor
    # knob, else adaptively sized from the first encoded request.
    _DEFAULT_INFLIGHT_BYTES = 32 * 1024

    def _inflight_cap(self, first_frame_len: int) -> int:
        """Effective in-flight byte cap for one pipelined pass."""
        if self.max_inflight_bytes is not None:
            return int(self.max_inflight_bytes)
        # Adaptive default: room for ~4 copies of the first request so
        # large-array workloads still overlap, clamped to HALF the
        # reported socket send buffer — Linux getsockopt(SO_SNDBUF)
        # returns the doubled bookkeeping value with only about half
        # usable for payload, and the cap's whole job is "every
        # sendall completable", so the clamp must undershoot.
        cap = max(self._DEFAULT_INFLIGHT_BYTES, 4 * first_frame_len)
        try:
            sndbuf = self._connect().getsockopt(
                socket.SOL_SOCKET, socket.SO_SNDBUF
            )
            if sndbuf > 1:
                # No floor after this clamp: an operator-shrunk send
                # buffer must WIN (a cap above it re-opens the
                # deadlock); a cap below one frame just degrades to
                # the proven-safe lock-step mode via the lone-frame
                # disjunct.
                cap = min(cap, sndbuf // 2)
        except OSError:
            pass
        return max(cap, 1)

    def _probe_batch(self) -> bool:
        """One-shot capability negotiation: a ZERO-item batch frame is
        the probe.  A batch-aware peer echoes an empty batch reply
        with the probe's uuid; a pre-batch peer (old C++ node) parses
        the frame as zero arrays or answers a decode-error frame —
        either way not a batch frame, so the answer is False and the
        client never coalesces toward it.  Cached per connection
        (``close()`` resets it)."""
        if self._batch_ok is None:
            sock = self._connect()
            uid = fast_uuid()
            _send_frame(sock, encode_batch([], uuid=uid))
            reply = self._read_frame()
            ok = False
            if is_batch_frame(reply):
                try:
                    items, ruid, err, _tid, _sp = decode_batch(reply)
                    ok = ruid == uid and err is None and not items
                # Capability NEGOTIATION: an undecodable echo means the
                # peer is pre-batch — the loud in-band verdict is
                # "capability absent", never an exception.
                except Exception:  # graftlint: disable=wire-loudness -- negotiation verdict lane
                    ok = False
            self._batch_ok = ok
            _flightrec.record(
                "rpc.batch_capability", transport="tcp", ok=ok,
                peer=f"{self.host}:{self.port}",
            )
        return self._batch_ok

    def evaluate_many(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[List[np.ndarray]]:
        """Pipelined batch over the SAME lock-step connection.

        The frame protocol is strictly FIFO per connection (the C++
        node's loop is recv -> compute -> send, native/cpp_node.cpp),
        so up to ``window`` requests stay in flight and replies
        correlate by order + per-frame uuid — client encode, both
        network legs, and node compute overlap.  Oversized requests
        degrade to lock-step via the byte cap (one in flight, the
        proven-safe per-call mode).

        Same semantics as the gRPC lane's ``evaluate_many``:
        all-or-nothing TRANSPORT retry (reconnect, re-run the whole
        batch); a server error reply raises
        :class:`RemoteComputeError` without retry after draining the
        in-flight replies so the connection stays correlated.

        ``batch``: "auto" (default) packs the window into wire BATCH
        FRAMES — ``min(window, 32)`` requests per frame — when the
        peer answers the zero-item probe frame (:meth:`_probe_batch`);
        the TCP protocol has no GetLoad, so the probe IS the
        capability negotiation.  ``False`` forces per-call frames;
        ``True`` requires support and raises if the peer lacks it.
        """
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        # Identity checks, not equality: 0/1 would pass an `in` test
        # (0 == False) yet route down the WRONG branch below.
        if batch != "auto" and batch is not True and batch is not False:
            raise ValueError(
                f"batch must be 'auto', True or False, got {batch!r}"
            )
        with _spans.span(
            "rpc.evaluate_many",
            transport="tcp",
            n=len(requests),
            window=window,
        ):
            with _spans.span("encode"):
                trace_id = (
                    _spans.current_trace_id() if _spans.enabled() else None
                )
                # (buffer-vector, frame length, uuid) per request: the
                # scatter/gather form survives until sendmsg (or, on
                # the batch-frame path, until the frames are packed).
                budget = _deadline.wire_budget()
                encoded = []
                for args in requests:
                    uid = fast_uuid()
                    parts = encode_arrays_sg(
                        [np.asarray(a) for a in args],
                        uuid=uid,
                        trace_id=trace_id,
                        deadline_s=budget,
                        tenant=self.tenant,
                    )
                    encoded.append((parts, sg_nbytes(parts), uid))
            if not encoded:
                return []
            t0 = time.perf_counter()
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.retry", transport="tcp", attempt=attempt,
                        batch=len(encoded),
                    )
                try:
                    use_batch = False
                    if batch is not False:
                        use_batch = self._probe_batch()
                        if batch is True and not use_batch:
                            raise RuntimeError(
                                f"node {self.host}:{self.port} does not "
                                "answer the batch-frame probe"
                            )
                    # Known wedge point: a pipelined window against a
                    # stalled peer can block in read — armed so a hang
                    # leaves an incident bundle (telemetry.watchdog).
                    with _watchdog.armed(
                        "tcp.batch_window", n=len(encoded), window=window
                    ):
                        if use_batch:
                            results = self._evaluate_many_batched_once(
                                encoded, window, trace_id
                            )
                        else:
                            results = self._evaluate_many_once(
                                encoded, window
                            )
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.drop", transport="tcp",
                        peer=f"{self.host}:{self.port}",
                    )
                    self.close()
                    continue
                _BATCH_S.labels(transport="tcp").observe(
                    time.perf_counter() - t0
                )
                return results
            raise ConnectionError(
                f"node {self.host}:{self.port} unreachable after "
                f"{self.retries + 1} attempts"
            ) from last_err

    def evaluate_many_partial(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ):
        """ONE pipelined pass with no reconnect-retry, surfacing
        partial progress: ``(results, transport_exc)`` with ``None``
        holes for requests whose reply never arrived — the failover
        primitive the replica pool (routing/) re-queues from, mirror
        of the gRPC client's ``evaluate_many_partial_async``.
        Deterministic server errors (:class:`RemoteComputeError`,
        corrupt frames, uuid desync) raise; only a dead/failed socket
        is returned as ``transport_exc``."""
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if batch != "auto" and batch is not True and batch is not False:
            raise ValueError(
                f"batch must be 'auto', True or False, got {batch!r}"
            )
        with _spans.span(
            "rpc.evaluate_many",
            transport="tcp",
            n=len(requests),
            window=window,
            partial=True,
        ):
            with _spans.span("encode"):
                trace_id = (
                    _spans.current_trace_id() if _spans.enabled() else None
                )
                # (buffer-vector, frame length, uuid) per request: the
                # scatter/gather form survives until sendmsg (or, on
                # the batch-frame path, until the frames are packed).
                budget = _deadline.wire_budget()
                encoded = []
                for args in requests:
                    uid = fast_uuid()
                    parts = encode_arrays_sg(
                        [np.asarray(a) for a in args],
                        uuid=uid,
                        trace_id=trace_id,
                        deadline_s=budget,
                        tenant=self.tenant,
                    )
                    encoded.append((parts, sg_nbytes(parts), uid))
            if not encoded:
                return [], None
            out: List[Optional[List[np.ndarray]]] = [None] * len(encoded)
            t0 = time.perf_counter()
            try:
                use_batch = False
                if batch is not False:
                    use_batch = self._probe_batch()
                    if batch is True and not use_batch:
                        raise RuntimeError(
                            f"node {self.host}:{self.port} does not "
                            "answer the batch-frame probe"
                        )
                with _watchdog.armed(
                    "tcp.batch_window", n=len(encoded), window=window
                ):
                    if use_batch:
                        self._evaluate_many_batched_once(
                            encoded, window, trace_id, out=out
                        )
                    else:
                        self._evaluate_many_once(encoded, window, out=out)
            except (ConnectionError, OSError) as e:
                _DROPS.labels(transport="tcp").inc()
                _flightrec.record(
                    "rpc.drop", transport="tcp",
                    peer=f"{self.host}:{self.port}",
                )
                self.close()
                return out, e
            _BATCH_S.labels(transport="tcp").observe(
                time.perf_counter() - t0
            )
            return out, None

    def evaluate_reduced(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        slices: int = 1,
        total: Optional[int] = None,
    ) -> List[np.ndarray]:
        """Reduce-scatter evaluation: ``[head_sum, flat_tail_sum]``.

        The window rides REDUCE batch frames (outer partition block):
        the node sums each frame's item replies — head (reply array 0)
        summed whole, tails flat-concatenated — and returns the sum as
        ``slices`` partition-indexed slices, reassembled here with the
        loud :class:`~..routing.partition.Reassembler` rules; partial
        sums from multiple frames are summed locally.  Wire bytes per
        reply drop from ``n_requests × tail_size`` to
        ``n_frames × tail_size`` — the ISSUE-13 bandwidth story.

        ``slices > 1`` splits each frame's reply into that many
        partition-indexed items (gradients larger than one reply frame
        stream home in pieces); ``total``, when given, is validated
        against the node's actual flat tail size (a driver/node shape
        disagreement fails in-band instead of mis-assembling).

        Deterministic server errors raise
        :class:`RemoteComputeError`/:class:`WireError` after a drain;
        transport trouble retries like :meth:`evaluate_many`."""
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if slices < 1:
            raise ValueError(f"slices must be >= 1, got {slices}")
        requests = list(requests)
        if not requests:
            raise _partition.PartitionError(
                "cannot reduce an empty request list"
            )
        with _spans.span(
            "rpc.evaluate_reduced",
            transport="tcp",
            n=len(requests),
            slices=slices,
        ):
            t0 = time.perf_counter()
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.retry", transport="tcp", attempt=attempt,
                        batch=len(requests),
                    )
                    _deadline.check_remaining("tcp reduce retry")
                try:
                    with _watchdog.armed(
                        "tcp.reduce_window",
                        n=len(requests),
                        window=window,
                    ):
                        result = self._evaluate_reduced_once(
                            requests, window, slices, total
                        )
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.drop", transport="tcp", peer=self._peer
                    )
                    self.close()
                    continue
                _BATCH_S.labels(transport="tcp").observe(
                    time.perf_counter() - t0
                )
                return result
            raise ConnectionError(
                f"node {self.host}:{self.port} unreachable after "
                f"{self.retries + 1} attempts"
            ) from last_err

    def _evaluate_reduced_once(self, requests, window, slices, total):
        sock = self._connect()
        trace_id = _spans.current_trace_id() if _spans.enabled() else None
        chunk = max(1, min(window, self._BATCH_CHUNK))
        req_part = (0, slices, 0, 0, 0 if total is None else int(total))
        head: Optional[np.ndarray] = None
        flat: Optional[np.ndarray] = None
        # Lock-step per frame on purpose: reduce replies are tiny (one
        # tail regardless of window width), so pipelining frames buys
        # little and the one-in-flight mode keeps the drain trivial.
        # Each frame encodes AT SEND TIME so it stamps the budget as
        # it stands after the earlier frames' wall time (the ISSUE-10
        # restamp posture; the shm twin does the same).
        for start in range(0, len(requests), chunk):
            part_reqs = requests[start : start + chunk]
            outer_uuid = fast_uuid()
            frame = encode_batch(
                [
                    encode_arrays(
                        [np.asarray(a) for a in args], uuid=fast_uuid()
                    )
                    for args in part_reqs
                ],
                uuid=outer_uuid,
                trace_id=trace_id,
                deadline_s=_deadline.wire_budget(),
                partition=req_part,
            )
            _FRAME_REQS.labels(transport="tcp").observe(len(part_reqs))
            if _fi.active_plan is not None:  # chaos seam
                _fi.send_frame_through(
                    "tcp.send", sock.sendall, frame, peer=self._peer
                )
            else:
                _send_frame(sock, frame)
            reply = self._read_frame()
            if _fi.active_plan is not None:  # chaos seam
                reply = _fi.filter_bytes("tcp.recv", reply, self._peer)
            f_head, f_flat = self._consume_reduce_reply(
                reply, outer_uuid, slices, total
            )
            if head is None:
                head, flat = f_head, f_flat
            else:
                if (
                    f_head.shape != head.shape
                    or f_flat.size != flat.size
                ):
                    self.close()
                    raise WireError(
                        "reduce frames disagree on reply geometry"
                    )
                head = head + f_head
                flat = flat + f_flat
        return [head, flat]

    def _consume_reduce_reply(self, reply, outer_uuid, slices, total):
        """One reduce reply frame -> (head_sum, flat_vector); loud on
        every anomaly (the Reassembler rules), closing the connection
        so the NEXT call reconnects cleanly."""
        try:
            items, ruid, outer_err, _tid, node_spans, rpart, _ver = (
                decode_batch_part(reply)
            )
            if node_spans:
                _reunion.ingest(node_spans)
        except Exception:
            _DROPS.labels(transport="tcp").inc()
            self.close()
            raise
        if outer_err is not None:
            if _deadline.is_deadline_error(outer_err):
                raise _deadline.DeadlineExceeded(outer_err)
            raise RemoteComputeError(outer_err)
        try:
            if ruid != outer_uuid:
                raise WireError(
                    "reduce reply does not correlate with its frame"
                )
            if rpart is None:
                raise _partition.PartitionError(
                    "reduce reply carries no partition block"
                )
            _i, count, _o, _l, r_total = rpart
            if count != slices or (
                total is not None and r_total != int(total)
            ):
                raise _partition.PartitionError(
                    f"reduce reply geometry ({count}, {r_total}) does "
                    f"not match the request ({slices}, {total})"
                )
            if len(items) != slices:
                raise _partition.PartitionError(
                    f"reduce reply carries {len(items)} slices, "
                    f"requested {slices}"
                )
            head: Optional[np.ndarray] = None
            reassembler: Optional[_partition.Reassembler] = None
            for item in items:
                arrays, _uid, err, _t, _sp, ipart, _iv = (
                    decode_arrays_part(item)
                )
                if err is not None:
                    raise RemoteComputeError(err)
                if ipart is None:
                    raise _partition.PartitionError(
                        "reduce reply item carries no partition block"
                    )
                p = _partition.GradPartition(*ipart).validate()
                # Cross-check the ITEM's block against the OUTER block
                # (itself validated against the request) BEFORE the
                # geometry sizes anything: a corrupt item total would
                # otherwise size the reassembly buffer — an
                # attacker/chaos-chosen allocation instead of the
                # contracted loud refusal.
                if p.count != count or p.total != r_total:
                    raise _partition.PartitionError(
                        f"reduce item geometry ({p.count}, {p.total}) "
                        f"does not match the window ({count}, {r_total})"
                    )
                if p.index == 0:
                    if len(arrays) != 2:
                        raise _partition.PartitionError(
                            "reduce reply item 0 must be [head, slice]"
                        )
                    head = arrays[0]
                    slice_arr = arrays[1]
                else:
                    if len(arrays) != 1:
                        raise _partition.PartitionError(
                            "reduce reply items 1.. must be [slice]"
                        )
                    slice_arr = arrays[0]
                if reassembler is None:
                    reassembler = _partition.Reassembler(
                        p.total,
                        p.count,
                        np.asarray(slice_arr).dtype
                        if np.asarray(slice_arr).size
                        else np.dtype(np.float64),
                    )
                reassembler.add(p, np.asarray(slice_arr), iuid=_uid.hex())
            assert reassembler is not None
            if head is None:
                raise _partition.PartitionError(
                    "reduce reply carried no head item (index 0)"
                )
            return head, reassembler.result()
        except RemoteComputeError:
            raise
        except (WireError, RuntimeError):
            # Mis-assembled / desynchronized reply: close so the NEXT
            # call reconnects cleanly; the error surfaces loudly.
            _DROPS.labels(transport="tcp").inc()
            self.close()
            raise

    def _evaluate_many_once(self, encoded, window, out=None):
        # ``out`` (optional, len(encoded) of None) is filled in place
        # as replies validate — the partial-progress channel
        # evaluate_many_partial / the replica pool's failover build on.
        # ``encoded`` entries are (buffer-vector, nbytes, uuid).
        sock = self._connect()
        n = len(encoded)
        max_inflight = self._inflight_cap(encoded[0][1])
        results: List[Optional[List[np.ndarray]]] = (
            out if out is not None else [None] * n
        )
        write_idx = read_idx = 0
        inflight_bytes = 0
        while read_idx < n:
            # Coalesce every writable frame into ONE sendmsg vector: on
            # localhost the per-call cost is syscall-dominated, so a
            # window of small frames pays one gather syscall — and the
            # array payloads ride as views, never joined in userspace.
            burst = []
            while write_idx < n and (
                write_idx == read_idx
                or (
                    write_idx - read_idx < window
                    and inflight_bytes + encoded[write_idx][1]
                    <= max_inflight
                )
            ):
                parts, nbytes, _uid = encoded[write_idx]
                burst.append((parts, nbytes))
                inflight_bytes += nbytes
                write_idx += 1
            if burst:
                if _fi.active_plan is not None:  # chaos seam: per frame
                    for parts, _nb in burst:
                        _fi.send_frame_through(
                            "tcp.send", sock.sendall, b"".join(parts),
                            peer=self._peer,
                        )
                else:
                    vec = []
                    for parts, nbytes in burst:
                        vec.append(_U32.pack(nbytes))
                        vec.extend(parts)
                    _sendmsg_all(sock, vec)
            _WINDOW_DEPTH.labels(transport="tcp").observe(
                write_idx - read_idx
            )
            reply = self._read_frame()
            if _fi.active_plan is not None:  # chaos seam
                reply = _fi.filter_bytes("tcp.recv", reply, self._peer)
            _parts, request_len, uid = encoded[read_idx]
            inflight_bytes -= request_len
            try:
                outputs, reply_uid, error, _tid, node_spans = (
                    decode_arrays_all(reply)
                )
                if node_spans:
                    _reunion.ingest(node_spans)
            except Exception:
                # Corrupt payload with replies still in flight: the
                # connection cannot be trusted to stay correlated —
                # close so the NEXT call reconnects cleanly, and let
                # the WireError surface loudly (CLAUDE.md invariant).
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise
            if error is not None:
                # Drain so the connection stays correlated for the
                # NEXT call, then surface the deterministic error.  If
                # the drain itself fails, the leftover in-flight
                # replies would poison later calls with stale frames —
                # close instead of leaving a desynchronized socket.
                try:
                    for _ in range(write_idx - read_idx - 1):
                        self._read_frame()
                except (ConnectionError, OSError):
                    _DROPS.labels(transport="tcp").inc()
                    self.close()
                if _deadline.is_deadline_error(error):
                    raise _deadline.DeadlineExceeded(error)
                raise RemoteComputeError(error)
            if reply_uid != uid:
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise RuntimeError(
                    "uuid mismatch: reply does not match request"
                )
            results[read_idx] = outputs
            read_idx += 1
        return results

    _BATCH_CHUNK = 32  # requests per batch frame (server-side max_batch)

    def _evaluate_many_batched_once(self, encoded, window, trace_id,
                                    out=None):
        """One pipelined pass using wire batch frames: the window is
        packed ``min(window, 32)`` requests per frame — one syscall,
        one node decode loop, one (possibly vmapped) dispatch per
        frame.  Per-item uuids still correlate; the first item error
        drains the in-flight frames and raises RemoteComputeError
        without retry (same semantics as the unbatched pass).
        ``out`` is the in-place partial-progress channel (frame-
        granular), as in ``_evaluate_many_once``."""
        sock = self._connect()
        n = len(encoded)
        chunk = max(1, min(window, self._BATCH_CHUNK))
        frames = []  # (frame_bytes, outer_uuid, start, part)
        for start in range(0, n, chunk):
            part = encoded[start : start + chunk]
            outer_uuid = fast_uuid()
            # Batch frames nest COMPLETE item frames, so the
            # scatter/gather vectors are joined here — one flattening
            # per item, same count as the pre-sendmsg wire.
            # The server peeks the OUTER frame only (serve_npwire
            # _payload), so admission and the ambient budget ride the
            # batch frame's deadline — same contract as the gRPC
            # lane's _encode_batch_frame and the shm doorbell.
            frame = encode_batch(
                [
                    req[0] if len(req) == 1 and isinstance(req[0], bytes)
                    else b"".join(req)
                    for req, _nb, _u in part
                ],
                uuid=outer_uuid,
                trace_id=trace_id,
                deadline_s=_deadline.wire_budget(),
            )
            _FRAME_REQS.labels(transport="tcp").observe(len(part))
            frames.append((frame, outer_uuid, start, part))
        results: List[Optional[List[np.ndarray]]] = (
            out if out is not None else [None] * n
        )
        nf = len(frames)
        max_inflight = self._inflight_cap(len(frames[0][0]))
        write_idx = read_idx = 0
        inflight_bytes = 0
        while read_idx < nf:
            burst = []
            while write_idx < nf and (
                write_idx == read_idx
                or inflight_bytes + len(frames[write_idx][0])
                <= max_inflight
            ):
                payload = frames[write_idx][0]
                burst.append(payload)
                inflight_bytes += len(payload)
                write_idx += 1
            if burst:
                if _fi.active_plan is not None:  # chaos seam: per frame
                    for payload in burst:
                        _fi.send_frame_through(
                            "tcp.send", sock.sendall, payload,
                            peer=self._peer,
                        )
                else:
                    # One gather syscall, no userspace concat copy.
                    vec = []
                    for p in burst:
                        vec.append(_U32.pack(len(p)))
                        vec.append(p)
                    _sendmsg_all(sock, vec)
            _WINDOW_DEPTH.labels(transport="tcp").observe(
                write_idx - read_idx
            )
            reply = self._read_frame()
            if _fi.active_plan is not None:  # chaos seam
                reply = _fi.filter_bytes("tcp.recv", reply, self._peer)
            frame, outer_uuid, start, part = frames[read_idx]
            inflight_bytes -= len(frame)
            try:
                items, ruid, outer_err, _tid, node_spans = decode_batch(
                    reply
                )
                if node_spans:
                    _reunion.ingest(node_spans)
            except Exception:
                # Corrupt reply with frames still in flight: close so
                # the NEXT call reconnects cleanly; the WireError
                # surfaces loudly (CLAUDE.md invariant).
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise
            # Outer error FIRST: outer-level failures carry a zeroed
            # uuid (serve_tcp_once / cpp_node batch_error_reply), so a
            # uuid-first check would misreport them as correlation
            # failures.
            first_error = outer_err
            if first_error is None and (
                ruid != outer_uuid or len(items) != len(part)
            ):
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise RuntimeError(
                    "batch reply does not correlate with its frame"
                )
            if first_error is None:
                for j, (item, (_req, _nb, uid)) in enumerate(
                    zip(items, part)
                ):
                    try:
                        outputs, reply_uid, error, _t, item_spans = (
                            decode_arrays_all(item)
                        )
                    except Exception:
                        # Corrupt nested item with frames still in
                        # flight: same posture as a corrupt reply —
                        # close so the NEXT call reconnects cleanly.
                        _DROPS.labels(transport="tcp").inc()
                        self.close()
                        raise
                    if item_spans:
                        _reunion.ingest(item_spans)
                    if error is not None:
                        first_error = error
                        break
                    if reply_uid != uid:
                        _DROPS.labels(transport="tcp").inc()
                        self.close()
                        raise RuntimeError(
                            "uuid mismatch: batch item does not match "
                            "its request"
                        )
                    results[start + j] = outputs
            if first_error is not None:
                # Drain in-flight frames so the connection stays
                # correlated for the NEXT call, then surface the
                # deterministic error (no retry).
                try:
                    for _ in range(write_idx - read_idx - 1):
                        self._read_frame()
                except (ConnectionError, OSError):
                    _DROPS.labels(transport="tcp").inc()
                    self.close()
                if _deadline.is_deadline_error(first_error):
                    raise _deadline.DeadlineExceeded(first_error)
                raise RemoteComputeError(first_error)
            read_idx += 1
        return results


def _serve_batch_payload(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    payload: bytes,
    *,
    transport: str = "tcp",
    request_views: bool = False,
) -> bytes:
    """One npwire batch frame in -> one batch frame out, per-item
    error isolation — the TCP server twin of the gRPC service's
    ``_run_batch_npwire`` (a zero-item frame is the capability probe
    and echoes an empty batch reply).  A same-signature window runs
    through the compute's ``.batch`` variant when present (one vmapped
    call), with scalar fallback on failure."""
    t_arrive = time.perf_counter()
    try:
        items, outer_uuid, _err, trace_id, _sp = decode_batch(payload)
    except Exception as e:
        _node_metrics.ERRORS.labels(kind="decode").inc()
        return encode_batch(
            [], uuid=b"\0" * 16, error=f"decode error: {e}"
        )
    t_decoded = time.perf_counter()
    # Zero-item frames are the pool's capability/health probe: they
    # must not feed the latency histograms the fleet plane merges, or
    # 1/s probe cadence dilutes every quantile toward the probe floor.
    is_probe = not items
    if not is_probe:
        _node_metrics.DECODE_S.observe(t_decoded - t_arrive)
    batch_fn = getattr(compute_fn, "batch", None)
    with _spans.trace_context(trace_id), _spans.span(
        "node.evaluate_batch", wire="npwire", transport=transport,
        n_items=len(items),
    ) as root:
        root.set_attr("decode_s", t_decoded - t_arrive)
        if _fi.active_plan is not None:  # chaos seam: compute path
            try:
                _fi.compute_filter()
            except _fi.FaultPlanError:
                raise  # a plan-authoring bug stays LOUD, never in-band
            except Exception as e:
                # In-band, frame-level: the injected compute failure
                # covers the whole window, exactly like a real one
                # raised before per-item dispatch.
                return encode_batch(
                    [], uuid=outer_uuid, error=str(e)
                )
        replies: List[Optional[bytes]] = [None] * len(items)
        decoded = []  # (slot, arrays, uuid)
        t_i0 = time.perf_counter()
        for i, item in enumerate(items):
            try:
                arrays, uid, _, _ = decode_arrays_ex(
                    item, copy=not request_views
                )
                decoded.append((i, arrays, uid))
            except Exception as e:
                _node_metrics.ERRORS.labels(kind="decode").inc()
                replies[i] = encode_arrays(
                    [], uuid=b"\0" * 16, error=f"decode error: {e}"
                )
        # Per-item decode is decode, not queue wait — book it in the
        # decode family so a decode-bound batch node shows up in the
        # fleet view as decode-bound, not queue-bound.
        item_decode_s = time.perf_counter() - t_i0
        if not is_probe:
            _node_metrics.DECODE_S.observe(item_decode_s)
        # Single source for dispatch semantics (vmapped-first, result
        # count validation, scalar fallback, per-item isolation):
        # batching.execute_window_sync — the sync twin of the gRPC
        # service's MicroBatcher path.
        t_c0 = time.perf_counter()
        if not is_probe:
            _node_metrics.QUEUE_S.observe(
                max(0.0, t_c0 - t_decoded - item_decode_s)
            )
        outcomes = _execute_window_sync(
            compute_fn, batch_fn, [arrs for _, arrs, _ in decoded]
        )
        if not is_probe:
            _node_metrics.COMPUTE_S.observe(time.perf_counter() - t_c0)
        t_e0 = time.perf_counter()
        for (i, _arrs, uid), res in zip(decoded, outcomes):
            if isinstance(res, Exception):
                _node_metrics.ERRORS.labels(kind="compute").inc()
                _flightrec.record(
                    "server.error", stage="compute", wire="npwire",
                    transport=transport, error=str(res)[:200],
                )
                replies[i] = encode_arrays([], uuid=uid, error=str(res))
            else:
                replies[i] = encode_arrays(
                    [np.asarray(o) for o in res], uuid=uid
                )
        reply = encode_batch(replies, uuid=outer_uuid)
        if not is_probe:
            _node_metrics.ENCODE_S.observe(time.perf_counter() - t_e0)
    if trace_id is not None and root.span is not None:
        reply = append_spans(reply, [root.span.to_dict()])
    return reply


def _serve_plain_payload(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    payload: bytes,
    *,
    transport: str = "tcp",
    request_views: bool = False,
) -> bytes:
    """One plain npwire frame in -> one reply frame out: decode,
    compute, encode, with in-band error replies and the reunion spans
    piggyback.  Shared by the TCP accept loop and the shm doorbell's
    npwire fallback lane (probes from a mixed pool).

    ``request_views=True`` decodes request arrays as READ-ONLY
    frombuffer views into the frame — one payload copy saved per
    request, at the cost of breaking compute_fns that mutate their
    inputs in place; the historical owned-copy semantics stay the
    default.

    A request PARTITION block (npwire flag bit 64) asks for the
    head/tail SLICED reply (routing/partition.py's rule: array 0
    whole, arrays 1.. flat-concatenated and sliced to the requested
    element range, the block echoed on the reply).  Geometry or shape
    disagreement is answered in-band, loudly — never a mis-sliced
    gradient."""
    t_arrive = time.perf_counter()
    try:
        arrays, uid, _err, trace_id, _sp, part, step_version = (
            decode_arrays_part(payload, copy=not request_views)
        )
    except Exception as e:
        # A corrupt request fails ITS reply in-band and the connection
        # keeps serving — a hostile or chaos-mangled frame must not
        # tear down the node (mirror of cpp_node's serve_plain).
        _node_metrics.ERRORS.labels(kind="decode").inc()
        _flightrec.record(
            "server.error", stage="decode",
            wire="npwire", transport=transport,
            error=str(e)[:200],
        )
        return encode_arrays(
            [], uuid=b"\0" * 16, error=f"decode error: {e}"
        )
    t_decoded = time.perf_counter()
    _node_metrics.DECODE_S.observe(t_decoded - t_arrive)
    # Node-side spans adopt the driver's wire trace id,
    # same contract as the gRPC server (server.py).
    with _spans.trace_context(trace_id), _spans.span(
        "node.evaluate", wire="npwire", transport=transport
    ) as root:
        root.set_attr("decode_s", t_decoded - t_arrive)
        try:
            if _fi.active_plan is not None:  # chaos seam
                _fi.compute_filter()
            reply_version: Optional[int] = None
            with _spans.span("compute") as c_span:
                t_c0 = time.perf_counter()
                queue_wait = max(0.0, t_c0 - t_decoded)
                _node_metrics.QUEUE_S.observe(queue_wait)
                c_span.set_attr("queue_wait_s", queue_wait)
                if step_version is not None:
                    # Versioned sharded-optimizer lane (ISSUE 16): the
                    # handler owns slicing/versioning — outputs come
                    # back shard-shaped, stamped with the NEW version.
                    # A version stamp on a compute with no handler is a
                    # dispatch error, answered loudly in-band.
                    handler = getattr(
                        compute_fn, "versioned_update", None
                    )
                    if handler is None:
                        raise WireError(
                            "versioned request (flag bit 128) but this"
                            " node's compute has no versioned_update"
                            " handler"
                        )
                    outputs, reply_version = handler(
                        arrays, part, step_version
                    )
                    outputs = [np.asarray(o) for o in outputs]
                else:
                    outputs = [
                        np.asarray(o) for o in compute_fn(*arrays)
                    ]
                _node_metrics.COMPUTE_S.observe(
                    time.perf_counter() - t_c0
                )
            if part is not None and step_version is None:
                # Sliced reply (the scatter half of ISSUE 13): loud on
                # geometry/shape disagreement — the PartitionError is a
                # WireError and rides the in-band error arm below.
                outputs = _partition.slice_reply(
                    outputs, _partition.GradPartition(*part)
                )
            with _spans.span("encode"):
                t_e0 = time.perf_counter()
                reply = encode_arrays(
                    outputs, uuid=uid, partition=part,
                    version=reply_version,
                )
                _node_metrics.ENCODE_S.observe(
                    time.perf_counter() - t_e0
                )
        except _fi.FaultPlanError:
            raise  # plan-authoring bug: LOUD, never in-band
        except Exception as e:  # error -> error payload
            _node_metrics.ERRORS.labels(kind="compute").inc()
            _flightrec.record(
                "server.error", stage="compute",
                wire="npwire", transport=transport,
                error=str(e)[:200],
            )
            reply = encode_arrays([], uuid=uid, error=str(e))
    # Reunion piggyback: traced requests get this node's span tree on
    # the reply tail (untraced frames stay byte-identical).
    if trace_id is not None and root.span is not None:
        reply = append_spans(reply, [root.span.to_dict()])
    return reply


def _serve_reduce_payload(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    payload: bytes,
    *,
    transport: str = "tcp",
    request_views: bool = False,
) -> bytes:
    """One REDUCE window (batch frame + outer partition block) -> one
    batch reply of ``count`` partition-indexed slices.

    The reduce half of ISSUE 13: the node sums its window's item
    replies elementwise (head summed whole, tails flat-concatenated —
    :func:`..routing.partition.reduce_replies`) and answers the sum as
    ``count`` partition-indexed items: item 0 is ``[head_sum,
    slice_0]``, items 1.. are ``[slice_i]``, each stamped with its
    partition block, the outer reply echoing the (server-completed)
    request block.  A compute_fn carrying a ``.reduce`` attribute —
    the mid-tier AGGREGATOR contract
    (:func:`..routing.partition`-based tree lowering of ``fed_sum``) —
    is handed the whole window and returns the already-summed
    ``[head, *tails]``, so a tree node forwards ONE reduced child
    window instead of computing items itself.

    Failure is all-or-nothing and in-band: any item decode or compute
    error fails the WHOLE window loudly (an outer error reply) —
    summing around a failed item would be the silent partial sum the
    loud-reassembly contract forbids."""
    t_arrive = time.perf_counter()
    try:
        items, outer_uuid, _err, trace_id, _sp, part, _ver = (
            decode_batch_part(payload)
        )
        assert part is not None  # dispatched on peek_partition
        req_part = _partition.GradPartition(*part)
    except WireError as e:
        _node_metrics.ERRORS.labels(kind="decode").inc()
        return encode_batch(
            [], uuid=b"\0" * 16, error=f"decode error: {e}"
        )
    t_decoded = time.perf_counter()
    _node_metrics.DECODE_S.observe(t_decoded - t_arrive)
    with _spans.trace_context(trace_id), _spans.span(
        "node.evaluate_reduce", wire="npwire", transport=transport,
        n_items=len(items), count=req_part.count,
    ) as root:
        root.set_attr("decode_s", t_decoded - t_arrive)
        if _fi.active_plan is not None:  # chaos seam: compute path
            try:
                _fi.compute_filter()
            except _fi.FaultPlanError:
                raise  # a plan-authoring bug stays LOUD, never in-band
            except Exception as e:
                return encode_batch([], uuid=outer_uuid, error=str(e))
        try:
            if not items:
                raise _partition.PartitionError(
                    "cannot reduce an empty window"
                )
            decoded = []
            for item in items:
                arrays, _uid, _e, _t = decode_arrays_ex(
                    item, copy=not request_views
                )
                decoded.append(list(arrays))
            reduce_fn = getattr(compute_fn, "reduce", None)
            t_c0 = time.perf_counter()
            _node_metrics.QUEUE_S.observe(max(0.0, t_c0 - t_decoded))
            if reduce_fn is not None:
                summed = [np.asarray(o) for o in reduce_fn(decoded)]
            else:
                outcomes = _execute_window_sync(
                    compute_fn, getattr(compute_fn, "batch", None),
                    decoded,
                )
                for res in outcomes:
                    if isinstance(res, Exception):
                        # All-or-nothing: a failed item fails the
                        # whole reduction (no silent partial sum).
                        raise res
                summed = _partition.reduce_replies(outcomes)
            _node_metrics.COMPUTE_S.observe(time.perf_counter() - t_c0)
            t_e0 = time.perf_counter()
            _layout, total, _dtype = _partition.tail_layout(summed)
            if req_part.total and req_part.total != total:
                raise _partition.PartitionError(
                    f"partition total {req_part.total} != window tail "
                    f"size {total} (driver/node shape disagreement)"
                )
            plan = _partition.plan_partitions(total, req_part.count)
            flat = _partition.concat_tail(summed)
            replies = []
            for p in plan:
                arrs = [flat[p.offset : p.offset + p.length]]
                if p.index == 0:
                    arrs.insert(0, np.asarray(summed[0]))
                replies.append(
                    encode_arrays(arrs, uuid=outer_uuid, partition=p)
                )
                _partition.PARTITION_SHARDS.labels(outcome="ok").inc()
            if _fi.active_plan is not None:  # chaos seam: shard lane
                # block_off: item frames carry flags=PARTITION only,
                # so the partition block sits right after the fixed
                # 26-byte npwire header.
                replies = _fi.shard_filter(
                    "partition.reply", replies, block_off=26
                )
            reply = encode_batch(
                replies,
                uuid=outer_uuid,
                partition=_partition.GradPartition(
                    0, req_part.count, 0, total, total
                ),
            )
            _node_metrics.ENCODE_S.observe(time.perf_counter() - t_e0)
        except _fi.FaultPlanError:
            raise  # plan-authoring bug: LOUD, never in-band
        except Exception as e:
            if isinstance(e, _partition.PartitionError):
                _partition.PARTITION_SHARDS.labels(
                    outcome="error"
                ).inc()
            _node_metrics.ERRORS.labels(kind="compute").inc()
            _flightrec.record(
                "server.error", stage="reduce", wire="npwire",
                transport=transport, error=str(e)[:200],
            )
            reply = encode_batch([], uuid=outer_uuid, error=str(e))
    if trace_id is not None and root.span is not None:
        reply = append_spans(reply, [root.span.to_dict()])
    return reply


def serve_npwire_payload(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    payload: bytes,
    *,
    transport: str = "tcp",
    request_views: bool = False,
) -> bytes:
    """One npwire frame (plain OR batch) in -> one reply frame out —
    the whole node-side npwire contract as a function, so any framed
    byte channel (TCP accept loop, shm doorbell) serves identically.
    ``request_views`` opts the request decode into zero-copy read-only
    views (see :func:`_serve_plain_payload`).

    Deadline admission (flag bit 16, :mod:`.deadline`): an expired
    budget is answered with the in-band deadline classification BEFORE
    any decode or compute cost is paid; a live one is re-bound as the
    handler's ambient deadline so the compute inherits it.

    Instrumented with the same ``pftpu_server_*`` families as the gRPC
    service (:mod:`._node_metrics`) so TCP and shm template nodes
    aggregate into the fleet view like gRPC nodes (``method`` is
    ``evaluate`` for plain frames, ``evaluate_batch`` for batch
    frames; a zero-item batch frame is the pool's capability/health
    probe and counts as ``probe`` — keeping probe cadence OUT of the
    SLO engine's goodput objective, the gRPC lane's GetLoad posture,
    so an idle-but-probed fleet never pages on a goodput floor)."""
    batch = is_batch_frame(payload)
    reduce_window = False
    if batch:
        # n_items sits at the fixed header offset (<4sBB16sI then
        # <I count) — the same cheap peek posture as peek_deadline.
        try:
            (n_items,) = struct.unpack_from("<I", payload, 22)
        except struct.error:
            n_items = None  # truncated: the full decoder rejects it
        try:
            # An outer partition block marks a REDUCE window (the
            # partial-reduction lane, routing/partition.py).
            reduce_window = peek_partition(payload) is not None
        except WireError:
            reduce_window = False  # the full decoder rejects it below
        method = (
            "probe"
            if n_items == 0
            else ("evaluate_reduce" if reduce_window else "evaluate_batch")
        )
    else:
        method = "evaluate"
    _node_metrics.REQUESTS.labels(method=method).inc()
    _node_metrics.INFLIGHT.inc()
    try:
        try:
            budget = peek_deadline(payload)
        except WireError:
            budget = None  # the full decoder rejects it loudly below
        err = _deadline.shed_expired_admission(
            budget, transport=transport
        )
        if err is not None:
            uid = frame_uuid(payload)
            if batch:
                return encode_batch([], uuid=uid, error=err)
            return encode_arrays([], uuid=uid, error=err)
        with _deadline.budget_scope(budget):
            if batch:
                if reduce_window:
                    return _serve_reduce_payload(
                        compute_fn, payload, transport=transport,
                        request_views=request_views,
                    )
                return _serve_batch_payload(
                    compute_fn, payload, transport=transport,
                    request_views=request_views,
                )
            return _serve_plain_payload(
                compute_fn, payload, transport=transport,
                request_views=request_views,
            )
    finally:
        _node_metrics.INFLIGHT.dec()


def _serve_tcp_connection(
    conn: socket.socket,
    compute_fn: Callable[..., Sequence[np.ndarray]],
    request_views: bool = False,
) -> None:
    """One connection's lock-step frame loop (shared by the sequential
    and ``concurrent=True`` accept modes of :func:`serve_tcp_once`)."""
    with conn:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                payload = _recv_frame(conn)
            except (ConnectionError, OSError):
                break
            if _fi.active_plan is not None:  # chaos seam
                try:
                    payload = _fi.filter_bytes(
                        "tcp.server.recv", payload
                    )
                except (ConnectionError, OSError):
                    break
            try:
                _serve_send(
                    conn,
                    serve_npwire_payload(
                        compute_fn, payload,
                        request_views=request_views,
                    ),
                )
            except (ConnectionError, OSError):
                break


def serve_tcp_once(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    ready_callback: Optional[Callable[[int], None]] = None,
    max_connections: Optional[int] = None,
    concurrent: bool = False,
    request_views: bool = False,
) -> None:
    """Blocking pure-Python server for the same protocol.

    The in-language peer of ``native/cpp_node.cpp`` — used to test the
    client without a compiler, and as a template for third-language
    nodes.  Serves connections sequentially by default;
    ``concurrent=True`` serves each accepted connection on its own
    daemon thread (the cpp_node accept model) so a held client
    connection cannot starve health probes — what a replica pool
    (routing/) needs from a pure-Python TCP node.  Each connection
    processes lock-step frames until the peer disconnects; corrupt
    frames are answered with in-band error replies, never a server
    crash.  Batch frames (npwire flag bit 8) are served with per-item
    error isolation; a compute_fn carrying a ``.batch`` attribute
    (``device_compute_fn(..., batched=True)``) executes same-signature
    windows as one vmapped call.  ``port=0`` binds an ephemeral port
    reported through ``ready_callback``.  ``max_connections`` bounds
    the accept loop (None = forever; in concurrent mode it bounds
    accepts, not completions).  ``request_views=True`` hands
    compute_fn READ-ONLY zero-copy views of request arrays instead of
    owned copies — one payload copy saved per request; leave it off
    for compute_fns that mutate their inputs in place.
    """
    import threading

    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as srv:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        if ready_callback is not None:
            ready_callback(srv.getsockname()[1])
        served = 0
        while max_connections is None or served < max_connections:
            conn, _ = srv.accept()
            served += 1
            if concurrent:
                threading.Thread(
                    target=_serve_tcp_connection,
                    args=(conn, compute_fn, request_views),
                    daemon=True,
                ).start()
            else:
                _serve_tcp_connection(conn, compute_fn, request_views)
