"""Plain-TCP transport for the npwire format — the cross-language lane.

The gRPC service (:mod:`.server`/:mod:`.client`) is the batteries-
included host-federation transport; this module is the *minimal* one: a
u32-length-prefixed npwire frame over a TCP socket.  Its purpose is the
capability the reference only gestures at — "the model implementation
could be C++" (reference: README.md:34-35): ``native/cpp_node.cpp``
implements this exact protocol with zero Python, and
:class:`TcpArraysClient` drives it from the driver process.

Frame layout: ``u32 little-endian payload length`` + npwire payload
(see :mod:`.npwire` for the payload layout).  Requests and replies are
lock-step per connection — the same one-in-flight pattern the reference
uses on its bidirectional streams (reference: service.py:150-158).
"""

from __future__ import annotations

import socket
import struct
import time
import uuid as uuid_mod
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..telemetry import flightrec as _flightrec
from ..telemetry import reunion as _reunion
from ..telemetry import spans as _spans
from ..telemetry import watchdog as _watchdog
from . import _rpc_metrics
from .npwire import (
    append_spans,
    decode_arrays_all,
    decode_arrays_ex,
    encode_arrays,
)

__all__ = ["TcpArraysClient", "serve_tcp_once", "RemoteComputeError"]

# Same metric families as the gRPC lane (client.py), labeled
# transport="tcp" so both lanes aggregate on one dashboard
# (metric catalog: docs/observability.md).
_CALL_S = _rpc_metrics.CALL_S
_RETRIES = _rpc_metrics.RETRIES
_DROPS = _rpc_metrics.DROPS
_BATCH_S = _rpc_metrics.BATCH_S
_WINDOW_DEPTH = _rpc_metrics.WINDOW_DEPTH


class RemoteComputeError(RuntimeError):
    """The remote node replied with an error payload."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        b = sock.recv(n)
        if not b:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    return _recv_exact(sock, n)


class TcpArraysClient:
    """Arrays-in → arrays-out over one persistent TCP connection.

    API parity with :class:`.client.ArraysToArraysServiceClient`'s sync
    surface: ``evaluate(*arrays) -> [arrays]`` with uuid correlation
    checking and lazy (re)connection.  ``retries`` reconnects on a dead
    socket — the failover analog for a single fixed peer (reference:
    service.py:408-416 rebalances across a pool; a TCP peer is pinned).
    """

    def __init__(self, host: str, port: int, *, retries: int = 2):
        self.host = host
        self.port = int(port)
        self.retries = retries
        self._sock: Optional[socket.socket] = None
        self._rfile = None  # buffered reader over _sock

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port), timeout=30.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            # Buffered reads: a frame costs one length + one payload
            # read from the buffer instead of 2+ raw recv syscalls.
            self._rfile = s.makefile("rb")
        return self._sock

    def _read_exact(self, n: int) -> bytes:
        buf = self._rfile.read(n)
        if buf is None or len(buf) < n:
            raise ConnectionError("peer closed mid-frame")
        return buf

    def _read_frame(self) -> bytes:
        (n,) = struct.unpack("<I", self._read_exact(4))
        return self._read_exact(n)

    def close(self) -> None:
        if self._sock is not None:
            try:
                if self._rfile is not None:
                    try:
                        self._rfile.close()
                    except OSError:
                        pass
                self._sock.close()
            finally:
                self._sock = None
                self._rfile = None

    def __del__(self):  # best-effort, mirrors client.py teardown
        try:
            self.close()
        except Exception:
            pass

    def evaluate(self, *arrays: np.ndarray) -> List[np.ndarray]:
        with _spans.span("rpc.evaluate", transport="tcp"):
            with _spans.span("encode"):
                uid = uuid_mod.uuid4().bytes
                trace_id = (
                    _spans.current_trace_id() if _spans.enabled() else None
                )
                request = encode_arrays(
                    [np.asarray(a) for a in arrays],
                    uuid=uid,
                    trace_id=trace_id,
                )
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.retry", transport="tcp", attempt=attempt
                    )
                t0 = time.perf_counter()
                try:
                    with _spans.span("call"):
                        sock = self._connect()
                        _send_frame(sock, request)
                        reply = self._read_frame()
                    break
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.drop", transport="tcp",
                        peer=f"{self.host}:{self.port}",
                    )
                    self.close()
            else:
                raise ConnectionError(
                    f"node {self.host}:{self.port} unreachable after "
                    f"{self.retries + 1} attempts"
                ) from last_err
            with _spans.span("decode"):
                outputs, reply_uid, error, _tid, node_spans = (
                    decode_arrays_all(reply)
                )
                if node_spans:
                    _reunion.ingest(node_spans)
            _CALL_S.labels(transport="tcp", mode="lockstep").observe(
                time.perf_counter() - t0
            )
            if error is not None:
                _flightrec.record(
                    "rpc.error", transport="tcp", error=error[:200]
                )
                raise RemoteComputeError(error)
            if reply_uid != uid:
                # A mismatched reply means this connection is
                # desynchronized (e.g. stale frames left by an aborted
                # batch) — close it so the NEXT call reconnects cleanly
                # instead of reading stale frames forever, matching
                # _evaluate_many_once (ADVICE r5 #3).
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise RuntimeError(
                    "uuid mismatch: reply does not match request"
                )
            return outputs

    __call__ = evaluate

    # in-flight REQUEST bytes cap: keeps every sendall completable so
    # the pipelining loop always reaches its read — without it, a
    # write-only burst can fill both sockets' buffers against a server
    # blocked sending replies nobody reads (the same deadlock geometry
    # as HTTP/2 flow control on the gRPC lane, client.py).
    _MAX_INFLIGHT_BYTES = 32 * 1024

    def evaluate_many(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
    ) -> List[List[np.ndarray]]:
        """Pipelined batch over the SAME lock-step connection.

        The frame protocol is strictly FIFO per connection (the C++
        node's loop is recv -> compute -> send, native/cpp_node.cpp),
        so up to ``window`` requests stay in flight and replies
        correlate by order + per-frame uuid — client encode, both
        network legs, and node compute overlap.  Oversized requests
        degrade to lock-step via the byte cap (one in flight, the
        proven-safe per-call mode).

        Same semantics as the gRPC lane's ``evaluate_many``:
        all-or-nothing TRANSPORT retry (reconnect, re-run the whole
        batch); a server error reply raises
        :class:`RemoteComputeError` without retry after draining the
        in-flight replies so the connection stays correlated.
        """
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        with _spans.span(
            "rpc.evaluate_many",
            transport="tcp",
            n=len(requests),
            window=window,
        ):
            with _spans.span("encode"):
                trace_id = (
                    _spans.current_trace_id() if _spans.enabled() else None
                )
                encoded = []
                for args in requests:
                    uid = uuid_mod.uuid4().bytes
                    encoded.append(
                        (
                            encode_arrays(
                                [np.asarray(a) for a in args],
                                uuid=uid,
                                trace_id=trace_id,
                            ),
                            uid,
                        )
                    )
            if not encoded:
                return []
            t0 = time.perf_counter()
            last_err: Optional[Exception] = None
            for attempt in range(self.retries + 1):
                if attempt:
                    _RETRIES.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.retry", transport="tcp", attempt=attempt,
                        batch=len(encoded),
                    )
                try:
                    # Known wedge point: a pipelined window against a
                    # stalled peer can block in read — armed so a hang
                    # leaves an incident bundle (telemetry.watchdog).
                    with _watchdog.armed(
                        "tcp.batch_window", n=len(encoded), window=window
                    ):
                        results = self._evaluate_many_once(encoded, window)
                except (ConnectionError, OSError) as e:
                    last_err = e
                    _DROPS.labels(transport="tcp").inc()
                    _flightrec.record(
                        "rpc.drop", transport="tcp",
                        peer=f"{self.host}:{self.port}",
                    )
                    self.close()
                    continue
                _BATCH_S.labels(transport="tcp").observe(
                    time.perf_counter() - t0
                )
                return results
            raise ConnectionError(
                f"node {self.host}:{self.port} unreachable after "
                f"{self.retries + 1} attempts"
            ) from last_err

    def _evaluate_many_once(self, encoded, window):
        sock = self._connect()
        n = len(encoded)
        results: List[Optional[List[np.ndarray]]] = [None] * n
        write_idx = read_idx = 0
        inflight_bytes = 0
        while read_idx < n:
            # Coalesce every writable frame into ONE sendall: on
            # localhost the per-call cost is syscall-dominated, so a
            # window of small frames should pay one write, not window.
            burst = []
            while write_idx < n and (
                write_idx == read_idx
                or (
                    write_idx - read_idx < window
                    and inflight_bytes + len(encoded[write_idx][0])
                    <= self._MAX_INFLIGHT_BYTES
                )
            ):
                payload = encoded[write_idx][0]
                burst.append(struct.pack("<I", len(payload)))
                burst.append(payload)
                inflight_bytes += len(payload)
                write_idx += 1
            if burst:
                sock.sendall(b"".join(burst))
            _WINDOW_DEPTH.labels(transport="tcp").observe(
                write_idx - read_idx
            )
            reply = self._read_frame()
            request, uid = encoded[read_idx]
            inflight_bytes -= len(request)
            try:
                outputs, reply_uid, error, _tid, node_spans = (
                    decode_arrays_all(reply)
                )
                if node_spans:
                    _reunion.ingest(node_spans)
            except Exception:
                # Corrupt payload with replies still in flight: the
                # connection cannot be trusted to stay correlated —
                # close so the NEXT call reconnects cleanly, and let
                # the WireError surface loudly (CLAUDE.md invariant).
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise
            if error is not None:
                # Drain so the connection stays correlated for the
                # NEXT call, then surface the deterministic error.  If
                # the drain itself fails, the leftover in-flight
                # replies would poison later calls with stale frames —
                # close instead of leaving a desynchronized socket.
                try:
                    for _ in range(write_idx - read_idx - 1):
                        self._read_frame()
                except (ConnectionError, OSError):
                    _DROPS.labels(transport="tcp").inc()
                    self.close()
                raise RemoteComputeError(error)
            if reply_uid != uid:
                _DROPS.labels(transport="tcp").inc()
                self.close()
                raise RuntimeError(
                    "uuid mismatch: reply does not match request"
                )
            results[read_idx] = outputs
            read_idx += 1
        return results


def serve_tcp_once(
    compute_fn: Callable[..., Sequence[np.ndarray]],
    host: str = "127.0.0.1",
    port: int = 0,
    *,
    ready_callback: Optional[Callable[[int], None]] = None,
    max_connections: Optional[int] = None,
) -> None:
    """Blocking pure-Python server for the same protocol.

    The in-language peer of ``native/cpp_node.cpp`` — used to test the
    client without a compiler, and as a template for third-language
    nodes.  Serves connections sequentially; each connection processes
    lock-step frames until the peer disconnects.  ``port=0`` binds an
    ephemeral port reported through ``ready_callback``.
    ``max_connections`` bounds the accept loop (None = forever).
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as srv:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        if ready_callback is not None:
            ready_callback(srv.getsockname()[1])
        served = 0
        while max_connections is None or served < max_connections:
            conn, _ = srv.accept()
            served += 1
            with conn:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    try:
                        payload = _recv_frame(conn)
                    except (ConnectionError, OSError):
                        break
                    arrays, uid, _, trace_id = decode_arrays_ex(payload)
                    # Node-side spans adopt the driver's wire trace id,
                    # same contract as the gRPC server (server.py).
                    with _spans.trace_context(trace_id), _spans.span(
                        "node.evaluate", wire="npwire", transport="tcp"
                    ) as root:
                        try:
                            with _spans.span("compute"):
                                outputs = [
                                    np.asarray(o)
                                    for o in compute_fn(*arrays)
                                ]
                            with _spans.span("encode"):
                                reply = encode_arrays(outputs, uuid=uid)
                        except Exception as e:  # error -> error payload
                            _flightrec.record(
                                "server.error", stage="compute",
                                wire="npwire", transport="tcp",
                                error=str(e)[:200],
                            )
                            reply = encode_arrays([], uuid=uid, error=str(e))
                    # Reunion piggyback: traced requests get this
                    # node's span tree on the reply tail (untraced
                    # frames stay byte-identical to the PR-1 wire).
                    if trace_id is not None and root.span is not None:
                        reply = append_spans(reply, [root.span.to_dict()])
                    _send_frame(conn, reply)
