"""Adaptive server-side micro-batching for the host-federation lane.

The host lane's per-call budget is dominated by fixed costs — decode,
jitted dispatch, encode, and the grpc.aio floor (docs/performance.md
"Host lane budget") — while the compute itself is microseconds.  DrJAX
(PAPERS.md) makes the federated-map point structurally: per-client work
should vectorize into ONE XLA program; NumPyro's vectorized chains make
the same point for probabilistic evaluation.  This module is that idea
applied to the serving path: requests that arrive while a device call
is in flight are coalesced and executed as one ``jax.vmap``-batched
call, so K pipelined requests pay one dispatch instead of K.

Policy (the "adaptive" in the name):

- **Idle: zero added latency.**  A lone request dispatches immediately
  — the drain loop starts on the submit and pops a single-entry group.
  There is no timer in front of the first request.
- **Under load: coalesce.**  Requests arriving while a call is in
  flight stack in the queue; when the call finishes the whole stack
  (same signature, up to ``max_batch``) dispatches as one batched
  call.  ``max_wait_us`` adds an optional post-batch pause to let a
  partially-filled next window top up — only ever paid when the queue
  is non-empty, i.e. when the lane is already saturated and latency is
  queue-bound anyway.

Error isolation is per request: a batched execution that fails falls
back to scalar re-execution of its window, so one poisoned input fails
only its own reply (``server.batch_fallback`` in the flight record).
Requests whose signatures differ are grouped — each signature group
dispatches as its own batch (XLA compiles one executable per static
signature, signatures.py).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..faultinject import runtime as _fi
from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics
from ..telemetry import spans as _spans
from . import deadline as _deadline

__all__ = ["MicroBatcher", "batched_compute_fn", "execute_window_sync"]

# Batcher instrumentation (metric catalog: docs/observability.md).
# Queue-wait/compute reuse the SERVER's families by name — the registry
# returns the same instrument, so the node's latency picture stays on
# one dashboard whether or not requests flowed through the batcher.
_BATCH_SIZE = _metrics.histogram(
    "pftpu_server_batch_size",
    "Requests coalesced per dispatched micro-batch",
    ("kind",),
    buckets=_metrics.DEFAULT_COUNT_BUCKETS,
)
_BATCH_WAIT_S = _metrics.histogram(
    "pftpu_server_batch_wait_seconds",
    "Coalesce wait: request enqueue to batch dispatch",
)
_BATCHES = _metrics.counter(
    "pftpu_server_batches_total",
    "Micro-batches dispatched, by execution kind",
    ("kind",),
)
_QUEUE_S = _metrics.histogram(
    "pftpu_server_queue_wait_seconds",
    "Wait between RPC decode and compute start (thread-executor queue)",
)
_COMPUTE_S = _metrics.histogram(
    "pftpu_server_compute_seconds", "compute_fn latency"
)
# Shared with the admission path in server.py (same family by name):
# work the node refused or abandoned instead of computing.
_ADMISSION_SHED = _metrics.counter(
    "pftpu_admission_shed_total",
    "Requests shed by server-side admission control, by reason",
    ("reason",),
)


def _signature(inputs: Sequence[np.ndarray]) -> Tuple:
    """Static signature of one request — the coalescing key.  Same
    notion as :func:`..signatures.spec_of` (XLA compiles per static
    signature) without materializing ShapeDtypeStructs per request."""
    return tuple((a.shape, a.dtype.str) for a in inputs)


def _bucket(k: int, cap: int) -> int:
    """Next power-of-two >= k, clamped to ``cap`` — the padded-bucket
    ladder that keeps the number of compiled batched executables
    logarithmic in ``max_batch`` instead of linear in every ragged
    window size the wire happens to produce."""
    b = 1
    while b < k:
        b <<= 1
    return min(b, max(cap, k))


def batched_compute_fn(
    fn: Callable, *, jit: bool = True, max_batch: int = 32
) -> Callable:
    """Vectorize a JAX compute fn over a leading batch axis with a
    padded-bucket jit cache.

    Returns ``batch(requests) -> [outputs_per_request]`` where
    ``requests`` is a list of same-signature argument tuples.  The
    stack is padded to the next power-of-two bucket (repeating the
    first row — a value the fn provably accepts, so padding cannot
    manufacture a domain error a real input didn't) and evaluated as
    one ``jax.vmap`` call; ``jax.jit`` caches per padded shape, so
    ragged window sizes compile at most ``log2(max_batch)+1``
    executables per signature instead of one per size.
    """
    import jax

    vfn = jax.vmap(fn)
    if jit:
        vfn = jax.jit(vfn)

    def batch(
        requests: Sequence[Sequence[np.ndarray]],
    ) -> List[List[np.ndarray]]:
        k = len(requests)
        if k == 0:
            return []
        if k > max_batch:
            # A caller with a larger window (e.g. a service configured
            # with a bigger max_batch than this fn was built with)
            # must not leak non-power-of-two padded shapes into the
            # jit cache — chunk to this fn's own cap instead.
            out: List[List[np.ndarray]] = []
            for s in range(0, k, max_batch):
                out.extend(batch(requests[s : s + max_batch]))
            return out
        n_args = len(requests[0])
        stacked = [
            np.stack([np.asarray(req[i]) for req in requests])
            for i in range(n_args)
        ]
        b = _bucket(k, max_batch)
        if b > k:
            pad = b - k
            stacked = [
                np.concatenate([s, np.repeat(s[:1], pad, axis=0)])
                for s in stacked
            ]
        outs = vfn(*stacked)
        return [[np.asarray(o[j]) for o in outs] for j in range(k)]

    return batch


def execute_window_sync(
    compute_fn: Callable,
    batch_fn: Optional[Callable],
    requests: Sequence[Sequence[np.ndarray]],
) -> List[object]:
    """Synchronous window execution: one outcome (output list or
    exception) per request — per-item error isolation.  A >= 2
    same-signature window with a ``batch_fn`` runs vectorized, with
    scalar re-execution fallback on failure; everything else runs
    scalar-wise.  The synchronous twin of :class:`MicroBatcher`'s
    dispatch (single source for the fallback semantics and the batch
    metrics), used by the TCP server (:func:`..tcp.serve_tcp_once`).
    """
    k = len(requests)
    if k == 0:
        return []
    outcomes: Optional[List[object]] = None
    vmapped_ok = False
    use_batch = (
        batch_fn is not None
        and k > 1
        and len({_signature(r) for r in requests}) == 1
    )
    if use_batch:
        try:
            outs = batch_fn(list(requests))
            if _fi.active_plan is not None:  # chaos: vectorized seam
                outs = _fi.mangle_batch_result(
                    "server.compute_batch", outs
                )
            if len(outs) != k:
                raise RuntimeError(
                    f"batch_fn returned {len(outs)} results for "
                    f"{k} requests"
                )
            outcomes = [list(o) for o in outs]
            vmapped_ok = True
        except Exception as e:
            _BATCHES.labels(kind="fallback").inc()
            _flightrec.record(
                "server.batch_fallback", size=k,
                error=f"{type(e).__name__}: {e}"[:200],
            )
            outcomes = None
    if outcomes is None:
        outcomes = []
        for req in requests:
            try:
                outcomes.append(
                    [np.asarray(o) for o in compute_fn(*req)]
                )
            except Exception as e:
                outcomes.append(e)
    kind = "vmapped" if vmapped_ok else ("single" if k == 1 else "serial")
    _BATCH_SIZE.labels(kind=kind).observe(k)
    _BATCHES.labels(kind=kind).inc()
    if k > 1:
        _flightrec.record("server.batch", size=k, exec_kind=kind)
    return outcomes


class _Pending:
    __slots__ = ("inputs", "sig", "future", "t_enqueue", "deadline")

    def __init__(self, inputs, sig, future, t_enqueue, deadline=None):
        self.inputs = inputs
        self.sig = sig
        self.future = future
        self.t_enqueue = t_enqueue
        # Absolute monotonic deadline captured at enqueue from the
        # ambient contextvar (None = unbounded): the shed key.
        self.deadline = deadline


class MicroBatcher:
    """Asyncio coalescing queue in front of a node's ``compute_fn``.

    ``compute_fn(*arrays) -> [arrays]`` is the scalar path;
    ``batch_fn(requests) -> [outputs_per_request]`` (e.g. from
    :func:`batched_compute_fn`, or the ``.batch`` attribute
    :func:`..server.device_compute_fn` attaches with ``batched=True``)
    is the vectorized path used whenever >= 2 same-signature requests
    coalesce.  Without a ``batch_fn`` the group runs scalar-wise —
    inline on the loop (one trip for the whole group, amortizing the
    handoffs that dominate sub-ms computes), or fanned out over the
    executor's workers so slow GIL-releasing computes keep the
    concurrency the pre-batching server had.

    ``inline=True`` executes on the event loop (the
    ``inline_compute`` contract of the service: sub-ms computes only);
    the default runs each group in the thread executor so a slow batch
    cannot stall GetLoad.
    """

    def __init__(
        self,
        compute_fn: Callable,
        batch_fn: Optional[Callable] = None,
        *,
        max_batch: int = 32,
        max_wait_us: float = 200.0,
        inline: bool = False,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.compute_fn = compute_fn
        self.batch_fn = batch_fn
        self.max_batch = int(max_batch)
        self.max_wait_us = float(max_wait_us)
        self.inline = bool(inline)
        self._pending: deque[_Pending] = deque()
        self._worker: Optional[asyncio.Task] = None
        # Plain always-on tallies (telemetry histograms are no-ops when
        # spans are disabled; GetLoad still wants the basic picture).
        self.n_dispatched = 0
        self.n_batches = 0
        self.n_fallbacks = 0
        self.n_shed = 0
        self.max_seen = 0

    # -- submission -------------------------------------------------------

    async def submit(self, inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
        """Enqueue one request; returns its outputs (or raises ITS
        error).  A lone request on an idle batcher dispatches
        immediately — no timer, no added latency."""
        return await self._enqueue(inputs)

    async def submit_many(
        self, inputs_list: Sequence[Sequence[np.ndarray]]
    ) -> List[object]:
        """Enqueue a whole window at once (the server side of a wire
        batch frame) and gather per-request outcomes: each slot is the
        request's output list OR its exception (never raises for a
        single poisoned item — the per-item error isolation contract).
        """
        futures = [
            self._enqueue(inputs, start=False) for inputs in inputs_list
        ]
        # Enqueue-all-then-start: the window must be visible to the
        # drain loop as ONE stack, not trickle in one dispatch each.
        tasks = [asyncio.ensure_future(f) for f in futures]
        self._start()
        return await asyncio.gather(*tasks, return_exceptions=True)

    def _enqueue(self, inputs, *, start: bool = True):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        arrays = [np.asarray(a) for a in inputs]
        self._pending.append(
            _Pending(
                arrays,
                _signature(arrays),
                fut,
                time.perf_counter(),
                _deadline.current_deadline(),
            )
        )
        self.max_seen = max(self.max_seen, len(self._pending))
        if start:
            self._start()
        return fut

    def _start(self) -> None:
        if self._worker is None and self._pending:
            self._worker = asyncio.ensure_future(self._drain())

    @property
    def queue_depth(self) -> int:
        return len(self._pending)

    def _shed_one(self, p: _Pending, where: str) -> None:
        """Fail one expired entry with the deadline classification —
        its reply races nothing downstream (never vmap'd in)."""
        self.n_shed += 1
        _ADMISSION_SHED.labels(reason="expired").inc()
        _deadline.DEADLINE_EXPIRED.labels(stage="queue").inc()
        _flightrec.record("admission.shed", reason="expired", where=where)
        if not p.future.done():
            p.future.set_exception(
                _deadline.DeadlineExceeded(
                    _deadline.deadline_error(f"shed in {where}")
                )
            )

    def shed_expired(self) -> int:
        """Drop every queued entry whose deadline is already spent
        (their callers stopped waiting: computing them is pure load)
        and fail their futures with the deadline classification.
        Returns how many were shed.  The admission path calls this
        BEFORE refusing new work — shedding the oldest-past-deadline
        first is how a full queue makes room for live requests."""
        if not self._pending:
            return 0
        now = time.monotonic()
        live: deque = deque()
        shed = 0
        for p in self._pending:
            if p.deadline is not None and now >= p.deadline:
                self._shed_one(p, "micro-batcher queue")
                shed += 1
            else:
                live.append(p)
        self._pending = live
        return shed

    def stats(self) -> dict:
        """Live batcher picture for GetLoad (:meth:`..server
        .ArraysToArraysService.determine_load`): always-on counts plus
        batch-size quantiles when telemetry is enabled."""
        out = {
            "max_batch": self.max_batch,
            "max_wait_us": self.max_wait_us,
            "queue_depth": self.queue_depth,
            "dispatched_total": self.n_dispatched,
            "batches_total": self.n_batches,
            "fallbacks_total": self.n_fallbacks,
            "shed_total": self.n_shed,
            "max_queue_seen": self.max_seen,
        }
        if _spans.enabled():
            import math

            def _q(hist, q):
                v = hist.approx_quantile(q)
                return None if math.isnan(v) or math.isinf(v) else v

            vmapped = _BATCH_SIZE.labels(kind="vmapped")
            out["size_p50"] = _q(vmapped, 0.5)
            out["size_p99"] = _q(vmapped, 0.99)
            out["wait_p99_s"] = _q(_BATCH_WAIT_S, 0.99)
        return out

    # -- the drain loop ---------------------------------------------------

    def _pop_group(self) -> List[_Pending]:
        """Pop the head request plus every queued same-signature
        sibling (stable order), up to ``max_batch``.  Mixed signatures
        stay queued and form their own group next iteration."""
        if not self._pending:
            return []
        head_sig = self._pending[0].sig
        group: List[_Pending] = []
        rest: List[_Pending] = []
        for p in self._pending:
            if p.sig == head_sig and len(group) < self.max_batch:
                group.append(p)
            else:
                rest.append(p)
        self._pending = deque(rest)
        return group

    async def _drain(self) -> None:
        try:
            under_load = False
            while self._pending:
                if (
                    under_load
                    and self.max_wait_us > 0
                    and len(self._pending) < self.max_batch
                ):
                    # Saturated lane: a short top-up pause fills the
                    # next window.  Never reached by a lone idle
                    # request (under_load is False on the first pass).
                    await asyncio.sleep(self.max_wait_us / 1e6)
                group = self._pop_group()
                await self._execute(group)
                under_load = True
        finally:
            self._worker = None
            if self._pending:
                # A submit raced the loop's exit check; reschedule so
                # nothing is stranded.
                self._start()

    async def _execute(self, group: List[_Pending]) -> None:
        # Shed expired entries AT DISPATCH: their callers are gone, so
        # stacking them into the vmapped call would spend device time
        # on replies nobody reads — the queue must never launder dead
        # work into compute (ISSUE 10 tentpole).
        now = time.monotonic()
        live: List[_Pending] = []
        for p in group:
            if p.deadline is not None and now >= p.deadline:
                self._shed_one(p, "micro-batcher dispatch")
            else:
                live.append(p)
        group = live
        k = len(group)
        if k == 0:
            return
        t_dispatch = time.perf_counter()
        for p in group:
            _BATCH_WAIT_S.observe(t_dispatch - p.t_enqueue)
            _QUEUE_S.observe(t_dispatch - p.t_enqueue)
        self.n_dispatched += k
        self.n_batches += 1
        use_batch = k > 1 and self.batch_fn is not None

        def scalar_one(p: _Pending) -> object:
            try:
                return list(self.compute_fn(*p.inputs))
            except Exception as e:
                return e

        def batch_job() -> Optional[List[object]]:
            """One trip through the vectorized path; None on failure —
            the caller then re-runs the window scalar-wise, so one
            poisoned input fails only ITS reply."""
            t0 = time.perf_counter()
            try:
                outs = self.batch_fn([p.inputs for p in group])
                if _fi.active_plan is not None:  # chaos: vectorized seam
                    outs = _fi.mangle_batch_result(
                        "server.compute_batch", outs
                    )
                if len(outs) != k:
                    raise RuntimeError(
                        f"batch_fn returned {len(outs)} results "
                        f"for {k} requests"
                    )
                _COMPUTE_S.observe(time.perf_counter() - t0)
                return [list(o) for o in outs]
            except Exception as e:
                self.n_fallbacks += 1
                _BATCHES.labels(kind="fallback").inc()
                _flightrec.record(
                    "server.batch_fallback", size=k,
                    error=f"{type(e).__name__}: {e}"[:200],
                )
                return None

        try:
            loop = asyncio.get_running_loop()
            results: Optional[List[object]] = None
            vmapped_ok = False
            if use_batch:
                # call_shimmed_async: the inline fast path runs
                # batch_job on the loop ONLY while no fault plan is
                # active — batch_job holds the sync vectorized chaos
                # seam (mangle_batch_result), whose delay kinds sleep
                # (graftflow async-blocking; the PR-5 bug class).
                results = await _fi.call_shimmed_async(
                    batch_job, inline=self.inline
                )
                vmapped_ok = results is not None
            if results is None:
                # Scalar path: no batch_fn, a lone request, or the
                # vectorized call failed.  Inline runs on the loop;
                # executor mode fans the group out CONCURRENTLY, so a
                # slow GIL-releasing compute keeps the multi-worker
                # overlap the pre-batching executor server had.
                t0 = time.perf_counter()
                if self.inline:
                    results = [scalar_one(p) for p in group]
                else:
                    results = list(
                        await asyncio.gather(
                            *(
                                loop.run_in_executor(None, scalar_one, p)
                                for p in group
                            )
                        )
                    )
                _COMPUTE_S.observe(time.perf_counter() - t0)
            # Recorded AFTER execution with the kind that actually
            # ran: a window whose vmapped call failed and re-ran
            # scalar-wise must not inflate the vmapped histograms an
            # operator reads off GetLoad.
            kind = (
                "vmapped"
                if vmapped_ok
                else ("single" if k == 1 else "serial")
            )
            _BATCH_SIZE.labels(kind=kind).observe(k)
            _BATCHES.labels(kind=kind).inc()
            if k > 1:
                _flightrec.record("server.batch", size=k, exec_kind=kind)
        except BaseException as e:
            # Engine failure (not a compute failure — those are caught
            # per request): fail the whole group loudly rather than
            # strand its futures.  BaseException matters: a cancelled
            # drain task (server shutdown) or a KeyboardInterrupt
            # escaping an inline compute would otherwise leave every
            # awaiting RPC handler blocked forever — the silent-wedge
            # class the watchdog exists for.
            err = (
                e
                if isinstance(e, Exception)
                else RuntimeError(f"batch execution aborted: {e!r}")
            )
            for p in group:
                if not p.future.done():
                    p.future.set_exception(err)
            if not isinstance(e, Exception):
                raise  # cancellation/KeyboardInterrupt still propagate
            return
        for p, res in zip(group, results):
            if p.future.done():  # cancelled caller; nothing to deliver
                continue
            if isinstance(res, Exception):
                p.future.set_exception(res)
            else:
                p.future.set_result(
                    [np.asarray(o) for o in res]  # type: ignore[union-attr]
                )
