"""No-U-Turn Sampler, iterative, XLA-compilable end to end.

The reference's flagship driver runs PyMC NUTS whose every leapfrog step
fans out gRPC calls to the federated nodes (reference: demo_model.py:38-42,
SURVEY §3.3).  Here the entire NUTS transition — tree doubling, U-turn
checks, the federated logp+grad psum — is one XLA program built from
``lax.while_loop``s: no Python recursion, no host round-trips, static
shapes throughout (checkpoint stacks are ``(max_depth, dim)``).

Algorithm: multinomial NUTS with biased progressive sampling and the
iterative power-of-two checkpoint scheme for intra-subtree U-turn
detection (Hoffman & Gelman 2014; Betancourt 2017 "A conceptual
introduction to HMC" appendix A.4; iterative formulation as popularized
by the NumPyro authors, Phan et al. 2019 — see PAPERS.md).  Implemented
from the published algorithm, TPU-first: flat state vectors (one fused
VPU update per leapfrog), diagonal OR dense mass matrix (the hmc
helpers branch on ``inv_mass.ndim``; dense velocities are matvecs),
generalized U-turn criterion with half-leaf correction.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from .hmc import (
    HMCState,
    IntegratorState,
    kinetic_energy,
    leapfrog,
    mass_velocity,
    sample_momentum,
)


class NUTSInfo(NamedTuple):
    accept_prob: jax.Array  # mean MH accept prob over visited leaves
    diverging: jax.Array
    depth: jax.Array
    num_leaves: jax.Array
    energy: jax.Array


class _Tree(NamedTuple):
    # Trajectory boundaries (trajectory-time order: left = backward end).
    z_left: jax.Array
    r_left: jax.Array
    grad_left: jax.Array
    z_right: jax.Array
    r_right: jax.Array
    grad_right: jax.Array
    # Current multinomial proposal.
    z_prop: jax.Array
    logp_prop: jax.Array
    grad_prop: jax.Array
    energy_prop: jax.Array
    # log-sum of multinomial weights exp(energy0 - energy) over leaves.
    log_weight: jax.Array
    r_sum: jax.Array
    turning: jax.Array
    diverging: jax.Array
    sum_accept: jax.Array
    num_leaves: jax.Array  # int32, leaves beyond the initial point


def _is_turning(inv_mass, r_left, r_right, r_sum):
    """Generalized U-turn criterion with half-leaf correction."""
    v_left = mass_velocity(inv_mass, r_left)
    v_right = mass_velocity(inv_mass, r_right)
    r_c = r_sum - 0.5 * (r_left + r_right)
    return (jnp.dot(v_left, r_c) <= 0.0) | (jnp.dot(v_right, r_c) <= 0.0)


def _leaf_to_ckpt_idxs(n):
    """Checkpoint index range for leaf ``n`` (power-of-two scheme).

    ``idx_max`` = popcount(n >> 1); ``idx_min`` = idx_max - (number of
    trailing one-bits of n) + 1.
    """
    n = n.astype(jnp.int32)

    def popcount(x):
        def body(carry):
            v, c = carry
            return v >> 1, c + (v & 1)

        _, c = jax.lax.while_loop(lambda s: s[0] > 0, body, (x, jnp.int32(0)))
        return c

    idx_max = popcount(n >> 1)

    def trailing_ones(x):
        def body(carry):
            v, c = carry
            return v >> 1, c + 1

        _, c = jax.lax.while_loop(
            lambda s: (s[0] & 1) != 0, body, (x, jnp.int32(0))
        )
        return c

    idx_min = idx_max - trailing_ones(n) + 1
    return idx_min, idx_max


def _ckpt_turning(inv_mass, r_ckpts, r_sum_ckpts, r_new, r_sum_new, idx_min, idx_max):
    """Check U-turns of the new leaf against every checkpointed sub-interval."""

    def body(state):
        i, _ = state
        sub_r_sum = r_sum_new - r_sum_ckpts[i] + r_ckpts[i]
        turning = _is_turning(inv_mass, r_ckpts[i], r_new, sub_r_sum)
        return i - 1, turning

    _, turning = jax.lax.while_loop(
        lambda s: (s[0] >= idx_min) & ~s[1], body, (idx_max, jnp.array(False))
    )
    return turning


def nuts_step(
    logp_and_grad: Callable,
    state: HMCState,
    key: jax.Array,
    *,
    step_size,
    inv_mass: jax.Array,
    max_depth: int = 10,
    divergence_threshold: float = 1000.0,
):
    """One NUTS transition.  Returns ``(HMCState, NUTSInfo)``."""
    dtype = state.x.dtype
    dim = state.x.shape[0]
    k_mom, k_loop = jax.random.split(key)
    r0 = sample_momentum(k_mom, state.x, inv_mass)
    energy0 = -state.logp + kinetic_energy(r0, inv_mass)

    init_tree = _Tree(
        z_left=state.x,
        r_left=r0,
        grad_left=state.grad,
        z_right=state.x,
        r_right=r0,
        grad_right=state.grad,
        z_prop=state.x,
        logp_prop=state.logp,
        grad_prop=state.grad,
        energy_prop=energy0,
        log_weight=jnp.zeros((), dtype),
        r_sum=r0,
        turning=jnp.array(False),
        diverging=jnp.array(False),
        sum_accept=jnp.zeros((), dtype),
        num_leaves=jnp.zeros((), jnp.int32),
    )

    def build_subtree(boundary: IntegratorState, num_new, direction, key):
        """Add ``num_new`` leaves beyond ``boundary`` in ``direction``.

        Returns the final Carry: last leaf reached plus subtree
        aggregates.  Uses the checkpoint stacks for intra-subtree U-turn
        detection.
        """
        signed_step = step_size * direction.astype(dtype)
        r_ckpts = jnp.zeros((max_depth + 1, dim), dtype)
        r_sum_ckpts = jnp.zeros((max_depth + 1, dim), dtype)

        class Carry(NamedTuple):
            leaf: IntegratorState
            z_prop: jax.Array
            logp_prop: jax.Array
            grad_prop: jax.Array
            energy_prop: jax.Array
            log_weight: jax.Array
            r_sum: jax.Array
            sum_accept: jax.Array
            k: jax.Array
            turning: jax.Array
            diverging: jax.Array
            r_ckpts: jax.Array
            r_sum_ckpts: jax.Array
            key: jax.Array

        def cond(c: Carry):
            return (c.k < num_new) & ~c.turning & ~c.diverging

        def body(c: Carry):
            key, k_sel = jax.random.split(c.key)
            leaf = leapfrog(logp_and_grad, c.leaf, signed_step, inv_mass)
            energy = -leaf.logp + kinetic_energy(leaf.r, inv_mass)
            delta = energy0 - energy  # log multinomial weight
            delta = jnp.where(jnp.isnan(delta), -jnp.inf, delta)
            diverging = -delta > divergence_threshold
            accept = jnp.minimum(1.0, jnp.exp(delta))

            # Streaming multinomial proposal within the subtree.
            new_log_weight = jnp.logaddexp(c.log_weight, delta)
            p_take = jnp.exp(delta - new_log_weight)
            take = jax.random.uniform(k_sel, dtype=dtype) < p_take
            z_prop = jnp.where(take, leaf.x, c.z_prop)
            logp_prop = jnp.where(take, leaf.logp, c.logp_prop)
            grad_prop = jnp.where(take, leaf.grad, c.grad_prop)
            energy_prop = jnp.where(take, energy, c.energy_prop)

            r_sum = c.r_sum + leaf.r
            # Checkpoint on even leaves, U-turn check on odd leaves.
            idx_min, idx_max = _leaf_to_ckpt_idxs(c.k)
            is_even = (c.k % 2) == 0
            r_ckpts = jnp.where(
                is_even, c.r_ckpts.at[idx_max].set(leaf.r), c.r_ckpts
            )
            r_sum_ckpts = jnp.where(
                is_even, c.r_sum_ckpts.at[idx_max].set(r_sum), c.r_sum_ckpts
            )
            turning = jax.lax.cond(
                is_even | diverging,
                lambda: jnp.array(False),
                lambda: _ckpt_turning(
                    inv_mass, r_ckpts, r_sum_ckpts, leaf.r, r_sum, idx_min, idx_max
                ),
            )
            return Carry(
                leaf=leaf,
                z_prop=z_prop,
                logp_prop=logp_prop,
                grad_prop=grad_prop,
                energy_prop=energy_prop,
                log_weight=new_log_weight,
                r_sum=r_sum,
                sum_accept=c.sum_accept + accept,
                k=c.k + 1,
                turning=turning,
                diverging=diverging,
                r_ckpts=r_ckpts,
                r_sum_ckpts=r_sum_ckpts,
                key=key,
            )

        init = Carry(
            leaf=boundary,
            z_prop=boundary.x,
            logp_prop=boundary.logp,
            grad_prop=boundary.grad,
            energy_prop=energy0,
            log_weight=jnp.full((), -jnp.inf, dtype),
            r_sum=jnp.zeros((dim,), dtype),
            sum_accept=jnp.zeros((), dtype),
            k=jnp.zeros((), jnp.int32),
            turning=jnp.array(False),
            diverging=jnp.array(False),
            r_ckpts=r_ckpts,
            r_sum_ckpts=r_sum_ckpts,
            key=key,
        )
        return jax.lax.while_loop(cond, body, init)

    class LoopCarry(NamedTuple):
        tree: _Tree
        depth: jax.Array
        key: jax.Array

    def loop_cond(c: LoopCarry):
        return (
            (c.depth < max_depth) & ~c.tree.turning & ~c.tree.diverging
        )

    def loop_body(c: LoopCarry):
        tree = c.tree
        key, k_dir, k_sub, k_comb = jax.random.split(c.key, 4)
        go_right = jax.random.bernoulli(k_dir)
        direction = jnp.where(go_right, 1, -1)

        # Boundary logp is never read by leapfrog (it recomputes after the
        # position update), so a zero placeholder is fine.
        zero = jnp.zeros((), dtype)
        boundary = jax.lax.cond(
            go_right,
            lambda: IntegratorState(tree.z_right, tree.r_right, zero, tree.grad_right),
            lambda: IntegratorState(tree.z_left, tree.r_left, zero, tree.grad_left),
        )
        # The new subtree must mirror the whole existing trajectory:
        # tree.num_leaves counts *added* leaves, so the total point count
        # (and thus the subtree size at this doubling) is num_leaves + 1.
        num_new = tree.num_leaves + 1
        sub = build_subtree(boundary, num_new, direction, k_sub)

        sub_incomplete = sub.turning | sub.diverging

        # Merge boundaries: the subtree's last leaf becomes the new
        # far end; its first leaf is adjacent to the old boundary.
        def merged_tree():
            z_left = jnp.where(go_right, tree.z_left, sub.leaf.x)
            r_left = jnp.where(go_right, tree.r_left, sub.leaf.r)
            grad_left = jnp.where(go_right, tree.grad_left, sub.leaf.grad)
            z_right = jnp.where(go_right, sub.leaf.x, tree.z_right)
            r_right = jnp.where(go_right, sub.leaf.r, tree.r_right)
            grad_right = jnp.where(go_right, sub.leaf.grad, tree.grad_right)

            # Biased progressive sampling toward the new subtree.
            p_new = jnp.minimum(1.0, jnp.exp(sub.log_weight - tree.log_weight))
            take = jax.random.uniform(k_comb, dtype=dtype) < p_new
            z_prop = jnp.where(take, sub.z_prop, tree.z_prop)
            logp_prop = jnp.where(take, sub.logp_prop, tree.logp_prop)
            grad_prop = jnp.where(take, sub.grad_prop, tree.grad_prop)
            energy_prop = jnp.where(take, sub.energy_prop, tree.energy_prop)

            r_sum = tree.r_sum + sub.r_sum
            turning = _is_turning(inv_mass, r_left, r_right, r_sum)
            return _Tree(
                z_left=z_left,
                r_left=r_left,
                grad_left=grad_left,
                z_right=z_right,
                r_right=r_right,
                grad_right=grad_right,
                z_prop=z_prop,
                logp_prop=logp_prop,
                grad_prop=grad_prop,
                energy_prop=energy_prop,
                log_weight=jnp.logaddexp(tree.log_weight, sub.log_weight),
                r_sum=r_sum,
                turning=turning,
                diverging=jnp.array(False),
                sum_accept=tree.sum_accept + sub.sum_accept,
                num_leaves=tree.num_leaves + sub.k,
            )

        def stopped_tree():
            # Subtree turned/diverged: discard its proposal, keep stats.
            return tree._replace(
                turning=sub.turning,
                diverging=sub.diverging,
                sum_accept=tree.sum_accept + sub.sum_accept,
                num_leaves=tree.num_leaves + sub.k,
            )

        new_tree = jax.lax.cond(sub_incomplete, stopped_tree, merged_tree)
        return LoopCarry(tree=new_tree, depth=c.depth + 1, key=key)

    final = jax.lax.while_loop(
        loop_cond, loop_body, LoopCarry(init_tree, jnp.zeros((), jnp.int32), k_loop)
    )
    tree = final.tree

    new_state = HMCState(x=tree.z_prop, logp=tree.logp_prop, grad=tree.grad_prop)
    info = NUTSInfo(
        accept_prob=tree.sum_accept / jnp.maximum(tree.num_leaves, 1).astype(dtype),
        diverging=tree.diverging,
        depth=final.depth,
        num_leaves=tree.num_leaves,
        energy=tree.energy_prop,
    )
    return new_state, info
