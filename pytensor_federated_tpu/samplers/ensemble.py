"""Affine-invariant ensemble sampler (stretch move) — gradient-free MCMC.

Net-new sampler family.  The reference can only sample blackbox
likelihoods whose *gradients* the nodes also serve (reference:
common.py:26-49 requires one grad per input); an ensemble sampler needs
only logp values, so it covers federated models where shards cannot
provide gradients at all — while staying TPU-shaped: all walkers move in
two half-ensemble batches per step, each a single big vmapped logp call.

The stretch move: to update walker ``x`` pick a partner ``c`` from the
complementary half-ensemble, draw ``z`` from ``g(z) ∝ 1/sqrt(z)`` on
``[1/a, a]``, propose ``y = c + z (x - c)``, accept with probability
``min(1, z^(d-1) p(y)/p(x))`` — affine-invariant, so it is insensitive
to linear correlation/scaling of the posterior.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from .util import flatten_logp


class EnsembleResult(NamedTuple):
    samples: Any  # user pytree, leaves lead with (n_steps, n_walkers)
    logps: jax.Array  # (n_steps, n_walkers)
    accept_rate: jax.Array  # scalar mean acceptance


def ensemble_sample(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    n_walkers: int = 64,
    num_warmup: int = 500,
    num_samples: int = 500,
    stretch_a: float = 2.0,
    init_jitter: float = 0.1,
    thin: int = 1,
) -> EnsembleResult:
    """Run the stretch-move ensemble sampler against ``logp_fn``.

    ``n_walkers`` must be even and should be >= 2x the parameter
    dimension.  The whole run (warmup + sampling) is one ``lax.scan``;
    per scan step both half-ensembles update, costing two batched logp
    evaluations of ``n_walkers/2`` particles each.
    """
    if n_walkers % 2 != 0:
        raise ValueError(f"n_walkers must be even, got {n_walkers}")
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    dim = flat_init.shape[0]
    dtype = flat_init.dtype
    if n_walkers < 2 * dim:
        raise ValueError(
            f"n_walkers={n_walkers} < 2*dim={2 * dim}; the stretch move "
            "degenerates when the ensemble does not span the space"
        )
    half = n_walkers // 2
    batch_logp = jax.vmap(flat_logp)

    k_init, k_run = jax.random.split(key)
    x0 = flat_init[None, :] + init_jitter * jax.random.normal(
        k_init, (n_walkers, dim), dtype
    )
    lp0 = batch_logp(x0)

    def half_update(key, movers, movers_lp, others):
        """Stretch-move update of one half-ensemble against the other."""
        k_z, k_c, k_u = jax.random.split(key, 3)
        # z ~ g(z) ∝ 1/sqrt(z) on [1/a, a]:  z = ((a-1) u + 1)^2 / a
        u = jax.random.uniform(k_z, (half,), dtype=dtype)
        z = ((stretch_a - 1.0) * u + 1.0) ** 2 / stretch_a
        partners = others[jax.random.randint(k_c, (half,), 0, half)]
        prop = partners + z[:, None] * (movers - partners)
        prop_lp = batch_logp(prop)
        log_ratio = (dim - 1) * jnp.log(z) + prop_lp - movers_lp
        acc = jnp.log(jax.random.uniform(k_u, (half,), dtype=dtype)) < log_ratio
        movers = jnp.where(acc[:, None], prop, movers)
        movers_lp = jnp.where(acc, prop_lp, movers_lp)
        return movers, movers_lp, jnp.mean(acc.astype(dtype))

    def step(carry, key):
        x, lp = carry
        k1, k2 = jax.random.split(key)
        a, a_lp, acc_a = half_update(k1, x[:half], lp[:half], x[half:])
        b, b_lp, acc_b = half_update(k2, x[half:], lp[half:], a)
        x = jnp.concatenate([a, b])
        lp = jnp.concatenate([a_lp, b_lp])
        return (x, lp), (x, lp, 0.5 * (acc_a + acc_b))

    total = num_warmup + num_samples * thin

    @jax.jit
    def run(x0, lp0, key):
        keys = jax.random.split(key, total)
        (_, _), (xs, lps, accs) = jax.lax.scan(step, (x0, lp0), keys)
        keep = xs[num_warmup :: thin][: num_samples]
        keep_lp = lps[num_warmup :: thin][: num_samples]
        return keep, keep_lp, jnp.mean(accs[num_warmup:])

    draws, draw_lps, accept = run(x0, lp0, k_run)
    samples = jax.vmap(jax.vmap(unravel))(draws)
    return EnsembleResult(samples=samples, logps=draw_lps, accept_rate=accept)
