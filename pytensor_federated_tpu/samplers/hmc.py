"""Hamiltonian Monte Carlo: leapfrog integrator + HMC kernel.

All control flow is ``lax``-level (static leapfrog count per step via
``lax.scan``) so a full HMC transition — including the federated
logp+grad psum — is one XLA program.  The gradient evaluations that cost
the reference a round of gRPC round-trips each (reference:
op_async.py:107-132, §3.3 of SURVEY.md) are here just fused device code.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp


class IntegratorState(NamedTuple):
    x: jax.Array
    r: jax.Array
    logp: jax.Array
    grad: jax.Array


def mass_velocity(inv_mass: jax.Array, r: jax.Array) -> jax.Array:
    """``v = M⁻¹ r``.  ``inv_mass`` is either the diagonal of M⁻¹ (a
    ``(d,)`` vector — elementwise product) or the full M⁻¹ (a ``(d, d)``
    matrix — a matvec, which the MXU likes).  The branch is on a static
    trace-time property, so each variant compiles to exactly its own
    code."""
    if inv_mass.ndim == 2:
        return inv_mass @ r
    return inv_mass * r


def leapfrog(
    logp_and_grad: Callable,
    state: IntegratorState,
    step_size,
    inv_mass: jax.Array,
) -> IntegratorState:
    """One leapfrog step (diagonal or dense mass matrix)."""
    r_half = state.r + 0.5 * step_size * state.grad
    if inv_mass.ndim == 2:
        x_new = state.x + step_size * (inv_mass @ r_half)
    else:
        # Bitwise-identical grouping to the pre-dense form:
        # (step_size * inv_mass) * r_half, NOT step_size * (inv_mass *
        # r_half) — the rounding difference flips borderline accepts.
        x_new = state.x + step_size * inv_mass * r_half
    logp_new, grad_new = logp_and_grad(x_new)
    r_new = r_half + 0.5 * step_size * grad_new
    return IntegratorState(x_new, r_new, logp_new, grad_new)


def kinetic_energy(r: jax.Array, inv_mass: jax.Array) -> jax.Array:
    if inv_mass.ndim == 2:
        return 0.5 * r @ (inv_mass @ r)
    # Keep the diagonal path BITWISE identical to the pre-dense form
    # (0.5 * Σ m⁻¹ r² rounds differently from 0.5 * Σ r·(m⁻¹r), which
    # is enough to flip borderline accept decisions and send seeded
    # posterior-recovery tests off their tolerance).
    return 0.5 * jnp.sum(inv_mass * r**2)


def sample_momentum(key, x: jax.Array, inv_mass: jax.Array) -> jax.Array:
    """``r ~ N(0, M)`` with ``M = inv_mass⁻¹``.

    Dense case: with ``inv_mass = L Lᵀ`` (Cholesky), ``r = L⁻ᵀ z`` has
    covariance ``L⁻ᵀ L⁻¹ = (L Lᵀ)⁻¹ = M``.  The factorization is one
    ``d³/3`` per transition — negligible next to the trajectory's
    leapfrog logp+grad evaluations for the moderate ``d`` this
    framework targets."""
    z = jax.random.normal(key, x.shape, x.dtype)
    if inv_mass.ndim == 2:
        chol = jnp.linalg.cholesky(inv_mass)
        return jax.scipy.linalg.solve_triangular(chol.T, z, lower=False)
    return z / jnp.sqrt(inv_mass)


class HMCState(NamedTuple):
    x: jax.Array
    logp: jax.Array
    grad: jax.Array


class HMCInfo(NamedTuple):
    accept_prob: jax.Array
    accepted: jax.Array
    energy: jax.Array
    diverging: jax.Array


def hmc_init(logp_and_grad: Callable, x0: jax.Array) -> HMCState:
    logp, grad = logp_and_grad(x0)
    return HMCState(x0, logp, grad)


def hmc_step(
    logp_and_grad: Callable,
    state: HMCState,
    key: jax.Array,
    *,
    step_size,
    inv_mass: jax.Array,
    num_steps: int = 16,
    divergence_threshold: float = 1000.0,
):
    """One HMC transition with ``num_steps`` leapfrog steps (static)."""
    k_mom, k_acc = jax.random.split(key)
    r0 = sample_momentum(k_mom, state.x, inv_mass)
    energy0 = -state.logp + kinetic_energy(r0, inv_mass)

    init = IntegratorState(state.x, r0, state.logp, state.grad)

    def body(carry, _):
        return leapfrog(logp_and_grad, carry, step_size, inv_mass), None

    end, _ = jax.lax.scan(body, init, None, length=num_steps)

    energy1 = -end.logp + kinetic_energy(end.r, inv_mass)
    delta = energy0 - energy1
    delta = jnp.where(jnp.isnan(delta), -jnp.inf, delta)
    diverging = -delta > divergence_threshold
    accept_prob = jnp.minimum(1.0, jnp.exp(delta))
    accept = jax.random.uniform(k_acc, dtype=accept_prob.dtype) < accept_prob

    new_state = HMCState(
        x=jnp.where(accept, end.x, state.x),
        logp=jnp.where(accept, end.logp, state.logp),
        grad=jnp.where(accept, end.grad, state.grad),
    )
    # Report the energy of the state the chain actually occupies, so
    # energy-marginal diagnostics (E-BFMI) are not polluted by rejected
    # (possibly divergent) trajectory endpoints.
    info = HMCInfo(accept_prob, accept, jnp.where(accept, energy1, energy0), diverging)
    return new_state, info


def find_reasonable_step_size(
    logp_and_grad: Callable,
    x0: jax.Array,
    key: jax.Array,
    inv_mass: jax.Array,
    *,
    init_step_size: float = 1.0,
    target: float = 0.8,
    max_iters: int = 60,
) -> jax.Array:
    """Heuristic initial step size (Hoffman & Gelman 2014, Algorithm 4)."""
    logp0, grad0 = logp_and_grad(x0)
    r0 = sample_momentum(key, x0, inv_mass)
    energy0 = -logp0 + kinetic_energy(r0, inv_mass)

    def accept_prob(step_size):
        st = IntegratorState(x0, r0, logp0, grad0)
        end = leapfrog(logp_and_grad, st, step_size, inv_mass)
        energy1 = -end.logp + kinetic_energy(end.r, inv_mass)
        delta = energy0 - energy1
        return jnp.where(jnp.isnan(delta), -jnp.inf, delta)

    init_delta = accept_prob(jnp.asarray(init_step_size, x0.dtype))
    direction = jnp.where(init_delta > jnp.log(target), 1.0, -1.0)

    def cond(carry):
        step_size, i = carry
        delta = accept_prob(step_size)
        crossed = jnp.where(
            direction > 0, delta < jnp.log(target), delta > jnp.log(target)
        )
        return (~crossed) & (i < max_iters)

    def body(carry):
        step_size, i = carry
        return step_size * (2.0**direction), i + 1

    step_size, _ = jax.lax.while_loop(
        cond, body, (jnp.asarray(init_step_size, x0.dtype), jnp.zeros((), jnp.int32))
    )
    return step_size
