"""Stochastic-gradient Langevin dynamics (Welling & Teh 2011).

The scale-out sampler for when even one federated pass is too much:
each step consumes an *unbiased stochastic* gradient — typically
``FederatedLogp.logp_and_grad_minibatch`` over a random subset of
shards, where the gather makes compute proportional to the subset —
plus injected Gaussian noise matched to the step size, so the iterates
sample (approximately) from the posterior rather than collapsing to the
MAP.

TPU-first shape: the whole chain is one ``lax.scan`` of jitted steps;
there is no Metropolis correction (standard SGLD), so the step size
trades bias for mixing — use the polynomial decay helper or a small
constant step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class SGLDResult:
    samples: Any  # pytree, leading axis num_samples
    logps: jax.Array  # (num_samples,) stochastic logp estimates
    unravel: Callable[[jax.Array], Any]


def polynomial_decay(
    a: float = 1e-3, b: float = 1.0, gamma: float = 0.55
) -> Callable[[jax.Array], jax.Array]:
    """Welling-Teh step schedule ``eps_t = a (b + t)^{-gamma}``
    (gamma in (0.5, 1] satisfies the SGLD convergence conditions)."""

    def schedule(t):
        return a * (b + t) ** (-gamma)

    return schedule


def _run_chain(step_fn, carry0, *, num_samples, num_burnin, thin, unravel):
    """Scan a Langevin chain and slice out the kept draws (shared by
    SGLD and SGHMC so the thinning/packaging can never diverge).

    ``step_fn(carry, t) -> (carry, (x_flat, logp_estimate))`` where the
    emitted pair refers to the SAME pre-update state."""
    total = num_burnin + num_samples * thin
    _, (xs, lps) = jax.lax.scan(step_fn, carry0, jnp.arange(total))
    keep = xs[num_burnin::thin][:num_samples]
    lps = lps[num_burnin::thin][:num_samples]
    return SGLDResult(
        samples=jax.vmap(unravel)(keep), logps=lps, unravel=unravel
    )


def _as_schedule(step_size):
    """Float-or-callable step size -> ``t -> eps_t`` callable (shared
    contract for both samplers)."""
    return step_size if callable(step_size) else (lambda t: step_size)


def sgld_sample(
    logp_and_grad_fn: Callable[[Any, jax.Array], tuple],
    init_params: Any,
    key: jax.Array,
    *,
    num_samples: int = 1000,
    num_burnin: int = 500,
    step_size: Any = 1e-3,
    thin: int = 1,
) -> SGLDResult:
    """Run one SGLD chain.

    ``logp_and_grad_fn(params, key) -> (logp_estimate, grad_estimate)``
    is any unbiased stochastic oracle — e.g.
    ``lambda p, k: fed.logp_and_grad_minibatch(p, k, num_shards=8)``
    for shard-subsampled federated likelihoods, or a deterministic
    ``value_and_grad`` closure (full-batch Langevin) that ignores the
    key.  ``step_size`` is a float or a ``t -> eps_t`` schedule
    (:func:`polynomial_decay`).

    Update: ``theta += eps/2 * grad + N(0, eps)`` — Langevin dynamics
    whose gradient-noise bias vanishes as ``eps -> 0``.
    """
    from jax.flatten_util import ravel_pytree

    flat_init, unravel = ravel_pytree(init_params)
    eps_fn = _as_schedule(step_size)

    def step(carry, t):
        x, k = carry
        k, k_grad, k_noise = jax.random.split(k, 3)
        lp, g = logp_and_grad_fn(unravel(x), k_grad)
        g_flat = ravel_pytree(g)[0]
        eps = eps_fn(t)
        noise = jnp.sqrt(eps) * jax.random.normal(
            k_noise, x.shape, x.dtype
        )
        x_new = x + 0.5 * eps * g_flat + noise
        # Emit (x, lp) for the SAME state: lp was estimated at the
        # pre-update x, so that's the iterate recorded with it.
        return (x_new, k), (x, lp)

    return _run_chain(
        step,
        (flat_init, key),
        num_samples=num_samples,
        num_burnin=num_burnin,
        thin=thin,
        unravel=unravel,
    )


def psgld_sample(
    logp_and_grad_fn: Callable[[Any, jax.Array], tuple],
    init_params: Any,
    key: jax.Array,
    *,
    num_samples: int = 1000,
    num_burnin: int = 500,
    step_size: Any = 1e-3,
    beta: float = 0.99,
    eps_rms: float = 1e-5,
    thin: int = 1,
) -> SGLDResult:
    """Preconditioned SGLD (Li et al., AAAI 2016): RMSProp-style
    diagonal preconditioning of the Langevin dynamics.

    Per step, with ``V`` the EMA of squared gradients and
    ``G = 1 / (eps_rms + sqrt(V))``:

        theta += eps/2 * G * grad + N(0, eps * G)

    Equalizes step scales across parameters whose gradients differ by
    orders of magnitude (hierarchical scales, stiff likelihoods) where
    plain SGLD must crawl at the smallest stable step.  (The Gamma(G)
    curvature-drift term of the paper is dropped, as is standard — it
    vanishes as the EMA stabilizes.)  Same oracle and float-or-schedule
    ``step_size`` contract as :func:`sgld_sample`.

    The EMA is warm-started from the init point's squared gradient (one
    extra oracle call) so the first steps are preconditioned by real
    scale information rather than ``G = 1/eps_rms`` (a huge
    posterior-agnostic kick that can overflow stiff likelihoods).
    Caveat: initializing *exactly* at a stationary point leaves the
    gradient with no scale information at all — jitter the init or use
    :func:`sgld_sample` there.
    """
    from jax.flatten_util import ravel_pytree

    flat_init, unravel = ravel_pytree(init_params)
    eps_fn = _as_schedule(step_size)

    key, k_warm = jax.random.split(key)
    _, g0 = logp_and_grad_fn(init_params, k_warm)
    V0 = ravel_pytree(g0)[0] ** 2

    def step(carry, t):
        x, V, k = carry
        k, k_grad, k_noise = jax.random.split(k, 3)
        lp, g = logp_and_grad_fn(unravel(x), k_grad)
        g_flat = ravel_pytree(g)[0]
        V = beta * V + (1.0 - beta) * g_flat**2
        G = 1.0 / (eps_rms + jnp.sqrt(V))
        eps = eps_fn(t)
        noise = jnp.sqrt(eps * G) * jax.random.normal(
            k_noise, x.shape, x.dtype
        )
        x_new = x + 0.5 * eps * G * g_flat + noise
        return (x_new, V, k), (x, lp)

    return _run_chain(
        step,
        (flat_init, V0, key),
        num_samples=num_samples,
        num_burnin=num_burnin,
        thin=thin,
        unravel=unravel,
    )


def sghmc_sample(
    logp_and_grad_fn: Callable[[Any, jax.Array], tuple],
    init_params: Any,
    key: jax.Array,
    *,
    num_samples: int = 1000,
    num_burnin: int = 500,
    step_size: Any = 1e-3,
    friction: float = 1.0,
    thin: int = 1,
) -> SGLDResult:
    """Stochastic-gradient Hamiltonian Monte Carlo (Chen et al. 2014).

    Same oracle and ``step_size`` (float or ``t -> eps_t`` schedule)
    contract as :func:`sgld_sample`, but with a momentum variable and
    friction: per step,

        v <- (1 - eps*C) v + eps * grad + N(0, 2*C*eps)
        theta <- theta + eps * v

    — underdamped Langevin whose friction ``C`` dissipates the
    stochastic-gradient noise, typically mixing faster than SGLD on
    correlated posteriors.  Identity mass, no Metropolis correction.
    """
    from jax.flatten_util import ravel_pytree

    flat_init, unravel = ravel_pytree(init_params)
    eps_fn = _as_schedule(step_size)

    def step(carry, t):
        x, v, k = carry
        k, k_grad, k_noise = jax.random.split(k, 3)
        lp, g = logp_and_grad_fn(unravel(x), k_grad)
        g_flat = ravel_pytree(g)[0]
        eps = eps_fn(t)
        noise_sd = jnp.sqrt(2.0 * friction * eps)
        v = (
            (1.0 - eps * friction) * v
            + eps * g_flat
            + noise_sd * jax.random.normal(k_noise, x.shape, x.dtype)
        )
        x_new = x + eps * v
        return (x_new, v, k), (x, lp)

    v0 = jnp.zeros_like(flat_init)
    return _run_chain(
        step,
        (flat_init, v0, key),
        num_samples=num_samples,
        num_burnin=num_burnin,
        thin=thin,
        unravel=unravel,
    )
