"""Simulation-based calibration (Talts et al. 2018, arXiv:1804.06788).

The end-to-end statistical correctness check for a sampler: draw
``theta* ~ prior``, simulate ``data | theta*``, sample the posterior,
and record the RANK of ``theta*`` among the posterior draws.  If (and
only if) the sampler targets the right posterior, ranks are uniform on
``{0..L}`` — a miscalibrated sampler (wrong step size bias, broken
gradient, wrong likelihood) shows up as U-shaped, humped, or skewed
rank histograms.  This is the statistical analog of the repo's
golden-model equivalence tests, and it exercises prior-sampling,
simulation, warmup, and the kernel in one loop.

TPU-shaped: all ``n_sims`` replications run as ONE jitted program —
the per-simulation warmup + NUTS chain is vmapped over the simulated
datasets, so there is exactly one compile however many replications
are requested (a Python loop of ``sample()`` calls would recompile per
dataset, since each closure's data is a fresh constant).

Caveat (as in the paper): ranks computed from autocorrelated draws
over-disperse slightly; use ``thin`` to decorrelate.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from .mcmc import _warmup, make_kernel_step

__all__ = ["SBCResult", "sbc_ranks", "sbc_uniformity"]


class SBCResult(NamedTuple):
    ranks: jax.Array  # (n_sims, dim) int32 in {0..L}
    n_levels: int  # L + 1 possible rank values
    param_names: Any  # flat-coordinate labels (best effort)


def sbc_ranks(
    prior_sample: Callable[[jax.Array], Any],
    simulate: Callable[[jax.Array, Any], Any],
    logp: Callable[[Any, Any], jax.Array],
    *,
    key: jax.Array,
    n_sims: int = 64,
    num_warmup: int = 200,
    num_samples: int = 128,
    thin: int = 4,
    max_depth: int = 6,
    target_accept: float = 0.8,
) -> SBCResult:
    """Rank statistics for ``n_sims`` prior-predictive replications.

    ``prior_sample(key) -> params``; ``simulate(key, params) -> data``
    (any pytree of arrays, FIXED shapes across draws); ``logp(params,
    data) -> scalar`` — note the explicit ``data`` argument, which is
    what lets every replication share one compiled program.

    The kept draws are thinned by ``thin``; ranks take values in
    ``{0, ..., num_samples // thin}``.
    """
    if num_samples < thin:
        raise ValueError(
            f"num_samples={num_samples} < thin={thin}: no draws would "
            "be kept and every rank would be 0"
        )
    k_prior, k_sim, k_mcmc = jax.random.split(key, 3)
    thetas = jax.vmap(prior_sample)(jax.random.split(k_prior, n_sims))
    datas = jax.vmap(simulate)(jax.random.split(k_sim, n_sims), thetas)

    theta0 = jax.tree_util.tree_map(lambda a: a[0], thetas)
    flat0, unravel = ravel_pytree(theta0)
    dim = flat0.shape[0]

    flat_thetas = jax.vmap(lambda t: ravel_pytree(t)[0])(thetas)
    kept = num_samples // thin

    def one(theta_flat, data, key):
        def lg(x):
            return jax.value_and_grad(
                lambda v: logp(unravel(v), data)
            )(x)

        kernel_step = make_kernel_step(lg, "nuts", max_depth=max_depth)
        k_warm, k_samp = jax.random.split(key)
        # Initialize AT the true draw: it is a perfect posterior sample
        # (that is the whole point of SBC), so no burn-in bias.
        warm = _warmup(
            lg,
            theta_flat,
            k_warm,
            num_warmup=num_warmup,
            kernel_step=kernel_step,
            target_accept=target_accept,
        )

        def body(state, key):
            state, _ = kernel_step(
                state,
                key,
                step_size=warm.step_size,
                inv_mass=warm.inv_mass,
            )
            return state, state.x

        _, draws = jax.lax.scan(
            body, warm.state, jax.random.split(k_samp, num_samples)
        )
        draws = draws[thin - 1 :: thin]  # (kept, dim)
        return jnp.sum(
            (draws < theta_flat[None, :]).astype(jnp.int32), axis=0
        )

    ranks = jax.jit(jax.vmap(one))(
        flat_thetas, datas, jax.random.split(k_mcmc, n_sims)
    )

    # best-effort flat-coordinate names from the pytree structure
    leaves = jax.tree_util.tree_leaves_with_path(theta0)
    names = []
    for path, leaf in leaves:
        base = jax.tree_util.keystr(path)
        size = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        names += (
            [base] if size == 1 else [f"{base}[{i}]" for i in range(size)]
        )
    return SBCResult(ranks=ranks, n_levels=kept + 1, param_names=names)


def sbc_uniformity(result: SBCResult, *, n_bins: int = 8):
    """Per-coordinate chi-square statistic against uniform ranks.

    Returns ``(stat, dof)`` arrays; under calibration each ``stat`` is
    ~chi2(dof).  A quick screen, not a substitute for LOOKING at the
    histograms (Talts et al. fig. 2-4): use e.g. ``stat < dof +
    4*sqrt(2*dof)`` as a loose gate in tests.
    """
    ranks = np.asarray(result.ranks)
    n_sims, dim = ranks.shape
    edges = np.linspace(0, result.n_levels, n_bins + 1)
    # Ranks are integers in [0, n_levels); when n_bins does not divide
    # n_levels the bins cover unequal numbers of integer levels, so the
    # expected count must be proportional to each bin's level coverage.
    levels = np.arange(result.n_levels)
    levels_per_bin, _ = np.histogram(levels, bins=edges)
    # n_levels < n_bins leaves some bins covering no integer level at
    # all; those contribute 0 observed and 0 expected — drop them (and
    # shrink the dof to the bins that remain) instead of dividing 0/0.
    keep = levels_per_bin > 0
    expected = n_sims * levels_per_bin[keep] / result.n_levels
    stats = np.empty((dim,))
    for j in range(dim):
        hist, _ = np.histogram(ranks[:, j], bins=edges)
        stats[j] = np.sum((hist[keep] - expected) ** 2 / expected)
    return stats, int(keep.sum()) - 1
