"""Sampler building blocks: flattening, Welford variance, dual averaging.

The reference delegates sampling to PyMC (reference: demo_model.py:38-42
``pm.find_MAP`` + ``pm.sample``); this framework ships its own on-device
samplers so the whole NUTS step — including the federated logp+grad —
compiles into one XLA program with no host round-trips (SURVEY §7 step 3).

Everything here is shape-static and jit/scan/vmap-safe.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def flatten_logp(logp_fn: Callable[[Any], jax.Array], example_params: Any):
    """Return ``(flat_logp, flat_init, unravel)`` over a flat float vector.

    Samplers work on a single flat vector (good for the VPU: one fused
    elementwise update per leapfrog step instead of a pytree of tiny
    kernels); ``unravel`` restores user structure at the boundary.
    """
    flat_init, unravel = ravel_pytree(example_params)

    def flat_logp(x):
        return logp_fn(unravel(x))

    return flat_logp, flat_init, unravel


class WelfordState(NamedTuple):
    """Streaming mean/variance (diagonal) — mass-matrix adaptation."""

    mean: jax.Array
    m2: jax.Array
    count: jax.Array


def welford_init(
    dim: int, dtype=jnp.float32, *, dense: bool = False
) -> WelfordState:
    """``dense=True`` accumulates the full ``(dim, dim)`` second-moment
    matrix (for dense-mass adaptation) instead of the diagonal."""
    m2_shape = (dim, dim) if dense else (dim,)
    return WelfordState(
        mean=jnp.zeros((dim,), dtype),
        m2=jnp.zeros(m2_shape, dtype),
        count=jnp.zeros((), dtype),
    )


def welford_update(state: WelfordState, x: jax.Array) -> WelfordState:
    count = state.count + 1.0
    delta = x - state.mean
    mean = state.mean + delta / count
    if state.m2.ndim == 2:
        m2 = state.m2 + jnp.outer(delta, x - mean)
    else:
        m2 = state.m2 + delta * (x - mean)
    return WelfordState(mean, m2, count)


def welford_variance(state: WelfordState, *, regularize: bool = True) -> jax.Array:
    """Diagonal variance estimate, Stan-style regularized toward unit."""
    var = state.m2 / jnp.maximum(state.count - 1.0, 1.0)
    if regularize:
        n = state.count
        var = (n / (n + 5.0)) * var + 1e-3 * (5.0 / (n + 5.0))
    return var


def welford_covariance(
    state: WelfordState, *, regularize: bool = True
) -> jax.Array:
    """Full covariance estimate from a ``dense=True`` accumulator,
    Stan-style shrunk toward (a small multiple of) the identity — the
    same ``n/(n+5)`` schedule as :func:`welford_variance`, which also
    keeps the estimate positive-definite at low counts."""
    cov = state.m2 / jnp.maximum(state.count - 1.0, 1.0)
    if regularize:
        n = state.count
        dim = state.mean.shape[0]
        eye = jnp.eye(dim, dtype=state.mean.dtype)
        cov = (n / (n + 5.0)) * cov + 1e-3 * (5.0 / (n + 5.0)) * eye
    return cov


class DualAveragingState(NamedTuple):
    """Nesterov dual averaging on log step size (Hoffman & Gelman 2014)."""

    log_step: jax.Array
    log_step_avg: jax.Array
    h_avg: jax.Array
    mu: jax.Array
    count: jax.Array


def da_init(step_size: jax.Array) -> DualAveragingState:
    log_step = jnp.log(step_size)
    return DualAveragingState(
        log_step=log_step,
        log_step_avg=jnp.zeros_like(log_step),
        h_avg=jnp.zeros_like(log_step),
        mu=jnp.log(10.0) + log_step,
        count=jnp.zeros_like(log_step),
    )


def da_update(
    state: DualAveragingState,
    accept_prob: jax.Array,
    *,
    target: float = 0.8,
    gamma: float = 0.05,
    t0: float = 10.0,
    kappa: float = 0.75,
) -> DualAveragingState:
    count = state.count + 1.0
    w = 1.0 / (count + t0)
    h_avg = (1.0 - w) * state.h_avg + w * (target - accept_prob)
    log_step = state.mu - jnp.sqrt(count) / gamma * h_avg
    eta = count ** (-kappa)
    log_step_avg = eta * log_step + (1.0 - eta) * state.log_step_avg
    return DualAveragingState(log_step, log_step_avg, h_avg, state.mu, count)


@dataclasses.dataclass(frozen=True)
class AdaptSchedule:
    """Stan-style three-stage warmup window schedule (static, host-side).

    ``update_mass[i]`` is True at the last step of each slow window —
    the moment the mass matrix refreshes and dual averaging restarts.
    """

    update_mass: jnp.ndarray  # bool[num_warmup]
    in_slow: jnp.ndarray  # bool[num_warmup] — collect samples into Welford

    @staticmethod
    def make(
        num_warmup: int,
        *,
        init_buffer: int = 75,
        term_buffer: int = 50,
        base_window: int = 25,
    ) -> "AdaptSchedule":
        import numpy as np

        update = np.zeros(num_warmup, dtype=bool)
        slow = np.zeros(num_warmup, dtype=bool)
        if num_warmup < 20:
            return AdaptSchedule(jnp.asarray(update), jnp.asarray(slow))
        if init_buffer + base_window + term_buffer > num_warmup:
            # Scale buffers down proportionally (Stan's fallback).
            total = init_buffer + base_window + term_buffer
            init_buffer = int(0.15 * num_warmup)
            term_buffer = int(0.1 * num_warmup)
            del total
        start = init_buffer
        window = base_window
        while start < num_warmup - term_buffer:
            end = min(start + window, num_warmup - term_buffer)
            # If the remaining tail can't fit another window, absorb it.
            if end + window > num_warmup - term_buffer:
                end = num_warmup - term_buffer
            slow[start:end] = True
            update[end - 1] = True
            start = end
            window *= 2
        return AdaptSchedule(jnp.asarray(update), jnp.asarray(slow))
