"""Model comparison: WAIC and PSIS-LOO from on-device draws.

The reference's consumers end their workflow in arviz (``az.waic`` /
``az.loo`` over an InferenceData with pointwise log-likelihoods); this
module provides the same estimators directly on this framework's
``SampleResult`` draws, with the pointwise log-likelihood evaluated in
ONE vmapped executable over every kept draw.

Estimators (Vehtari, Gelman & Gabry, 2017, "Practical Bayesian model
evaluation using leave-one-out cross-validation and WAIC"):

- :func:`waic` — elpd_waic = Σ_i lppd_i − p_waic, p_waic = Σ_i
  Var_s(ll_is); fast, no importance sampling.
- :func:`psis_loo` — importance-sampled exact LOO with Pareto-smoothed
  tails: the raw ratios 1/p(y_i|θ_s) have heavy tails, so the top-M
  ratios are replaced by expected order statistics of a generalized
  Pareto fitted by the Zhang–Stephens (2009) posterior-mean method.
  Per-point shape diagnostics ``k`` are returned: k > 0.7 flags an
  unreliable point (same rule as arviz).
- :func:`compare` — rank models by elpd with paired-difference SEs.

The smoothing runs host-side in numpy (it is O(draws log draws) per
point and entirely off the hot path); the log-likelihood sweep is jax.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "pointwise_loglik_matrix",
    "waic",
    "psis_loo",
    "compare",
]


def pointwise_loglik_matrix(
    pointwise_fn: Callable[[Any], jax.Array],
    samples: Any,
    mask: Any = None,
) -> np.ndarray:
    """``(n_draws_total, n_points)`` pointwise log-likelihoods.

    ``pointwise_fn(params)`` maps ONE parameter pytree (no chain/draw
    axes) to per-observation log-likelihoods of any shape;
    ``samples`` has leading ``(chains, draws)`` axes.  ``mask`` (same
    shape as the fn output) drops padded slots — a padded point would
    otherwise enter the sums as a real observation with ll=0.
    """
    leaves = jax.tree_util.tree_leaves(samples)
    c, d = leaves[0].shape[:2]
    flat = jax.tree_util.tree_map(
        lambda a: a.reshape((c * d,) + a.shape[2:]), samples
    )
    ll = jax.vmap(pointwise_fn)(flat)  # (S_total, ...)
    ll = np.asarray(ll.reshape(c * d, -1))
    if mask is not None:
        keep = np.asarray(mask).reshape(-1) > 0
        ll = ll[:, keep]
    return ll


def _logmeanexp(a: np.ndarray, axis: int = 0) -> np.ndarray:
    m = a.max(axis=axis)
    return m + np.log(np.mean(np.exp(a - m), axis=axis))


def _logsumexp(a: np.ndarray) -> float:
    m = a.max()
    return float(m + np.log(np.sum(np.exp(a - m))))


def waic(ll: np.ndarray) -> Dict[str, Any]:
    """WAIC from an ``(n_draws, n_points)`` log-likelihood matrix."""
    lppd_i = _logmeanexp(ll, axis=0)
    p_i = ll.var(axis=0, ddof=1)
    elpd_i = lppd_i - p_i
    n = ll.shape[1]
    return {
        "elpd_waic": float(elpd_i.sum()),
        "p_waic": float(p_i.sum()),
        "se": float(np.sqrt(n * elpd_i.var(ddof=1))),
        "elpd_i": elpd_i,
    }


def _gpd_fit(x: np.ndarray) -> tuple[float, float]:
    """Zhang & Stephens (2009) posterior-mean fit of a generalized
    Pareto to exceedances ``x`` (sorted ascending).

    Returns ``(xi, sigma)`` in the ξ convention (cdf
    ``1 - (1 + ξx/σ)^{-1/ξ}``; heavy tail = ξ > 0) — the convention
    the quantile formula in :func:`_psis_smooth_tail` and the
    ``k > 0.7`` reliability threshold use.  Zhang–Stephens derive with
    ``k = -ξ``; the sign flip happens at the return."""
    n = x.size
    prior_bs = 3.0
    q25 = float(np.quantile(x, 0.25))
    if not np.isfinite(q25) or q25 <= 1e-20:
        # Tie-heavy exceedances (routine with duplicated Metropolis draws):
        # >=25% of the tail sits at the cutoff, the quartile collapses to
        # the clamp, bs explodes and log1p(-bs*x) goes NaN — and a NaN k
        # silently PASSES the k > 0.7 bad-point check (NaN > 0.7 is
        # False).  Flag the point unreliable instead.
        return np.inf, np.nan
    m = 30 + int(np.sqrt(n))
    bs = 1.0 - np.sqrt(m / (np.arange(1, m + 1) - 0.5))
    bs = bs / (prior_bs * q25) + 1.0 / x[-1]
    ks = -np.mean(np.log1p(-bs[:, None] * x[None, :]), axis=1)
    L = n * (np.log(bs / ks) + ks - 1.0)
    if not np.all(np.isfinite(ks)) or not np.all(np.isfinite(L)):
        return np.inf, np.nan
    # posterior weights w_j ∝ exp(L_j), computed as a stable softmax
    e = np.exp(L - L.max())
    w = e / e.sum()
    b_post = float(np.sum(bs * w))
    xi = float(np.mean(np.log1p(-b_post * x)))
    sigma = -xi / b_post
    return xi, sigma


def _psis_smooth_tail(log_ratios_i: np.ndarray) -> tuple[np.ndarray, float]:
    """Smooth one point's log importance ratios in place; returns the
    smoothed log-ratios and the fitted Pareto k."""
    s = log_ratios_i.size
    # tail size from Vehtari et al. (2017): min(S/5, 3*sqrt(S))
    m = min(int(np.ceil(0.2 * s)), int(np.ceil(3.0 * np.sqrt(s))), s - 1)
    if m < 5:
        return log_ratios_i, -np.inf  # too few draws to smooth
    order = np.argsort(log_ratios_i)
    tail_idx = order[-m:]
    cutoff = log_ratios_i[order[-m - 1]]
    exceed = np.exp(log_ratios_i[tail_idx]) - np.exp(cutoff)
    exceed = np.sort(exceed)
    if not np.all(np.isfinite(exceed)) or exceed[-1] <= 0:
        return log_ratios_i, np.inf
    k, sigma = _gpd_fit(np.maximum(exceed, 1e-30))
    if not (np.isfinite(k) and np.isfinite(sigma)):
        # degenerate fit (see _gpd_fit guards): leave the ratios raw and
        # report k = inf so psis_loo flags the point, never NaN-cascades
        return log_ratios_i, np.inf
    # expected order statistics of the fitted gPd
    p = (np.arange(1, m + 1) - 0.5) / m
    if abs(k) < 1e-8:
        q = -sigma * np.log1p(-p)
    else:
        q = sigma / k * (np.power(1.0 - p, -k) - 1.0)
    smoothed = log_ratios_i.copy()
    smoothed[tail_idx[np.argsort(log_ratios_i[tail_idx])]] = np.log(
        q + np.exp(cutoff)
    )
    # cap at the max raw ratio (arviz does the same)
    smoothed = np.minimum(smoothed, log_ratios_i.max())
    return smoothed, k


def psis_loo(ll: np.ndarray) -> Dict[str, Any]:
    """PSIS-LOO from an ``(n_draws, n_points)`` log-likelihood matrix."""
    n_s, n = ll.shape
    elpd_i = np.empty(n)
    ks = np.empty(n)
    for i in range(n):
        lr = -ll[:, i]
        lr = lr - lr.max()
        sm, k = _psis_smooth_tail(lr)
        ks[i] = k
        # elpd_i = log Σ_s w̃_s p(y_i|θ_s) with self-normalized weights
        lw = sm - _logsumexp(sm)
        elpd_i[i] = _logsumexp(lw + ll[:, i])
    lppd_i = _logmeanexp(ll, axis=0)
    return {
        "elpd_loo": float(elpd_i.sum()),
        "p_loo": float((lppd_i - elpd_i).sum()),
        "se": float(np.sqrt(n * elpd_i.var(ddof=1))),
        "pareto_k": ks,
        "n_bad_k": int(np.sum(ks > 0.7)),
        "elpd_i": elpd_i,
    }


def compare(models: Dict[str, np.ndarray]) -> list:
    """Rank models by PSIS-LOO elpd.

    ``models`` maps name -> ``(n_draws, n_points)`` ll matrix (all over
    the SAME observations).  Returns rows sorted best-first with
    paired-difference SEs vs the best model (the honest comparison SE:
    pointwise differences are correlated across models).
    """
    loos = {name: psis_loo(ll) for name, ll in models.items()}
    ranked = sorted(loos, key=lambda k: -loos[k]["elpd_loo"])
    best = ranked[0]
    rows = []
    for name in ranked:
        d_i = loos[name]["elpd_i"] - loos[best]["elpd_i"]
        n = d_i.size
        rows.append(
            {
                "model": name,
                "elpd_loo": loos[name]["elpd_loo"],
                "p_loo": loos[name]["p_loo"],
                "d_elpd": float(d_i.sum()),
                "d_se": float(np.sqrt(n * d_i.var(ddof=1))),
                "n_bad_k": loos[name]["n_bad_k"],
            }
        )
    return rows
