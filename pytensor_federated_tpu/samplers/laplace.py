"""Laplace approximation: Gaussian posterior from MAP + Hessian.

A deterministic fast-path posterior: find the MAP, take the Hessian of
the log-posterior there (``jax.hessian`` — which differentiates twice
through the whole federated evaluator, vmaps, ``shard_map`` and psums;
the reference hard-rejects second-order autodiff at its federated
boundary, reference: wrapper_ops.py:123-125, so this capability is only
possible in the collapsed on-mesh design), and return
``N(map, (-H)^{-1})`` plus vmapped draws in the original pytree
structure.

Useful as a cheap posterior when the target is near-Gaussian, as an
initializer/mass-matrix source for NUTS, and as a sanity oracle in
tests (exact for Gaussian posteriors).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .mcmc import find_map
from .util import flatten_logp


@dataclasses.dataclass
class LaplaceResult:
    """MAP point, flat Gaussian moments, and draw machinery."""

    mode: Any  # pytree MAP point
    mean_flat: jax.Array  # (dim,)
    cov_flat: jax.Array  # (dim, dim)
    scale_flat: jax.Array  # (dim, dim), scale_flat' @ scale_flat == cov
    unravel: Callable[[jax.Array], Any]
    logp_at_mode: float

    def sample(self, key: jax.Array, num_draws: int = 1000) -> Any:
        """Draws from the Gaussian approximation, as a pytree with a
        leading ``(num_draws,)`` axis.  Uses the covariance factor
        computed at fit time — no re-factorization (which could go NaN
        on a precision->covariance round-trip of a barely-identified
        posterior)."""
        eps = jax.random.normal(
            key, (num_draws,) + self.mean_flat.shape, self.mean_flat.dtype
        )
        flat = self.mean_flat + eps @ self.scale_flat
        return jax.vmap(self.unravel)(flat)

    def stddev(self) -> Any:
        """Marginal posterior standard deviations, as a pytree."""
        return self.unravel(jnp.sqrt(jnp.diag(self.cov_flat)))


def laplace_approximation(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    jitter: float = 0.0,
    mode: Optional[Any] = None,
    **map_kwargs,
) -> LaplaceResult:
    """Fit ``N(theta_MAP, (-Hessian)^{-1})`` to the posterior.

    ``mode``: optionally skip the MAP search and expand around a given
    point.  ``jitter`` adds ``jitter * I`` to ``-H`` before inversion
    for barely-identified directions.  Extra keyword arguments
    (``num_steps``, ``learning_rate``, ...) forward to
    :func:`..mcmc.find_map` so its defaults stay the single source of
    truth.  Raises ``ValueError`` if the Hessian is non-finite
    (diverged MAP search / NaN logp) or ``-H`` is not positive definite
    at the expansion point (not a local maximum) — a silent non-PD
    covariance would produce NaN draws downstream.
    """
    if mode is None:
        mode = find_map(logp_fn, init_params, **map_kwargs)
    flat_logp, flat_mode, unravel = flatten_logp(logp_fn, mode)
    H = jax.hessian(flat_logp)(flat_mode)
    if not bool(jnp.all(jnp.isfinite(H))):
        raise ValueError(
            "non-finite Hessian at the expansion point — the MAP search "
            "diverged or logp is NaN there (try a smaller learning_rate "
            "or pass a finite mode=)"
        )
    prec = -H + jitter * jnp.eye(H.shape[0], dtype=H.dtype)
    # Cholesky doubles as the PD check and the inversion workhorse.
    chol = jnp.linalg.cholesky(prec)
    if bool(jnp.any(jnp.isnan(chol))):
        raise ValueError(
            "-Hessian at the expansion point is not positive definite; "
            "the point is not a local maximum (try more MAP steps or a "
            "jitter > 0)"
        )
    eye = jnp.eye(H.shape[0], dtype=H.dtype)
    inv_chol = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    cov = inv_chol.T @ inv_chol
    return LaplaceResult(
        mode=mode,
        mean_flat=flat_mode,
        cov_flat=cov,
        scale_flat=inv_chol,
        unravel=unravel,
        logp_at_mode=float(flat_logp(flat_mode)),
    )
