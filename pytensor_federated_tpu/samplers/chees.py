"""ChEES-HMC: cross-chain adaptive HMC built for lockstep vmapped chains.

NUTS's tree doubling makes every vmapped chain wait for the deepest
tree in the batch each draw — the lockstep tax this framework's
profiling measured at ~3-4x the raw gradient cost.  ChEES-HMC
(Hoffman, Radul & Sountsov, AISTATS 2021, "An Adaptive MCMC Scheme
for Setting Trajectory Lengths in Hamiltonian Monte Carlo") is the
SIMD-native alternative: every chain runs the SAME jittered
fixed-length trajectory each iteration, and the trajectory length is
adapted by ascending the Change-in-the-Estimator-of-the-Expected-
Square (ChEES) criterion with a cross-chain stochastic gradient —
the many parallel chains a TPU wants are exactly the statistic the
adaptation needs.

Per iteration t (all chains in lockstep):

- jitter ``h_t`` from a Halton sequence; every chain integrates
  ``L_t = ceil(h_t * 2 T / eps)`` leapfrog steps (state-independent
  length — a valid MCMC kernel every iteration);
- the ChEES gradient estimate combines per-chain proposal quantities
  (centered squared-radius change times proposal-velocity projection),
  accept-probability weighted, and updates ``log T`` by Adam;
- the step size follows dual averaging on the across-chain mean
  accept probability, and the diagonal mass matrix is the
  across-(chains x recent draws) variance — cross-chain adaptation
  again, no per-chain Welford warm start needed.

After warmup, ``(eps, T, mass)`` freeze and sampling keeps the Halton
jitter.  Returns the same ``SampleResult`` as :func:`..mcmc.sample`.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .hmc import (
    IntegratorState,
    find_reasonable_step_size,
    kinetic_energy,
    leapfrog,
    sample_momentum,
)
from .mcmc import SampleResult, make_flat_logp_and_grad
from .util import da_init, da_update

__all__ = ["chees_sample"]


def _halton(i, base=2):
    """i-th element (0-based) of the base-2 Halton sequence in (0, 1).

    32 bits of radical inverse: stays strictly inside (0, 1) for every
    iteration count a sampler can reach (16 bits would return exactly
    0.0 whenever i+1 is a multiple of 2^16)."""
    i = i.astype(jnp.uint32) + 1
    bits = jnp.arange(32, dtype=jnp.uint32)
    digits = (i >> bits) & 1
    # f32 is enough: the smallest nonzero value (bit 31 alone) is
    # 2^-32, representable; only exact-zero must be avoided.
    return jnp.sum(digits * 0.5 ** (bits.astype(jnp.float32) + 1.0))


class _AdamState(NamedTuple):
    m: jax.Array
    v: jax.Array
    t: jax.Array


def _adam_init():
    z = jnp.zeros(())
    return _AdamState(z, z, z)


def _adam_update(s: _AdamState, grad, lr=0.025, b1=0.9, b2=0.95):
    t = s.t + 1.0
    m = b1 * s.m + (1 - b1) * grad
    v = b2 * s.v + (1 - b2) * grad**2
    mhat = m / (1 - b1**t)
    vhat = v / (1 - b2**t)
    step = lr * mhat / (jnp.sqrt(vhat) + 1e-8)
    return _AdamState(m, v, t), step


def chees_sample(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    num_warmup: int = 500,
    num_samples: int = 500,
    num_chains: int = 16,
    target_accept: float = 0.75,
    jitter: float = 1.0,
    max_leapfrogs: int = 1024,
    logp_and_grad_fn: Optional[Callable] = None,
    chain_sharding: Optional[Any] = None,
) -> SampleResult:
    """Cross-chain adaptive HMC; more chains = better adaptation.

    ``max_leapfrogs`` bounds the per-iteration trajectory (the scan is
    masked beyond the active length, so the bound costs nothing when
    the adapted length is short).

    ``chain_sharding`` (a ``NamedSharding`` whose spec partitions the
    leading axis, e.g. ``NamedSharding(mesh, P("chains"))``) places the
    chain batch across a device mesh.  Computation follows sharding:
    the per-chain transitions run data-parallel on their devices and
    the cross-chain adaptation reductions (mean accept-stat, ChEES
    gradient, cross-chain variance mass) lower to XLA collectives over
    the mesh — the lockstep design needs no other change to scale past
    one device.  ``num_chains`` must be divisible by the mesh axis."""
    flat_logp, flat_init, unravel, lg = make_flat_logp_and_grad(
        logp_fn, init_params, logp_and_grad_fn
    )
    dim = flat_init.shape[0]
    dtype = flat_init.dtype
    C = num_chains

    k_init, k_warm, k_samp = jax.random.split(key, 3)
    x0 = flat_init[None, :] + jitter * jax.random.normal(
        k_init, (C, dim), dtype
    )
    from .mcmc import place_with_sharding

    x0 = place_with_sharding(
        x0, chain_sharding, axis_desc=f"num_chains={C}"
    )
    logp0, grad0 = jax.vmap(lg)(x0)

    def one_iteration(x, logp, grad, inv_mass, step_size, traj_len, it, key):
        """All chains take one jittered-length HMC transition."""
        h = _halton(it)
        n_steps = jnp.clip(
            jnp.ceil(2.0 * h * traj_len / step_size).astype(jnp.int32),
            1,
            max_leapfrogs,
        )
        k_mom, k_acc = jax.random.split(key)
        r0 = jax.vmap(lambda k, xi: sample_momentum(k, xi, inv_mass))(
            jax.random.split(k_mom, C), x
        )
        energy0 = -logp + jax.vmap(
            lambda r: kinetic_energy(r, inv_mass)
        )(r0)

        init = IntegratorState(x, r0, logp, grad)

        def body(_i, carry):
            return jax.vmap(
                lambda ci: leapfrog(lg, ci, step_size, inv_mass)
            )(carry)

        # traced upper bound lowers to while_loop: only the ACTIVE
        # steps execute (max_leapfrogs merely caps n_steps above).
        end = jax.lax.fori_loop(0, n_steps, body, init)

        energy1 = -end.logp + jax.vmap(
            lambda r: kinetic_energy(r, inv_mass)
        )(end.r)
        delta = energy0 - energy1
        delta = jnp.where(jnp.isnan(delta), -jnp.inf, delta)
        accept_prob = jnp.minimum(1.0, jnp.exp(delta))
        u = jax.random.uniform(k_acc, (C,), dtype)
        accepted = u < accept_prob
        x_new = jnp.where(accepted[:, None], end.x, x)
        logp_new = jnp.where(accepted, end.logp, logp)
        grad_new = jnp.where(accepted[:, None], end.grad, grad)

        # ChEES gradient (paper eq. 14): centered squared-radius change
        # times the proposal-velocity projection, accept-weighted.
        # Divergent trajectories produce NaN endpoints with accept
        # weight 0 — but 0 * NaN = NaN, so non-finite contributions
        # must be ZEROED or one early divergence would poison the Adam
        # state (and hence log_traj) for the whole run.
        # nan-aware centering: jnp.mean over chains would go NaN if
        # ANY chain diverged, zeroing every chain's contribution below
        # — one bad chain must not erase 15 healthy ones.
        end_ok = jnp.all(jnp.isfinite(end.x), axis=1, keepdims=True)
        n_ok = jnp.maximum(jnp.sum(end_ok), 1.0)
        end_safe = jnp.where(end_ok, end.x, 0.0)
        xc = x - jnp.mean(x, axis=0)
        pc = end_safe - jnp.sum(end_safe, axis=0) / n_ok
        dsq = jnp.sum(pc**2, axis=1) - jnp.sum(xc**2, axis=1)
        v_end = end.r * inv_mass[None, :]  # final velocity
        proj = jnp.sum(pc * v_end, axis=1)
        contrib = dsq * proj
        finite = jnp.isfinite(contrib) & end_ok[:, 0]
        w = jnp.where(finite, accept_prob, 0.0)
        contrib = jnp.where(finite, contrib, 0.0)
        chees_grad = h * jnp.sum(w * contrib) / (jnp.sum(w) + 1e-10)

        info = {
            "accept_prob": accept_prob,
            "diverging": delta < -1000.0,
            # occupied state's energy (rejected proposals must not leak
            # NaN/huge endpoint energies into E-BFMI — hmc.py does the
            # same)
            "energy": jnp.where(accepted, energy1, energy0),
            "n_steps": jnp.full((C,), n_steps),
        }
        return x_new, logp_new, grad_new, accept_prob, chees_grad, info

    # ---- warmup: adapt eps (dual averaging), T (Adam on ChEES),
    # mass (cross-chain variance with decay) --------------------------
    k_step, k_warm = jax.random.split(k_warm)
    step0 = find_reasonable_step_size(
        lg, x0[0], k_step, jnp.ones((dim,), dtype)
    )
    da = da_init(step0)
    adam = _adam_init()
    log_traj = jnp.log(jnp.asarray(1.0, dtype))
    inv_mass0 = jnp.ones((dim,), dtype)

    def warm_body(carry, inputs):
        (x, logp, grad, da, adam, log_traj, inv_mass) = carry
        it, key = inputs
        step_size = jnp.exp(da.log_step)
        x, logp, grad, accept, chees_grad, _ = one_iteration(
            x, logp, grad, inv_mass, step_size, jnp.exp(log_traj), it, key
        )
        da = da_update(da, jnp.mean(accept), target=target_accept)
        adam, step = _adam_update(adam, chees_grad)
        log_traj = log_traj + step  # ascend the criterion
        # cap T so eps*L stays sane early in warmup
        log_traj = jnp.clip(log_traj, jnp.log(1e-3), jnp.log(1e3))
        # cross-chain variance, exponentially mixed in
        var_c = jnp.var(x, axis=0) + 1e-6
        inv_mass = 0.9 * inv_mass + 0.1 * var_c
        return (x, logp, grad, da, adam, log_traj, inv_mass), None

    its = jnp.arange(num_warmup)
    keys = jax.random.split(k_warm, num_warmup)
    (x, logp, grad, da, adam, log_traj, inv_mass), _ = jax.lax.scan(
        warm_body,
        (x0, logp0, grad0, da, adam, log_traj, inv_mass0),
        (its, keys),
    )
    # num_warmup=0: no da_update ever ran, log_step_avg is still its
    # zero init — fall back to the probed initial step (mcmc.py's
    # _warmup carries the same guard).
    step_size = jnp.exp(
        jnp.where(da.count > 0, da.log_step_avg, da.log_step)
    )
    traj_len = jnp.exp(log_traj)

    # ---- sampling: frozen (eps, T, mass), jitter continues ----------
    def samp_body(carry, inputs):
        x, logp, grad = carry
        it, key = inputs
        x, logp, grad, _accept, _cg, info = one_iteration(
            x, logp, grad, inv_mass, step_size, traj_len, it, key
        )
        return (x, logp, grad), (x, info)

    its = jnp.arange(num_warmup, num_warmup + num_samples)
    keys = jax.random.split(k_samp, num_samples)
    (_, _, _), (draws, stats) = jax.lax.scan(
        samp_body, (x, logp, grad), (its, keys)
    )
    # draws: (num_samples, C, dim) -> (C, num_samples, dim)
    draws = jnp.swapaxes(draws, 0, 1)
    stats = {k: jnp.swapaxes(v, 0, 1) for k, v in stats.items()}

    samples = jax.vmap(jax.vmap(unravel))(draws)
    return SampleResult(
        samples=samples,
        stats=stats,
        step_size=jnp.full((C,), step_size),
        inv_mass=jnp.tile(inv_mass[None, :], (C, 1)),
    )
