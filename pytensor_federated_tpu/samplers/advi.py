"""ADVI — stochastic variational inference on the federated logp.

Net-new capability: the reference's only point-estimate tool is
``pm.find_MAP`` (reference: demo_model.py:38-39); ADVI adds a calibrated
posterior *approximation* at a fraction of MCMC cost.  TPU-shaped by
construction: each optimization step draws ``n_mc`` reparameterized
samples and evaluates the (sharded, psum-reduced) logp as one batched
call, so the gradient of the ELBO is a single fused XLA program.

Two approximation families:

- :func:`advi_fit` — fully factorized (mean-field) Gaussian
  ``q(x) = N(mu, diag(exp(log_sd)^2))``;
- :func:`fullrank_advi_fit` — full-rank Gaussian ``q(x) = N(mu, LLᵀ)``
  with a learned Cholesky factor (Stan's ``fullrank`` method): captures
  posterior correlations mean-field cannot, the VI counterpart of the
  samplers' ``dense_mass`` option.  The reparameterized draw is
  ``mu + L eps`` (a (d, d) matvec — MXU work), the entropy is
  ``Σ log L_ii`` in closed form.

Both run the entire optimization in one ``lax.scan`` under jit —
through the shared ELBO core (:mod:`..ppl.elbo`, ISSUE 15): the
Gaussian-entropy kernel and the jitted scan loop live there ONCE,
shared with the flow lane and the ``ppl`` SVI lanes.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..ppl.elbo import gaussian_entropy, meanfield_neg_elbo, scan_vi
from .util import flatten_logp

try:
    import optax

    _HAS_OPTAX = True
except ModuleNotFoundError:  # pragma: no cover
    _HAS_OPTAX = False


class ADVIResult(NamedTuple):
    mean: Any  # user pytree — posterior mean of q
    sd: Any  # user pytree — posterior sd of q
    elbo_trace: jax.Array  # (num_steps,)
    flat_mean: jax.Array
    flat_log_sd: jax.Array

    def sample(self, key: jax.Array, n: int, unravel) -> Any:
        eps = jax.random.normal(
            key, (n, self.flat_mean.shape[0]), self.flat_mean.dtype
        )
        flat = self.flat_mean[None, :] + jnp.exp(self.flat_log_sd)[None, :] * eps
        return jax.vmap(unravel)(flat)


def advi_fit(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    num_steps: int = 2000,
    n_mc: int = 8,
    learning_rate: float = 1e-2,
    init_log_sd: float = -2.0,
    stochastic_logp_fn: Optional[Callable[[Any, jax.Array], jax.Array]] = None,
) -> tuple[ADVIResult, Callable]:
    """Fit mean-field ADVI to ``logp_fn``; returns ``(result, unravel)``.

    The whole optimization (all steps) runs in one ``lax.scan`` under
    jit.  ``result.sample(key, n, unravel)`` draws from the fitted
    approximation in user pytree structure.

    ``stochastic_logp_fn(params, key) -> scalar`` switches to DOUBLY
    stochastic VI: the MC expectation over q AND an unbiased minibatch
    estimate of the logp itself — e.g.
    ``lambda p, k: fed.logp_minibatch(p, k, num_shards=m)`` subsamples
    federated shards per optimization step, so per-step cost drops
    with the subsample while the ELBO gradient stays unbiased.
    ``logp_fn`` is still used to fix the parameter pytree structure.
    """
    if not _HAS_OPTAX:
        raise ModuleNotFoundError("advi_fit requires optax")
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    dim = flat_init.shape[0]
    dtype = flat_init.dtype
    if stochastic_logp_fn is None:
        batch_logp = jax.vmap(flat_logp)

        def e_logp_fn(x, key):
            return jnp.mean(batch_logp(x))

    else:

        def e_logp_fn(x, key):
            keys = jax.random.split(key, x.shape[0])
            vals = jax.vmap(
                lambda xi, ki: stochastic_logp_fn(unravel(xi), ki)
            )(x, keys)
            return jnp.mean(vals)

    opt = optax.adam(learning_rate)

    # The shared estimator: split_keys=False keeps the non-stochastic
    # RNG stream EXACTLY as before the stochastic option existed
    # (seeded tests pin it).
    neg_elbo = meanfield_neg_elbo(
        e_logp_fn,
        dim,
        n_mc=n_mc,
        split_keys=stochastic_logp_fn is not None,
    )
    var0 = (flat_init, jnp.full((dim,), init_log_sd, dtype))
    (mu, log_sd), elbos = scan_vi(
        neg_elbo, var0, key=key, num_steps=num_steps, optimizer=opt
    )
    result = ADVIResult(
        mean=unravel(mu),
        sd=unravel(jnp.exp(log_sd)),
        elbo_trace=elbos,
        flat_mean=mu,
        flat_log_sd=log_sd,
    )
    return result, unravel


class FullRankADVIResult(NamedTuple):
    mean: Any  # user pytree — posterior mean of q
    sd: Any  # user pytree — posterior marginal sds of q
    elbo_trace: jax.Array  # (num_steps,)
    flat_mean: jax.Array
    flat_chol: jax.Array  # (d, d) lower-triangular factor of cov(q)

    @property
    def covariance(self) -> jax.Array:
        """(d, d) covariance of the fitted approximation."""
        return self.flat_chol @ self.flat_chol.T

    def sample(self, key: jax.Array, n: int, unravel) -> Any:
        eps = jax.random.normal(
            key, (n, self.flat_mean.shape[0]), self.flat_mean.dtype
        )
        flat = self.flat_mean[None, :] + eps @ self.flat_chol.T
        return jax.vmap(unravel)(flat)


def _chol_from_theta(theta, dim, tril_idx):
    """Lower-triangular L from the unconstrained packed vector; the
    diagonal is exp'd for positivity (the standard bijection)."""
    L = jnp.zeros((dim, dim), theta.dtype).at[tril_idx].set(theta)
    diag = jnp.exp(jnp.diagonal(L))
    return L - jnp.diag(jnp.diagonal(L)) + jnp.diag(diag)


def fullrank_advi_fit(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    num_steps: int = 3000,
    n_mc: int = 8,
    learning_rate: float = 5e-3,
    init_log_sd: float = -2.0,
) -> tuple[FullRankADVIResult, Callable]:
    """Fit a full-rank Gaussian ``q(x) = N(mu, LLᵀ)`` to ``logp_fn``.

    Same contract as :func:`advi_fit`; the extra d(d-1)/2 off-diagonal
    parameters let q match correlated posteriors exactly (for a
    Gaussian target the optimum IS the target).  Cost per step is one
    (n_mc, d) @ (d, d) matmul on top of mean-field's elementwise ops.
    """
    if not _HAS_OPTAX:
        raise ModuleNotFoundError("fullrank_advi_fit requires optax")
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    dim = flat_init.shape[0]
    dtype = flat_init.dtype
    batch_logp = jax.vmap(flat_logp)
    tril_idx = jnp.tril_indices(dim)
    # diag positions within the packed theta vector: entry (i, i) is
    # the last element of packed row i -> index i(i+3)/2.
    rows = jnp.arange(dim)
    diag_pos = (rows * (rows + 3)) // 2

    opt = optax.adam(learning_rate)

    def neg_elbo(var_params, key):
        mu, theta = var_params
        L = _chol_from_theta(theta, dim, tril_idx)
        eps = jax.random.normal(key, (n_mc, dim), dtype)
        x = mu[None, :] + eps @ L.T
        e_logp = jnp.mean(batch_logp(x))
        # Σ log L_ii is the full-rank log_sd_sum (shared kernel).
        entropy = gaussian_entropy(dim, jnp.sum(jnp.log(jnp.diagonal(L))))
        return -(e_logp + entropy)

    theta0 = (
        jnp.zeros((dim * (dim + 1) // 2,), dtype)
        .at[diag_pos]
        .set(init_log_sd)
    )
    var0 = (flat_init, theta0)
    (mu, theta), elbos = scan_vi(
        neg_elbo, var0, key=key, num_steps=num_steps, optimizer=opt
    )
    L = _chol_from_theta(theta, dim, tril_idx)
    sd = jnp.sqrt(jnp.sum(L**2, axis=1))
    result = FullRankADVIResult(
        mean=unravel(mu),
        sd=unravel(sd),
        elbo_trace=elbos,
        flat_mean=mu,
        flat_chol=L,
    )
    return result, unravel
