"""Elastic sampling: checkpointed MCMC that survives device/host loss.

The round-5 integration of three subsystems that already exist
separately — in-band failure detection (``parallel.multihost``:
heartbeats + ``detect_dead_peers``), mesh recovery
(``remesh_after_failure``), and chunked checkpoint/resume
(``checkpoint.sample_checkpointed``, bit-identical continuation) —
into the one driver a long-running job actually wants:

    def build_logp(mesh):
        data = place_my_shards(mesh)        # host copies re-place
        return FederatedLogp(..., mesh=mesh).logp

    res = elastic_sample(build_logp, init, key=key, mesh=mesh,
                         checkpoint_path="run.ckpt", peers=peer_map)

Failure model (matches the reference's, one level up): the reference
detects node death in-band — the failed CALL raises, then the client
rebalances and re-sends (reference: service.py:407-416).  Here the
failed SEGMENT raises (a dead device/host surfaces as a runtime error
from the collective or evaluation), then:

1. the optional heartbeat ``peers`` map is probed
   (:func:`~pytensor_federated_tpu.parallel.multihost.detect_dead_peers`)
   so the rebuilt mesh drops known-dead processes knowingly;
2. the mesh is rebuilt over surviving devices
   (:func:`~pytensor_federated_tpu.parallel.multihost.remesh_after_failure`,
   or a caller-supplied ``on_failure`` policy);
3. ``build_logp(new_mesh)`` re-places data and re-jits — state lives
   on the host (the reference's nodes are stateless for the same
   reason);
4. sampling RESUMES from the last completed chunk —
   :func:`~pytensor_federated_tpu.checkpoint.sample_checkpointed`'s
   fold_in-per-chunk key discipline means the draw stream cannot
   depend on where the failure happened; see ``elastic_sample``'s
   docstring for the precise bit-identical-vs-exact-in-distribution
   continuation guarantee.

TWO RECOVERY TIERS — be honest about which one a failure lands in:

- **In-process (caught here):** failures that surface as Python
  exceptions — a host-federation node dying (blackbox/pure_callback
  raises, service client exhausts retries), a single-device runtime
  error.  The except path below detects, remeshes, rebuilds and
  resumes without leaving the process.
- **Process restart (the checkpoint's job):** a failure that wedges a
  CROSS-DEVICE COLLECTIVE cannot be caught in-process — the surviving
  participants block at the rendezvous and XLA aborts the process
  after its termination timeout ("Exiting to ensure a consistent
  program state"; measured on the 8-device CPU mesh).  Recovery is to
  re-run the SAME ``elastic_sample`` call (manually or under a
  supervisor): the checkpoint resumes after the last completed chunk,
  bit-identically, and ``build_logp`` naturally re-places over
  whatever devices the fresh process sees.  This is the same
  restart-resume contract ``sample_checkpointed`` documents for
  kill-anywhere crashes, proven across real process boundaries in
  tests/test_elastic.py (TestProcessRestart).

The warmup caveat: warmup is not chunk-checkpointed (same as
``sample_checkpointed``), so a failure during warmup restarts warmup —
the expensive artifact being protected is the draw phase of a long
run.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Mapping, Optional, Tuple

import jax

from ..telemetry import flightrec as _flightrec
from ..telemetry import watchdog as _watchdog

__all__ = ["elastic_sample"]

_log = logging.getLogger("pytensor_federated_tpu")


def _segment_watchdog_s(value: Optional[float]) -> float:
    """The sampling-segment arm deadline: explicit arg, else
    ``PFTPU_WATCHDOG_SAMPLE_S``, else 0 (disarmed).  Disarmed by
    default because a legitimate segment can run for hours — the env
    knob is for deployments that know their segment budget."""
    if value is not None:
        return float(value)
    return _watchdog.env_timeout_s("PFTPU_WATCHDOG_SAMPLE_S", 0.0)


def elastic_sample(
    build_logp: Callable[[Optional[Any]], Callable],
    init_params: Any,
    *,
    key: jax.Array,
    checkpoint_path: str,
    mesh: Optional[Any] = None,
    peers: Optional[Mapping[int, Tuple[str, int]]] = None,
    node_pool: Optional[Any] = None,
    max_failures: int = 2,
    on_failure: Optional[Callable[[Optional[Any], list], Optional[Any]]] = None,
    watchdog_s: Optional[float] = None,
    **sample_kwargs,
):
    """Checkpointed sampling with failure-triggered mesh recovery.

    ``build_logp(mesh) -> logp_fn`` must be re-invokable: each call
    places (or re-places) data for the given mesh and returns the logp
    closure.  ``mesh=None`` is allowed (single-device jobs still get
    checkpointed crash tolerance; recovery then just rebuilds).

    ``peers`` (process id -> heartbeat address) feeds dead-peer
    DETECTION into recovery; without it, recovery is local-view only.

    ``node_pool`` (a :class:`~pytensor_federated_tpu.routing.NodePool`,
    optional) adds a HOST-LANE recovery tier ahead of the mesh one:
    when the failed segment's logp rides a replica pool
    (:class:`~pytensor_federated_tpu.routing.PooledArraysClient`
    inside ``build_logp``), recovery probes the pool NOW — the dead
    replica's breaker trips, the pool shrinks around it, and the
    rebuilt logp routes over the survivors without touching the mesh
    at all (pool GROWTH is the operator's move: ``add_replica`` on a
    live pool is picked up by the same rebuild).  A segment failure
    with zero admitted replicas left still falls through to the mesh
    tiers (remesh, then process restart), so the tier ordering is:
    pool shrink → remesh → checkpoint-resume restart.
    ``on_failure(mesh, dead_process_ids) -> new_mesh`` overrides the
    default :func:`remesh_after_failure` policy (e.g. to rebuild a
    multi-host mesh after out-of-band agreement).  ``max_failures``
    bounds recovery attempts — a failure with no surviving devices
    re-raises.

    ``watchdog_s`` arms the hang watchdog around each sampling
    segment — THE psum-rendezvous wedge point: a participant dying
    mid-collective leaves the survivors blocked at the rendezvous
    until XLA aborts the process, and nothing in-process can catch it
    (module docstring, tier 2).  An armed deadline turns that silent
    wait into an incident bundle (all-thread dump + flight record +
    trace reunion, :mod:`~pytensor_federated_tpu.telemetry.watchdog`)
    written BEFORE the abort, so the restart tier has forensics.
    Default: ``PFTPU_WATCHDOG_SAMPLE_S`` env, else disarmed (a
    legitimate segment can run for hours).

    Remaining ``sample_kwargs`` go to
    :func:`~pytensor_federated_tpu.checkpoint.sample_checkpointed`
    (num_warmup/num_samples/num_chains/checkpoint_every/kernel/...).
    Returns its :class:`SampleResult`.

    Continuation guarantee, stated precisely: the resumed run uses the
    checkpointed kernel state and the same fold_in-per-chunk key
    stream, so when the rebuilt logp is NUMERICALLY IDENTICAL to the
    original (same mesh layout — restarts, host-node recovery, or a
    rebuild over the same devices) the draws are BIT-identical to an
    uninterrupted run (tested).  When recovery SHRINKS the mesh, data
    re-placement changes the partial-sum order of the federated
    reduction, which can perturb logp values in the final float bits —
    the continuation is then exact in distribution (same posterior,
    same kernel, checkpointed state) but not bit-reproducible against
    the uninterrupted counterfactual.
    """
    from ..checkpoint import sample_checkpointed

    arm_s = _segment_watchdog_s(watchdog_s)
    failures = 0
    current_mesh = mesh
    while True:
        logp_fn = build_logp(current_mesh)
        try:
            with _watchdog.armed(
                "elastic.sample_segment", arm_s, attempt=failures
            ):
                return sample_checkpointed(
                    logp_fn,
                    init_params,
                    key=key,
                    checkpoint_path=checkpoint_path,
                    **sample_kwargs,
                )
        except Exception as e:  # noqa: BLE001 — any device/runtime loss
            failures += 1
            _flightrec.record(
                "sampler.segment_failed",
                attempt=failures,
                max_failures=max_failures,
                error=f"{type(e).__name__}: {e}"[:200],
            )
            if failures > max_failures:
                raise
            _log.warning(
                "elastic_sample: segment failed (%s: %s) — recovering "
                "(%d/%d)",
                type(e).__name__,
                e,
                failures,
                max_failures,
            )
            if node_pool is not None:
                # Tier 0, host lane: probe the replica pool so dead
                # nodes are quarantined (their breakers trip on the
                # failed probe) before the logp is rebuilt over the
                # survivors.  Cheap, side-effect-bounded, and enough
                # on its own when the failure was a host-federation
                # node dying — the mesh tiers below then find nothing
                # to do (dead stays empty without heartbeat peers).
                healthy = node_pool.recover()
                _flightrec.record(
                    "sampler.pool_recovered",
                    attempt=failures,
                    healthy_replicas=healthy,
                    total_replicas=len(node_pool.replicas),
                )
                _log.warning(
                    "elastic_sample: pool recovery — %d/%d replicas "
                    "admit traffic",
                    healthy,
                    len(node_pool.replicas),
                )
            dead: list = []
            if peers:
                from ..parallel.multihost import detect_dead_peers

                dead = detect_dead_peers(peers)
            if on_failure is not None:
                current_mesh = on_failure(current_mesh, dead)
            elif current_mesh is not None:
                from ..parallel.multihost import remesh_after_failure

                current_mesh = remesh_after_failure(
                    current_mesh, dead_process_ids=dead
                )
            _flightrec.record(
                "sampler.recovered",
                attempt=failures,
                dead_process_ids=sorted(dead),
            )
            # loop: rebuild logp over the recovered mesh and RESUME
            # from the last completed chunk (sample_checkpointed finds
            # the matching checkpoint on disk).
