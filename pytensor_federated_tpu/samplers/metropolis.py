"""Gaussian random-walk Metropolis — the reference's CI sampler.

The reference's end-to-end tests sample with PyMC Metropolis against the
federated logp (reference: test_wrapper_ops.py:80-118); this is the same
algorithm as a pure-JAX kernel so the whole chain runs in one
``lax.scan`` on device.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp


class MetropolisState(NamedTuple):
    x: jax.Array
    logp: jax.Array
    n_accept: jax.Array


def metropolis_init(flat_logp: Callable, x0: jax.Array) -> MetropolisState:
    return MetropolisState(
        x=x0, logp=flat_logp(x0), n_accept=jnp.zeros((), x0.dtype)
    )


def metropolis_step(
    flat_logp: Callable,
    state: MetropolisState,
    key: jax.Array,
    *,
    step_size,
) -> MetropolisState:
    k_prop, k_acc = jax.random.split(key)
    prop = state.x + step_size * jax.random.normal(
        k_prop, state.x.shape, state.x.dtype
    )
    logp_prop = flat_logp(prop)
    log_u = jnp.log(jax.random.uniform(k_acc, dtype=state.logp.dtype))
    accept = log_u < (logp_prop - state.logp)
    return MetropolisState(
        x=jnp.where(accept, prop, state.x),
        logp=jnp.where(accept, logp_prop, state.logp),
        n_accept=state.n_accept + accept.astype(state.x.dtype),
    )
