"""Export draws to arviz's InferenceData (or its plain-dict shape).

The reference's demo workflow ends in arviz (``pm.sample`` returns an
InferenceData; reference demo_model.py prints an az summary).  This
module gives the native samplers the same exit ramp:

- :func:`to_dataset_dict` — always available: the draws, sample stats,
  and (optionally) pointwise log-likelihoods as plain
  ``{group: {var: ndarray(chains, draws, ...)}}`` dicts in arviz's
  exact layout.
- :func:`to_inference_data` — the same content as a real
  ``az.InferenceData`` when arviz is installed (import-gated like the
  PyTensor bridge; the package does not depend on arviz).

Variable naming matches PyMC conventions (``log_likelihood`` group,
``sample_stats`` with ``diverging``/``energy``/``tree_depth``) so
``az.loo``, ``az.summary``, ``az.plot_trace`` work unmodified.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

__all__ = ["to_dataset_dict", "to_inference_data"]

_STAT_RENAMES = {
    "accept_prob": "acceptance_rate",
    "diverging": "diverging",
    "depth": "tree_depth",
    "energy": "energy",
}


def to_dataset_dict(
    result: Any,
    *,
    pointwise_fn: Optional[Any] = None,
    mask: Optional[Any] = None,
) -> Dict[str, Dict[str, np.ndarray]]:
    """arviz-layout dict-of-groups from a ``SampleResult``.

    ``pointwise_fn(params)`` (e.g. ``model.pointwise_loglik``) adds a
    ``log_likelihood`` group evaluated over every kept draw in one
    vmapped executable; ``mask`` drops padded observation slots.
    """
    posterior = {
        k: np.asarray(v) for k, v in _as_mapping(result.samples).items()
    }
    groups: Dict[str, Dict[str, np.ndarray]] = {"posterior": posterior}
    stats = getattr(result, "stats", None)
    if stats:
        groups["sample_stats"] = {
            _STAT_RENAMES.get(k, k): np.asarray(v) for k, v in stats.items()
        }
    if pointwise_fn is not None:
        from .model_comparison import pointwise_loglik_matrix

        leaves = jax.tree_util.tree_leaves(result.samples)
        c, d = leaves[0].shape[:2]
        ll = pointwise_loglik_matrix(pointwise_fn, result.samples, mask=mask)
        groups["log_likelihood"] = {"obs": ll.reshape((c, d, -1))}
    return groups


def to_inference_data(
    result: Any,
    *,
    pointwise_fn: Optional[Any] = None,
    mask: Optional[Any] = None,
):
    """``az.InferenceData`` built from :func:`to_dataset_dict`.

    Raises ImportError when arviz is not installed (install the
    ``arviz`` extra); use :func:`to_dataset_dict` for the dependency-
    free layout.
    """
    try:
        import arviz as az
    except ModuleNotFoundError as e:  # pragma: no cover - env-dependent
        raise ImportError(
            "to_inference_data requires arviz (pip install "
            "pytensor-federated-tpu[arviz]); to_dataset_dict gives the "
            "same content as plain dicts"
        ) from e

    groups = to_dataset_dict(result, pointwise_fn=pointwise_fn, mask=mask)
    kwargs = {"posterior": groups["posterior"]}
    if "sample_stats" in groups:
        kwargs["sample_stats"] = groups["sample_stats"]
    if "log_likelihood" in groups:
        kwargs["log_likelihood"] = groups["log_likelihood"]
    return az.from_dict(**kwargs)


def _as_mapping(samples: Any) -> Dict[str, Any]:
    """Param pytree -> flat name->array mapping (dicts pass through;
    other pytrees get positional names)."""
    if isinstance(samples, dict):
        out = {}
        for k, v in samples.items():
            if isinstance(v, dict):
                for k2, v2 in _as_mapping(v).items():
                    out[f"{k}.{k2}"] = v2
            else:
                out[k] = v
        return out
    leaves = jax.tree_util.tree_leaves(samples)
    return {f"param_{i}": leaf for i, leaf in enumerate(leaves)}
