"""Tempered Sequential Monte Carlo — massively parallel posterior sampling.

Net-new sampler family (the reference delegates all sampling to PyMC,
reference: demo_model.py:38-42, and ships only NUTS/Metropolis drivers).
SMC is the most TPU-shaped inference algorithm in the toolbox: thousands
of particles advance in lockstep, so every logp evaluation is a huge
batched call — exactly what the MXU wants — and there is no sequential
chain to serialize.

Algorithm (SMC sampler with likelihood tempering from a Gaussian
reference distribution fitted to the initial particles):

1. particles ~ init + jitter; ``q0`` = diagonal Gaussian moment-match.
2. anneal ``logp_b(x) = (1-b) log q0(x) + b logp(x)`` from b=0 to b=1;
   each stage picks the next ``b`` by bisection so the effective sample
   size (ESS) of the incremental weights stays at ``ess_target``.
3. systematic resampling, then ``n_mutations`` random-walk Metropolis
   steps per particle at the current temperature, with the proposal
   scaled by the particle standard deviation.

Everything — bisection, resampling, mutation — runs inside one
``lax.while_loop`` on device; the number of stages is data-dependent but
bounded by ``max_stages``.  Also returns the log model evidence
estimate (a capability NUTS does not have).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..utils import LOG_2PI
from .util import flatten_logp


class SMCResult(NamedTuple):
    samples: Any  # user pytree, leaves lead with (n_particles,)
    log_evidence: jax.Array  # SMC estimate of log Z
    n_stages: jax.Array  # tempering stages actually used
    final_beta: jax.Array  # 1.0 on a clean run
    accept_rate: jax.Array  # mean mutation acceptance, last stage


def _systematic_resample(key, log_w, n):
    """Systematic resampling: indices with expected counts ∝ softmax(log_w)."""
    w = jax.nn.softmax(log_w)
    positions = (jax.random.uniform(key) + jnp.arange(n)) / n
    return jnp.searchsorted(jnp.cumsum(w), positions, side="left").clip(0, n - 1)


def _ess(log_w):
    w = jax.nn.softmax(log_w)
    return 1.0 / jnp.sum(w**2)


def smc_sample(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    n_particles: int = 2048,
    n_mutations: int = 5,
    ess_target: float = 0.5,
    max_stages: int = 50,
    init_jitter: float = 1.0,
    step_scale: float = 0.5,
    logp_and_grad_fn: Optional[Callable] = None,  # accepted for API symmetry
) -> SMCResult:
    """Sample ``logp_fn`` (params pytree -> scalar) with tempered SMC.

    The ``logp_fn`` may be any federated/sharded evaluator
    (:class:`~pytensor_federated_tpu.FederatedLogp`); particle evaluation
    vmaps over it, so per-stage cost is one big SPMD batch.
    """
    del logp_and_grad_fn
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    dim = flat_init.shape[0]
    dtype = flat_init.dtype
    batch_logp = jax.vmap(flat_logp)

    k_init, k_loop = jax.random.split(key)
    x0 = flat_init[None, :] + init_jitter * jax.random.normal(
        k_init, (n_particles, dim), dtype
    )

    # Gaussian reference q0 moment-matched to the initial cloud.
    mu0 = jnp.mean(x0, axis=0)
    sd0 = jnp.std(x0, axis=0) + 1e-6

    def log_q0(x):
        # Fully normalized — the evidence estimate depends on it.
        return jnp.sum(
            -0.5 * ((x - mu0) / sd0) ** 2 - jnp.log(sd0) - 0.5 * LOG_2PI,
            axis=-1,
        )

    def tempered(lp_batch, lq_batch, beta):
        return (1.0 - beta) * lq_batch + beta * lp_batch

    lp0 = batch_logp(x0)
    lq0 = log_q0(x0)

    class Carry(NamedTuple):
        x: jax.Array
        lp: jax.Array  # target logp of each particle
        lq: jax.Array  # reference logp of each particle
        beta: jax.Array
        log_z: jax.Array
        stage: jax.Array
        key: jax.Array
        accept: jax.Array

    def next_beta(lp, lq, beta):
        """Largest beta' in (beta, 1] keeping ESS of incremental weights
        >= ess_target * n, by bisection (monotone in beta')."""
        target = ess_target * n_particles

        def w_ess(b):
            dlw = (b - beta) * (lp - lq)
            return _ess(dlw)

        def cond(state):
            lo, hi, it = state
            return it < 30

        def body(state):
            lo, hi, it = state
            mid = 0.5 * (lo + hi)
            ok = w_ess(mid) >= target
            return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid), it + 1

        full = jnp.asarray(1.0, beta.dtype)
        lo, hi, _ = jax.lax.while_loop(
            cond, body, (beta, full, jnp.zeros((), jnp.int32))
        )
        # If even beta'=1 keeps ESS above target, jump straight to 1.
        return jnp.where(w_ess(full) >= target, full, lo)

    def mutate(key, x, lp, lq, beta):
        """n_mutations random-walk MH steps at temperature beta.

        Carries (lp, lq) of the current particles so no evaluation is
        repeated: exactly one batched logp per proposal — the batched
        call is the expensive sharded federated evaluator.
        """
        sd = jnp.std(x, axis=0) + 1e-8

        def step(carry, k):
            x, lp, lq, n_acc = carry
            k1, k2 = jax.random.split(k)
            prop = x + step_scale * sd[None, :] * jax.random.normal(
                k1, x.shape, dtype
            )
            lp_prop, lq_prop = batch_logp(prop), log_q0(prop)
            log_u = jnp.log(
                jax.random.uniform(k2, (n_particles,), dtype=dtype)
            )
            acc = log_u < (
                tempered(lp_prop, lq_prop, beta) - tempered(lp, lq, beta)
            )
            x = jnp.where(acc[:, None], prop, x)
            lp = jnp.where(acc, lp_prop, lp)
            lq = jnp.where(acc, lq_prop, lq)
            return (x, lp, lq, n_acc + jnp.mean(acc.astype(dtype))), None

        (x, lp, lq, n_acc), _ = jax.lax.scan(
            step,
            (x, lp, lq, jnp.zeros((), dtype)),
            jax.random.split(key, n_mutations),
        )
        return x, lp, lq, n_acc / n_mutations

    def cond(c: Carry):
        return jnp.logical_and(c.beta < 1.0, c.stage < max_stages)

    def body(c: Carry):
        k_res, k_mut, k_next = jax.random.split(c.key, 3)
        beta_new = next_beta(c.lp, c.lq, c.beta)
        dlw = (beta_new - c.beta) * (c.lp - c.lq)
        # Evidence increment: log mean incremental weight.
        log_z = c.log_z + jax.nn.logsumexp(dlw) - jnp.log(float(n_particles))
        idx = _systematic_resample(k_res, dlw, n_particles)
        # Gather cached logps along with the particles — no re-evaluation.
        x, lp, lq = c.x[idx], c.lp[idx], c.lq[idx]
        x, lp, lq, acc = mutate(k_mut, x, lp, lq, beta_new)
        return Carry(x, lp, lq, beta_new, log_z, c.stage + 1, k_next, acc)

    init = Carry(
        x=x0,
        lp=lp0,
        lq=lq0,
        beta=jnp.zeros((), dtype),
        log_z=jnp.zeros((), dtype),
        stage=jnp.zeros((), jnp.int32),
        key=k_loop,
        accept=jnp.zeros((), dtype),
    )
    # One device-resident program for the whole anneal.
    final = jax.jit(lambda c: jax.lax.while_loop(cond, body, c))(init)

    samples = jax.vmap(unravel)(final.x)
    return SMCResult(
        samples=samples,
        log_evidence=final.log_z,
        n_stages=final.stage,
        final_beta=final.beta,
        accept_rate=final.accept,
    )
