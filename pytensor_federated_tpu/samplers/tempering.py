"""Parallel tempering (replica exchange) — the multimodal-posterior
sampler, shaped for the accelerator.

NUTS/HMC mix within a mode; for well-separated modes the gradient
pushes every chain back to the mode it started in and the posterior
weights come out wrong.  Replica exchange runs K replicas of the SAME
posterior at temperatures ``beta_1 = 1 > beta_2 > ... > beta_K``
(flatter and flatter tempered targets ``beta * logp``) and periodically
proposes swapping adjacent replicas' states, accepted with the exact
Metropolis ratio ``exp((beta_i - beta_j) (U_j - U_i))`` — hot replicas
cross between modes freely and the swaps transport those crossings down
to the cold chain, whose draws remain EXACTLY distributed per the
target (the swap kernel leaves the joint product distribution
invariant).

TPU shape: the K replicas advance in LOCKSTEP — one vmapped HMC update
over a (K, dim) state block per iteration (every replica shares the
leapfrog program; only ``beta`` and the per-replica step size differ),
then one O(K) swap pass of elementwise where/gather — so the whole
sampler is a single ``lax.scan`` with no data-dependent Python control
flow, exactly like :mod:`.chees`'s lockstep-chains design.  The
reference has no sampler layer at all (its driver defers to PyMC,
reference: demo_model.py:38-45); within THIS framework's suite,
tempering complements NUTS (within-mode efficiency) the way SMC does,
but with an exact stationary cold chain instead of a particle
approximation.

Swap proposals alternate even/odd adjacent pairs (the standard
deterministic-even-odd scheme: all non-overlapping pairs propose
simultaneously, so information travels one rung per iteration with no
randomized-pair bookkeeping).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from .mcmc import (
    SampleResult,
    make_flat_logp_and_grad,
    place_with_sharding,
)
from .util import welford_init, welford_update, welford_variance

__all__ = ["pt_sample"]


def _hmc_step(lg, x, u, g, beta, step, inv_mass, key, num_leapfrog):
    """One HMC transition for a single replica of the TEMPERED target
    ``beta * logp`` (u, g are the UNTEMPERED logp and gradient, so the
    swap ratio can reuse them).  ``inv_mass`` is this rung's diagonal
    of M⁻¹ (hmc.py conventions: momentum ~ N(0, M), kinetic
    ``0.5 pᵀM⁻¹p``, position update ``step * inv_mass * p``).
    Returns (x', u', g', accept_prob)."""
    dim = x.shape[0]
    k_mom, k_acc = jax.random.split(key)
    p0 = jax.random.normal(k_mom, (dim,), x.dtype) / jnp.sqrt(inv_mass)

    def leap(carry, _):
        xq, pq, _uq, gq = carry
        pq = pq + 0.5 * step * beta * gq
        xq = xq + step * inv_mass * pq
        uq2, gq2 = lg(xq)
        pq = pq + 0.5 * step * beta * gq2
        return (xq, pq, uq2, gq2), None

    # u rides through the scan carry: the final leapfrog step already
    # evaluated lg(x1), so no extra target evaluation is needed.
    (x1, p1, u1, g1), _ = jax.lax.scan(
        leap, (x, p0, u, g), None, length=num_leapfrog
    )
    # Hamiltonian of the tempered target; divergences (non-finite
    # energies) fall out as accept_prob 0 via the where below.
    h0 = -beta * u + 0.5 * jnp.sum(p0**2 * inv_mass)
    h1 = -beta * u1 + 0.5 * jnp.sum(p1**2 * inv_mass)
    log_alpha = h0 - h1
    log_alpha = jnp.where(jnp.isfinite(log_alpha), log_alpha, -jnp.inf)
    accept_prob = jnp.minimum(1.0, jnp.exp(log_alpha))
    take = jax.random.uniform(k_acc) < accept_prob
    return (
        jnp.where(take, x1, x),
        jnp.where(take, u1, u),
        jnp.where(take, g1, g),
        accept_prob,
    )


def _swap_pass(u, betas, key, parity):
    """Even/odd adjacent swap proposals (all pairs of the given parity
    at once).  Exact Metropolis: ``log alpha = (b_i - b_{i+1}) *
    (u_{i+1} - u_i)``.  Returns the induced replica PERMUTATION plus
    per-pair (accept, propose, alpha) (K-1,) — ``alpha`` is the swap
    PROBABILITY min(1, e^{log alpha}), what the ladder adaptation
    regresses on; the caller applies the permutation to every
    per-replica array."""
    K = u.shape[0]
    i = jnp.arange(K - 1)
    propose = (i % 2) == parity
    log_alpha = (betas[:-1] - betas[1:]) * (u[1:] - u[:-1])
    alpha = jnp.exp(jnp.minimum(log_alpha, 0.0))
    accept = (
        jnp.log(jax.random.uniform(key, (K - 1,))) < log_alpha
    ) & propose
    # Build the permutation induced by the accepted, non-overlapping
    # swaps: perm[i] = i+1 and perm[i+1] = i for each accepted pair.
    perm = jnp.arange(K)
    perm = perm.at[:-1].set(jnp.where(accept, perm[1:], perm[:-1]))
    perm = perm.at[1:].set(
        jnp.where(accept, jnp.arange(K - 1), perm[1:])
    )
    return perm, accept, propose, alpha


def pt_sample(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key,
    num_chains: int = 1,
    num_warmup: int = 500,
    num_samples: int = 500,
    num_temps: int = 8,
    beta_min: float = 0.05,
    num_leapfrog: int = 8,
    target_accept: float = 0.7,
    jitter: float = 1.0,
    logp_and_grad_fn: Optional[Callable] = None,
    temp_sharding: Optional[Any] = None,
    adapt_ladder: bool = False,
    target_swap: float = 0.4,
    adapt_mass: bool = True,
) -> SampleResult:
    """Replica-exchange HMC; returns the COLD (beta = 1) chain's draws
    as a :class:`SampleResult` with ``chains = num_chains``.

    ``num_chains > 1`` runs that many INDEPENDENT tempering stacks
    (vmapped — each with its own ladder, masses and step sizes), which
    is what makes ``res.summary()``'s split-R̂ meaningful: cross-chain
    disagreement exposes a stack that never found the second mode.
    Incompatible with ``temp_sharding`` (shard one stack's ladder OR
    replicate stacks, not both at once).

    ``betas`` form a geometric ladder from 1 to ``beta_min`` (the
    standard choice: constant acceptance needs geometric spacing when
    the energy variance is roughly constant).  During warmup each
    temperature's step size adapts by Robbins-Monro toward
    ``target_accept``; replicas start from ``init_params`` plus
    ``jitter``-scaled Gaussian offsets so the hot rungs begin spread
    out.  ``logp_and_grad_fn`` forwards node-supplied gradients (the
    federated contract) exactly as in :func:`.mcmc.sample`.

    Diagnostics: ``stats["swap_accept"]`` is the per-draw fraction of
    proposed swaps accepted (``stats`` stays strictly (chains, draws)
    so the arviz exporters accept the result unmodified); the ladder
    diagnostics live in ``extra`` — ``swap_rate_per_pair`` ``(K-1,)``,
    each rung's acceptance rate over the draw phase (rungs near zero
    mean the ladder has a gap; add temperatures or raise ``beta_min``),
    and ``betas`` — both with a leading ``(chains, ...)`` axis.

    ``adapt_mass=True`` (default) adapts a per-rung DIAGONAL mass from
    each rung's own warmup samples: Welford variance accumulated over
    the first warmup half (per temperature — hot rungs see flatter,
    wider tempered targets and get their own scale), applied for the
    second half and the draw phase.  Identity mass otherwise.

    ``adapt_ladder=True`` tunes the ladder SPACING during warmup by
    stochastic approximation (Miasojedow-Moulines-Vihola style): each
    rung's log-gap ``rho_i = log beta_i - log beta_{i+1}`` moves with
    the proposed pairs' swap PROBABILITY toward ``target_swap`` —
    too-easy rungs widen, dead rungs shrink — with ``beta_1`` pinned
    at 1 so the cold chain stays exact.  The ladder freezes for the
    draw phase (adaptation during draws would bias the chain); the
    FINAL ladder is reported in ``extra["betas"]``.  Off by default:
    the geometric ladder is reproducible and usually adequate.

    ``temp_sharding`` (a ``NamedSharding`` partitioning the leading
    axis, e.g. ``NamedSharding(mesh, P("temps"))``) places the replica
    block across a device mesh — computation follows sharding: each
    device advances its rungs' leapfrogs data-parallel and the swap
    pass's O(K) permutation lowers to a collective gather of (dim,)
    states, the only cross-device traffic per iteration (the
    :func:`.chees.chees_sample` ``chain_sharding`` pattern).
    """
    if num_temps < 2:
        raise ValueError(
            f"parallel tempering needs >= 2 temperatures, got {num_temps}"
            " (with one, use samplers.sample)"
        )
    if not 0.0 < beta_min < 1.0:
        raise ValueError(
            f"beta_min must be in (0, 1), got {beta_min} (0 or negative "
            "makes the geometric ladder NaN)"
        )
    if num_chains < 1:
        raise ValueError(f"num_chains must be >= 1, got {num_chains}")
    if num_chains > 1 and temp_sharding is not None:
        raise ValueError(
            "num_chains > 1 is incompatible with temp_sharding: shard "
            "one stack's temperature ladder OR run replicated stacks "
            "(vmapped), not both"
        )
    _, flat_init, unravel, lg = make_flat_logp_and_grad(
        logp_fn, init_params, logp_and_grad_fn
    )
    dim = flat_init.shape[0]
    dtype = flat_init.dtype
    betas0 = jnp.geomspace(1.0, beta_min, num_temps).astype(dtype)
    # Ladder parameterization for adaptation: positive log-beta gaps
    # rho with beta_1 == 1 pinned; log beta_i = -sum_{j<i} rho_j.
    log_rho0 = jnp.log(jnp.diff(-jnp.log(betas0)))

    def _betas_of(log_rho):
        return jnp.exp(
            -jnp.concatenate(
                [jnp.zeros((1,), dtype), jnp.cumsum(jnp.exp(log_rho))]
            )
        )

    def _run(key):
        """One full tempering stack (warmup + draws) for one chain."""
        k_init, k_warm, k_draw = jax.random.split(jnp.asarray(key), 3)
        x0 = flat_init[None, :] + jitter * jax.random.normal(
            k_init, (num_temps, dim), dtype
        )
        x0 = place_with_sharding(
            x0, temp_sharding, axis_desc=f"num_temps={num_temps}"
        )
        u0, g0 = jax.vmap(lg)(x0)
        # NaN-safe start: a hot replica jittered into a -inf region would
        # freeze (every proposal from -inf accepts, but gradients NaN);
        # fall back to the unjittered start for those replicas.
        bad = ~jnp.isfinite(u0)
        x0 = jnp.where(bad[:, None], flat_init[None, :], x0)
        u0, g0 = jax.vmap(lg)(x0)

        vmapped_hmc = jax.vmap(
            _hmc_step, in_axes=(None, 0, 0, 0, 0, 0, 0, 0, None)
        )

        def make_iteration(adapt: bool, collect: bool):
            """Scan body with the phase flags baked in as PYTHON constants
            (each phase is its own scan, so a traced flag would only force
            dead Welford/adaptation arithmetic through every iteration)."""

            def iteration(carry, inp):
                x, u, g, log_step, log_rho, inv_mass, wf, t = carry
                k_iter = inp
                # Without adaptation the ladder is the EXACT geomspace
                # constant (bitwise — no log/exp round trip perturbing
                # seeded runs, no per-iteration rebuild of a loop
                # invariant).
                betas = _betas_of(log_rho) if adapt_ladder else betas0
                k_hmc, k_swap = jax.random.split(k_iter)
                xs, us, gs, acc = vmapped_hmc(
                    lg, x, u, g, betas, jnp.exp(log_step), inv_mass,
                    jax.random.split(k_hmc, num_temps), num_leapfrog,
                )
                if collect:
                    # Per-rung Welford (mass window only): each temperature
                    # estimates ITS OWN tempered target's scale — the
                    # shared util.welford accumulator, vmapped over rungs.
                    wf = jax.vmap(welford_update)(wf, xs)
                # Robbins-Monro per-temperature step-size adaptation
                # (warmup only): eta_t ~ t^-0.6 like the Metropolis warmup
                # in mcmc.py.
                eta = (2.0 if adapt else 0.0) / (t + 10.0) ** 0.6
                log_step = log_step + eta * (acc - target_accept)
                parity = (t % 2).astype(jnp.int32)
                perm, accept, propose, alpha = _swap_pass(
                    us, betas, k_swap, parity
                )
                if adapt_ladder and adapt:
                    # Widen rungs that swap too easily, shrink dead
                    # ones — only the pairs actually proposed this parity
                    # move.  A non-finite alpha (two replicas stuck at
                    # -inf logp) must not poison the ladder: treat it as a
                    # dead rung (0).
                    alpha = jnp.where(jnp.isfinite(alpha), alpha, 0.0)
                    # Clamp RELATIVE to the requested ladder so a
                    # deliberately tight (or wide) geomspace is never
                    # snapped to absolute bounds on step one: each gap may
                    # shrink/grow by at most e^3 (~20x) from its requested
                    # value, which also keeps the ladder from collapsing
                    # or blowing past float range.
                    log_rho = jnp.clip(
                        log_rho + eta * propose * (alpha - target_swap),
                        log_rho0 - 3.0,
                        log_rho0 + 3.0,
                    )
                # a swap exchanges WHOLE states: x, u and g permute
                # together (no re-evaluation — the swap kernel touches no
                # new points)
                xs, us, gs = xs[perm], us[perm], gs[perm]
                n_prop = jnp.maximum(jnp.sum(propose), 1)
                swap_frac = jnp.sum(accept) / n_prop
                # acc permutes with the state so the recorded accept_prob
                # belongs to the SAME transition as the emitted (post-swap)
                # cold draw — acc[0] alone would describe a different
                # replica whenever the cold swap fired.
                out = (xs[0], acc[perm][0], swap_frac, accept, propose)
                return (
                    (xs, us, gs, log_step, log_rho, inv_mass, wf, t + 1),
                    out,
                )

            return iteration

        # find a crude initial step size: 0.1 / dim^0.25, per temperature
        log_step0 = jnp.full(
            (num_temps,), jnp.log(0.1 / dim**0.25), dtype
        )
        wf0 = jax.vmap(lambda _: welford_init(dim, dtype))(
            jnp.arange(num_temps)
        )
        inv_mass0 = jnp.ones((num_temps, dim), dtype)
        carry = (
            x0, u0, g0, log_step0, log_rho0, inv_mass0, wf0,
            jnp.asarray(0, jnp.int32),
        )
        # Warmup phases: [init buffer: discard the jittered-start
        # transient, like AdaptSchedule's init_buffer] -> [mass window:
        # collect per-rung variance] -> [phase 2: adapted mass, step sizes
        # re-adapt to it].  A contaminated transient would bake a
        # direction-dependent overestimate into the mass for the whole run.
        w1 = num_warmup // 2
        w_buf = min(75, w1 // 3) if adapt_mass else 0
        warm_keys = jax.random.split(k_warm, num_warmup)
        carry, _ = jax.lax.scan(
            make_iteration(adapt=True, collect=False),
            carry,
            warm_keys[:w_buf],
        )
        carry, _ = jax.lax.scan(
            make_iteration(adapt=True, collect=adapt_mass),
            carry,
            warm_keys[w_buf:w1],
        )
        if adapt_mass and num_warmup >= 8:
            x_c, u_c, g_c, log_step_c, log_rho_c, _, wf_c, t_c = carry
            # The shared Stan-schedule regularization (decaying unit
            # shrinkage), vmapped per rung.
            inv_mass1 = jax.vmap(welford_variance)(wf_c)
            carry = (
                x_c, u_c, g_c, log_step_c, log_rho_c, inv_mass1, wf0, t_c
            )
        carry, _ = jax.lax.scan(
            make_iteration(adapt=True, collect=False),
            carry,
            warm_keys[w1:],
        )
        draw_keys = jax.random.split(k_draw, num_samples)
        carry, (draws, acc0, swap_frac, accepts, proposes) = jax.lax.scan(
            make_iteration(adapt=False, collect=False),
            carry,
            draw_keys,
        )

        return (
            draws, acc0, swap_frac, accepts, proposes,
            jnp.exp(carry[3][0]), carry[5][0],
            _betas_of(carry[4]) if adapt_ladder else betas0,
        )

    # Independent stacks vmap over chain keys.  num_chains == 1 calls
    # _run DIRECTLY (same seeding as ever, and temp_sharding's
    # device_put cannot run under vmap) and prepends the chains axis.
    if num_chains == 1:
        outs = jax.tree_util.tree_map(
            lambda a: a[None], _run(jnp.asarray(key))
        )
    else:
        outs = jax.vmap(_run)(
            jax.random.split(jnp.asarray(key), num_chains)
        )
    (
        draws, acc0, swap_frac, accepts, proposes,
        cold_step, cold_inv_mass, final_betas,
    ) = outs

    samples = jax.vmap(jax.vmap(unravel))(draws)
    # honest per-rung rate: accepted / actually-proposed (parity
    # alternation makes proposal counts differ by one for odd
    # num_samples — no n/2 assumption); per chain.
    n_prop_pair = jnp.maximum(
        jnp.sum(proposes.astype(dtype), axis=1), 1.0
    )
    per_pair = jnp.sum(accepts.astype(dtype), axis=1) / n_prop_pair
    # Ladder diagnostics go in ``extra``, NOT ``stats``: stats entries
    # must be (chains, draws) — the arviz exporters forward them
    # verbatim as sample_stats.
    return SampleResult(
        samples=samples,
        stats={
            "accept_prob": acc0,
            "swap_accept": swap_frac,
        },
        step_size=cold_step,
        inv_mass=cold_inv_mass,
        extra={
            "swap_rate_per_pair": per_pair,
            # EXACTLY the ladder each chain's iterations used: the
            # geomspace constant when fixed (bitwise), adapted
            # otherwise; leading axis = chains.
            "betas": final_betas,
        },
    )
