"""Posterior/prior predictive sampling over on-device draws.

The reference's users finish a PyMC workflow with
``pm.sample_posterior_predictive`` over the trace their federated model
produced; this is the on-device counterpart operating directly on
:class:`~pytensor_federated_tpu.samplers.mcmc.SampleResult` pytrees
(leading ``(chains, draws)`` axes).  The whole sweep is one vmapped
executable: a per-draw simulator ``predictive_fn(params, key) -> data``
runs across all (sub)sampled draws with split PRNG keys.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

__all__ = ["posterior_predictive", "prior_predictive"]


def _flatten_chain_draws(samples: Any) -> Any:
    """(chains, draws, *event) -> (chains*draws, *event) per leaf."""
    return jax.tree_util.tree_map(
        lambda l: jnp.reshape(l, (-1,) + l.shape[2:]), samples
    )


def posterior_predictive(
    predictive_fn: Callable[[Any, jax.Array], Any],
    samples: Any,
    key: jax.Array,
    *,
    num_draws: Optional[int] = None,
) -> Any:
    """Simulate data from every (or ``num_draws`` subsampled) posterior
    draw.

    ``predictive_fn(params, key)`` receives ONE parameter pytree (no
    chain/draw axes) and a PRNG key, and returns simulated data;
    ``samples`` is a pytree with leading ``(chains, draws)`` axes
    (``SampleResult.samples``).  Returns the simulator output with a
    single leading draws axis.  Subsampling (``num_draws``) picks
    evenly spaced draws — cheaper than the full sweep and unbiased for
    stationary chains.
    """
    flat = _flatten_chain_draws(samples)
    total = jax.tree_util.tree_leaves(flat)[0].shape[0]
    if num_draws is not None and num_draws < total:
        idx = jnp.linspace(0, total - 1, num_draws).astype(jnp.int32)
        flat = jax.tree_util.tree_map(lambda l: l[idx], flat)
        total = num_draws
    keys = jax.random.split(key, total)
    # vmap only — a fresh jit wrapper here would re-trace on every call
    # (each call makes a new closure); callers jit their outer step if
    # they want one compiled sweep.
    return jax.vmap(predictive_fn)(flat, keys)


def prior_predictive(
    sample_prior_fn: Callable[[jax.Array], Any],
    predictive_fn: Callable[[Any, jax.Array], Any],
    key: jax.Array,
    *,
    num_draws: int = 500,
) -> Any:
    """Simulate data from the prior: draw ``num_draws`` parameter sets
    with ``sample_prior_fn(key) -> params`` and push each through
    ``predictive_fn`` — one vmapped executable."""
    def one(k):
        kp, kd = jax.random.split(k)
        return predictive_fn(sample_prior_fn(kp), kd)

    return jax.vmap(one)(jax.random.split(key, num_draws))
