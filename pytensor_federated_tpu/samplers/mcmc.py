"""The sampling front door: warmup adaptation, chains, on-device scan.

Replaces the reference's driver-side ``pm.sample`` / ``pm.find_MAP``
(reference: demo_model.py:38-42).  Where the reference runs chains in
separate host processes with the federated client re-pickled per process
(reference: service.py:266-275, test_wrapper_ops.py:305-317), chains here
are a ``vmap`` axis — shardable over a mesh ``"chains"`` axis — and the
entire warmup+sampling loop is a ``lax.scan`` on device.

Returned samples keep the user's params-pytree structure with leading
``(chains, draws)`` axes.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics
from ..telemetry import spans as _tspans
from .hmc import HMCState, find_reasonable_step_size, hmc_init, hmc_step
from .metropolis import MetropolisState, metropolis_init, metropolis_step
from .nuts import nuts_step
from .util import (
    AdaptSchedule,
    da_init,
    da_update,
    flatten_logp,
    welford_covariance,
    welford_init,
    welford_update,
    welford_variance,
)


# Sampler step timing (metric catalog: docs/observability.md).  The
# whole warmup+sampling program is ONE jitted scan, so per-step times
# are derived host-side: device wall / total transitions.  That is the
# number to line up against the RPC histograms — a federated logp makes
# every step an evaluate() fanout, and step_seconds vs
# pftpu_client_call_seconds says how much of a step is the wire.
_SAMPLE_RUN_S = _metrics.histogram(
    "pftpu_sampler_run_seconds",
    "Device wall time of one sample() run (all chains, warmup+draws)",
    ("kernel",),
)
_STEP_S = _metrics.histogram(
    "pftpu_sampler_step_seconds",
    "Derived per-transition time: run wall / (chains * (warmup+draws))",
    ("kernel",),
)
_DRAWS = _metrics.counter(
    "pftpu_sampler_draws_total",
    "Posterior draws produced (chains * num_samples)",
    ("kernel",),
)


def _record_run(kernel, out, t0, num_chains, num_warmup, num_samples):
    """Telemetry-on path only: block on ``out`` (jit dispatch is async;
    an un-synced wall time would rate the dispatch, not the run), then
    record run wall, derived per-transition time, and draws.  The run
    settling is also a sampler phase transition for the flight record
    — an incident dump shows whether the process died inside or
    between sampling runs."""
    jax.block_until_ready(out)
    wall = time.perf_counter() - t0
    _SAMPLE_RUN_S.labels(kernel=kernel).observe(wall)
    transitions = num_chains * (num_warmup + num_samples)
    if transitions:
        _STEP_S.labels(kernel=kernel).observe(wall / transitions)
    _DRAWS.labels(kernel=kernel).inc(num_chains * num_samples)
    _flightrec.record(
        "sampler.run",
        kernel=kernel,
        chains=num_chains,
        warmup=num_warmup,
        draws=num_samples,
        wall_s=wall,
    )


class WarmupResult(NamedTuple):
    state: HMCState
    step_size: jax.Array
    inv_mass: jax.Array


def make_flat_logp_and_grad(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    logp_and_grad_fn: Optional[Callable] = None,
):
    """Flatten the target and build its fused value+grad over the flat
    vector — shared by :func:`sample` and ``checkpoint.sample_checkpointed``.

    Returns ``(flat_logp, flat_init, unravel, lg)`` where ``lg(x) ->
    (logp, grad)``; with ``logp_and_grad_fn`` the gradient is the
    forward-supplied one (the federated node contract), else autodiff.
    """
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)

    if logp_and_grad_fn is not None:
        from jax.flatten_util import ravel_pytree

        def lg(x):
            v, g = logp_and_grad_fn(unravel(x))
            return v, ravel_pytree(g)[0]

    else:

        def lg(x):
            return jax.value_and_grad(flat_logp)(x)

    return flat_logp, flat_init, unravel, lg


def place_with_sharding(x, sharding, *, axis_desc: str):
    """Validate that ``sharding`` partitions ``x``'s leading axis and
    place ``x`` — THE one shard-validate-then-device_put implementation
    shared by sample/chees_sample/pt_sample (a fix to the validation
    or the error hint must not have to land in three copies)."""
    if sharding is None:
        return x
    try:
        sharding.shard_shape(x.shape)
    except Exception as e:
        raise ValueError(
            f"{axis_desc} is not shardable by sharding={sharding}: {e} "
            "— the leading dimension must be divisible by the mesh "
            "axis the spec partitions it over"
        ) from None
    return jax.device_put(x, sharding)


def make_kernel_step(
    lg: Callable, kernel: str, *, max_depth: int = 8, num_hmc_steps: int = 16
):
    """Gradient-based transition kernel by name ("nuts" or "hmc")."""
    if kernel == "nuts":
        return partial(nuts_step, lg, max_depth=max_depth)
    if kernel == "hmc":
        return partial(hmc_step, lg, num_steps=num_hmc_steps)
    raise ValueError(f"unknown kernel {kernel!r}")


def _warmup(
    logp_and_grad,
    x0,
    key,
    *,
    num_warmup: int,
    kernel_step,
    target_accept: float = 0.8,
    dense_mass: bool = False,
) -> WarmupResult:
    """Stan-style three-stage warmup: step size + diagonal (or, with
    ``dense_mass``, full-covariance) mass adaptation."""
    dtype = x0.dtype
    dim = x0.shape[0]
    sched = AdaptSchedule.make(num_warmup)
    k_init, k_scan = jax.random.split(key)

    inv_mass = jnp.eye(dim, dtype=dtype) if dense_mass else jnp.ones(
        (dim,), dtype
    )
    step0 = find_reasonable_step_size(logp_and_grad, x0, k_init, inv_mass)
    da = da_init(step0)
    wf = welford_init(dim, dtype, dense=dense_mass)
    state = hmc_init(logp_and_grad, x0)

    def body(carry, inputs):
        state, da, wf, inv_mass = carry
        key, update_mass, in_slow = inputs
        step_size = jnp.exp(da.log_step)
        state, info = kernel_step(
            state, key, step_size=step_size, inv_mass=inv_mass
        )
        da = da_update(da, info.accept_prob, target=target_accept)
        wf = jax.tree_util.tree_map(
            partial(jnp.where, in_slow), welford_update(wf, state.x), wf
        )

        def refresh(da, wf, inv_mass):
            new_inv_mass = (
                welford_covariance(wf) if dense_mass else welford_variance(wf)
            )
            # Restart step-size search around the current averaged value.
            new_da = da_init(jnp.exp(da.log_step_avg))
            return new_da, welford_init(dim, dtype, dense=dense_mass), (
                new_inv_mass
            )

        da, wf, inv_mass = jax.tree_util.tree_map(
            partial(jnp.where, update_mass),
            refresh(da, wf, inv_mass),
            (da, wf, inv_mass),
        )
        return (state, da, wf, inv_mass), None

    keys = jax.random.split(k_scan, num_warmup)
    (state, da, _, inv_mass), _ = jax.lax.scan(
        body, (state, da, wf, inv_mass), (keys, sched.update_mass, sched.in_slow)
    )
    # With num_warmup=0 no da_update ever ran and log_step_avg is still
    # its zero init — fall back to the found reasonable step size.
    log_step = jnp.where(da.count > 0, da.log_step_avg, da.log_step)
    return WarmupResult(state, jnp.exp(log_step), inv_mass)


@dataclasses.dataclass
class SampleResult:
    """Posterior draws plus per-draw diagnostics."""

    samples: Any  # user pytree with leading (chains, draws)
    stats: dict  # accept_prob / diverging / depth / energy, (chains, draws)
    step_size: jax.Array  # (chains,)
    inv_mass: jax.Array  # (chains, dim) — or (chains, dim, dim) dense
    #: sampler-specific NON-per-draw diagnostics (e.g. pt_sample's
    #: temperature ladder).  Kept OUT of ``stats`` on purpose: every
    #: ``stats`` entry must be (chains, draws) because the arviz
    #: exporters forward stats verbatim as sample_stats.
    extra: Optional[dict] = None

    def summary(
        self, *, hdi_prob: float = 0.94, rank_normalized: bool = False
    ) -> dict:
        """mean/sd/HDI/split-R̂/ESS per component (samplers.convergence)."""
        from .convergence import summary as _summary

        return _summary(
            self.samples,
            hdi_prob=hdi_prob,
            rank_normalized=rank_normalized,
        )


def sample(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    num_warmup: int = 500,
    num_samples: int = 500,
    num_chains: int = 4,
    kernel: str = "nuts",
    max_depth: int = 8,
    num_hmc_steps: int = 16,
    target_accept: float = 0.8,
    jitter: float = 1.0,
    logp_and_grad_fn: Optional[Callable] = None,
    dense_mass: bool = False,
    chain_sharding: Optional[Any] = None,
) -> SampleResult:
    """Run adaptive MCMC against ``logp_fn`` (params pytree -> scalar).

    ``pm.sample`` analog (reference: demo_model.py:40-42).  ``kernel`` is
    one of ``"nuts"`` (default, matching the reference's NUTS driver),
    ``"hmc"``, or ``"metropolis"`` (the reference's CI sampler,
    test_wrapper_ops.py:97-103).  Pass ``logp_and_grad_fn`` to supply a
    fused value+grad (e.g. ``FederatedLogp.logp_and_grad`` or a
    forward-supplied-gradient :class:`~pytensor_federated_tpu.LogpGradOp`);
    otherwise gradients come from autodiff of ``logp_fn``.

    ``dense_mass=True`` adapts a full covariance mass matrix during
    warmup (Stan-style shrunk Welford covariance) instead of the
    diagonal — worth it for strongly correlated posteriors; every
    momentum/velocity op becomes a small matvec (MXU-friendly).

    ``chain_sharding`` (e.g. ``NamedSharding(mesh, P("chains"))``)
    places the chain batch across a device mesh; chains are
    independent, so the vmapped program partitions with zero
    collectives — the single-host path to device-parallel chains
    (``num_chains`` must be divisible by the mesh axis; for
    data-sharded logp use ``parallel.multichain_sample``).

    Everything (warmup + sampling, all chains) runs in one jitted
    program; chains are a vmap axis.
    """
    flat_logp, flat_init, unravel, lg = make_flat_logp_and_grad(
        logp_fn, init_params, logp_and_grad_fn
    )
    dtype = flat_init.dtype

    k_jit, k_run = jax.random.split(key)
    init_flat = jnp.broadcast_to(flat_init, (num_chains,) + flat_init.shape)
    if jitter:
        init_flat = init_flat + jitter * jax.random.normal(
            k_jit, init_flat.shape, dtype
        )

    init_flat = place_with_sharding(
        init_flat, chain_sharding, axis_desc=f"num_chains={num_chains}"
    )

    if kernel == "metropolis":
        with _tspans.span(
            "mcmc.sample", kernel="metropolis", chains=num_chains
        ):
            t0 = time.perf_counter()
            result = _sample_metropolis(
                flat_logp, unravel, init_flat, k_run, num_warmup,
                num_samples,
            )
            if _tspans.enabled():
                _record_run(
                    "metropolis", result.samples, t0,
                    num_chains, num_warmup, num_samples,
                )
        return result

    kernel_step = make_kernel_step(
        lg, kernel, max_depth=max_depth, num_hmc_steps=num_hmc_steps
    )

    def one_chain(x0, key):
        k_warm, k_samp = jax.random.split(key)
        warm = _warmup(
            lg,
            x0,
            k_warm,
            num_warmup=num_warmup,
            kernel_step=kernel_step,
            target_accept=target_accept,
            dense_mass=dense_mass,
        )

        def body(state, key):
            state, info = kernel_step(
                state,
                key,
                step_size=warm.step_size,
                inv_mass=warm.inv_mass,
            )
            stats = {
                "accept_prob": info.accept_prob,
                "diverging": info.diverging,
                "energy": info.energy,
            }
            if hasattr(info, "depth"):
                stats["depth"] = info.depth
            return state, (state.x, stats)

        keys = jax.random.split(k_samp, num_samples)
        _, (draws, stats) = jax.lax.scan(body, warm.state, keys)
        return draws, stats, warm.step_size, warm.inv_mass

    chain_keys = jax.random.split(k_run, num_chains)
    with _tspans.span(
        "mcmc.sample",
        kernel=kernel,
        chains=num_chains,
        warmup=num_warmup,
        draws=num_samples,
    ):
        t0 = time.perf_counter()
        draws, stats, step_sizes, inv_masses = jax.jit(jax.vmap(one_chain))(
            init_flat, chain_keys
        )
        if _tspans.enabled():
            _record_run(
                kernel, draws, t0, num_chains, num_warmup, num_samples
            )
    samples = jax.vmap(jax.vmap(unravel))(draws)
    return SampleResult(
        samples=samples, stats=stats, step_size=step_sizes, inv_mass=inv_masses
    )


def _sample_metropolis(flat_logp, unravel, init_flat, key, num_warmup, num_samples):
    """Adaptive-scale random-walk Metropolis over all chains."""
    dtype = init_flat.dtype

    def one_chain(x0, key):
        state = metropolis_init(flat_logp, x0)
        log_scale0 = jnp.zeros((), dtype)

        # Warmup: Robbins-Monro proposal-scale adaptation toward 0.35
        # acceptance (the reference relies on PyMC's tuning phase,
        # reference: test_wrapper_ops.py:99 ``tune=200``).
        def warm_scan(carry, key):
            state, log_scale = carry
            prev_acc = state.n_accept
            state = metropolis_step(
                flat_logp, state, key, step_size=jnp.exp(log_scale)
            )
            accepted = state.n_accept - prev_acc
            log_scale = log_scale + 0.1 * (accepted - 0.35)
            return (state, log_scale), None

        keys = jax.random.split(key, num_warmup + num_samples)
        (state, log_scale), _ = jax.lax.scan(
            warm_scan, (state, log_scale0), keys[:num_warmup]
        )

        def body(state, key):
            state = metropolis_step(
                flat_logp, state, key, step_size=jnp.exp(log_scale)
            )
            return state, (state.x, {"accept_total": state.n_accept})

        _, (draws, stats) = jax.lax.scan(body, state, keys[num_warmup:])
        return draws, stats, jnp.exp(log_scale)

    chain_keys = jax.random.split(key, init_flat.shape[0])
    draws, stats, scales = jax.jit(jax.vmap(one_chain))(init_flat, chain_keys)
    samples = jax.vmap(jax.vmap(unravel))(draws)
    return SampleResult(
        samples=samples,
        stats=stats,
        step_size=scales,
        inv_mass=jnp.ones_like(init_flat),
    )


def find_map(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    num_steps: int = 500,
    learning_rate: float = 0.05,
    logp_and_grad_fn: Optional[Callable] = None,
) -> Any:
    """Maximum a-posteriori point via Adam — ``pm.find_MAP`` analog
    (reference: demo_model.py:38-39)."""
    import optax

    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)

    if logp_and_grad_fn is not None:
        from jax.flatten_util import ravel_pytree

        def neg_grad(x):
            _, g = logp_and_grad_fn(unravel(x))
            return -ravel_pytree(g)[0]

    else:

        def neg_grad(x):
            return -jax.grad(flat_logp)(x)

    opt = optax.adam(learning_rate)

    @jax.jit
    def run(x0):
        def body(carry, _):
            x, opt_state = carry
            g = neg_grad(x)
            updates, opt_state = opt.update(g, opt_state, x)
            return (optax.apply_updates(x, updates), opt_state), None

        (x, _), _ = jax.lax.scan(
            body, (x0, opt.init(x0)), None, length=num_steps
        )
        return x

    return unravel(run(flat_init))
