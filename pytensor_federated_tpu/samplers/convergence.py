"""Convergence diagnostics: split-R̂, effective sample size, summaries.

The reference delegates posterior-quality checks to arviz (reference:
test_wrapper_ops.py:112-117 asserts a posterior median from an
``arviz.InferenceData``; requirements-dev.txt pulls arviz via pymc).
This framework samples on-device without PyMC, so the standard
diagnostics live here as pure-jnp functions — jit/vmap-friendly, and
they run on the draws wherever they already are (device HBM) instead
of round-tripping through host DataFrames.

Definitions follow Vehtari, Gelman, Simpson, Carpenter, Bürkner (2021)
"Rank-normalization, folding, and localization: An improved R̂":
split-chain R̂ and the Geyer initial-monotone-sequence ESS (the same
estimators Stan and arviz report), with optional rank-normalization
(``rank_normalized=True``: pooled draws are replaced by normal
quantiles of their Blom-adjusted ranks, making the diagnostics robust
to heavy tails and nonlinear transformations — the paper's "bulk"
variants).
Computation promotes to at least float32 but preserves float64 inputs
(the x64 opt-in policy) — diagnostics of large-location/small-scale
parameters would quantize to garbage if downcast.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

__all__ = [
    "split_rhat",
    "effective_sample_size",
    "hdi",
    "summary",
    "tail_ess",
]


def _split_chains(draws: jax.Array) -> jax.Array:
    """(chains, n, ...) -> (2*chains, n//2, ...), dropping an odd tail."""
    c, n = draws.shape[0], draws.shape[1]
    half = n // 2
    first = draws[:, :half]
    second = draws[:, half : 2 * half]
    return jnp.concatenate([first, second], axis=0)


def _compute_dtype(d):
    return jnp.promote_types(d.dtype, jnp.float32)


def _rhat_scalar(draws: jax.Array) -> jax.Array:
    """Split-R̂ for one scalar parameter; ``draws``: (chains, n)."""
    x = _split_chains(draws.astype(_compute_dtype(draws)))
    m, n = x.shape
    chain_means = jnp.mean(x, axis=1)
    w = jnp.mean(jnp.var(x, axis=1, ddof=1))
    b = n * jnp.var(chain_means, ddof=1)
    var_plus = (n - 1) / n * w + b / n
    return jnp.sqrt(var_plus / w)


def _autocov(x: jax.Array) -> jax.Array:
    """Per-chain autocovariance via FFT; ``x``: (chains, n) -> (chains, n)."""
    n = x.shape[1]
    xc = x - jnp.mean(x, axis=1, keepdims=True)
    size = 2 * n  # zero-pad to avoid circular wrap
    f = jnp.fft.rfft(xc, n=size, axis=1)
    acov = jnp.fft.irfft(f * jnp.conj(f), n=size, axis=1)[:, :n]
    return acov / n


def _ess_scalar(draws: jax.Array) -> jax.Array:
    """Geyer initial-monotone-sequence ESS; ``draws``: (chains, n)."""
    x = _split_chains(draws.astype(_compute_dtype(draws)))
    m, n = x.shape
    acov = _autocov(x)
    chain_var = acov[:, 0] * n / (n - 1.0)
    w = jnp.mean(chain_var)
    chain_means = jnp.mean(x, axis=1)
    var_plus = (n - 1) / n * w + jnp.var(chain_means, ddof=1)

    rho = 1.0 - (w - jnp.mean(acov, axis=0)) / var_plus  # (n,)
    # Geyer: sum consecutive-lag pairs while the pair sums stay
    # positive (initial positive sequence), with a running minimum so
    # the used sequence is also non-increasing (initial monotone
    # sequence) — a noisy upward fluctuation in the tail must not
    # inflate tau.
    n_pairs = n // 2
    pair = rho[: 2 * n_pairs].reshape(n_pairs, 2).sum(axis=1)
    positive = jnp.cumprod(pair > 0.0)  # 1 until the first non-positive pair
    pair_mono = jax.lax.cummin(pair)
    # rho_0 = 1 is part of pair[0]; subtract it back out of tau below.
    tau = -1.0 + 2.0 * jnp.sum(pair_mono * positive)
    tau = jnp.maximum(tau, 1.0 / jnp.log10(jnp.asarray(float(m * n))))
    return m * n / tau


def _rank_normalize(x: jax.Array) -> jax.Array:
    """Replace (chains, n) draws by normal quantiles of their pooled
    Blom-adjusted AVERAGE ranks (Vehtari et al. 2021, eq. 14).

    Average ranks (via two searchsorteds) match the paper/Stan/arviz
    tie handling: duplicated draws — routine under Metropolis
    rejections or SMC resampling — get identical z-scores instead of
    chain-ordered distinct ranks that would fabricate between-chain
    variance.  NaN draws stay NaN so a diverged chain still alarms
    instead of being laundered into large finite z-scores.
    """
    c, n = x.shape
    flat = x.reshape(-1)
    s = jnp.sort(flat)
    lo = jnp.searchsorted(s, flat, side="left")
    hi = jnp.searchsorted(s, flat, side="right")
    ranks = 0.5 * (lo + hi + 1).astype(x.dtype)  # 1-based average rank
    z = jax.scipy.special.ndtri((ranks - 0.375) / (flat.size + 0.25))
    z = jnp.where(jnp.isnan(flat), jnp.nan, z)
    return z.reshape(c, n)


def _rank_normalize_tree(samples: Any) -> Any:
    """Rank-normalize every scalar component of every leaf once."""

    def leaf(d):
        d = jnp.asarray(d)
        c, n = d.shape[0], d.shape[1]
        flat = d.reshape(c, n, -1).astype(_compute_dtype(d))
        z = jax.vmap(_rank_normalize, in_axes=2, out_axes=2)(flat)
        return z.reshape((c, n) + d.shape[2:])

    return jax.tree_util.tree_map(leaf, samples)


def _per_param(fn, samples: Any, *, rank_normalized: bool = False) -> Any:
    """Apply a (chains, n)->scalar diagnostic over every scalar component
    of every leaf; leaves have shape (chains, draws, *event)."""

    def scalar_fn(d2):
        if rank_normalized:
            d2 = _rank_normalize(d2.astype(_compute_dtype(d2)))
        return fn(d2)

    def leaf(d):
        d = jnp.asarray(d)
        c, n = d.shape[0], d.shape[1]
        flat = d.reshape(c, n, -1)
        out = jax.vmap(scalar_fn, in_axes=2)(flat)  # (prod(event),)
        return out.reshape(d.shape[2:]) if d.ndim > 2 else out.reshape(())

    return jax.tree_util.tree_map(leaf, samples)


def split_rhat(samples: Any, *, rank_normalized: bool = False) -> Any:
    """Split-chain potential-scale-reduction R̂ per scalar component.

    ``samples``: pytree of arrays shaped (chains, draws, *event) — e.g.
    ``SampleResult.samples``.  Values near 1 (< 1.01) indicate the
    chains agree; mixing failures show up as R̂ >> 1.
    ``rank_normalized=True`` gives the 2021 bulk-R̂ (robust to heavy
    tails/infinite variance).
    """
    return _per_param(_rhat_scalar, samples, rank_normalized=rank_normalized)


def effective_sample_size(
    samples: Any, *, rank_normalized: bool = False
) -> Any:
    """Bulk effective sample size per scalar component (Geyer/Stan
    estimator on split chains); ``rank_normalized=True`` gives the
    2021 bulk-ESS."""
    return _per_param(_ess_scalar, samples, rank_normalized=rank_normalized)


def _tail_ess_scalar(draws: jax.Array) -> jax.Array:
    x = draws.astype(_compute_dtype(draws))
    q05 = jnp.nanquantile(x, 0.05)
    q95 = jnp.nanquantile(x, 0.95)
    e05 = _ess_scalar((x <= q05).astype(x.dtype))
    e95 = _ess_scalar((x <= q95).astype(x.dtype))
    # (nan <= q) is False, which would launder diverged draws into
    # healthy-looking indicator chains — propagate the alarm instead
    # (the module-wide NaN policy, see _rank_normalize).
    return jnp.where(
        jnp.any(jnp.isnan(x)), jnp.nan, jnp.minimum(e05, e95)
    )


def tail_ess(samples: Any) -> Any:
    """Tail effective sample size (Vehtari et al. 2021): the minimum
    ESS of the 5% / 95% quantile-exceedance indicators — how reliably
    the chain resolves its own tails.  A chain can have healthy bulk
    ESS while its intervals are garbage; this is the diagnostic that
    notices (arviz's ``ess_tail``)."""
    return _per_param(_tail_ess_scalar, samples)


def hdi(samples: Any, prob: float = 0.94) -> Any:
    """Highest-density interval per scalar component.

    Returns a pytree matching ``samples`` (minus chain/draw axes) with
    a trailing axis of 2: ``[lower, upper]``.  Computed the standard
    way (arviz's default): the narrowest window containing ``prob`` of
    the pooled sorted draws — exact for unimodal posteriors.
    """
    if not 0.0 < prob < 1.0:
        raise ValueError(f"prob must be in (0, 1), got {prob}")

    def leaf(d):
        flat = d.reshape((-1,) + d.shape[2:])
        s = jnp.sort(flat, axis=0)
        n = s.shape[0]
        k = max(int(jnp.floor(prob * n)), 1)
        widths = s[k:] - s[: n - k]
        i = jnp.argmin(widths, axis=0)
        lower = jnp.take_along_axis(s, i[None], axis=0)[0]
        upper = jnp.take_along_axis(s, (i + k)[None], axis=0)[0]
        return jnp.stack([lower, upper], axis=-1)

    return jax.tree_util.tree_map(leaf, samples)


def summary(
    samples: Any,
    *,
    hdi_prob: float = 0.94,
    rank_normalized: bool = False,
) -> Dict[str, Any]:
    """Posterior summary: mean, sd, HDI, split-R̂, ESS per component.

    The on-device counterpart of the ``arviz.summary`` table the
    reference's workflow ends with (same default 94% HDI);
    ``rank_normalized=True`` switches R̂/ESS to the 2021 bulk variants
    arviz reports by default.
    """
    mean = jax.tree_util.tree_map(lambda d: jnp.mean(d, axis=(0, 1)), samples)
    sd = jax.tree_util.tree_map(lambda d: jnp.std(d, axis=(0, 1)), samples)
    # Rank-normalize ONCE and feed the plain estimators — calling each
    # with rank_normalized=True would redo the sort per diagnostic.
    diag_samples = (
        _rank_normalize_tree(samples) if rank_normalized else samples
    )
    return {
        "mean": mean,
        "sd": sd,
        "hdi": hdi(samples, hdi_prob),
        "rhat": split_rhat(diag_samples),
        "ess": effective_sample_size(diag_samples),
        "ess_tail": tail_ess(samples),
    }
