"""Pathfinder variational inference (Zhang, Carpenter et al., JMLR 2022).

Follow an optimization path toward the posterior mode, fit a local
Gaussian at every iterate from the accumulated curvature, score each by
its Monte-Carlo ELBO, and return draws from the best one.  Compared to
NUTS this costs an optimization run instead of a chain; compared to the
Laplace approximation (:mod:`.laplace`) it does not need the mode —
early path points often beat the mode's Gaussian on skewed targets, and
a non-PD Hessian at a saddle is never an issue.

TPU-first shape: the whole path — optimizer scan, BFGS curvature
accumulation, per-iterate Gaussian fits, the (L x K) ELBO draw matrix —
is one jitted program of scans and vmaps; multi-path is a further vmap
over seeds.  The inverse-Hessian estimate is maintained *densely* (the
windowed BFGS recurrence), which is exact for the curvature pairs and
ideal for the moderate-dimension parameter spaces of this framework's
model families (the paper's low-rank form matters only at dims >> 10³).

Positive-definiteness: a BFGS update preserves PD iff the curvature
condition ``s·y > 0`` holds; updates violating it are skipped, so every
per-iterate covariance is PD by construction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

from .util import flatten_logp


@dataclasses.dataclass
class PathfinderResult:
    """Draws from the ELBO-best Gaussian along the path(s)."""

    samples: Any  # pytree, leading axis num_draws
    elbo: jax.Array  # scalar, ELBO of the selected approximation
    best_iter: jax.Array  # iterate index of the selected point (its path)
    best_path: jax.Array  # path index (always 0 for single-path)
    mean_flat: jax.Array
    cov_flat: jax.Array
    unravel: Callable[[jax.Array], Any]


def _gaussian_logq(z, mu, chol):
    """log N(z; mu, chol chol') for a batch of z rows."""
    d = mu.shape[-1]
    sol = jax.scipy.linalg.solve_triangular(chol, (z - mu).T, lower=True).T
    return (
        -0.5 * jnp.sum(sol**2, axis=-1)
        - jnp.sum(jnp.log(jnp.diagonal(chol)))
        - 0.5 * d * jnp.log(2.0 * jnp.pi)
    )


def _fit_path(flat_logp, flat_init, eps_common, *, num_steps, jitter):
    """One optimization path -> per-iterate (elbo, mu, cov, has_curv).

    Pure array-in/array-out (no Python control flow on values), so it
    vmaps cleanly over paths.  ``eps_common`` is the shared CRN draw
    matrix used to score every candidate.
    """
    dim = flat_init.shape[0]

    import optax

    # L-BFGS with line search drives the path (as in the paper): its
    # steps span the curvature directions, which is what makes the
    # windowed BFGS fits below accurate.  (A first-order optimizer like
    # Adam oscillates along the dominant eigendirection near the
    # optimum, leaving the window's pairs nearly collinear.)
    def neg_logp(x):
        return -flat_logp(x)

    opt = optax.lbfgs(learning_rate=None)
    vg = optax.value_and_grad_from_state(neg_logp)

    def opt_step(carry, _):
        x, opt_state = carry
        value, grad = vg(x, state=opt_state)
        updates, opt_state = opt.update(
            grad, opt_state, x, value=value, grad=grad, value_fn=neg_logp
        )
        x_new = optax.apply_updates(x, updates)
        # Emit the (pre-step) gradient too: the scan already paid for
        # it, and re-differentiating the whole path would double the
        # number of logp gradient evaluations.
        return (x_new, opt_state), (x_new, -grad)

    (x_last, _), (path, g_path) = jax.lax.scan(
        opt_step, (flat_init, opt.init(flat_init)), None, length=num_steps
    )
    xs = jnp.concatenate([flat_init[None], path], axis=0)
    g_last = jax.grad(flat_logp)(x_last)
    gs = jnp.concatenate([g_path, g_last[None]], axis=0)

    # Inverse-Hessian estimate at each iterate, rebuilt from the J most
    # recent curvature pairs (the paper's windowed form): stale early-
    # path curvature would otherwise pollute late-path fits.  The init
    # scale gamma = s.y / y.y of the newest valid pair is the standard
    # Nocedal-Wright H0; zero-padded (pre-path) pairs are skipped by
    # the curvature condition automatically.
    J = 20
    s_pairs = xs[1:] - xs[:-1]
    y_pairs = gs[:-1] - gs[1:]
    pad = jnp.zeros((J - 1, dim), flat_init.dtype)
    s_padded = jnp.concatenate([pad, s_pairs], axis=0)
    y_padded = jnp.concatenate([pad, y_pairs], axis=0)
    eye_d = jnp.eye(dim, dtype=flat_init.dtype)

    def _curvature_ok(s, y):
        # RELATIVE curvature condition: an absolute threshold would
        # reject the tiny (but perfectly informative) steps of a
        # converged optimizer and silently leave H at its identity
        # init — whose too-wide q then wins the argmax on ELBO noise.
        sty = s @ y
        scale = jnp.linalg.norm(s) * jnp.linalg.norm(y)
        return sty > 1e-4 * scale

    def bfgs_update(H, s, y):
        ok = _curvature_ok(s, y)
        sty = s @ y
        rho = 1.0 / jnp.where(ok, sty, 1.0)
        V = eye_d - rho * jnp.outer(s, y)
        H_new = V @ H @ V.T + rho * jnp.outer(s, s)
        return jnp.where(ok, H_new, H)

    def inv_hessian_at(l):
        sw = jax.lax.dynamic_slice_in_dim(s_padded, l, J, axis=0)
        yw = jax.lax.dynamic_slice_in_dim(y_padded, l, J, axis=0)
        valid = jax.vmap(_curvature_ok)(sw, yw)
        stys = jnp.sum(sw * yw, axis=1)
        ytys = jnp.sum(yw * yw, axis=1)
        gammas = jnp.where(valid, stys / jnp.where(valid, ytys, 1.0), 1.0)
        has_valid = jnp.any(valid)
        # Newest valid pair's gamma; 1.0 when none valid.
        newest = jnp.where(
            has_valid,
            gammas[jnp.argmax(jnp.where(valid, jnp.arange(J), -1))],
            1.0,
        )
        H = newest * eye_d

        def body(j, H):
            return bfgs_update(H, sw[j], yw[j])

        return jax.lax.fori_loop(0, J, body, H), has_valid

    Hs, has_curv = jax.vmap(inv_hessian_at)(jnp.arange(num_steps))

    def fit_one(x, g, H):
        cov = H + jitter * eye_d
        chol = jnp.linalg.cholesky(cov)
        mu = x + H @ g  # Newton correction toward the local maximum
        z = mu + eps_common @ chol.T
        logq = _gaussian_logq(z, mu, chol)
        logp = jax.vmap(flat_logp)(z)
        elbo = jnp.mean(logp - logq)
        # A NaN ELBO (divergent path point) must never win the argmax.
        return jnp.where(jnp.isfinite(elbo), elbo, -jnp.inf), mu, cov

    elbos, mus, covs = jax.vmap(fit_one)(xs[1:], gs[1:], Hs)
    # Iterates with no curvature information fit q = N(., gamma I) —
    # not a real approximation; never let one win the selection.
    elbos = jnp.where(has_curv, elbos, -jnp.inf)
    return elbos, mus, covs, has_curv


def _draw(mu, cov, unravel, key, num_draws):
    chol = jnp.linalg.cholesky(cov)
    eps = jax.random.normal(key, (num_draws,) + mu.shape, mu.dtype)
    return jax.vmap(unravel)(mu + eps @ chol.T)


def pathfinder(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    key: jax.Array,
    *,
    num_steps: int = 200,
    num_elbo_draws: int = 16,
    num_draws: int = 1000,
    jitter: float = 1e-6,
) -> PathfinderResult:
    """Single-path Pathfinder from ``init_params``.

    Returns draws from the Gaussian ``N(x_l + H_l g_l, H_l)`` (the
    Newton-corrected fit from the windowed-BFGS inverse-Hessian
    ``H_l``) at the path point ``l`` with the highest Monte-Carlo ELBO
    (common random numbers across candidates).  Raises ``ValueError``
    when the path produced no curvature information at all (e.g.
    started exactly at a stationary point) — there is no Gaussian fit
    to return in that case.
    """
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    k_elbo, k_draw = jax.random.split(key)
    eps_common = jax.random.normal(
        k_elbo, (num_elbo_draws, flat_init.shape[0]), flat_init.dtype
    )
    elbos, mus, covs, has_curv = _fit_path(
        flat_logp, flat_init, eps_common, num_steps=num_steps, jitter=jitter
    )
    if not bool(jnp.any(has_curv)):
        raise ValueError(
            "no path point produced valid curvature (did the path start "
            "at a stationary point?); cannot fit a Gaussian — use "
            "laplace_approximation from a mode instead"
        )
    best = jnp.argmax(elbos)
    mu_b, cov_b = mus[best], covs[best]
    return PathfinderResult(
        samples=_draw(mu_b, cov_b, unravel, k_draw, num_draws),
        elbo=elbos[best],
        best_iter=best,
        best_path=jnp.asarray(0),
        mean_flat=mu_b,
        cov_flat=cov_b,
        unravel=unravel,
    )


def multipath_pathfinder(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    key: jax.Array,
    *,
    num_paths: int = 4,
    init_jitter: float = 1.0,
    num_steps: int = 200,
    num_elbo_draws: int = 16,
    num_draws: int = 1000,
    jitter: float = 1e-6,
) -> PathfinderResult:
    """Multi-path Pathfinder: ``num_paths`` vmapped paths from jittered
    inits; the winner is the highest-ELBO point across ALL paths' path
    points, scored with the same CRN draws so the cross-path argmax
    compares fits rather than Monte-Carlo luck.  (The paper's
    importance resampling across paths needs PSIS; max-ELBO selection
    is the standard dependency-free variant.)
    """
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    k_init, k_elbo, k_draw = jax.random.split(key, 3)
    inits = flat_init + init_jitter * jax.random.normal(
        k_init, (num_paths,) + flat_init.shape, flat_init.dtype
    )
    # One shared CRN matrix for every candidate of every path.
    eps_common = jax.random.normal(
        k_elbo, (num_elbo_draws, flat_init.shape[0]), flat_init.dtype
    )
    elbos, mus, covs, has_curv = jax.vmap(
        lambda x0: _fit_path(
            flat_logp, x0, eps_common, num_steps=num_steps, jitter=jitter
        )
    )(inits)
    if not bool(jnp.any(has_curv)):
        raise ValueError(
            "no path of any seed produced valid curvature; cannot fit "
            "a Gaussian approximation"
        )
    flat_idx = jnp.argmax(elbos.reshape(-1))
    best_path, best_iter = jnp.unravel_index(flat_idx, elbos.shape)
    mu_b, cov_b = mus[best_path, best_iter], covs[best_path, best_iter]
    return PathfinderResult(
        samples=_draw(mu_b, cov_b, unravel, k_draw, num_draws),
        elbo=elbos[best_path, best_iter],
        best_iter=best_iter,
        best_path=best_path,
        mean_flat=mu_b,
        cov_flat=cov_b,
        unravel=unravel,
    )
