"""On-device samplers (replaces the reference's PyMC driver dependency)."""

from .advi import ADVIResult, FullRankADVIResult, advi_fit, fullrank_advi_fit
from .flows import FlowADVIResult, realnvp_advi_fit
from .sbc import SBCResult, sbc_ranks, sbc_uniformity
from .convergence import (
    effective_sample_size,
    hdi,
    split_rhat,
    summary,
    tail_ess,
)
from .arviz_export import to_dataset_dict, to_inference_data
from .chees import chees_sample
from .elastic import elastic_sample
from .tempering import pt_sample
from .model_comparison import (
    compare,
    pointwise_loglik_matrix,
    psis_loo,
    waic,
)
from .predictive import posterior_predictive, prior_predictive
from .ensemble import EnsembleResult, ensemble_sample
from .laplace import LaplaceResult, laplace_approximation
from .pathfinder import PathfinderResult, multipath_pathfinder, pathfinder
from .sgld import (
    SGLDResult,
    polynomial_decay,
    psgld_sample,
    sghmc_sample,
    sgld_sample,
)
from .hmc import HMCState, find_reasonable_step_size, hmc_init, hmc_step, leapfrog
from .mcmc import SampleResult, find_map, sample
from .metropolis import metropolis_init, metropolis_step
from .nuts import NUTSInfo, nuts_step
from .smc import SMCResult, smc_sample
from .util import AdaptSchedule, flatten_logp

__all__ = [
    "ADVIResult",
    "AdaptSchedule",
    "EnsembleResult",
    "LaplaceResult",
    "PathfinderResult",
    "SGLDResult",
    "SMCResult",
    "advi_fit",
    "fullrank_advi_fit",
    "FullRankADVIResult",
    "FlowADVIResult",
    "realnvp_advi_fit",
    "SBCResult",
    "sbc_ranks",
    "sbc_uniformity",
    "ensemble_sample",
    "smc_sample",
    "HMCState",
    "NUTSInfo",
    "SampleResult",
    "effective_sample_size",
    "find_map",
    "find_reasonable_step_size",
    "laplace_approximation",
    "multipath_pathfinder",
    "pathfinder",
    "polynomial_decay",
    "psgld_sample",
    "sghmc_sample",
    "sgld_sample",
    "flatten_logp",
    "split_rhat",
    "hdi",
    "summary",
    "tail_ess",
    "hmc_init",
    "hmc_step",
    "leapfrog",
    "metropolis_init",
    "metropolis_step",
    "nuts_step",
    "chees_sample",
    "elastic_sample",
    "pt_sample",
    "compare",
    "to_dataset_dict",
    "to_inference_data",
    "pointwise_loglik_matrix",
    "posterior_predictive",
    "psis_loo",
    "waic",
    "prior_predictive",
    "sample",
]
