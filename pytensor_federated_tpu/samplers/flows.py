"""Normalizing-flow variational inference (RealNVP couplings).

The top rung of the VI ladder (mean-field → full-rank → flow): a
RealNVP flow pushes ``N(0, I)`` through alternating affine coupling
layers, so ``q`` can fit curved, non-Gaussian posteriors (bananas,
funnels) that no Gaussian family can.  Pure JAX — the coupling nets
are two-layer tanh MLPs stored as plain pytrees, optimized by optax
exactly like :mod:`.advi`; the whole fit is one ``lax.scan`` under
jit, and a flow draw is a stack of small matmuls (MXU work).

ELBO with the reparameterization trick through the flow::

    x = f(z),  z ~ N(0, I)
    ELBO = E_z[ logp(x) + logdet Jf(z) ] + H[N(0, I)]

(the base entropy is closed-form; the log-determinant of an affine
coupling is the sum of its scale outputs).

Dimension-1 targets have nothing to couple; ``realnvp_advi_fit``
requires ``d >= 2`` and points dim-1 users at :func:`.advi.advi_fit`.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

from ..ppl.elbo import gaussian_entropy, scan_vi
from ..utils import LOG_2PI
from .util import flatten_logp

try:
    import optax

    _HAS_OPTAX = True
except ModuleNotFoundError:  # pragma: no cover
    _HAS_OPTAX = False

__all__ = ["FlowADVIResult", "realnvp_advi_fit"]


def _mlp_init(key, in_dim, hidden, out_dim, dtype):
    k1, _ = jax.random.split(key)
    s1 = 1.0 / jnp.sqrt(in_dim)
    return {
        "w1": s1 * jax.random.normal(k1, (in_dim, hidden), dtype),
        "b1": jnp.zeros((hidden,), dtype),
        # zero-init output layer: the flow starts as the identity,
        # which keeps early ELBO gradients sane (standard RealNVP
        # practice).
        "w2": jnp.zeros((hidden, 2 * out_dim), dtype),
        "b2": jnp.zeros((2 * out_dim,), dtype),
    }


def _mlp_apply(p, x):
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    return h @ p["w2"] + p["b2"]


def _coupling_forward(p, x, mask):
    """One affine coupling: the masked half parameterizes an affine
    map of the complement.  Returns ``(y, logdet)``."""
    xm = x * mask
    st = _mlp_apply(p, xm)
    d = x.shape[-1]
    s, t = st[..., :d], st[..., d:]
    # soft-clamp the log-scale so one bad step cannot explode the flow
    s = jnp.tanh(s) * 2.0
    free = 1.0 - mask
    y = xm + free * (x * jnp.exp(s) + t)
    logdet = jnp.sum(free * s, axis=-1)
    return y, logdet


class FlowADVIResult(NamedTuple):
    flow_params: Any  # list of coupling-net pytrees
    masks: jax.Array  # (num_layers, d) binary masks
    shift: jax.Array  # (d,) base-distribution shift (the init point)
    elbo_trace: jax.Array  # (num_steps,)
    dim: int

    def _forward(self, z):
        """The SAME map the ELBO optimized: shifted base through the
        coupling stack.  The shift is volume-preserving (logdet 0)."""
        logdet = jnp.zeros(z.shape[:-1], z.dtype)
        x = z + self.shift
        for p, mask in zip(self.flow_params, self.masks):
            x, ld = _coupling_forward(p, x, mask)
            logdet = logdet + ld
        return x, logdet

    def sample(self, key: jax.Array, n: int, unravel) -> Any:
        z = jax.random.normal(key, (n, self.dim))
        x, _ = self._forward(z)
        return jax.vmap(unravel)(x)

    def sample_with_logq(self, key: jax.Array, n: int):
        """Flat draws and their variational log-density (for
        importance reweighting / PSIS diagnostics)."""
        z = jax.random.normal(key, (n, self.dim))
        x, logdet = self._forward(z)
        log_base = -0.5 * jnp.sum(z**2, axis=-1) - 0.5 * self.dim * LOG_2PI
        return x, log_base - logdet


def realnvp_advi_fit(
    logp_fn: Callable[[Any], jax.Array],
    init_params: Any,
    *,
    key: jax.Array,
    num_layers: int = 6,
    hidden: int = 32,
    num_steps: int = 3000,
    n_mc: int = 16,
    learning_rate: float = 3e-3,
) -> tuple[FlowADVIResult, Callable]:
    """Fit a RealNVP flow posterior to ``logp_fn``.

    Same contract as :func:`.advi.advi_fit`: returns ``(result,
    unravel)``; ``result.sample(key, n, unravel)`` draws in the user's
    pytree structure.
    """
    if not _HAS_OPTAX:
        raise ModuleNotFoundError("realnvp_advi_fit requires optax")
    flat_logp, flat_init, unravel = flatten_logp(logp_fn, init_params)
    dim = flat_init.shape[0]
    if dim < 2:
        raise ValueError(
            "RealNVP couplings need d >= 2; use advi_fit for scalars"
        )
    dtype = flat_init.dtype
    batch_logp = jax.vmap(flat_logp)

    # alternating even/odd masks
    base_mask = (jnp.arange(dim) % 2).astype(dtype)
    masks = jnp.stack(
        [base_mask if i % 2 == 0 else 1.0 - base_mask
         for i in range(num_layers)]
    )

    k_init, k_fit = jax.random.split(key)
    flow0 = [
        _mlp_init(k, dim, hidden, dim, dtype)
        for k in jax.random.split(k_init, num_layers)
    ]

    opt = optax.adam(learning_rate)
    # base-distribution entropy: the shared ppl.elbo Gaussian kernel
    # with log_sd_sum = 0 (standard normal base).
    base_entropy = gaussian_entropy(dim)

    def neg_elbo(flow, key):
        z = jax.random.normal(key, (n_mc, dim), dtype)
        # shift the base by the MAP-ish init so the identity-init flow
        # starts centered where the user's init_params point
        x = z + flat_init[None, :]
        logdet = jnp.zeros((n_mc,), dtype)
        for p, mask in zip(flow, masks):
            x, ld = _coupling_forward(p, x, mask)
            logdet = logdet + ld
        elbo = jnp.mean(batch_logp(x) + logdet) + base_entropy
        return -elbo

    flow, elbos = scan_vi(
        neg_elbo, flow0, key=k_fit, num_steps=num_steps, optimizer=opt
    )
    result = FlowADVIResult(
        flow_params=flow,
        masks=masks,
        shift=flat_init,
        elbo_trace=elbos,
        dim=dim,
    )
    return result, unravel
