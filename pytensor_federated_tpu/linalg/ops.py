"""Blocked linear-algebra drivers (ISSUE 19).

Two families, per the paper's "N^2 data, N workers" spine (*Large Scale
Distributed Linear Algebra With TPUs*, PAPERS.md):

- **fed-program ops** — :func:`matmul` (SUMMA-style k-panel GEMM:
  partial products per shard, ``fed_sum`` reduction),
  :func:`block_quadratic_form` (block-row reduce through
  :func:`...fed.lowering.canonical_round` — scalar contract, so a
  ``PoolPlacement(reduce=True)`` lowers it to ONE PR-13 reduce
  window), and the per-step row-update round inside
  :func:`triangular_solve`.  These lower to devices, tcp/shm/ring
  pools, or aggregator trees unchanged, like every other fed program.
- **block-store ops** — :class:`BlockedCholesky` /(:func:`cholesky`)
  and :class:`BlockedMatmul` drive the stateful store compute
  (:mod:`.service`): tiles ship once (pinning in the PR-9 arena on
  shm/ring), each factorization step moves only the panel, and a
  replica failure is recovered by restoring THAT replica's trailing
  tiles — never by re-shipping the matrix, and never by silently
  continuing with a stale store (the node refuses mismatched steps
  loudly).

Accuracy rides :mod:`...precision`'s f32-strict policy: every
contraction routes through ``pdot``/``dot_kernel`` so the bf16x3
split applies on chip where a plain f32 ``@`` is bf16-accurate.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..fed.lowering import canonical_round, program
from ..fed.primitives import fed_broadcast, fed_map, fed_sum
from ..precision import pdot, resolve_policy
from ..telemetry import flightrec as _flightrec
from .blocks import (
    OPCODES,
    BlockError,
    BlockLayout,
    encode_op_header,
)
from .service import LocalBlockClient, dot_kernel, is_restore_needed

__all__ = [
    "matmul",
    "matmul_per_shard",
    "block_quadratic_form",
    "quadratic_per_shard",
    "triangular_solve",
    "triangular_update_per_shard",
    "cholesky",
    "BlockedCholesky",
    "BlockedMatmul",
]

#: Transport failures the Cholesky driver treats as a dead/restartable
#: replica (restore-then-retry).  Deterministic failures — in-band
#: ``RemoteComputeError`` (RuntimeError), ``WireError``/``BlockError``
#: (ValueError) — propagate: retrying them would re-run the same wrong
#: request, and a silently absorbed geometry error is exactly the
#: corruption the loud-failure contract forbids.
_TRANSIENT = (ConnectionError, TimeoutError, OSError)


# ---------------------------------------------------------------------------
# fed-program ops
# ---------------------------------------------------------------------------


def matmul_per_shard(policy: Optional[str] = None) -> Callable:
    """The per-shard SUMMA term ``(a_k, b_k) -> a_k @ b_k`` — exposed
    so pool nodes deploy the SAME callable the driver's ``fed_map``
    maps (``fed.placements.make_node_compute(matmul_per_shard(...),
    grads=False)``), the no-drift convention every fed lane follows."""

    def per_shard(a_k: Any, b_k: Any) -> Any:
        return pdot(a_k, b_k, policy)

    return per_shard


def _k_panels(
    a: np.ndarray, b: np.ndarray, n_shards: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Split the contraction axis into ``n_shards`` equal panels,
    zero-padding the tail panel (zero columns of ``a`` against zero
    rows of ``b`` contribute exactly zero to every partial product)."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise BlockError(
            f"matmul shapes do not contract: {a.shape} @ {b.shape}"
        )
    s = int(n_shards)
    if s < 1:
        raise BlockError(f"n_shards must be >= 1, got {n_shards!r}")
    k = a.shape[1]
    s = min(s, k)
    kb = -(-k // s)
    pad = s * kb - k
    if pad:
        a = np.concatenate([a, np.zeros((a.shape[0], pad), a.dtype)], axis=1)
        b = np.concatenate([b, np.zeros((pad, b.shape[1]), b.dtype)], axis=0)
    ap = np.ascontiguousarray(
        a.reshape(a.shape[0], s, kb).transpose(1, 0, 2)
    )
    bp = np.ascontiguousarray(b.reshape(s, kb, b.shape[1]))
    return ap, bp


def matmul(
    a: Any,
    b: Any,
    *,
    n_shards: int,
    placement: Any = None,
    policy: Optional[str] = None,
) -> Any:
    """Blocked GEMM ``a @ b`` as a fed program: the contraction axis
    splits into ``n_shards`` k-panels, each shard contributes one
    partial product, and ``fed_sum`` reduces them — SUMMA's
    broadcast-multiply-reduce round on the repo's federated algebra.

    ``placement=None`` runs eagerly in-process; a ``MeshPlacement``
    shards over devices; a ``PoolPlacement`` ships each panel pair as
    one request to nodes deployed with :func:`matmul_per_shard`.
    Computes in JAX's default float width — for float64 or pinned
    steady-state iteration use :class:`BlockedMatmul`.
    """
    resolve_policy(policy)
    ap, bp = _k_panels(a, b, n_shards)
    per_shard = matmul_per_shard(policy)

    def model(sa: Any, sb: Any) -> Any:
        parts = fed_map(lambda sh: per_shard(sh[0], sh[1]), (sa, sb))
        return fed_sum(parts)

    return program(model, placement)(ap, bp)


def quadratic_per_shard(policy: Optional[str] = None) -> Callable:
    """Per-shard block-row term of ``x^T A x``:
    ``(x, (panel, x_rows)) -> x_rows @ (panel @ x)`` — one scalar per
    shard, the logp-style contract that keeps the reduce-window
    lowering eligible."""

    def per_shard(x: Any, shard_data: Any) -> Any:
        panel, x_rows = shard_data
        return pdot(x_rows, pdot(panel, x, policy), policy)

    return per_shard


def block_quadratic_form(
    a: Any,
    x: Any,
    *,
    n_shards: int,
    placement: Any = None,
    policy: Optional[str] = None,
) -> Any:
    """``x^T A x`` with ``A`` sharded by block-rows, through the
    canonical broadcast->map->sum round (:func:`canonical_round`).

    The per-shard term is scalar and every inexact mapped operand is
    either broadcast-derived (``x``) or trace-time-baked (the row
    panels), so under ``PoolPlacement(reduce=True)`` the whole round
    lowers to ONE PR-13 reduce window — reply bytes scale with pool
    width, not shard count.  Registered as the ``linalg-block-row-
    reduce`` fixture in ``fed/lint_fixtures.py`` so graftlint's
    fed-placement rule covers this lowering.
    """
    resolve_policy(policy)
    a = np.asarray(a)
    x = np.asarray(x)
    if a.ndim != 2 or x.ndim != 1 or a.shape[1] != x.shape[0]:
        raise BlockError(
            f"quadratic form shapes do not contract: {a.shape} with {x.shape}"
        )
    s = min(int(n_shards), a.shape[0])
    if s < 1:
        raise BlockError(f"n_shards must be >= 1, got {n_shards!r}")
    rb = -(-a.shape[0] // s)
    pad = s * rb - a.shape[0]
    rows = np.concatenate([a, np.zeros((pad, a.shape[1]), a.dtype)], axis=0)
    # x padded along ROWS pairs with the zero panels: zero contribution.
    xr = np.concatenate([x, np.zeros(pad, x.dtype)])
    panels = np.ascontiguousarray(rows.reshape(s, rb, a.shape[1]))
    x_rows = np.ascontiguousarray(xr.reshape(s, rb))
    model = canonical_round(
        quadratic_per_shard(policy), (panels, x_rows), s
    )
    return program(model, placement)(x)


def triangular_update_per_shard(policy: Optional[str] = None) -> Callable:
    """Per-shard term of the triangular solve's trailing row update:
    ``(x_j, (l_rows, b_rows)) -> b_rows - l_rows @ x_j``.  Exposed so
    pool nodes deploy the same callable the driver maps."""

    def per_shard(x_j: Any, l_rows: Any, b_rows: Any) -> Any:
        return b_rows - pdot(l_rows, x_j, policy)

    return per_shard


def _fwd_solve(
    l_jj: np.ndarray, rhs: np.ndarray, policy: Optional[str]
) -> np.ndarray:
    """``x = inv(L_jj) @ rhs`` for one lower-triangular diagonal tile."""
    if l_jj.dtype == np.float64:
        return np.linalg.solve(l_jj, rhs)
    import jax.numpy as jnp
    from jax.scipy.linalg import solve_triangular

    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(policy):
        x = solve_triangular(jnp.asarray(l_jj), jnp.asarray(rhs), lower=True)
    return np.asarray(x, dtype=rhs.dtype)


def _bwd_solve(
    l_jj: np.ndarray, rhs: np.ndarray, policy: Optional[str]
) -> np.ndarray:
    """``x = inv(L_jj^T) @ rhs`` (the transposed/backward tile solve)."""
    if l_jj.dtype == np.float64:
        return np.linalg.solve(l_jj.T, rhs)
    import jax.numpy as jnp
    from jax.scipy.linalg import solve_triangular

    from ..precision import matmul_precision_ctx

    with matmul_precision_ctx(policy):
        x = solve_triangular(
            jnp.asarray(l_jj), jnp.asarray(rhs), lower=True, trans=1
        )
    return np.asarray(x, dtype=rhs.dtype)


def triangular_solve(
    l: Any,
    b: Any,
    *,
    block: int = 64,
    policy: Optional[str] = None,
    placement: Any = None,
    n_shards: Optional[int] = None,
    trans: bool = False,
) -> np.ndarray:
    """Blocked triangular solve ``L x = b`` (``trans=True`` solves
    ``L^T x = b``) for lower-triangular ``L`` — forward (or backward)
    substitution over the tile grid.

    The sequential spine is the per-step diagonal solve; the
    parallelizable bulk is each step's trailing row update
    ``b_rest -= L_panel @ x_j``, which runs as a fed round
    (broadcast ``x_j``, map over row shards) when ``placement`` and
    ``n_shards`` are given, and as a host contraction otherwise.
    """
    resolve_policy(policy)
    l = np.asarray(l)
    b = np.asarray(b)
    if l.ndim != 2 or l.shape[0] != l.shape[1]:
        raise BlockError(f"L must be square, got {l.shape}")
    vec = b.ndim == 1
    rhs = b.reshape(-1, 1) if vec else b
    if rhs.shape[0] != l.shape[0]:
        raise BlockError(
            f"rhs has {rhs.shape[0]} rows, L is {l.shape[0]}x{l.shape[1]}"
        )
    n = l.shape[0]
    bb = min(int(block), n)
    nb = -(-n // bb)
    x = rhs.astype(np.result_type(l, rhs)).copy()
    steps = range(nb) if not trans else range(nb - 1, -1, -1)
    for j in steps:
        j0 = j * bb
        j1 = min(n, j0 + bb)
        if not trans:
            x[j0:j1] = _fwd_solve(l[j0:j1, j0:j1], x[j0:j1], policy)
            if j1 < n:
                x[j1:] = _row_update(
                    l[j1:, j0:j1], x[j0:j1], x[j1:],
                    placement, n_shards, policy,
                )
        else:
            x[j0:j1] = _bwd_solve(l[j0:j1, j0:j1], x[j0:j1], policy)
            if j0 > 0:
                x[:j0] = _row_update(
                    np.ascontiguousarray(l[j0:j1, :j0].T),
                    x[j0:j1], x[:j0], placement, n_shards, policy,
                )
    return x[:, 0] if vec else x


def _row_update(
    l_panel: np.ndarray,
    x_j: np.ndarray,
    b_rest: np.ndarray,
    placement: Any,
    n_shards: Optional[int],
    policy: Optional[str],
) -> np.ndarray:
    """``b_rest - l_panel @ x_j``, as a fed row-shard round when a
    placement is given (zero-padded tail shard: zero panel rows update
    zero rhs rows — exact), else one host contraction."""
    if placement is None or not n_shards or b_rest.shape[0] < 2:
        return b_rest - dot_kernel(l_panel, x_j, policy).astype(b_rest.dtype)
    s = min(int(n_shards), b_rest.shape[0])
    r = b_rest.shape[0]
    rb = -(-r // s)
    pad = s * rb - r
    lp = np.concatenate(
        [l_panel, np.zeros((pad,) + l_panel.shape[1:], l_panel.dtype)]
    ).reshape(s, rb, l_panel.shape[1])
    bp = np.concatenate(
        [b_rest, np.zeros((pad,) + b_rest.shape[1:], b_rest.dtype)]
    ).reshape(s, rb, b_rest.shape[1])

    per_shard = triangular_update_per_shard(policy)

    def model(xj: Any, slp: Any, sbp: Any) -> Any:
        pb = fed_broadcast((xj,), s)
        return fed_map(
            lambda sh: per_shard(sh[0][0], sh[1][0], sh[1][1]),
            (pb, (slp, sbp)),
        )

    out = np.asarray(program(model, placement)(x_j, lp, bp))
    return out.reshape(s * rb, b_rest.shape[1])[:r].astype(b_rest.dtype)


# ---------------------------------------------------------------------------
# block-store drivers
# ---------------------------------------------------------------------------


class BlockedMatmul:
    """Steady-state blocked GEMM over ONE block-store replica.

    The k-panels split once into stable contiguous arrays and every
    :meth:`run` re-sends the SAME objects, so on the shm/ring lanes
    the PR-9 pin cache promotes them after the second sighting and
    subsequent iterations move zero request payload bytes (the
    zero-re-ship claim tests/test_linalg.py measures via
    ``pftpu_wire_bytes_copied_total``).
    """

    def __init__(
        self,
        a: Any,
        b: Any,
        client: Any,
        *,
        n_panels: int = 4,
        window: int = 8,
        policy: Optional[str] = None,
    ) -> None:
        ap, bp = _k_panels(np.asarray(a), np.asarray(b), n_panels)
        hdr = encode_op_header(OPCODES["GEMM_PANEL"])
        # One shared header object + per-panel stable arrays: every
        # request operand keeps its identity across run() calls.
        self._requests: List[Tuple[np.ndarray, ...]] = [
            (hdr, np.ascontiguousarray(ap[i]), np.ascontiguousarray(bp[i]))
            for i in range(ap.shape[0])
        ]
        self.client = client
        self.window = int(window)

    def run(self) -> np.ndarray:
        if hasattr(self.client, "evaluate_many"):
            replies = self.client.evaluate_many(
                self._requests, window=self.window
            )
        else:
            replies = [self.client.evaluate(*r) for r in self._requests]
        out = np.asarray(replies[0][0]).copy()
        for r in replies[1:]:
            out += np.asarray(r[0])
        return out


class BlockedCholesky:
    """Distributed right-looking blocked Cholesky over a pool of
    block-store replicas (block-row cyclic placement).

    Per outer step ``k``: the owner of block-row ``k`` factors the
    diagonal tile and panel-solves its own rows (``CHOL_PANEL``), the
    other replicas panel-solve theirs against the shipped ``L_kk``
    (``TRSM_PANEL``), the driver gathers the full panel column from
    the replies, and one ``SYRK_UPDATE`` broadcast applies the
    trailing update — wire traffic per step is O(panel), the matrix
    itself having shipped exactly once at distribution time.

    The driver assembles ``L`` from the panel REPLIES, so node stores
    are only ever read forward; that is what makes recovery local: a
    replica that dies mid-factorization (classified by a transient
    transport error) is reconnected, restored with a fresh ``PUT`` of
    ITS rows' current trailing state — recomputed driver-side from the
    original tiles and the collected panels, bit-identical to the node
    path because both use :func:`..service.dot_kernel` — and the step
    leg retries.  No other replica re-ships anything, and the node's
    step checks turn any missed/duplicated update into a loud
    :class:`BlockError` instead of a silently wrong factor.
    """

    def __init__(
        self,
        layout: BlockLayout,
        clients: Optional[Sequence[Any]] = None,
        *,
        policy: Optional[str] = None,
        reconnect: Optional[Callable[[int], Any]] = None,
        restore_attempts: int = 4,
        reconnect_timeout_s: float = 30.0,
    ) -> None:
        if layout.rows != layout.cols or layout.block_rows != layout.block_cols:
            raise BlockError(
                "Cholesky needs a square layout with square tiles, got "
                f"{layout.shape} in {layout.block_rows}x{layout.block_cols}"
            )
        resolve_policy(policy)
        self.layout = layout
        self.policy = policy
        self.clients: List[Any] = (
            list(clients)
            if clients is not None
            else [LocalBlockClient(layout, policy=policy)]
        )
        if not self.clients:
            raise BlockError("need at least one block-store client")
        self.reconnect = reconnect
        self.restore_attempts = int(restore_attempts)
        self.reconnect_timeout_s = float(reconnect_timeout_s)
        #: Accounting for the O(panel) / recovery-locality claims:
        #: (replica, coord) of every tile shipped, split by phase.
        self.shipped: List[Tuple[int, Tuple[int, int]]] = []
        self.reshipped: List[Tuple[int, Tuple[int, int]]] = []
        self.restores = 0
        self._a0: Dict[Tuple[int, int], np.ndarray] = {}
        self._l: Dict[Tuple[int, int], np.ndarray] = {}

    # -- placement helpers -------------------------------------------------

    def _owned(self, p: int) -> List[Tuple[int, int]]:
        n = len(self.clients)
        return [c for c in self.layout.lower_coords() if c[0] % n == p]

    def _has_rows_after(self, p: int, k: int) -> bool:
        rows = self.layout.rows_owned(p, len(self.clients))
        return bool(rows) and max(rows) > k

    # -- transport ---------------------------------------------------------

    def _call(self, p: int, k: int, arrays: Sequence[np.ndarray]) -> List[Any]:
        last: Optional[BaseException] = None
        needs_restore = False
        for _attempt in range(self.restore_attempts + 1):
            if needs_restore:
                try:
                    self._restore(p, k)
                    needs_restore = False
                except _TRANSIENT as e2:
                    # A restore that itself hits the dying connection
                    # (the replica is still coming back) burns one
                    # attempt and MUST run again before the leg — a
                    # leg retried over an unrestored store would only
                    # bounce off the node's state guards.
                    last = e2
                    continue
            try:
                return self.clients[p].evaluate(*arrays)
            except _TRANSIENT as e:
                last = e
                _flightrec.record(
                    "linalg.replica_lost",
                    replica=p, step=k, error=type(e).__name__,
                )
                needs_restore = True
            except (BlockError, RuntimeError) as e:
                # The stateful protocol's OTHER loss signal: transport
                # clients reconnect and re-send transparently, so a
                # request can land on a cold restarted store with no
                # transport error ever reaching this driver — the
                # node's state guards report it in-band instead.
                # Geometry/numerical refusals never classify and
                # propagate deterministically.
                if not is_restore_needed(e):
                    raise
                last = e
                _flightrec.record(
                    "linalg.replica_lost",
                    replica=p, step=k, error="stale_store",
                )
                needs_restore = True
        raise BlockError(
            f"replica {p} failed step {k} after "
            f"{self.restore_attempts} restores: {last!r}"
        ) from last

    def _distribute(self, p: int) -> None:
        """Initial tile distribution with the same transient posture as
        the factorization steps: a replica dying mid-PUT reconnects and
        re-ships, bounded by the attempt budget."""
        tiles = {c: self._a0[c] for c in self._owned(p)}
        last: Optional[BaseException] = None
        for _attempt in range(self.restore_attempts + 1):
            try:
                self._put(p, tiles, step=0)
                return
            except _TRANSIENT as e:
                last = e
                _flightrec.record(
                    "linalg.replica_lost",
                    replica=p, step=0, error=type(e).__name__,
                )
                try:
                    self._reconnect(p)
                except _TRANSIENT as e2:
                    last = e2
        raise BlockError(
            f"replica {p} failed initial distribution after "
            f"{self.restore_attempts} reconnects: {last!r}"
        ) from last

    def _reconnect(self, p: int) -> None:
        if self.reconnect is None:
            return
        deadline = time.monotonic() + self.reconnect_timeout_s
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            try:
                fresh = self.reconnect(p)
                # Transport constructors are LAZY (no connect until the
                # first evaluate), so a fresh client against a replica
                # that is still respawning looks healthy here and every
                # downstream attempt fast-fails — probe with a stateless
                # STATS round trip so THIS loop (bounded by
                # reconnect_timeout_s) is the one that waits out the
                # respawn.
                fresh.evaluate(encode_op_header(OPCODES["STATS"]))
                old, self.clients[p] = self.clients[p], fresh
                try:
                    old.close()
                except Exception:
                    pass
                return
            except _TRANSIENT as e:
                last = e
                time.sleep(0.2)
        raise BlockError(
            f"could not reconnect replica {p} within "
            f"{self.reconnect_timeout_s:.0f}s: {last!r}"
        ) from last

    # -- recovery ----------------------------------------------------------

    def _trailing_value(self, i: int, j: int, k: int) -> np.ndarray:
        """The current value of trailing tile ``(i, j)`` with ``k``
        updates applied: ``A0_ij - sum_{t<k} L_it @ L_jt^T`` — the
        driver-side twin of the node's SYRK path."""
        v = self._a0[(i, j)].copy()
        for t in range(k):
            v -= dot_kernel(
                self._l[(i, t)], self._l[(j, t)].T, self.policy
            ).astype(v.dtype)
        return v

    def _restore(self, p: int, k: int) -> None:
        """Reconnect replica ``p`` and re-ship ONLY its rows' live
        trailing tiles (columns >= k; earlier columns are finalized in
        the driver's collected factor and never read again)."""
        self.restores += 1
        self._reconnect(p)
        coords = [(i, j) for (i, j) in self._owned(p) if j >= k]
        tiles = {c: self._trailing_value(c[0], c[1], k) for c in coords}
        self._put(p, tiles, step=k, reship=True)
        _flightrec.record(
            "linalg.replica_restored",
            replica=p, step=k, tiles=len(coords),
        )

    def _put(
        self,
        p: int,
        tiles: Dict[Tuple[int, int], np.ndarray],
        *,
        step: int,
        reship: bool = False,
    ) -> None:
        coords = sorted(tiles)
        req: List[np.ndarray] = [
            encode_op_header(OPCODES["PUT"], step, len(coords))
        ]
        for c in coords:
            req.append(self.layout.encode_tile_header(*c))
            req.append(np.ascontiguousarray(tiles[c]))
        self.clients[p].evaluate(*req)
        log = self.reshipped if reship else self.shipped
        log.extend((p, c) for c in coords)

    # -- the factorization -------------------------------------------------

    def factor(self, a: Any) -> np.ndarray:
        lay = self.layout
        a = np.asarray(a)
        if a.shape != lay.shape:
            raise BlockError(
                f"matrix shape {a.shape} does not match layout {lay.shape}"
            )
        n_grid = lay.grid_rows
        n_rep = len(self.clients)
        self._a0 = {
            c: np.ascontiguousarray(a[lay.tile_slice(*c)])
            for c in lay.lower_coords()
        }
        self._l = {}
        self.shipped.clear()
        self.reshipped.clear()
        for p in range(n_rep):
            if self._owned(p):
                self._distribute(p)
        for k in range(n_grid):
            owner = k % n_rep
            reply = self._call(
                owner, k, [encode_op_header(OPCODES["CHOL_PANEL"], k)]
            )
            if len(reply) < 2:
                raise BlockError(
                    f"CHOL_PANEL({k}) reply carries {len(reply)} arrays"
                )
            l_kk = np.asarray(reply[0])
            self._l[(k, k)] = l_kk
            panel = self._merge_panel({}, k, reply[1], reply[2:])
            for q in range(n_rep):
                if q == owner or not self._has_rows_after(q, k):
                    continue
                rep = self._call(
                    q, k,
                    [encode_op_header(OPCODES["TRSM_PANEL"], k), l_kk],
                )
                panel = self._merge_panel(panel, k, rep[0], rep[1:])
            want = set(range(k + 1, n_grid))
            if set(panel) != want:
                raise BlockError(
                    f"panel column {k} incomplete: have rows "
                    f"{sorted(panel)}, want {sorted(want)} — refusing "
                    "to assemble a silently partial factor"
                )
            for i, tile in panel.items():
                self._l[(i, k)] = tile
            if panel:
                rows_arr = np.asarray(sorted(panel), dtype=np.int64)
                ptiles = [panel[int(i)] for i in rows_arr]
                req = [
                    encode_op_header(
                        OPCODES["SYRK_UPDATE"], k, len(ptiles)
                    ),
                    rows_arr,
                    *ptiles,
                ]
                for q in range(n_rep):
                    if self._has_rows_after(q, k):
                        self._call(q, k, req)
        return lay.assemble(self._l, lower_only=True)

    def _merge_panel(
        self,
        panel: Dict[int, np.ndarray],
        k: int,
        rows: Any,
        tiles: Sequence[Any],
    ) -> Dict[int, np.ndarray]:
        rows_arr = np.asarray(rows)
        if rows_arr.dtype != np.int64 or rows_arr.ndim != 1:
            raise BlockError(
                f"panel rows reply must be int64 (n,), got "
                f"{rows_arr.dtype} {rows_arr.shape}"
            )
        if len(tiles) != rows_arr.shape[0]:
            raise BlockError(
                f"panel reply claims {rows_arr.shape[0]} rows but "
                f"carries {len(tiles)} tiles"
            )
        for i, t in zip(rows_arr, tiles):
            i = int(i)
            if i <= k:
                raise BlockError(f"panel column {k} reply names row {i}")
            if i in panel:
                raise BlockError(
                    f"panel row {i} replied by two replicas — "
                    "placement disagreement"
                )
            panel[i] = self.layout.check_tile(i, k, np.asarray(t))
        return panel


def cholesky(
    a: Any,
    *,
    block: int = 64,
    clients: Optional[Sequence[Any]] = None,
    policy: Optional[str] = None,
    reconnect: Optional[Callable[[int], Any]] = None,
) -> np.ndarray:
    """Lower-Cholesky of a symmetric positive-definite matrix via the
    blocked right-looking factorization.

    With ``clients=None`` the whole algorithm runs against one
    in-process block store (the clientless lane — same code path, no
    wire); with a list of transport clients the tiles distribute
    block-row-cyclically and the factorization runs over the pool.
    """
    a = np.asarray(a)
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise BlockError(f"cholesky needs a square matrix, got {a.shape}")
    bb = min(int(block), a.shape[0])
    layout = BlockLayout(a.shape[0], a.shape[1], bb, bb)
    return BlockedCholesky(
        layout, clients, policy=policy, reconnect=reconnect
    ).factor(a)
