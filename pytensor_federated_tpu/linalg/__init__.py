"""Distributed block-partitioned linear algebra over the pool (ISSUE 19).

Per *Large Scale Distributed Linear Algebra With TPUs* (PAPERS.md):
block-partitioned GEMM, Cholesky, and triangular solve expressed on
the repo's existing machinery — fed programs for the map/reduce-shaped
rounds, the stateful block store (:mod:`.service`) for the
panel-factorization loops where tiles ship once and pin in the PR-9
arena.  :mod:`.blocks` owns the tile geometry and the wire headers
(declared in ``service/wire_registry.py`` first, like every wire
feature).
"""

from .blocks import BlockError, BlockLayout
from .ops import (
    BlockedCholesky,
    BlockedMatmul,
    block_quadratic_form,
    cholesky,
    matmul,
    matmul_per_shard,
    quadratic_per_shard,
    triangular_solve,
)
from .service import LocalBlockClient, make_block_store_compute

__all__ = [
    "BlockError",
    "BlockLayout",
    "BlockedCholesky",
    "BlockedMatmul",
    "LocalBlockClient",
    "block_quadratic_form",
    "cholesky",
    "make_block_store_compute",
    "matmul",
    "matmul_per_shard",
    "quadratic_per_shard",
    "triangular_solve",
]
