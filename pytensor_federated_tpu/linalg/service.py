"""The block-store node compute (ISSUE 19).

A stateful arrays-in/arrays-out compute serving the blocked-linalg
operation set declared in :mod:`..service.wire_registry`
(``LINALG_OPCODES``): tiles ship ONCE (``PUT``), live node-side keyed
by grid coordinate, and every subsequent panel operation references
them by block id — steady-state factorization steps move only the
panel, never the matrix.  Deployed on any transport lane
(``run_node``/``serve_tcp``/``serve_shm``/``serve_ring``) like any
other compute; on the shm/ring lanes the PR-9 pin cache additionally
makes repeated request operands (headers, re-broadcast panels) zero
copy-bytes.

Protocol state is deliberately minimal — a tile dict plus one
``applied_step`` counter — because the DRIVER (:mod:`.ops`) owns
recovery: on a replica failure it restores that replica's trailing
state with a fresh ``PUT`` before retrying the step, so every op here
can assume its inputs are current.  ``applied_step`` exists to make a
retried trailing update idempotent (an update the node already applied
whose reply was lost must not double-subtract) and to make a MISSED
update a loud :class:`..linalg.blocks.BlockError` instead of silent
numerical corruption.

Numeric kernels route contractions through :func:`...precision.pdot`
(the f32-strict policy seam — blocked contractions are exactly the
>= few-hundred-term case CLAUDE.md flags as bf16-accurate on chip);
float64 tiles use numpy kernels directly (the split path is an
f32-only mitigation and would downcast).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..precision import matmul_precision_ctx, resolve_policy
from .blocks import (
    OPCODES,
    BlockError,
    BlockLayout,
    decode_op_header,
    unpack_coords,
)

__all__ = [
    "make_block_store_compute",
    "LocalBlockClient",
    "chol_kernel",
    "trsm_kernel",
    "dot_kernel",
    "is_restore_needed",
]

#: In-band refusals a DRIVER can heal by restoring the replica's
#: trailing tiles and retrying the leg (the store is in the wrong
#: state, not the wrong geometry).  Transport clients retry
#: transparently (reconnect + re-send), so a re-sent panel op can land
#: on a cold respawned store with no transport error ever reaching the
#: driver — these markers are how the stateful protocol reports that
#: loss in-band.  Kept as exact message fragments because the error
#: crosses the wire as text (:class:`..service.tcp.RemoteComputeError`
#: erases the type, the PR-15 lesson).
_RESTORE_MARKS = (
    "must be restored with PUT first",
    "the driver must restore before retrying",
    "a missed panel would silently corrupt the factor",
)


def is_restore_needed(exc: BaseException) -> bool:
    """True when ``exc`` is a block-store state refusal the driver heals
    with a restore (re-``PUT`` of trailing tiles) + retry.  Geometry and
    numerical refusals (wrong layout, non-PD tile) never match — those
    are deterministic and must propagate."""
    msg = str(exc)
    return any(mark in msg for mark in _RESTORE_MARKS)


# ---------------------------------------------------------------------------
# numeric kernels (shared with the driver in ops.py — one implementation,
# so a driver-side recovery recompute is BIT-identical to the node's path)
# ---------------------------------------------------------------------------


def dot_kernel(
    a: np.ndarray, b: np.ndarray, policy: Optional[str] = None
) -> np.ndarray:
    """Policy-routed tile contraction ``a @ b`` on host arrays."""
    a = np.asarray(a)
    b = np.asarray(b)
    if a.dtype == np.float64 or b.dtype == np.float64:
        # The bf16x3 split is an f32 mitigation; float64 contracts
        # exactly in numpy (the reference framework's CPU posture).
        return np.matmul(a, b)
    from ..precision import pdot

    return np.asarray(pdot(a, b, policy), dtype=np.result_type(a, b))


def chol_kernel(a: np.ndarray, policy: Optional[str] = None) -> np.ndarray:
    """Lower Cholesky of one diagonal tile; loud on non-PD input."""
    a = np.asarray(a)
    try:
        if a.dtype == np.float64:
            return np.linalg.cholesky(a)
        import jax.numpy as jnp

        with matmul_precision_ctx(policy):
            l = np.asarray(jnp.linalg.cholesky(jnp.asarray(a)), dtype=a.dtype)
        if not np.all(np.isfinite(l)):
            raise np.linalg.LinAlgError("non-finite factor")
        return l
    except np.linalg.LinAlgError as e:
        raise BlockError(f"diagonal tile is not positive definite: {e}") from e


def trsm_kernel(
    a_ik: np.ndarray, l_kk: np.ndarray, policy: Optional[str] = None
) -> np.ndarray:
    """Panel solve ``X = A_ik @ inv(L_kk)^T`` (right-looking Cholesky's
    off-diagonal step), via the triangular solve ``L_kk X^T = A_ik^T``."""
    a_ik = np.asarray(a_ik)
    l_kk = np.asarray(l_kk)
    if a_ik.dtype == np.float64:
        return np.linalg.solve(l_kk, a_ik.T).T
    import jax.numpy as jnp
    from jax.scipy.linalg import solve_triangular

    with matmul_precision_ctx(policy):
        x = solve_triangular(
            jnp.asarray(l_kk), jnp.asarray(a_ik).T, lower=True
        ).T
    return np.asarray(x, dtype=a_ik.dtype)


# ---------------------------------------------------------------------------
# the block store
# ---------------------------------------------------------------------------


class _BlockStore:
    """One node's tile state: the dict plus the trailing-update clock."""

    def __init__(self, layout: BlockLayout, policy: Optional[str]) -> None:
        self.layout = layout
        self.policy = policy
        self.tiles: Dict[Tuple[int, int], np.ndarray] = {}
        #: Number of trailing updates applied (updates for panel steps
        #: ``0..applied_step-1`` are in the stored tiles).
        self.applied_step = 0
        #: Exactly-once replay cache for the current step's panel ops.
        #: CHOL_PANEL/TRSM_PANEL solve tiles IN PLACE, so a re-sent
        #: request (transport clients reconnect and re-send after a
        #: lost reply) re-solving an already-solved panel would be
        #: silent corruption — the replay returns the recorded reply
        #: instead.  Invalidated by PUT (a restore replaces the tiles)
        #: and by the step advancing.
        self._panel_replies: Dict[Tuple[str, int], List[np.ndarray]] = {}

    # -- op handlers -------------------------------------------------------

    def put(self, step: int, count: int, args: List[np.ndarray]) -> List[np.ndarray]:
        if len(args) != 2 * count:
            raise BlockError(
                f"PUT header claims {count} tiles but carries "
                f"{len(args)} arrays (want {2 * count}: header+tile pairs)"
            )
        staged: Dict[Tuple[int, int], np.ndarray] = {}
        for t in range(count):
            coord = self.layout.decode_tile_header(args[2 * t])
            if coord in staged:
                raise BlockError(f"PUT ships tile {coord} twice")
            tile = self.layout.check_tile(*coord, args[2 * t + 1])
            staged[coord] = np.ascontiguousarray(tile)
        self.tiles.update(staged)
        # The driver stamps the restore point: tiles as shipped have
        # exactly `step` trailing updates applied.
        self.applied_step = step
        self._panel_replies.clear()
        return [np.int64(len(self.tiles))]

    def get(self, args: List[np.ndarray]) -> List[np.ndarray]:
        if len(args) != 1:
            raise BlockError(f"GET wants one coordinate array, got {len(args)}")
        out = []
        for coord in unpack_coords(args[0]):
            tile = self.tiles.get(coord)
            if tile is None:
                raise BlockError(
                    f"GET of tile {coord} this store does not hold "
                    f"({len(self.tiles)} tiles stored) — geometry "
                    "disagreement or a restarted replica"
                )
            out.append(tile)
        return out

    def gemm_panel(self, args: List[np.ndarray]) -> List[np.ndarray]:
        if len(args) != 2:
            raise BlockError(f"GEMM_PANEL wants [a, b], got {len(args)} arrays")
        a, b = np.asarray(args[0]), np.asarray(args[1])
        if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
            raise BlockError(
                f"GEMM_PANEL shapes do not contract: {a.shape} @ {b.shape}"
            )
        return [dot_kernel(a, b, self.policy)]

    def _own_panel_rows(self, k: int) -> List[int]:
        return sorted(
            i for (i, j) in self.tiles if j == k and i > k
        )

    def _require(self, coord: Tuple[int, int], what: str) -> np.ndarray:
        tile = self.tiles.get(coord)
        if tile is None:
            raise BlockError(
                f"{what} needs tile {coord} this store does not hold — "
                "a restarted replica must be restored with PUT first"
            )
        return tile

    def chol_panel(self, k: int, args: List[np.ndarray]) -> List[np.ndarray]:
        if args:
            raise BlockError("CHOL_PANEL carries no arrays beyond the header")
        if self.applied_step != k:
            raise BlockError(
                f"CHOL_PANEL step {k} but this store has "
                f"{self.applied_step} trailing updates applied — "
                "the driver must restore before retrying"
            )
        cached = self._panel_replies.get(("chol", k))
        if cached is not None:
            # A re-sent request after a lost reply: the solves already
            # happened in place; solving again would corrupt silently.
            return cached
        a_kk = self._require((k, k), f"CHOL_PANEL({k})")
        l_kk = chol_kernel(a_kk, self.policy)
        self.tiles[(k, k)] = l_kk
        rows = self._own_panel_rows(k)
        out: List[np.ndarray] = [l_kk, np.asarray(rows, dtype=np.int64)]
        for i in rows:
            l_ik = trsm_kernel(self.tiles[(i, k)], l_kk, self.policy)
            self.tiles[(i, k)] = l_ik
            out.append(l_ik)
        self._panel_replies[("chol", k)] = out
        return out

    def trsm_panel(self, k: int, args: List[np.ndarray]) -> List[np.ndarray]:
        if len(args) != 1:
            raise BlockError(f"TRSM_PANEL wants [L_kk], got {len(args)} arrays")
        if self.applied_step != k:
            raise BlockError(
                f"TRSM_PANEL step {k} but this store has "
                f"{self.applied_step} trailing updates applied — "
                "the driver must restore before retrying"
            )
        cached = self._panel_replies.get(("trsm", k))
        if cached is not None:
            return cached
        l_kk = self.layout.check_tile(k, k, args[0])
        rows = self._own_panel_rows(k)
        out: List[np.ndarray] = [np.asarray(rows, dtype=np.int64)]
        for i in rows:
            l_ik = trsm_kernel(self.tiles[(i, k)], l_kk, self.policy)
            self.tiles[(i, k)] = l_ik
            out.append(l_ik)
        self._panel_replies[("trsm", k)] = out
        return out

    def syrk_update(self, k: int, args: List[np.ndarray]) -> List[np.ndarray]:
        if not args:
            raise BlockError("SYRK_UPDATE wants [rows, panel tiles...]")
        rows_arr = np.asarray(args[0])
        if rows_arr.dtype != np.int64 or rows_arr.ndim != 1:
            raise BlockError(
                f"SYRK_UPDATE rows must be int64 (n,), got "
                f"{rows_arr.dtype} {rows_arr.shape}"
            )
        if self.applied_step > k:
            # Already applied (a retried update whose reply was lost):
            # idempotent no-op, signalled in-band with the -1 sentinel.
            return [np.int64(-1)]
        if self.applied_step < k:
            raise BlockError(
                f"SYRK_UPDATE step {k} but only {self.applied_step} "
                "updates applied — a missed panel would silently "
                "corrupt the factor"
            )
        rows = [int(i) for i in rows_arr]
        if len(args) != 1 + len(rows):
            raise BlockError(
                f"SYRK_UPDATE claims {len(rows)} panel rows but "
                f"carries {len(args) - 1} tiles"
            )
        panel = {}
        for i, tile in zip(rows, args[1:]):
            if i <= k:
                raise BlockError(
                    f"SYRK_UPDATE({k}) panel row {i} is not below the panel"
                )
            panel[i] = self.layout.check_tile(i, k, tile)
        updated = 0
        for (i, j), tile in list(self.tiles.items()):
            if j <= k or j > i:
                continue
            l_ik = panel.get(i)
            l_jk = panel.get(j)
            if l_ik is None or l_jk is None:
                raise BlockError(
                    f"SYRK_UPDATE({k}) needs panel rows {i} and {j} "
                    f"for stored tile ({i}, {j}) but the request only "
                    f"carries rows {sorted(panel)}"
                )
            self.tiles[(i, j)] = tile - dot_kernel(
                l_ik, l_jk.T, self.policy
            ).astype(tile.dtype)
            updated += 1
        self.applied_step = k + 1
        # The step advanced: step-k panel replays are now impossible
        # (the applied_step guard refuses them loudly) and the cache
        # would only pin dead tiles.
        self._panel_replies.clear()
        return [np.int64(updated)]

    def reset(self) -> List[np.ndarray]:
        n = len(self.tiles)
        self.tiles.clear()
        self.applied_step = 0
        self._panel_replies.clear()
        return [np.int64(n)]

    def stats(self) -> List[np.ndarray]:
        return [
            np.int64(len(self.tiles)),
            np.int64(sum(t.nbytes for t in self.tiles.values())),
        ]


def make_block_store_compute(
    layout: BlockLayout, *, policy: Optional[str] = None
) -> Callable[..., List[np.ndarray]]:
    """Node-side compute serving the block-store operation set for ONE
    block layout (the layout bakes at deploy time, like a pool
    compute's per-shard function; a driver speaking a different
    geometry gets a loud in-band :class:`BlockError`)."""
    resolve_policy(policy)  # typo'd policies refuse at deploy time
    store = _BlockStore(layout, policy)
    ops = OPCODES

    def compute(*arrays: Any) -> List[np.ndarray]:
        if not arrays:
            raise BlockError("block-store request carries no op header")
        args = [np.asarray(a) for a in arrays]
        opcode, step, count = decode_op_header(args[0])
        rest = args[1:]
        if opcode == ops["PUT"]:
            return store.put(step, count, rest)
        if opcode == ops["GET"]:
            return store.get(rest)
        if opcode == ops["GEMM_PANEL"]:
            return store.gemm_panel(rest)
        if opcode == ops["CHOL_PANEL"]:
            return store.chol_panel(step, rest)
        if opcode == ops["TRSM_PANEL"]:
            return store.trsm_panel(step, rest)
        if opcode == ops["SYRK_UPDATE"]:
            return store.syrk_update(step, rest)
        if opcode == ops["RESET"]:
            return store.reset()
        if opcode == ops["STATS"]:
            return store.stats()
        raise BlockError(f"unhandled linalg opcode {opcode}")

    # Tests and the local lane reach the state for accounting.
    compute.store = store  # type: ignore[attr-defined]
    return compute


class LocalBlockClient:
    """In-process stand-in for a transport client over one block-store
    compute — the clientless lane (``linalg.cholesky(a)`` with no pool)
    and the unit-test seam.  Mirrors the pinned-client ``evaluate``
    surface the driver uses."""

    def __init__(
        self, layout: BlockLayout, *, policy: Optional[str] = None
    ) -> None:
        self._compute = make_block_store_compute(layout, policy=policy)

    @property
    def store(self) -> _BlockStore:
        return self._compute.store  # type: ignore[attr-defined]

    def evaluate(self, *arrays: np.ndarray) -> List[np.ndarray]:
        return [np.asarray(a) for a in self._compute(*arrays)]

    def close(self) -> None:  # surface parity with transport clients
        pass
