"""Block layouts for distributed tiled linear algebra (ISSUE 19).

A :class:`BlockLayout` describes a 2-D tile grid over a matrix: how a
``rows x cols`` array splits into ``grid_rows x grid_cols`` tiles of at
most ``block_rows x block_cols`` elements (edge tiles are smaller, never
padded — padding would silently change Cholesky/GEMM numerics on the
edge panels).  The layout also owns the two wire headers every linalg
operation leads with — packed per :data:`..service.wire_registry.
LINALG_OP_STRUCT` / :data:`..service.wire_registry.LINALG_TILE_STRUCT`,
imported from the registry so the declaration and the single
implementation cannot drift — and the deterministic block -> replica
placement the block store and the driver must agree on.

Failure posture follows the wire contract (CLAUDE.md): any geometry
mismatch, missing tile, duplicate tile, or malformed header is a loud
:class:`BlockError` (a ``WireError`` subclass), never a silently
mis-assembled matrix.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, Iterator, List, Sequence, Tuple

import numpy as np

from ..service.npwire import WireError
from ..service.wire_registry import (
    LINALG_OP_STRUCT,
    LINALG_OPCODES,
    LINALG_TILE_STRUCT,
)

__all__ = [
    "BlockError",
    "BlockLayout",
    "encode_op_header",
    "decode_op_header",
    "OPCODES",
]

#: Opcode table re-exported from the registry (the registry is the
#: declaration; this module is the one implementation).
OPCODES: Dict[str, int] = dict(LINALG_OPCODES)
_OPCODE_NAMES = {v: k for k, v in OPCODES.items()}

_OP_STRUCT = struct.Struct(LINALG_OP_STRUCT)
_TILE_STRUCT = struct.Struct(LINALG_TILE_STRUCT)


class BlockError(WireError):
    """A blocked-linalg geometry or protocol violation.

    Subclasses ``WireError`` so every transport, pool, and chaos lane
    classifies it like any other corrupt-frame condition: loud,
    deterministic, non-retryable.
    """


def encode_op_header(opcode: int, step: int = 0, count: int = 0) -> np.ndarray:
    """Pack one operation header as the leading ``uint8`` request array."""
    if opcode not in _OPCODE_NAMES:
        raise BlockError(f"unknown linalg opcode {opcode!r}")
    return np.frombuffer(
        _OP_STRUCT.pack(opcode, step, count, 0), dtype=np.uint8
    ).copy()


def decode_op_header(arr: np.ndarray) -> Tuple[int, int, int]:
    """Unpack ``(opcode, step, count)``; loud on malformed headers."""
    a = np.ascontiguousarray(arr)
    if a.dtype != np.uint8 or a.nbytes != _OP_STRUCT.size:
        raise BlockError(
            "linalg op header must be a "
            f"uint8[{_OP_STRUCT.size}] array, got dtype {a.dtype} "
            f"with {a.nbytes} bytes"
        )
    opcode, step, count, flags = _OP_STRUCT.unpack(a.tobytes())
    if flags != 0:
        raise BlockError(
            f"linalg op header carries unknown flag bits {flags:#x} "
            "(reserved field must be zero)"
        )
    if opcode not in _OPCODE_NAMES:
        raise BlockError(f"unknown linalg opcode {opcode}")
    return opcode, step, count


@dataclass(frozen=True)
class BlockLayout:
    """A 2-D tile grid over a ``rows x cols`` matrix."""

    rows: int
    cols: int
    block_rows: int
    block_cols: int

    def __post_init__(self) -> None:
        for name in ("rows", "cols", "block_rows", "block_cols"):
            v = getattr(self, name)
            if not isinstance(v, (int, np.integer)) or v <= 0:
                raise BlockError(f"BlockLayout.{name} must be > 0, got {v!r}")
        if self.block_rows > self.rows or self.block_cols > self.cols:
            raise BlockError(
                f"block shape ({self.block_rows}, {self.block_cols}) "
                f"exceeds matrix shape ({self.rows}, {self.cols})"
            )

    # -- geometry ----------------------------------------------------------

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.rows, self.cols)

    @property
    def grid_rows(self) -> int:
        return -(-self.rows // self.block_rows)

    @property
    def grid_cols(self) -> int:
        return -(-self.cols // self.block_cols)

    @classmethod
    def for_matrix(cls, a: np.ndarray, block: int) -> "BlockLayout":
        a = np.asarray(a)
        if a.ndim != 2:
            raise BlockError(f"expected a 2-D matrix, got shape {a.shape}")
        b = int(block)
        return cls(a.shape[0], a.shape[1], min(b, a.shape[0]), min(b, a.shape[1]))

    def tile_shape(self, i: int, j: int) -> Tuple[int, int]:
        self._check_coord(i, j)
        r = min(self.block_rows, self.rows - i * self.block_rows)
        c = min(self.block_cols, self.cols - j * self.block_cols)
        return (r, c)

    def tile_slice(self, i: int, j: int) -> Tuple[slice, slice]:
        r, c = self.tile_shape(i, j)
        r0 = i * self.block_rows
        c0 = j * self.block_cols
        return (slice(r0, r0 + r), slice(c0, c0 + c))

    def _check_coord(self, i: int, j: int) -> None:
        if not (0 <= i < self.grid_rows and 0 <= j < self.grid_cols):
            raise BlockError(
                f"tile ({i}, {j}) outside the "
                f"{self.grid_rows}x{self.grid_cols} grid"
            )

    def coords(self) -> Iterator[Tuple[int, int]]:
        for i in range(self.grid_rows):
            for j in range(self.grid_cols):
                yield (i, j)

    def lower_coords(self) -> Iterator[Tuple[int, int]]:
        """Coordinates of the lower-triangle tiles (j <= i) — the tile
        set a Cholesky factorization stores and touches."""
        for i in range(self.grid_rows):
            for j in range(min(i, self.grid_cols - 1) + 1):
                yield (i, j)

    # -- placement ---------------------------------------------------------

    def owner(self, i: int, j: int, n_replicas: int) -> int:
        """Deterministic block -> replica placement: block-ROW cyclic.

        Row-cyclic (not 2-D cyclic) on purpose: the right-looking
        Cholesky's panel solve and trailing update are row-local, so
        owning whole block-rows keeps every per-step op a single
        request per replica and balances the trailing work to within
        one block-row.
        """
        self._check_coord(i, j)
        n = int(n_replicas)
        if n < 1:
            raise BlockError(f"n_replicas must be >= 1, got {n_replicas!r}")
        return i % n

    def rows_owned(self, replica: int, n_replicas: int) -> List[int]:
        return [i for i in range(self.grid_rows) if i % int(n_replicas) == replica]

    # -- split / assemble --------------------------------------------------

    def split(self, a: np.ndarray) -> Dict[Tuple[int, int], np.ndarray]:
        """Tile a matrix.  Tiles are contiguous COPIES (stable objects
        the PR-9 pin cache can key on across iterations)."""
        a = np.asarray(a)
        if a.shape != self.shape:
            raise BlockError(
                f"matrix shape {a.shape} does not match layout "
                f"shape {self.shape}"
            )
        return {
            (i, j): np.ascontiguousarray(a[self.tile_slice(i, j)])
            for i, j in self.coords()
        }

    def assemble(
        self,
        tiles: Dict[Tuple[int, int], np.ndarray],
        *,
        lower_only: bool = False,
    ) -> np.ndarray:
        """Reassemble a matrix from tiles; loud on missing/extra tiles,
        wrong tile shapes, or mixed dtypes.  ``lower_only=True``
        accepts exactly the lower-triangle tile set and zero-fills the
        strict upper triangle (a Cholesky factor)."""
        want = set(self.lower_coords() if lower_only else self.coords())
        got = set(tiles)
        if got != want:
            missing = sorted(want - got)
            extra = sorted(got - want)
            raise BlockError(
                "cannot assemble: "
                f"missing tiles {missing[:8]}{'...' if len(missing) > 8 else ''}, "
                f"unexpected tiles {extra[:8]}{'...' if len(extra) > 8 else ''}"
            )
        dtypes = sorted({str(np.asarray(t).dtype) for t in tiles.values()})
        if len(dtypes) > 1:
            raise BlockError(f"cannot assemble tiles of mixed dtypes {dtypes}")
        out = np.zeros(self.shape, dtype=np.asarray(next(iter(tiles.values()))).dtype)
        for (i, j), t in tiles.items():
            t = np.asarray(t)
            if t.shape != self.tile_shape(i, j):
                raise BlockError(
                    f"tile ({i}, {j}) has shape {t.shape}, layout "
                    f"expects {self.tile_shape(i, j)}"
                )
            out[self.tile_slice(i, j)] = t
        return out

    # -- wire headers ------------------------------------------------------

    def encode_tile_header(self, i: int, j: int) -> np.ndarray:
        r, c = self.tile_shape(i, j)
        return np.frombuffer(
            _TILE_STRUCT.pack(self.grid_rows, self.grid_cols, i, j, r, c),
            dtype=np.uint8,
        ).copy()

    def decode_tile_header(self, arr: np.ndarray) -> Tuple[int, int]:
        """Unpack and VALIDATE one tile header against this layout ->
        ``(row, col)``.  Every mismatch is a loud :class:`BlockError`."""
        a = np.ascontiguousarray(arr)
        if a.dtype != np.uint8 or a.nbytes != _TILE_STRUCT.size:
            raise BlockError(
                "linalg tile header must be a "
                f"uint8[{_TILE_STRUCT.size}] array, got dtype {a.dtype} "
                f"with {a.nbytes} bytes"
            )
        gr, gc, i, j, r, c = _TILE_STRUCT.unpack(a.tobytes())
        if (gr, gc) != (self.grid_rows, self.grid_cols):
            raise BlockError(
                f"tile header is for a {gr}x{gc} grid, this store's "
                f"layout is {self.grid_rows}x{self.grid_cols} "
                f"({self.rows}x{self.cols} in blocks of "
                f"{self.block_rows}x{self.block_cols})"
            )
        self._check_coord(i, j)
        if (r, c) != self.tile_shape(i, j):
            raise BlockError(
                f"tile ({i}, {j}) header claims shape ({r}, {c}), "
                f"layout expects {self.tile_shape(i, j)}"
            )
        return (i, j)

    def check_tile(self, i: int, j: int, tile: np.ndarray) -> np.ndarray:
        """Validate a tile array's shape against the layout (loud)."""
        t = np.asarray(tile)
        if t.shape != self.tile_shape(i, j):
            raise BlockError(
                f"tile ({i}, {j}) array has shape {t.shape}, layout "
                f"expects {self.tile_shape(i, j)}"
            )
        return t


def pack_coords(coords: Sequence[Tuple[int, int]]) -> np.ndarray:
    """Coordinate list -> the ``int64 (n, 2)`` wire array."""
    if not coords:
        return np.zeros((0, 2), dtype=np.int64)
    return np.asarray(list(coords), dtype=np.int64).reshape(-1, 2)


def unpack_coords(arr: np.ndarray) -> List[Tuple[int, int]]:
    a = np.asarray(arr)
    if a.dtype != np.int64 or a.ndim != 2 or a.shape[1] != 2:
        raise BlockError(
            f"coordinate array must be int64 (n, 2), got {a.dtype} {a.shape}"
        )
    return [(int(i), int(j)) for i, j in a]
