"""Ring collectives: sequence/context parallelism over the device mesh.

Net-new capability relative to the reference (SURVEY §5 records "long
context / sequence parallelism: N/A" — the reference has no sequences at
all; its only scale axis is the shard count, reference: demo_model.py:34-36).
This module makes long sequences a first-class scale axis: a sequence is
sharded along the ``"seq"`` mesh axis, and cross-shard coupling is
computed with ``lax.ppermute`` rings over ICI — no host round-trips, no
all-gather of the full sequence on any single device.

Three layers of generality:

- :func:`ring_shift` / :func:`shift_right_across_shards` — boundary
  passing for Markov-factored likelihoods (state-space, AR): each device
  only needs its left neighbour's last element.
- :func:`ring_all_pairs_sum` — all-pairs block reductions for densely
  coupled likelihoods (pairwise potentials, GP-style kernels): every
  block visits every device once around the ring; memory stays
  O(local block), compute is overlapped with ICI transfers by XLA.
- :func:`ring_attention` — blockwise-softmax attention over the ring
  (the ring-attention pattern: online max/normalizer update per incoming
  key/value block), for attention-based sequence likelihoods.

All three are written to be used *inside* ``shard_map`` (they take an
axis name), with jittable wrappers that build the ``shard_map`` for you.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from .._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import SEQ_AXIS, mark_varying as _mark_varying


def _ring_perm(n: int, *, reverse: bool = False) -> list:
    """Permutation sending block j -> j+1 (mod n); device i ends up
    holding block (i - step) mod n after each application."""
    if reverse:
        return [(j, (j - 1) % n) for j in range(n)]
    return [(j, (j + 1) % n) for j in range(n)]


def ring_shift(x: Any, axis_name: str, n: int, *, reverse: bool = False) -> Any:
    """One ring step: pass the local value to the next device on the ring.

    Must be called inside ``shard_map`` over ``axis_name``; ``n`` is the
    static ring size (``mesh.shape[axis_name]``).
    """
    perm = _ring_perm(n, reverse=reverse)
    return jax.tree_util.tree_map(
        lambda l: lax.ppermute(l, axis_name, perm), x
    )


def shift_right_across_shards(x: jax.Array, axis_name: str, n: int) -> jax.Array:
    """Shift a sequence-sharded array right by one *global* position.

    Local view: device i holds a contiguous chunk ``x[i*Tb:(i+1)*Tb]``.
    The returned chunk is the same slice of the globally right-shifted
    sequence: element 0 is the left neighbour's last element (zero on
    device 0 — ``ppermute`` leaves unaddressed destinations zero-filled).

    This is the entire communication cost of a Markov-factored
    sequence likelihood: one scalar-row exchange per step, riding ICI.
    """
    boundary = x[-1:]
    # Send each device's last element to its right neighbour; device 0
    # receives nothing and keeps zeros.
    prev_last = lax.ppermute(
        boundary, axis_name, [(j, j + 1) for j in range(n - 1)]
    )
    return jnp.concatenate([prev_last, x[:-1]], axis=0)


def seq_sharded_markov_logp(
    trans_logp: Callable[[Any, jax.Array, jax.Array], jax.Array],
    init_logp: Callable[[Any, jax.Array], jax.Array],
    y: jax.Array,
    *,
    mesh: Mesh,
    axis: str = SEQ_AXIS,
) -> Callable[[Any], jax.Array]:
    """Sequence-parallel log-likelihood of a Markov-factored model.

    ``logp(params) = init_logp(params, y[0]) + Σ_{t>=1} trans_logp(params,
    y[t-1], y[t])`` with ``y`` (length T, optionally trailing feature
    dims) sharded along ``axis``.  ``trans_logp`` is vectorized over
    time (inputs ``y_prev``, ``y_curr`` of shape ``(Tb, ...)`` -> per-step
    logps ``(Tb,)``).

    The reference's federated sum-of-potentials (reference:
    demo_model.py:34-36) has independent terms; a Markov chain's terms
    couple neighbouring positions, which is exactly what
    :func:`shift_right_across_shards` provides.  Differentiable: the
    whole thing is ``ppermute`` + elementwise, so ``jax.grad`` flows
    through the collective.
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
    n = mesh.shape[axis]
    if y.shape[0] % n != 0:
        raise ValueError(f"sequence length {y.shape[0]} not divisible by {n}")

    def local(params, y_local):
        idx = lax.axis_index(axis)
        y_prev = shift_right_across_shards(y_local, axis, n)
        step_lp = trans_logp(params, y_prev, y_local)
        # Global position of each local element:
        tb = y_local.shape[0]
        pos = idx * tb + jnp.arange(tb)
        # t=0 contributes init_logp instead of a transition term.
        first = init_logp(params, y_local[0])
        lp = jnp.sum(jnp.where(pos > 0, step_lp, 0.0))
        lp = lp + jnp.where(idx == 0, first, 0.0)
        return lax.psum(lp, axis)

    def logp(params):
        return shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(), params), P(axis)),
            out_specs=P(),
        )(params, y)

    return jax.jit(logp)


def ring_all_pairs_sum(
    pair_fn: Callable[[Any, Any], jax.Array],
    data: Any,
    *,
    mesh: Mesh,
    axis: str = SEQ_AXIS,
    include_self: bool = True,
) -> jax.Array:
    """Σ over all *ordered* block pairs ``pair_fn(my_block, other_block)``.

    ``data`` is a pytree sharded along ``axis`` (leading dim).  Each
    device keeps its resident block and receives every other block once
    as it travels around the ring — the classic systolic all-pairs
    pattern (memory O(block), ``n`` ring steps).  With
    ``include_self=False`` the diagonal (r=0) term is skipped.

    For a symmetric ``pair_fn`` this evaluates each unordered pair twice;
    divide by 2 at the call site if needed.  Differentiable end-to-end.
    """
    treedef = jax.tree_util.tree_structure(data)
    fn = _all_pairs_jitted(pair_fn, mesh, axis, include_self, treedef)
    return fn(data)


# The jitted-program builders below are lru_cached so repeated calls
# (e.g. one per sampler step) reuse the compiled executable instead of
# re-tracing a fresh closure every time.  NOTE: the cache keys on
# ``pair_fn`` *identity* — pass a module-level function (or hold on to
# one closure), not a fresh lambda per call, to get cache hits.  The
# maxsize bounds retained executables/Mesh references.


@functools.lru_cache(maxsize=64)
def _all_pairs_jitted(pair_fn, mesh, axis, include_self, treedef):
    n = mesh.shape[axis]

    def local(my):
        def fold(r, acc, travelling):
            term = pair_fn(my, travelling)
            return acc + jnp.where(
                jnp.logical_or(include_self, r > 0), term, 0.0
            )

        def body(r, carry):
            acc, travelling = carry
            acc = fold(r, acc, travelling)
            return acc, ring_shift(travelling, axis, n)

        acc0 = _mark_varying(jnp.zeros(()), axis)
        # n-1 shift-and-fold steps, then fold the final block without
        # the (dead) last ring shift — n folds, n-1 ICI transfers.
        acc, travelling = lax.fori_loop(0, n - 1, body, (acc0, my))
        acc = fold(n - 1, acc, travelling)
        return lax.psum(acc, axis)

    specs = jax.tree_util.tree_unflatten(
        treedef, [P(axis)] * treedef.num_leaves
    )
    return jax.jit(
        shard_map(local, mesh=mesh, in_specs=(specs,), out_specs=P())
    )


def _online_softmax_block(q, k, v, m, l, o, valid_mask):
    """One incoming (k, v) block's contribution, flash-attention style.

    ``q``: (Tq, d); ``k``/``v``: (Tk, d); running max ``m`` (Tq,),
    normalizer ``l`` (Tq,), output accumulator ``o`` (Tq, d).
    ``valid_mask`` (Tq, Tk) — True where attention is allowed.
    """
    d = q.shape[-1]
    s = (q @ k.T) / jnp.sqrt(jnp.asarray(d, q.dtype))
    s = jnp.where(valid_mask, s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # exp(-inf - -inf) guard: rows with no valid key yet keep m=-inf.
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    p = jnp.where(valid_mask, jnp.exp(s - safe_m[:, None]), 0.0)
    l_new = alpha * l + jnp.sum(p, axis=-1)
    o_new = alpha[:, None] * o + p.astype(v.dtype) @ v
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = SEQ_AXIS,
    causal: bool = False,
) -> jax.Array:
    """Exact attention over a sequence sharded along ``axis``.

    ``q, k, v``: shape ``(T, d)`` global, partitioned on ``T``.  Key/value
    blocks circulate the ring; each device folds every incoming block
    into a running (max, normalizer, accumulator) triple — the blockwise
    online softmax — so no device ever materializes the full ``T×T``
    score matrix or the full K/V.  Compute per step is a ``(Tb, d) @
    (d, Tb)`` matmul (MXU-shaped); communication is the K/V block on ICI,
    overlapped with compute by XLA's latency-hiding scheduler.

    Numerically exact (same result as full softmax attention), and
    differentiable — the VJP of ``ppermute`` is the reverse ring, which
    XLA derives automatically.

    For multi-head / batched attention, ``jax.vmap`` this function over
    the leading axes.
    """
    n = mesh.shape[axis]
    if q.shape[0] % n != 0:
        raise ValueError(f"sequence length {q.shape[0]} not divisible by {n}")
    return _ring_attention_jitted(mesh, axis, causal)(q, k, v)


@functools.lru_cache(maxsize=64)
def _ring_attention_jitted(mesh, axis, causal):
    n = mesh.shape[axis]

    def local(q_local, k_local, v_local):
        idx = lax.axis_index(axis)
        tb = q_local.shape[0]
        q_pos = idx * tb + jnp.arange(tb)

        def fold(r, m, l, o, kb, vb):
            # After r ring steps, this device holds block (idx - r) mod n.
            src = (idx - r) % n
            k_pos = src * tb + jnp.arange(tb)
            if causal:
                valid = q_pos[:, None] >= k_pos[None, :]
            else:
                valid = jnp.ones((tb, tb), dtype=bool)
            return _online_softmax_block(q_local, kb, vb, m, l, o, valid)

        m0 = _mark_varying(jnp.full((tb,), -jnp.inf, dtype=q_local.dtype), axis)
        l0 = _mark_varying(jnp.zeros((tb,), dtype=q_local.dtype), axis)
        o0 = jnp.zeros_like(q_local)

        def body(r, carry):
            m, l, o, kb, vb = carry
            m, l, o = fold(r, m, l, o, kb, vb)
            kb, vb = ring_shift((kb, vb), axis, n)
            return m, l, o, kb, vb

        # n-1 fold+shift steps, then the final fold with no trailing
        # (dead) ring shift — n folds, n-1 K/V block transfers on ICI.
        m, l, o, kb, vb = lax.fori_loop(
            0, n - 1, body, (m0, l0, o0, k_local, v_local)
        )
        m, l, o = fold(n - 1, m, l, o, kb, vb)
        return o / jnp.maximum(l, jnp.finfo(l.dtype).tiny)[:, None]

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis)),
            out_specs=P(axis),
        )
    )
