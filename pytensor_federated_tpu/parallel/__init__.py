"""Mesh, sharding, packing, and collective layer (reference L2 analog)."""

from .mesh import (
    CHAINS_AXIS,
    SEQ_AXIS,
    SHARDS_AXIS,
    DeviceLoad,
    get_load,
    healthy_devices,
    make_mesh,
    single_device_mesh,
)
from .federated import (
    fedavg,
    federated_broadcast,
    federated_map,
    federated_mean,
    federated_sum,
)
from .multihost import (
    HeartbeatServer,
    detect_dead_peers,
    initialize_multihost,
    make_multihost_mesh,
    probe_peer,
    remesh_after_failure,
)
from .packing import ShardedData, pack_shards
from .ring import (
    ring_all_pairs_sum,
    ring_attention,
    ring_shift,
    seq_sharded_markov_logp,
    shift_right_across_shards,
)
from .expert import EXPERTS_AXIS, ExpertShardedMixture
from .sharded import FederatedLogp, sharded_compute
from .tensor import TP_AXIS, TensorParallelLogistic
from .ulysses import heads_to_seq, seq_to_heads, ulysses_attention
from .zero import ScatteredGrads, ZeroShardedLogpGrad

__all__ = [
    "CHAINS_AXIS",
    "SEQ_AXIS",
    "SHARDS_AXIS",
    "DeviceLoad",
    "FederatedLogp",
    "ScatteredGrads",
    "ShardedData",
    "ZeroShardedLogpGrad",
    "ring_all_pairs_sum",
    "ring_attention",
    "ring_shift",
    "seq_sharded_markov_logp",
    "shift_right_across_shards",
    "heads_to_seq",
    "seq_to_heads",
    "ulysses_attention",
    "EXPERTS_AXIS",
    "ExpertShardedMixture",
    "TP_AXIS",
    "TensorParallelLogistic",
    "fedavg",
    "federated_broadcast",
    "federated_map",
    "federated_mean",
    "federated_sum",
    "get_load",
    "healthy_devices",
    "HeartbeatServer",
    "detect_dead_peers",
    "probe_peer",
    "initialize_multihost",
    "make_mesh",
    "make_multihost_mesh",
    "remesh_after_failure",
    "pack_shards",
    "sharded_compute",
    "single_device_mesh",
]
