"""All-to-all (Ulysses-style) sequence parallelism.

The second of the two standard sequence/context-parallel schemes (the
first, ring attention, lives in :mod:`.ring`).  Net-new relative to the
reference, which has no sequence axis at all (SURVEY §5: "long context /
sequence parallelism: N/A"); together the two modules make long
sequences a first-class scale axis of this framework.

Scheme: ``q, k, v`` of global shape ``(T, H, d)`` arrive sharded along
the sequence axis (each device holds ``(T/n, H, d)``).  One
``lax.all_to_all`` re-shards them to *head* sharding — every device now
holds the FULL sequence for ``H/n`` heads — so each head's attention is
an ordinary dense (or blockwise) local computation with no further
communication.  A second ``all_to_all`` moves the output back to
sequence sharding.

Trade-off vs. ring attention (when to use which):

- **Communication**: Ulysses does 2 all-to-alls moving ``O(T·H·d / n)``
  per device regardless of ring size; ring attention does ``n-1``
  neighbour hops moving the K/V block each step.  All-to-all rides the
  ICI torus in one fused collective and usually wins at moderate ``n``.
- **Memory**: Ulysses materializes per-head ``T×T`` scores locally (or
  needs a local flash kernel); ring attention never holds more than a
  ``(T/n)²`` block.  For very long ``T``, ring wins.
- **Constraint**: Ulysses needs ``H % n == 0`` (heads are the second
  shard axis); ring attention has no head-count constraint.

Both are exact (same numbers as dense softmax attention) and
differentiable — the VJP of ``all_to_all`` is the inverse ``all_to_all``,
which XLA derives automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import SEQ_AXIS


def seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """Re-shard ``(T/n, H, d)``-local (sequence-sharded) to
    ``(T, H/n, d)``-local (head-sharded) with one ``all_to_all``.

    Must be called inside ``shard_map`` over ``axis_name``.  Head chunk
    ``j`` of every device travels to device ``j``; received sequence
    blocks concatenate in source-device order, which is global sequence
    order because device ``i`` owns contiguous block ``i``.
    """
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0, tiled=True)


def heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """Inverse of :func:`seq_to_heads`: ``(T, H/n, d)``-local back to
    ``(T/n, H, d)``-local.  Heads concatenate in source-device order,
    restoring the global head order."""
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=1, tiled=True)


def _dense_heads_attention(q, k, v, *, causal: bool):
    """Per-head dense softmax attention; ``q, k, v``: (T, Hl, d)."""
    t = q.shape[0]
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    # (Hl, T, T) scores; heads moved to front for the matmul batch dim.
    s = jnp.einsum("thd,shd->hts", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask[None, :, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hts,shd->thd", p, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = SEQ_AXIS,
    causal: bool = False,
) -> jax.Array:
    """Exact multi-head attention over a sequence sharded along ``axis``.

    ``q, k, v``: global shape ``(T, H, d)``, partitioned on ``T``.
    Requires ``T % n == 0`` and ``H % n == 0`` for ``n`` devices on the
    axis.  Returns the attention output, same shape/sharding as ``q``.

    See the module docstring for the communication/memory trade-off
    against :func:`..ring.ring_attention` (which handles the
    single-head / head-count-indivisible cases).
    """
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
    n = mesh.shape[axis]
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    if q.ndim != 3:
        raise ValueError(f"expected (T, H, d) inputs, got shape {q.shape}")
    t, h = q.shape[0], q.shape[1]
    if t % n != 0:
        raise ValueError(f"sequence length {t} not divisible by {n} devices")
    if h % n != 0:
        raise ValueError(
            f"head count {h} not divisible by {n} devices "
            "(use ring_attention for head-count-indivisible layouts)"
        )
    return _ulysses_jitted(mesh, axis, causal)(q, k, v)


@functools.lru_cache(maxsize=64)
def _ulysses_jitted(mesh, axis, causal):
    def local(q_local, k_local, v_local):
        qh = seq_to_heads(q_local, axis)
        kh = seq_to_heads(k_local, axis)
        vh = seq_to_heads(v_local, axis)
        o = _dense_heads_attention(qh, kh, vh, causal=causal)
        return heads_to_seq(o, axis)

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(axis)),
            out_specs=P(axis),
        )
    )
