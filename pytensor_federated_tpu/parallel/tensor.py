"""Tensor parallelism: feature-sharded GLM evaluation.

The reference has no tensor-parallel concept (SURVEY.md §2: TP is
"not present — design fresh"); this is the TPU-native design for the
regime where the DESIGN MATRIX, not the observation count, is what
outgrows a device: ``X`` is ``(n, d)`` with huge ``d`` (genomics,
one-hot text, interaction expansions), so ``X`` and the coefficient
vector ``w`` are partitioned column-wise over a ``"tp"`` mesh axis and
the contraction ``X @ w`` runs as per-device partial matvecs that XLA
all-reduces over ICI.

Idiomatic-JAX recipe (scaling-book style): arrays carry
``NamedSharding``s and the computation is PLAIN ``jnp`` code under
``jit`` — GSPMD partitions the matmul and inserts the psum; there is
no shard_map here to maintain.  The tests pin the two facts that make
it real TP: the sharded build never materializes a full replica of
``X``, and the gradient w.r.t. ``w`` comes back SHARDED (each device
owns its coefficient block's gradient, ZeRO-style).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.linear import _normal_logpdf

TP_AXIS = "tp"

__all__ = ["TP_AXIS", "TensorParallelLogistic"]


class TensorParallelLogistic:
    """Bernoulli GLM with features (columns of ``X``, entries of ``w``)
    sharded over a mesh axis.

    Same posterior as
    :class:`~pytensor_federated_tpu.models.logistic.FederatedLogisticRegression`
    on a single un-split shard — the parallel axis here is the FEATURE
    dimension, complementary to the federated shard axis (rows).  Pass
    ``rows_axis`` to compose both on a 2-D mesh: ``X`` is then
    row-AND-column sharded ``P(rows_axis, axis)`` (each device holds
    one tile), ``y`` row-sharded, ``w`` column-sharded — GSPMD reduces
    the contraction over the ``tp`` axis and the loglik sum over both.
    """

    def __init__(
        self,
        X,
        y,
        *,
        mesh: Optional[Mesh] = None,
        axis: str = TP_AXIS,
        rows_axis: Optional[str] = None,
        prior_scale: float = 5.0,
    ):
        self.mesh = mesh
        self.axis = axis
        self.prior_scale = prior_scale
        X = jnp.asarray(X, jnp.float32)
        y = jnp.asarray(y, jnp.float32)
        self.n, self.d = X.shape
        if mesh is not None:
            k = mesh.shape[axis]
            if self.d % k != 0:
                raise ValueError(
                    f"d={self.d} not divisible by mesh axis {axis!r} "
                    f"of size {k}"
                )
            if rows_axis is not None and self.n % mesh.shape[rows_axis]:
                raise ValueError(
                    f"n={self.n} not divisible by mesh axis "
                    f"{rows_axis!r} of size {mesh.shape[rows_axis]}"
                )
            self._x_sharding = NamedSharding(mesh, P(rows_axis, axis))
            self._w_sharding = NamedSharding(mesh, P(axis))
            X = jax.device_put(X, self._x_sharding)
            y = jax.device_put(y, NamedSharding(mesh, P(rows_axis)))
        else:
            self._x_sharding = self._w_sharding = None
        self.X, self.y = X, y

        def logp(params):
            w, b = params["w"], params["b"]
            # GSPMD: per-device partial matvec over the column blocks,
            # all-reduced — the TP contraction.
            logits = self.X @ w + b
            ll = jnp.sum(y * logits - jnp.logaddexp(0.0, logits))
            lp = jnp.sum(_normal_logpdf(w, 0.0, prior_scale))
            lp += _normal_logpdf(b, 0.0, prior_scale)
            return ll + lp

        self._logp = jax.jit(logp)
        self._logp_and_grad = jax.jit(jax.value_and_grad(logp))

    def init_params(self) -> Any:
        w = jnp.zeros((self.d,))
        if self._w_sharding is not None:
            # The coefficient vector lives sharded from the start; its
            # gradient (and any optimizer state built from it) inherits
            # the sharding — each device owns d/k coefficients.
            w = jax.device_put(w, self._w_sharding)
        return {"w": w, "b": jnp.zeros(())}

    def logp(self, params: Any) -> jax.Array:
        return self._logp(params)

    def logp_and_grad(self, params: Any):
        return self._logp_and_grad(params)

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)


def generate_wide_logistic_data(
    n_obs: int = 256, n_features: int = 64, *, seed: int = 13
):
    """Wide-feature single-shard data for the TP regime."""
    rng = np.random.default_rng(seed)
    w = (rng.normal(size=n_features) / np.sqrt(n_features)).astype(
        np.float32
    )
    X = rng.normal(size=(n_obs, n_features)).astype(np.float32)
    logits = X @ w
    y = (rng.uniform(size=n_obs) < 1.0 / (1.0 + np.exp(-logits))).astype(
        np.float32
    )
    return X, y, w
