"""Multi-host (DCN) scaling and elastic mesh recovery.

The reference scales by adding worker processes and recovers from node
death by client-side failover to surviving servers (reference:
demo_node.py:98-108 pool; service.py:408-416 retry+rebalance).  The
TPU-native equivalents:

- **Scale-out**: one process per host, joined into a single logical
  device set via ``jax.distributed`` — collectives ride ICI inside a
  slice and DCN across hosts.  :func:`initialize_multihost` wraps the
  init; :func:`make_multihost_mesh` lays out a mesh whose *outer* axis
  spans hosts (DCN-friendly: only the reduction crosses DCN, exactly
  like the reference's sum of per-node replies crossing the network)
  while inner axes stay within a slice on ICI.
- **Elastic recovery**: the reference's per-call failover becomes mesh
  reconstruction — drop dead devices, rebuild the mesh at the largest
  size the surviving devices support, re-place the data, re-jit
  (SURVEY §7 step 5).  :func:`remesh_after_failure` implements the
  policy; re-placement is just constructing a new evaluator (host
  copies of shard data are the recovery source, like the reference's
  stateless nodes re-serving their static private data).
- **Failure detection**: the reference detects node death IN-BAND — a
  dropped gRPC stream raises ``StreamTerminatedError`` and the client
  rebalances (reference: service.py:407-416).  The mesh-level analog is
  :class:`HeartbeatServer` + :func:`detect_dead_peers`: every process
  answers a trivial TCP liveness probe, survivors poll their peers,
  and a peer that refuses N consecutive probes is declared dead — the
  verdict feeds ``remesh_after_failure(dead_process_ids=...)``.  (The
  ``jax.distributed`` coordination service has its own missed-heartbeat
  detector, but surfaces it by SHUTTING THE RUNTIME DOWN, and its
  client handle is private API — a framework-owned probe keeps
  detection observable and the survivor alive.)
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..telemetry import flightrec as _flightrec
from .mesh import SHARDS_AXIS, healthy_devices, make_mesh

_log = logging.getLogger("pytensor_federated_tpu")


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    *,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join this process into the multi-host runtime; returns process count.

    With no arguments, ``jax.distributed.initialize()`` auto-detects the
    cluster environment (TPU pod metadata / SLURM / coordinator env
    vars); if there is no cluster to join — or the launcher already
    initialized the runtime, or JAX is already in use single-host — the
    failure is swallowed and the current process count is returned.
    With *explicit* arguments a failure re-raises: the caller asked for
    a specific cluster, and must call this before any other JAX use
    (``jax.distributed.initialize`` has to run before the XLA backend
    comes up).  This replaces the reference's manual "start N servers on
    N ports, point the client at the list" bootstrap (reference:
    demo_node.py:111-134, demo_model.py:17).
    """
    explicit = coordinator_address is not None or num_processes is not None
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError) as e:
        if explicit:
            raise
        _log.debug("multihost auto-init skipped: %s", e)
    return jax.process_count()


def make_multihost_mesh(
    inner: Optional[Mapping[str, int]] = None,
    *,
    host_axis: str = SHARDS_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh whose leading ``host_axis`` spans hosts (DCN), inner axes ICI.

    Devices are ordered host-major (``process_index`` first), so
    positions along ``host_axis`` map to hosts: the psum over
    ``host_axis`` does one cross-host reduction — the exact traffic
    pattern of the reference's sum-of-node-replies, but over DCN
    collectives instead of gRPC.  ``inner`` axes (e.g. ``{"chains": 4}``)
    subdivide each host's local devices.  On a single host this
    degrades gracefully to a normal mesh with ``host_axis`` over all
    local devices (inner axes must then divide the device count).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    n_hosts = len({d.process_index for d in devices})
    inner = dict(inner or {})
    if host_axis in inner:
        raise ValueError(
            f"inner axes must not include the host axis {host_axis!r}"
        )
    inner_size = int(np.prod(list(inner.values()))) if inner else 1
    if len(devices) % inner_size != 0:
        raise ValueError(
            f"inner axes {inner} (size {inner_size}) do not divide "
            f"{len(devices)} devices"
        )
    outer = len(devices) // inner_size
    if n_hosts > 1 and outer % n_hosts != 0:
        raise ValueError(
            f"outer axis size {outer} not divisible by {n_hosts} hosts"
        )
    shape = {host_axis: outer, **inner}
    return make_mesh(shape, devices=devices)


class HeartbeatServer:
    """Answer peer liveness probes: one daemon thread, one TCP accept
    loop, replies ``alive:<process_index>:<pid>`` and closes.

    The in-band half of the mesh failure-detection story (module
    docstring): a process that dies — SIGKILL included — stops
    accepting, and its peers' :func:`detect_dead_peers` probes turn
    connection-refused within one kernel RST, no launcher or operator
    in the loop.  Start one per process, before the work loop:

        hb = HeartbeatServer(port=base_port + idx, process_index=idx)

    ``port=0`` picks a free port; a fixed convention like
    ``base + process_index`` needs no exchange at all.  The default
    bind is LOOPBACK: the reply leaks the pid and process identity, so
    answering liveness probes from arbitrary interfaces is an explicit
    deployment decision, not a default (same posture as the telemetry
    exporter, :mod:`...telemetry.export`).  A real multi-host mesh —
    where peers on OTHER hosts must reach the probe — opts in with
    ``allow_external=True`` (binds the given ``host``, default then
    ``"0.0.0.0"``); passing a non-loopback ``host`` without the opt-in
    raises.  NOTE when sharing the endpoint: with the wildcard bind,
    ``address[0]`` is ``"0.0.0.0"``, which is NOT routable from
    another host (a remote peer connecting to it reaches its own
    loopback) — share ``(this_host_ip, hb.port)``, pairing the port
    with an address peers can actually route to.

    ``process_index`` goes into the reply banner so probers can verify
    they reached the RIGHT peer (a recycled port after a supervisor
    restart must not impersonate the old incarnation).  It is a plain
    argument — deliberately NOT read via ``jax.process_index()``,
    which would force backend initialization from inside a liveness
    utility (and on a wedged PJRT plugin, hang it; CLAUDE.md).
    """

    def __init__(
        self,
        host: Optional[str] = None,
        port: int = 0,
        *,
        process_index: Optional[int] = None,
        allow_external: bool = False,
    ):
        if host is None:
            host = "0.0.0.0" if allow_external else "127.0.0.1"
        elif not allow_external and host not in (
            # AF_INET loopback spellings only ("::1" would pass the
            # guard and then fail at the IPv4 socket's bind with a
            # confusing address-family error).
            "127.0.0.1", "localhost",
        ):
            raise ValueError(
                f"refusing to bind heartbeat to {host!r} without "
                "allow_external=True — an externally routable liveness "
                "endpoint is an explicit deployment decision"
            )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self._sock.settimeout(0.25)  # lets the serve loop see _stop
        self._stop = threading.Event()
        idx = -1 if process_index is None else int(process_index)
        self._reply = f"alive:{idx}:{os.getpid()}".encode()
        self._thread = threading.Thread(
            target=self._serve, name="pftpu-heartbeat", daemon=True
        )
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        """The BOUND (host, port) — under the ``allow_external=True``
        wildcard bind the host is ``"0.0.0.0"``; see the class
        docstring before sharing it with remote peers."""
        host, port = self._sock.getsockname()[:2]
        return host, port

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed under us during stop()
            try:
                conn.sendall(self._reply)
            except OSError:
                pass  # prober vanished mid-reply: its problem, not ours
            finally:
                conn.close()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._sock.close()


def probe_peer(
    address: Tuple[str, int],
    *,
    timeout: float = 1.0,
    expect_process_index: Optional[int] = None,
) -> bool:
    """One liveness probe: connect, read the banner, verdict.

    With ``expect_process_index``, a banner that carries a DIFFERENT
    index fails the probe: an unrelated service (or another mesh's
    heartbeat) recycling the port must not impersonate the peer.  A
    banner index of -1 (server started without ``process_index``)
    cannot be identity-checked and is accepted on prefix alone.
    """
    try:
        with socket.create_connection(address, timeout=timeout) as s:
            s.settimeout(timeout)
            # Read to EOF: the server closes after its sendall, and a
            # single recv may deliver a PARTIAL banner (TCP gives no
            # message boundaries) — a truncated b"aliv" must not turn
            # into a false dead/wrong-identity verdict.
            chunks = []
            total = 0
            while total < 64:
                chunk = s.recv(64 - total)
                if not chunk:
                    break
                chunks.append(chunk)
                total += len(chunk)
            banner = b"".join(chunks)
    except OSError:
        return False
    if not banner.startswith(b"alive:"):
        return False
    if expect_process_index is None:
        return True
    try:
        idx = int(banner.split(b":")[1])
    except (IndexError, ValueError):
        return False
    return idx == -1 or idx == int(expect_process_index)


def detect_dead_peers(
    peers: Mapping[int, Tuple[str, int]],
    *,
    timeout: float = 1.0,
    retries: int = 3,
    retry_wait: float = 0.5,
) -> List[int]:
    """Probe each peer's :class:`HeartbeatServer` CONCURRENTLY; return
    the process ids that failed ``retries`` consecutive probes (or
    answered with the wrong identity).

    The reference's failure detection is in-band and per-call
    (StreamTerminatedError -> rebalance, reference service.py:407-416);
    here detection is an explicit poll because XLA collectives have no
    per-call error channel a survivor can observe — a dead peer just
    hangs the collective.  So the pattern is: probe BETWEEN collective
    steps, and only enter a collective with peers that answered.
    Retries absorb transient refusals (a peer mid-restart, a SYN
    dropped under load); one failed probe is suspicion, ``retries``
    failures are a verdict.  Peers are probed on separate threads so
    the sweep costs one worst-case peer, not the sum over dead peers
    — detection latency must not itself stall the step loop.
    """

    def verdict(item):
        pid, addr = item
        for attempt in range(retries):
            if probe_peer(
                addr, timeout=timeout, expect_process_index=pid
            ):
                return None
            if attempt + 1 < retries:
                time.sleep(retry_wait)
        _log.warning(
            "peer %d at %s:%d failed %d consecutive liveness probes: "
            "declaring dead",
            pid,
            addr[0],
            addr[1],
            retries,
        )
        # A death verdict is exactly the kind of pre-incident breadcrumb
        # the flight recorder exists for: the remesh/abort that follows
        # reads back to this moment.
        _flightrec.record(
            "mesh.peer_dead",
            peer=pid,
            addr=f"{addr[0]}:{addr[1]}",
            retries=retries,
        )
        return pid

    items = sorted(peers.items())
    if not items:
        return []
    if len(items) == 1:
        results = [verdict(items[0])]
    else:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=len(items)) as pool:
            results = list(pool.map(verdict, items))
    return [pid for pid in results if pid is not None]


def remesh_after_failure(
    mesh: Mesh,
    *,
    axis: Optional[str] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    dead_process_ids: Optional[Sequence[int]] = None,
) -> Mesh:
    """Rebuild a mesh over the devices that still respond.

    The TPU failover analog (reference: service.py:408-416 drops the
    dead connection and rebalances): probe ``mesh``'s devices (or the
    given candidate list), keep the healthy ones, and rebuild the same
    axis layout at the largest size they support — the ``axis``
    dimension shrinks, other axes keep their extent.  Raises if no
    healthy devices remain (parity with the reference's ``TimeoutError``
    when every server is dead, reference: service.py:257-260).

    The caller then re-places data and re-jits by constructing a new
    evaluator over the returned mesh — state lives on the host, so no
    migration is needed (the reference's nodes are stateless for the
    same reason).

    ``dead_process_ids`` carries a DETECTION verdict (from
    :func:`detect_dead_peers`): those processes' devices are dropped
    knowingly and silently.  Remaining non-addressable devices — other
    processes nobody declared dead — still get dropped (local-view
    recovery, below) but with a warning, because dropping a live peer's
    devices is only correct if that peer independently rebuilds its own
    side.

    Multi-process scope: recovery is LOCAL-VIEW.  A peer's devices are
    never addressable from this process, so on a mesh spanning several
    processes the rebuilt mesh keeps only THIS process's healthy
    devices — correct in the survivor-after-host-death scenario
    (tests/test_multihost_procs.py), but it means calling this on a
    fully healthy multi-process mesh also drops the other hosts; a
    warning is logged whenever non-addressable devices are discarded
    without a detection verdict.  Rebuilding a new multi-HOST mesh
    requires the surviving processes to agree out-of-band and re-run
    :func:`initialize_multihost` + :func:`make_multihost_mesh` with the
    new process set.
    """
    axis = axis or mesh.axis_names[0]
    candidates = (
        list(mesh.devices.flat) if devices is None else list(devices)
    )
    dead_set = set(dead_process_ids or ())
    candidates = [
        d for d in candidates if d.process_index not in dead_set
    ]
    n_remote = sum(
        1 for d in candidates if d.process_index != jax.process_index()
    )
    if n_remote:
        _log.warning(
            "remesh: dropping %d non-addressable device(s) from other "
            "processes NOT declared dead (local-view recovery; see "
            "remesh_after_failure docstring)",
            n_remote,
        )
    alive = healthy_devices(candidates)
    if not alive:
        raise TimeoutError("no healthy devices remain")
    other = {
        name: size for name, size in mesh.shape.items() if name != axis
    }
    other_size = int(np.prod(list(other.values()))) if other else 1
    new_axis_size = len(alive) // other_size
    if new_axis_size < 1:
        raise TimeoutError(
            f"{len(alive)} healthy devices cannot fill axes {other}"
        )
    if new_axis_size < mesh.shape[axis]:
        _log.warning(
            "remesh: axis %r shrinking %d -> %d after device failure",
            axis,
            mesh.shape[axis],
            new_axis_size,
        )
    # Preserve the original axis ORDER (it encodes the ICI/DCN layout —
    # make_multihost_mesh puts the host axis first on purpose).
    shape = {
        name: (new_axis_size if name == axis else size)
        for name, size in mesh.shape.items()
    }
    _flightrec.record(
        "mesh.remesh",
        axis=axis,
        old_size=mesh.shape[axis],
        new_size=new_axis_size,
        dead_process_ids=sorted(dead_set),
        n_alive=len(alive),
    )
    return make_mesh(shape, devices=alive)
