"""Multi-host (DCN) scaling and elastic mesh recovery.

The reference scales by adding worker processes and recovers from node
death by client-side failover to surviving servers (reference:
demo_node.py:98-108 pool; service.py:408-416 retry+rebalance).  The
TPU-native equivalents:

- **Scale-out**: one process per host, joined into a single logical
  device set via ``jax.distributed`` — collectives ride ICI inside a
  slice and DCN across hosts.  :func:`initialize_multihost` wraps the
  init; :func:`make_multihost_mesh` lays out a mesh whose *outer* axis
  spans hosts (DCN-friendly: only the reduction crosses DCN, exactly
  like the reference's sum of per-node replies crossing the network)
  while inner axes stay within a slice on ICI.
- **Elastic recovery**: the reference's per-call failover becomes mesh
  reconstruction — drop dead devices, rebuild the mesh at the largest
  size the surviving devices support, re-place the data, re-jit
  (SURVEY §7 step 5).  :func:`remesh_after_failure` implements the
  policy; re-placement is just constructing a new evaluator (host
  copies of shard data are the recovery source, like the reference's
  stateless nodes re-serving their static private data).
"""

from __future__ import annotations

import logging
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import SHARDS_AXIS, healthy_devices, make_mesh

_log = logging.getLogger("pytensor_federated_tpu")


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    *,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join this process into the multi-host runtime; returns process count.

    With no arguments, ``jax.distributed.initialize()`` auto-detects the
    cluster environment (TPU pod metadata / SLURM / coordinator env
    vars); if there is no cluster to join — or the launcher already
    initialized the runtime, or JAX is already in use single-host — the
    failure is swallowed and the current process count is returned.
    With *explicit* arguments a failure re-raises: the caller asked for
    a specific cluster, and must call this before any other JAX use
    (``jax.distributed.initialize`` has to run before the XLA backend
    comes up).  This replaces the reference's manual "start N servers on
    N ports, point the client at the list" bootstrap (reference:
    demo_node.py:111-134, demo_model.py:17).
    """
    explicit = coordinator_address is not None or num_processes is not None
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError) as e:
        if explicit:
            raise
        _log.debug("multihost auto-init skipped: %s", e)
    return jax.process_count()


def make_multihost_mesh(
    inner: Optional[Mapping[str, int]] = None,
    *,
    host_axis: str = SHARDS_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh whose leading ``host_axis`` spans hosts (DCN), inner axes ICI.

    Devices are ordered host-major (``process_index`` first), so
    positions along ``host_axis`` map to hosts: the psum over
    ``host_axis`` does one cross-host reduction — the exact traffic
    pattern of the reference's sum-of-node-replies, but over DCN
    collectives instead of gRPC.  ``inner`` axes (e.g. ``{"chains": 4}``)
    subdivide each host's local devices.  On a single host this
    degrades gracefully to a normal mesh with ``host_axis`` over all
    local devices (inner axes must then divide the device count).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    n_hosts = len({d.process_index for d in devices})
    inner = dict(inner or {})
    if host_axis in inner:
        raise ValueError(
            f"inner axes must not include the host axis {host_axis!r}"
        )
    inner_size = int(np.prod(list(inner.values()))) if inner else 1
    if len(devices) % inner_size != 0:
        raise ValueError(
            f"inner axes {inner} (size {inner_size}) do not divide "
            f"{len(devices)} devices"
        )
    outer = len(devices) // inner_size
    if n_hosts > 1 and outer % n_hosts != 0:
        raise ValueError(
            f"outer axis size {outer} not divisible by {n_hosts} hosts"
        )
    shape = {host_axis: outer, **inner}
    return make_mesh(shape, devices=devices)


def remesh_after_failure(
    mesh: Mesh,
    *,
    axis: Optional[str] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Rebuild a mesh over the devices that still respond.

    The TPU failover analog (reference: service.py:408-416 drops the
    dead connection and rebalances): probe ``mesh``'s devices (or the
    given candidate list), keep the healthy ones, and rebuild the same
    axis layout at the largest size they support — the ``axis``
    dimension shrinks, other axes keep their extent.  Raises if no
    healthy devices remain (parity with the reference's ``TimeoutError``
    when every server is dead, reference: service.py:257-260).

    The caller then re-places data and re-jits by constructing a new
    evaluator over the returned mesh — state lives on the host, so no
    migration is needed (the reference's nodes are stateless for the
    same reason).

    Multi-process scope: recovery is LOCAL-VIEW.  A peer's devices are
    never addressable from this process, so on a mesh spanning several
    processes the rebuilt mesh keeps only THIS process's healthy
    devices — correct in the survivor-after-host-death scenario
    (tests/test_multihost_procs.py), but it means calling this on a
    fully healthy multi-process mesh also drops the other hosts; a
    warning is logged whenever non-addressable devices are discarded.
    Rebuilding a new multi-HOST mesh requires the surviving processes
    to agree out-of-band and re-run :func:`initialize_multihost` +
    :func:`make_multihost_mesh` with the new process set.
    """
    axis = axis or mesh.axis_names[0]
    candidates = (
        list(mesh.devices.flat) if devices is None else list(devices)
    )
    n_remote = sum(
        1 for d in candidates if d.process_index != jax.process_index()
    )
    if n_remote:
        _log.warning(
            "remesh: dropping %d non-addressable device(s) from other "
            "processes (local-view recovery; see remesh_after_failure "
            "docstring)",
            n_remote,
        )
    alive = healthy_devices(candidates)
    if not alive:
        raise TimeoutError("no healthy devices remain")
    other = {
        name: size for name, size in mesh.shape.items() if name != axis
    }
    other_size = int(np.prod(list(other.values()))) if other else 1
    new_axis_size = len(alive) // other_size
    if new_axis_size < 1:
        raise TimeoutError(
            f"{len(alive)} healthy devices cannot fill axes {other}"
        )
    if new_axis_size < mesh.shape[axis]:
        _log.warning(
            "remesh: axis %r shrinking %d -> %d after device failure",
            axis,
            mesh.shape[axis],
            new_axis_size,
        )
    # Preserve the original axis ORDER (it encodes the ICI/DCN layout —
    # make_multihost_mesh puts the host axis first on purpose).
    shape = {
        name: (new_axis_size if name == axis else size)
        for name, size in mesh.shape.items()
    }
    return make_mesh(shape, devices=alive)
