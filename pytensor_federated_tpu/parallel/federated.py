"""Federated MapReduce API + federated averaging (FedAvg).

The reference frames everything as "arrays in -> arrays out per node,
summed by the driver's graph" (reference: README.md:27-35,
demo_model.py:34-36).  This module names that algebra directly, in the
style of DrJAX's MapReduce primitives (PAPERS.md): ``federated_map``
runs a function over every shard's private data, ``federated_sum`` /
``federated_mean`` reduce across shards, ``federated_broadcast``
replicates driver state.

Since the ``fed`` subsystem landed, these are thin wrappers over the
REAL JAX primitives in :mod:`pytensor_federated_tpu.fed.primitives`
(``fed_map_p`` / ``fed_sum_p`` / ``fed_broadcast_p``, with their own
JVP/transpose rules): single-device calls carry the primitives' dense
semantics, and ``mesh=`` routes through
:class:`~pytensor_federated_tpu.fed.MeshPlacement` — the same shard_map
/psum lowering, now shared with the pool and mixed placements.  The
public signatures are unchanged.

On top of them, :func:`fedavg` implements federated averaging
(McMahan et al.): per round, every shard takes ``local_steps`` SGD
steps from the broadcast global params on its own data, and the new
global params are the (weighted) mean of the local results.  The whole
optimization — all rounds, all shards — is ONE jitted ``lax.scan``;
shards advance in lockstep as a vmapped batch, so each local step is a
single batched gradient evaluation (MXU-friendly), and the reduction
rides ICI.  The reference could not express FedAvg at all (its nodes
only *evaluate*; training state never leaves the driver).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from .mesh import SHARDS_AXIS
from .sharded import sharded_compute


def federated_map(
    fn: Callable[[Any], Any],
    data: Any,
    *,
    mesh: Optional[Mesh] = None,
    axis: str = SHARDS_AXIS,
) -> Any:
    """Apply ``fn`` to every shard's data; outputs stacked along shards.

    ``fn(shard_data) -> pytree``.  The data-parallel "map" primitive:
    the TPU-native form of one RPC round over the node pool (reference:
    op_async.py:107-132 fans N calls out concurrently; here it is one
    SPMD program).  Binds :data:`fed.fed_map_p`; with ``mesh=`` the
    call lowers through :class:`fed.MeshPlacement` (shard_map + vmap,
    closure constants replicated and marked varying).
    """
    from .. import fed

    if mesh is None:
        return fed.fed_map(fn, data)
    placement = fed.MeshPlacement(mesh, axis=axis)
    return fed.program(lambda d: fed.fed_map(fn, d), placement)(data)


def federated_sum(values: Any) -> Any:
    """Reduce shard-stacked values (leading shards axis) by summation.

    Under a mesh the leading axis is device-sharded, so XLA lowers this
    to the psum collective — the driver-side "sum of potentials"
    (reference: demo_model.py:34-36) without a graph in the middle.
    Binds :data:`fed.fed_sum_p`, whose transpose is
    :func:`federated_broadcast` (the DrJAX identity).
    """
    from ..fed import fed_sum

    return fed_sum(values)


def federated_mean(values: Any, weights: Optional[jax.Array] = None) -> Any:
    """(Weighted) mean across shards of shard-stacked values.

    ``weights`` must have exactly one entry per shard; a wrong-length
    vector that merely broadcasts raises ``ValueError`` (it would
    silently weight the wrong axis).
    """
    from ..fed import fed_mean

    return fed_mean(values, weights)


def federated_broadcast(value: Any, n_shards: int) -> Any:
    """Replicate driver state to every shard (stacked along shards).
    Binds :data:`fed.fed_broadcast_p`, whose transpose is
    :func:`federated_sum` — the gradient of replicated state is the sum
    of shard cotangents."""
    from ..fed import fed_broadcast

    return fed_broadcast(value, n_shards)


def fedavg(
    local_loss_fn: Callable[[Any, Any], jax.Array],
    data: Any,
    init_params: Any,
    *,
    mesh: Optional[Mesh] = None,
    axis: str = SHARDS_AXIS,
    rounds: int = 50,
    local_steps: int = 5,
    learning_rate: float = 0.05,
    weights: Optional[jax.Array] = None,
) -> Tuple[Any, jax.Array]:
    """Federated averaging over shard-private data.

    ``local_loss_fn(params, shard_data) -> scalar`` is each node's
    private objective.  Returns ``(final_params, loss_history)`` where
    ``loss_history[r]`` is the weighted-mean local loss at the start of
    round ``r``.  ``weights`` (per shard, e.g. observation counts)
    default to uniform.

    Structure per round (all inside one scan step):
      broadcast global params -> vmapped ``local_steps`` SGD steps on
      every shard -> weighted-mean reduce of the local params.
    """
    leaves = jax.tree_util.tree_leaves(data)
    n_shards = int(leaves[0].shape[0])
    if weights is None:
        w = jnp.ones((n_shards,), jnp.float32) / n_shards
    else:
        w = jnp.asarray(weights, jnp.float32)
        w = w / jnp.sum(w)

    grad_fn = jax.grad(local_loss_fn)

    def local_train(params, shard_data):
        """One shard's round: local_steps of SGD from the global params."""

        def step(p, _):
            g = grad_fn(p, shard_data)
            p = jax.tree_util.tree_map(
                lambda a, b: a - learning_rate * b, p, g
            )
            return p, None

        loss0 = local_loss_fn(params, shard_data)
        params, _ = jax.lax.scan(step, params, None, length=local_steps)
        return params, loss0

    # Per-round shard work as one batched map (vmap inside, psum-shaped
    # reduce outside) — reuse the sharded evaluator machinery.
    round_map = sharded_compute(local_train, data, mesh=mesh, axis=axis)

    @jax.jit
    def run(params0):
        def round_step(params, _):
            local_params, losses = round_map(params)
            new_params = federated_mean(local_params, w)
            return new_params, jnp.sum(w * losses)

        return jax.lax.scan(round_step, params0, None, length=rounds)

    final, history = run(init_params)
    return final, history
