"""Pack heterogeneous per-shard datasets into one SPMD-friendly layout.

The reference's federated nodes each own private data of *arbitrary* size
(reference: demo_node.py:58-61 — every node draws its own dataset; the
wire format carries any shape, reference: npproto/utils.py:9-15).  SPMD
wants uniform static shapes, so "each node has different data" becomes
pad-to-max + mask (SURVEY §7 "hard parts").  The mask rides along as a
first-class array; likelihoods multiply by it so padded rows contribute
exactly zero to logp *and* grad.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class ShardedData:
    """Stacked per-shard data with a validity mask.

    ``data`` is a pytree whose leaves have shape ``(n_shards, max_len, ...)``;
    ``mask`` is ``(n_shards, max_len)`` float32 with 1.0 on real rows.
    """

    data: Any
    mask: jax.Array

    @property
    def n_shards(self) -> int:
        return int(self.mask.shape[0])

    @property
    def max_len(self) -> int:
        return int(self.mask.shape[1])

    def tree(self) -> Any:
        """The pytree handed to the sharded evaluator: (data, mask)."""
        return (self.data, self.mask)


def pack_shards(shards: Sequence[Any], *, pad_to_multiple: int = 1) -> ShardedData:
    """Stack a list of per-shard pytrees, padding the leading axis to max.

    Each element of ``shards`` is a pytree of arrays whose *leading* axis
    is that shard's number of observations (axes beyond the first must
    match across shards).  ``pad_to_multiple`` rounds the padded length up
    (e.g. to 8/128 multiples so downstream ops tile cleanly onto the VPU/MXU).
    """
    if not shards:
        raise ValueError("need at least one shard")
    treedef = jax.tree_util.tree_structure(shards[0])
    for s in shards[1:]:
        if jax.tree_util.tree_structure(s) != treedef:
            raise ValueError("all shards must share one pytree structure")

    lengths = []
    for s in shards:
        leaves = jax.tree_util.tree_leaves(s)
        ns = {np.shape(l)[0] for l in leaves}
        if len(ns) != 1:
            raise ValueError(
                f"leaves of one shard must share a leading axis, got {ns}"
            )
        lengths.append(ns.pop())
    max_len = max(lengths)
    if pad_to_multiple > 1:
        max_len = -(-max_len // pad_to_multiple) * pad_to_multiple

    def pad_leaf(*leaves):
        padded = []
        for l in leaves:
            l = np.asarray(l)
            pad = [(0, max_len - l.shape[0])] + [(0, 0)] * (l.ndim - 1)
            padded.append(np.pad(l, pad))
        return jnp.asarray(np.stack(padded))

    data = jax.tree_util.tree_map(lambda *ls: pad_leaf(*ls), *shards)
    mask = np.zeros((len(shards), max_len), dtype=np.float32)
    for i, n in enumerate(lengths):
        mask[i, :n] = 1.0
    return ShardedData(data=data, mask=jnp.asarray(mask))
