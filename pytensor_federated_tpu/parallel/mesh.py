"""Device mesh construction, axis conventions, and device health.

TPU-native replacement for the reference's server pool + placement layer.
The reference runs one OS process per port and load-balances clients onto
them (reference: demo_node.py:98-108, service.py:240-263); here "nodes" are
positions along a named mesh axis and placement is static SPMD.  The
``GetLoad`` control-plane RPC (reference: service.py:88-96, rpc.py:60-71)
maps to :func:`get_load` over live device memory statistics.

Axis conventions (all optional — models use what they need):

- ``"shards"``  : federated data shards (the reference's one scale axis).
- ``"chains"``  : independent MCMC chains (the reference's sampler-level
  parallelism, reference: test_wrapper_ops.py:305-317, runs chains in
  separate host processes; here chains are a mesh axis).
- ``"seq"``     : sequence/context parallelism for long-sequence
  likelihoods (net-new capability; absent from the reference, SURVEY §5).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

SHARDS_AXIS = "shards"
CHAINS_AXIS = "chains"
SEQ_AXIS = "seq"


def mark_varying(x, axis_name: str):
    """Mark a replicated pytree as device-varying over ``axis_name``.

    shard_map tracks which values vary across a mesh axis.  Two places
    need an explicit mark: (a) loop carries that *become* varying (e.g.
    accumulators fed by ppermute'd data) must start varying or the scan
    carry types mismatch; (b) replicated params that user code will
    ``jax.grad`` *inside* the body — an implicit pvary inserted inside
    the differentiated region transposes to a psum over the axis,
    silently summing all shards' gradients into each local result.
    """
    from jax import lax  # local import: keep mesh.py import-light

    def f(l):
        # Idempotent: pcast rejects varying->varying, so skip values
        # already varying over this axis.  (Under check_vma=False the
        # vma set stays empty and pcast is a harmless no-op.)  Real
        # errors — e.g. an axis name not bound by the enclosing
        # shard_map — still raise loudly.  jax.typeof is newer than the
        # pvary fallback below, so resolve it defensively.
        typeof = getattr(jax, "typeof", None)
        if typeof is not None and axis_name in getattr(
            typeof(l), "vma", frozenset()
        ):
            return l
        if hasattr(lax, "pcast"):
            return lax.pcast(l, axis_name, to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(l, axis_name)
        # Pre-vma jax: no varying-manual-axes tracking exists, so there
        # is nothing to mark — check_rep's rewrite machinery handles
        # replicated operands itself and the identity is correct.
        return l

    return jax.tree_util.tree_map(f, x)


def make_mesh(
    shape: Optional[Mapping[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a named device mesh.

    ``shape`` maps axis name -> size; by default a 1-D ``("shards",)``
    mesh over all visible devices.  This is the TPU analog of the
    reference's node pool: where the reference starts ``len(ports)``
    server processes (reference: demo_node.py:98-108), we lay the same
    logical nodes out along the ``"shards"`` axis of one SPMD program.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if shape is None:
        shape = {SHARDS_AXIS: len(devices)}
    names = tuple(shape.keys())
    sizes = tuple(int(shape[n]) for n in names)
    n_needed = int(np.prod(sizes)) if sizes else 1
    if n_needed > len(devices):
        raise ValueError(
            f"Mesh shape {dict(shape)} needs {n_needed} devices, "
            f"only {len(devices)} available."
        )
    dev_array = np.array(devices[:n_needed]).reshape(sizes)
    return Mesh(dev_array, names)


def single_device_mesh(axis: str = SHARDS_AXIS) -> Mesh:
    """A 1-device mesh — lets all sharded code paths run on one chip."""
    return make_mesh({axis: 1}, devices=[jax.devices()[0]])


@dataclasses.dataclass(frozen=True)
class DeviceLoad:
    """Health/load snapshot of one device.

    Parity with the reference's ``GetLoadResult`` (reference: rpc.py:60-71):
    ``n_clients`` -> ``n_live_buffers``, ``percent_cpu``/``percent_ram`` ->
    HBM utilization; plus device identity fields.
    """

    device_id: int
    platform: str
    process_index: int
    bytes_in_use: Optional[int]
    bytes_limit: Optional[int]

    @property
    def percent_hbm(self) -> Optional[float]:
        if self.bytes_in_use is None or not self.bytes_limit:
            return None
        return 100.0 * self.bytes_in_use / self.bytes_limit


def get_load(devices: Optional[Sequence[jax.Device]] = None) -> list[DeviceLoad]:
    """Load snapshot for every device.

    The reference polls each server's ``GetLoad`` RPC concurrently with a
    timeout and maps failures to ``None`` (reference: service.py:161-211);
    device liveness here is synchronous — an unhealthy device raises and
    is reported as a ``DeviceLoad`` with ``None`` stats.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    out = []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        out.append(
            DeviceLoad(
                device_id=d.id,
                platform=d.platform,
                process_index=d.process_index,
                bytes_in_use=stats.get("bytes_in_use"),
                bytes_limit=stats.get("bytes_limit"),
            )
        )
    return out


def healthy_devices(
    devices: Optional[Sequence[jax.Device]] = None,
) -> list[jax.Device]:
    """Devices this process can drive that respond to a trivial
    computation.

    The failover analog: the reference excludes unresponsive servers at
    connect time (reference: service.py:181-184, 257-260); on TPU, a dead
    device is excluded at mesh-construction time and the caller re-jits
    over the surviving mesh (SURVEY §7 step 5).

    Scope: the probe is LOCAL-VIEW by construction.  A peer process's
    devices are never addressable from here, so on a multi-process mesh
    they are filtered out whether that peer is alive or dead — this
    function answers "what can THIS process compute on right now", not
    "which hosts are up" (cross-host liveness needs out-of-band
    agreement; cf. the reference's per-server GetLoad probe,
    service.py:181-184, which is likewise a local client's view).
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    alive = []
    for d in devices:
        if d.process_index != jax.process_index():
            continue  # non-addressable: cannot be probed, let alone used
        try:
            x = jax.device_put(np.float32(1.0), d)
            if float(x) == 1.0:
                alive.append(d)
        except Exception:
            continue
    return alive
