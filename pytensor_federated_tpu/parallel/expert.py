"""Expert parallelism: mixture components sharded over a mesh axis.

The reference has no expert-parallel concept (SURVEY.md §2: EP is
"not present — design fresh").  The Bayesian analog of MoE expert
sharding is a mixture likelihood whose COMPONENT set outgrows a
device: each device owns a block of components (its "experts") and
evaluates their densities for every observation; the per-observation
mixture loglik is then a cross-device ``logsumexp`` — implemented as
the max-shift trick over collectives (``pmax`` for the shift, ``psum``
for the sum), with the shift under ``stop_gradient`` so the gradient
flows only through the (smooth) sum term, exactly as in the one-device
logsumexp identity.

Unlike token-routing MoE there is no all_to_all: every observation
"visits" every expert, but each device only ever materializes its own
component block — the memory/compute win EP exists for.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from .._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.linear import _normal_logpdf

EXPERTS_AXIS = "experts"

__all__ = ["EXPERTS_AXIS", "ExpertShardedMixture"]


def _local_terms(y, log_w_block, mu_block, log_sigma_block):
    """(n, K_local) component log-terms: log w_k + logN(y | mu_k, s_k)."""
    sigma = jnp.exp(log_sigma_block)
    return log_w_block[None, :] + _normal_logpdf(
        y[:, None], mu_block[None, :], sigma[None, :]
    )


class ExpertShardedMixture:
    """Gaussian mixture with components sharded over ``"experts"``.

    ``params``: ``mu`` (K,), ``log_sigma`` (K,), ``weight_logits``
    (K,) — each sharded ``P(axis)`` on a mesh, replicated otherwise.
    The softmax over component weights is itself a cross-device
    logsumexp (same max-shift construction).

    Free (unordered) means with Normal priors: label switching is the
    user's concern exactly as in
    :class:`~pytensor_federated_tpu.models.mixture.FederatedGaussianMixture`'s
    docstring discussion — this class is about the PARALLELISM of the
    component axis, and its logp equals the unsharded mixture's
    bit-for-bit modulo reduction order (equality-tested).
    """

    def __init__(
        self,
        y,
        n_components: int,
        *,
        mesh: Optional[Mesh] = None,
        axis: str = EXPERTS_AXIS,
        prior_scale: float = 3.0,
    ):
        self.mesh = mesh
        self.axis = axis
        self.k = int(n_components)
        self.prior_scale = prior_scale
        y = jnp.asarray(y, jnp.float32)
        self.y = y

        if mesh is not None:
            n_dev = mesh.shape[axis]
            if self.k % n_dev != 0:
                raise ValueError(
                    f"n_components={self.k} not divisible by mesh axis "
                    f"{axis!r} of size {n_dev}"
                )
            self._p_sharding = NamedSharding(mesh, P(axis))

            def loglik(params):
                def _axis_max(local):
                    # Cross-device max for the logsumexp SHIFT.  pmax
                    # has no differentiation rule (even stop_gradient
                    # still traces its JVP), so gather the per-device
                    # maxes — the shift is gradient-neutral anyway and
                    # stop_gradient makes that explicit.
                    return jax.lax.stop_gradient(
                        jnp.max(jax.lax.all_gather(local, axis), axis=0)
                    )

                def body(y_rep, mu_b, ls_b, wl_b):
                    # log-softmax over ALL experts, computed blockwise:
                    # a cross-device logsumexp of the weight logits.
                    m_w = _axis_max(jnp.max(wl_b))
                    z = jax.lax.psum(jnp.sum(jnp.exp(wl_b - m_w)), axis)
                    log_w_b = wl_b - m_w - jnp.log(z)
                    t = _local_terms(y_rep, log_w_b, mu_b, ls_b)
                    m = _axis_max(jnp.max(t, axis=1))
                    s = jax.lax.psum(
                        jnp.sum(jnp.exp(t - m[:, None]), axis=1), axis
                    )
                    return jnp.sum(m + jnp.log(s))

                fn = shard_map(
                    body,
                    mesh=mesh,
                    in_specs=(P(), P(axis), P(axis), P(axis)),
                    out_specs=P(),
                    check_vma=False,
                )
                return fn(
                    y,
                    params["mu"],
                    params["log_sigma"],
                    params["weight_logits"],
                )

        else:

            def loglik(params):
                log_w = jax.nn.log_softmax(params["weight_logits"])
                t = _local_terms(
                    y, log_w, params["mu"], params["log_sigma"]
                )
                return jnp.sum(jax.scipy.special.logsumexp(t, axis=1))

        self._loglik = loglik

    def prior_logp(self, params: Any) -> jax.Array:
        lp = jnp.sum(_normal_logpdf(params["mu"], 0.0, self.prior_scale))
        lp += jnp.sum(_normal_logpdf(params["log_sigma"], 0.0, 1.0))
        lp += jnp.sum(_normal_logpdf(params["weight_logits"], 0.0, 1.0))
        return lp

    def logp(self, params: Any) -> jax.Array:
        return self.prior_logp(params) + self._loglik(params)

    def logp_and_grad(self, params: Any):
        return jax.value_and_grad(self.logp)(params)

    def init_params(self) -> Any:
        # Spread initial means over the data range so components
        # separate; deterministic (no RNG) for reproducible tests.
        lo = float(jnp.min(self.y))
        hi = float(jnp.max(self.y))
        mu = jnp.linspace(lo, hi, self.k)
        params = {
            "mu": mu,
            "log_sigma": jnp.zeros((self.k,)),
            "weight_logits": jnp.zeros((self.k,)),
        }
        if self.mesh is not None:
            params = {
                k: jax.device_put(v, self._p_sharding)
                for k, v in params.items()
            }
        return params

    def find_map(self, **kwargs):
        from ..samplers import find_map

        return find_map(self.logp, self.init_params(), **kwargs)


def generate_expert_mixture_data(
    n_obs: int = 512,
    mus=(-4.0, -1.0, 1.5, 4.0),
    sigmas=(0.5, 0.4, 0.6, 0.5),
    *,
    seed: int = 23,
):
    rng = np.random.default_rng(seed)
    mus = np.asarray(mus)
    sigmas = np.asarray(sigmas)
    z = rng.integers(0, mus.size, size=n_obs)
    y = (mus[z] + sigmas[z] * rng.normal(size=n_obs)).astype(np.float32)
    return y, {"mu": mus, "sigma": sigmas}
