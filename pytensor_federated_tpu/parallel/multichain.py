"""Chain-parallel federated sampling over a 2-D device mesh.

The reference's two outer parallelism axes — PyMC chains in separate
host processes (reference: test_wrapper_ops.py:305-317, ``cores=4``) and
federated shards behind gRPC (reference: demo_model.py:33-36) — become
the two axes of one device mesh:

    mesh = make_mesh({"chains": C, "shards": S})

One ``shard_map`` spans both axes: chain state is partitioned over
``"chains"`` and replicated over ``"shards"``; shard data is partitioned
over ``"shards"`` and replicated over ``"chains"``.  Inside, each chain
row runs an independent NUTS/HMC transition whose logp+grad reduces over
``"shards"`` with ``lax.psum`` — so the collective rides ICI within a
row, and chains never communicate at all.  Every device executes the
same program (SPMD); per-row control flow stays in lockstep because all
row members see identical psum results.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..samplers.hmc import HMCState, hmc_init, hmc_step
from ..samplers.nuts import nuts_step
from .mesh import CHAINS_AXIS, SHARDS_AXIS
from .sharded import _leading_dim, _shard_data_to_mesh


def multichain_sample(
    per_shard_logp: Callable[[Any, Any], jax.Array],
    data: Any,
    init_params: Any,
    *,
    mesh: Mesh,
    key: jax.Array,
    num_samples: int = 100,
    num_warmup: int = 0,
    step_size: float = 0.1,
    kernel: str = "nuts",
    max_depth: int = 6,
    num_hmc_steps: int = 16,
    target_accept: float = 0.8,
    dense_mass: bool = False,
    prior_logp: Optional[Callable[[Any], jax.Array]] = None,
    chains_axis: str = CHAINS_AXIS,
    shards_axis: str = SHARDS_AXIS,
    jitter: float = 0.5,
):
    """Run C independent chains over S-sharded data in ONE SPMD program.

    ``init_params`` is a single params pytree; each chain starts from a
    jittered copy.  Returns ``(draws, accept, unravel)`` where ``draws``
    has shape ``(chains, num_samples, dim)`` (flat parameter vectors).

    ``num_warmup > 0`` runs the same Stan-style warmup as
    :func:`pytensor_federated_tpu.samplers.sample` (dual-averaged step
    size + diagonal mass) per chain, INSIDE the shard_map: the
    adaptation statistics are per-chain (no cross-chain traffic), and
    every rank of a chain row sees bit-identical deterministic-sum
    logp values, so the data-dependent warmup loops stay in lockstep
    exactly like the NUTS tree itself.  ``dense_mass=True`` adapts the
    full covariance (see ``samplers.sample``).  With ``num_warmup=0``
    the given ``step_size`` and a unit mass are used as before.

    This is the scale path — for single-host convenience sampling use
    :func:`pytensor_federated_tpu.samplers.sample` (vmap chains).
    """
    if kernel not in ("nuts", "hmc"):
        raise ValueError(f"unknown kernel {kernel!r}")
    n_chains = mesh.shape[chains_axis]
    flat0, unravel = ravel_pytree(init_params)
    dim = flat0.shape[0]
    dtype = flat0.dtype

    k_init, k_run = jax.random.split(key)
    init_flat = flat0 + jitter * jax.random.normal(
        k_init, (n_chains, dim), dtype
    )
    chain_keys = jax.random.split(k_run, n_chains)

    n_shards = _leading_dim(data)
    if n_shards % mesh.shape[shards_axis] != 0:
        raise ValueError(
            f"n_shards={n_shards} not divisible by mesh axis "
            f"{shards_axis!r} of size {mesh.shape[shards_axis]}"
        )
    placed = _shard_data_to_mesh(data, mesh, shards_axis)
    data_specs = jax.tree_util.tree_map(lambda _: P(shards_axis), placed)

    def _det_allsum(t):
        """Deterministic cross-shard sum: all_gather + fixed-order local sum.

        Two reasons this is NOT a plain ``lax.psum``:
        (1) gradients: total grad = sum of per-rank local grads, computed
        explicitly rather than relying on collective transposes inside
        ``shard_map``; (2) *bitwise determinism across ranks* — NUTS's
        tree-doubling ``while_loop`` is data-dependent, so every rank in
        a chain row must take identical branches or the row's next
        collective deadlocks.  All-reduce implementations may reduce in
        rank-dependent order; gathering and summing locally in a fixed
        order makes every rank's result bit-identical.
        """
        return jnp.sum(jax.lax.all_gather(t, shards_axis), axis=0)

    def local_logp_and_grad(x, local_data):
        """logp+grad of one chain: local value_and_grad over this rank's
        shard block, then a deterministic sum over the shards axis."""

        def local_lp(x):
            params = unravel(x)
            lp = jax.vmap(lambda d: per_shard_logp(params, d))(local_data)
            return jnp.sum(lp)

        lv, lg = jax.value_and_grad(local_lp)(x)
        v = _det_allsum(lv)
        g = _det_allsum(lg)
        if prior_logp is not None:
            pv, pg = jax.value_and_grad(lambda x: prior_logp(unravel(x)))(x)
            v = v + pv
            g = g + pg
        return v, g

    inv_mass0 = jnp.ones((dim,), dtype)

    def chain_block(x0_block, keys_block, local_data):
        """Runs this device's chains (block of the chains axis)."""

        def one_chain(x0, key):
            lg = lambda x: local_logp_and_grad(x, local_data)

            def kernel_step(state, key, *, step_size, inv_mass):
                if kernel == "nuts":
                    return nuts_step(
                        lg,
                        state,
                        key,
                        step_size=step_size,
                        inv_mass=inv_mass,
                        max_depth=max_depth,
                    )
                return hmc_step(
                    lg,
                    state,
                    key,
                    step_size=step_size,
                    inv_mass=inv_mass,
                    num_steps=num_hmc_steps,
                )

            if num_warmup > 0:
                from ..samplers.mcmc import _warmup

                k_warm, key = jax.random.split(key)
                warm = _warmup(
                    lg,
                    x0,
                    k_warm,
                    num_warmup=num_warmup,
                    kernel_step=kernel_step,
                    target_accept=target_accept,
                    dense_mass=dense_mass,
                )
                state = warm.state
                eps, inv_mass = warm.step_size, warm.inv_mass
            else:
                state = hmc_init(lg, x0)
                eps, inv_mass = step_size, inv_mass0

            def body(state, key):
                state, info = kernel_step(
                    state, key, step_size=eps, inv_mass=inv_mass
                )
                return state, (state.x, info.accept_prob)

            keys = jax.random.split(key, num_samples)
            _, (draws, accept) = jax.lax.scan(body, state, keys)
            return draws, accept

        return jax.vmap(one_chain)(x0_block, keys_block)

    fn = shard_map(
        chain_block,
        mesh=mesh,
        in_specs=(P(chains_axis), P(chains_axis), data_specs),
        out_specs=(P(chains_axis), P(chains_axis)),
        check_vma=False,
    )

    # Chain state enters sharded over chains, replicated over shards.
    init_flat = jax.device_put(init_flat, NamedSharding(mesh, P(chains_axis)))
    chain_keys = jax.device_put(chain_keys, NamedSharding(mesh, P(chains_axis)))
    draws, accept = jax.jit(fn)(init_flat, chain_keys, placed)
    return draws, accept, unravel
