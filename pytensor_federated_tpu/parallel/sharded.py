"""The sharded evaluator — this framework's replacement for the gRPC core.

In the reference, evaluating the federated log-likelihood means N
concurrent network round-trips: encode arrays, HTTP/2 to each node, the
node runs its compiled function, reply, decode, and the driver's graph
sums the per-node logps (reference: service.py:150-158 hot loop;
op_async.py:107-132 fan-out; demo_model.py:34-36 sum-of-potentials).

Here the entire exchange collapses into ONE XLA program: per-shard data
lives device-resident along a mesh axis, the per-shard logp runs as SPMD
under ``shard_map``, and the sum-of-potentials is a ``lax.psum`` over ICI.
Gradients come from ``jax.value_and_grad`` *through* the collective (psum
transposes to psum), so logp+grad is a single fused executable — zero
serialization, zero gRPC (BASELINE.json north star).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from .._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import SHARDS_AXIS, mark_varying as _mark_varying

# per_shard_logp(params, shard_data) -> scalar logp contribution of one shard.
PerShardLogpFn = Callable[[Any, Any], jax.Array]
# per_shard_fn(params, shard_data) -> pytree of per-shard outputs.
PerShardComputeFn = Callable[[Any, Any], Any]


def _leading_dim(data: Any) -> int:
    leaves = jax.tree_util.tree_leaves(data)
    if not leaves:
        raise ValueError("data pytree has no leaves")
    dims = {jnp.shape(l)[0] for l in leaves}
    if len(dims) != 1:
        raise ValueError(f"all data leaves must share a leading shard axis, got {dims}")
    return dims.pop()


def _shard_data_to_mesh(data: Any, mesh: Mesh, axis: str) -> Any:
    """Place the stacked data pytree with its leading axis split over ``axis``.

    This is the moment the reference ships private datasets to node
    processes (reference: demo_node.py:58-61); here it is a one-time
    host->device layout, after which data never moves again.
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda l: jax.device_put(l, sharding), data)


class NoFederatedShards:
    """Sentinel for models built without a federated shard axis.

    Assigned to ``model.fed`` when a construction option (e.g.
    ``flatten=True``) collapses the shard axis, so that any attempt to
    use a ``.fed``-dependent API (``logp_minibatch``, mesh placement,
    the doubly-stochastic ADVI hook) fails with a targeted message
    instead of an opaque ``AttributeError`` on ``None``.
    """

    def __init__(self, reason: str):
        self._reason = reason

    def __bool__(self) -> bool:
        return False

    def __getattr__(self, name: str):
        raise AttributeError(
            f"this model has no federated shard axis ({self._reason}); "
            f"'.fed.{name}' is unavailable — construct the model without "
            "that option to use federated/minibatch/mesh APIs"
        )


class FederatedLogp:
    """Sharded log-potential: ``logp(params) = Σ_shards per_shard_logp``.

    The TPU-native ``ArraysToArraysService`` + ``LogpGradServiceClient``
    + ``ParallelAsyncOp`` stack in one object (reference: service.py:75-115,
    common.py:105-161, op_async.py:68-132):

    - each "node" is a slot along ``axis`` on the mesh;
    - ``logp`` / ``logp_and_grad`` are jitted SPMD executables;
    - aggregation is ``lax.psum`` over ICI, not a sum of RPC replies.

    ``data`` is a pytree whose leaves carry a leading ``n_shards`` axis
    (build heterogeneous shards with :func:`..parallel.packing.pack_shards`).
    ``n_shards`` may exceed the mesh axis size: each device then vmaps over
    its local block of shards — large, batched, MXU-friendly.

    With ``mesh=None`` the same model runs single-device (vmap + sum),
    which is also the fastest single-chip layout.

    ``remat=True`` wraps the per-shard logp in ``jax.checkpoint``: the
    backward pass recomputes shard activations instead of holding them
    in HBM — the standard TPU trade of MXU FLOPs for HBM residency when
    shards are large.

    Unlike the reference's federated boundary — which hard-rejects
    gradients of its gradient outputs (reference: wrapper_ops.py:123-125),
    so no second-order autodiff crosses it — this evaluator is a pure
    JAX function of ``params``: ``jax.hessian`` / HVPs differentiate
    straight through the vmap, ``shard_map``, and psum (tested in
    test_sharded.py).  The forward-supplied-gradient ops keep the
    reference's one-order contract (see ops/ops.py:LogpGradOp).
    """

    def __init__(
        self,
        per_shard_logp: PerShardLogpFn,
        data: Any,
        *,
        mesh: Optional[Mesh] = None,
        axis: str = SHARDS_AXIS,
        remat: bool = False,
    ):
        if remat:
            per_shard_logp = jax.checkpoint(per_shard_logp)
        self.per_shard_logp = per_shard_logp
        self.axis = axis
        self.mesh = mesh
        self.n_shards = _leading_dim(data)

        if mesh is not None:
            if axis not in mesh.axis_names:
                raise ValueError(f"mesh has no axis {axis!r}: {mesh.axis_names}")
            axis_size = mesh.shape[axis]
            if self.n_shards % axis_size != 0:
                raise ValueError(
                    f"n_shards={self.n_shards} not divisible by mesh axis "
                    f"{axis!r} of size {axis_size}"
                )
            self.data = _shard_data_to_mesh(data, mesh, axis)

            # Stored once: the minibatch path reuses the same specs, so
            # a future layout change can't silently diverge between the
            # full and subsampled evaluators.
            self._data_specs = jax.tree_util.tree_map(
                lambda _: P(axis), self.data
            )
            data_specs = self._data_specs

            def total_logp(params, data):
                def local(params, local_data):
                    # local_data: this device's block of shards.
                    lp = jax.vmap(lambda d: self.per_shard_logp(params, d))(
                        local_data
                    )
                    return jax.lax.psum(jnp.sum(lp), axis)

                return shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(
                        jax.tree_util.tree_map(lambda _: P(), params),
                        data_specs,
                    ),
                    out_specs=P(),
                )(params, data)

        else:
            self.data = data

            def total_logp(params, data):
                lp = jax.vmap(lambda d: self.per_shard_logp(params, d))(data)
                return jnp.sum(lp)

        self._total_logp = total_logp
        # Data is a jit ARGUMENT, not a closure constant: its sharding
        # flows in with the array (zero-copy — it is already placed),
        # and multi-process meshes REQUIRE it (closing over an array
        # spanning non-addressable devices is an error; exercised by
        # tests/test_multihost_procs.py).
        self._logp = jax.jit(total_logp)
        self._logp_and_grad = jax.jit(jax.value_and_grad(total_logp))

    # -- the public evaluation surface (reference: common.py:52-161) --

    def logp(self, params: Any) -> jax.Array:
        """Scalar total log-potential (``LogpServiceClient.evaluate`` analog)."""
        return self._logp(params, self.data)

    def logp_and_grad(self, params: Any):
        """(logp, grads) in one fused executable
        (``LogpGradServiceClient.evaluate`` analog, reference: common.py:134-155)."""
        return self._logp_and_grad(params, self.data)

    __call__ = logp

    def logp_batch(self, params_batch: Any) -> jax.Array:
        """Evaluate B parameter sets in ONE program: leaves carry a
        leading batch axis; returns ``(B,)`` logps.

        The reference serves many concurrent clients by multiplexing
        streams over the connection pool (reference: service.py:104-112,
        test_service.py:180-224); on-mesh the same fan-in is a vmap over
        the parameter batch — one executable, MXU-batched.  (The SMC and
        ensemble samplers batch the same way over their own flattened
        evaluators; this method is the public entry for user-driven
        particle/population sweeps.)
        """
        fn = getattr(self, "_logp_batch", None)
        if fn is None:
            fn = jax.jit(
                jax.vmap(self._total_logp, in_axes=(0, None))
            )
            self._logp_batch = fn
        return fn(params_batch, self.data)

    def logp_minibatch(
        self, params: Any, key: jax.Array, num_shards: int
    ) -> jax.Array:
        """Unbiased minibatch estimate of :meth:`logp` from a random
        subset of ``num_shards`` shards (scaled by ``S/k``).

        The subsample is a *gather*, not a mask, so compute really
        drops to ``k/S`` of the full pass — the federated-scale analog
        of data subsampling for stochastic-gradient samplers (see
        ``samplers.sgld``).  On a mesh each device subsamples its own
        local block (``num_shards`` must be divisible by the axis
        size), so no shard data ever moves between devices.
        """
        return self._minibatch_fns(num_shards)[0](params, key)

    def logp_and_grad_minibatch(
        self, params: Any, key: jax.Array, num_shards: int
    ):
        """(estimate, grad-estimate) of the minibatch logp — the
        stochastic gradient for SGLD/SGHMC-style samplers."""
        return self._minibatch_fns(num_shards)[1](params, key)

    def _minibatch_fns(self, num_shards: int):
        cache = getattr(self, "_minibatch_cache", None)
        if cache is None:
            cache = self._minibatch_cache = {}
        if num_shards in cache:
            return cache[num_shards]
        if not (0 < num_shards <= self.n_shards):
            raise ValueError(
                f"num_shards must be in 1..{self.n_shards}, got {num_shards}"
            )
        scale = self.n_shards / num_shards

        if self.mesh is not None:
            axis, mesh = self.axis, self.mesh
            axis_size = mesh.shape[axis]
            if num_shards % axis_size != 0:
                raise ValueError(
                    f"num_shards={num_shards} not divisible by mesh axis "
                    f"{axis!r} of size {axis_size}"
                )
            k_local = num_shards // axis_size
            data_specs = self._data_specs

            def estimate(params, data, key):
                def local(params, local_data, key):
                    s_local = _leading_dim(local_data)
                    dev_key = jax.random.fold_in(
                        key, jax.lax.axis_index(axis)
                    )
                    idx = jax.random.choice(
                        dev_key, s_local, (k_local,), replace=False
                    )
                    sub = jax.tree_util.tree_map(
                        lambda a: jnp.take(a, idx, axis=0), local_data
                    )
                    lp = jax.vmap(
                        lambda d: self.per_shard_logp(params, d)
                    )(sub)
                    return jax.lax.psum(jnp.sum(lp), axis) * scale

                return shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(
                        jax.tree_util.tree_map(lambda _: P(), params),
                        data_specs,
                        P(),
                    ),
                    out_specs=P(),
                )(params, data, key)

        else:

            def estimate(params, data, key):
                idx = jax.random.choice(
                    key, self.n_shards, (num_shards,), replace=False
                )
                sub = jax.tree_util.tree_map(
                    lambda a: jnp.take(a, idx, axis=0), data
                )
                lp = jax.vmap(lambda d: self.per_shard_logp(params, d))(sub)
                return jnp.sum(lp) * scale

        # Data as a jit argument (not a traced-in constant) for the
        # same multi-process reason as the full evaluators above, and
        # read from self.data at CALL time like logp/logp_and_grad —
        # a cached snapshot would silently diverge if data is ever
        # re-placed (e.g. after a remesh).
        logp_mb_full = jax.jit(estimate)
        vg_full = jax.jit(jax.value_and_grad(estimate, argnums=0))
        fns = (
            lambda p, k: logp_mb_full(p, self.data, k),
            lambda p, k: vg_full(p, self.data, k),
        )
        cache[num_shards] = fns
        return fns

    def per_shard_logps(self, params: Any) -> jax.Array:
        """Vector of per-shard contributions (diagnostic; the reference
        exposes these as individual node replies)."""

        def f(params, data):
            return jax.vmap(lambda d: self.per_shard_logp(params, d))(data)

        if self.mesh is None:
            return jax.jit(f)(params, self.data)
        return jax.jit(
            shard_map(
                f,
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: P(), params),
                    jax.tree_util.tree_map(lambda _: P(self.axis), self.data),
                ),
                out_specs=P(self.axis),
            )
        )(params, self.data)


def sharded_compute(
    per_shard_fn: PerShardComputeFn,
    data: Any,
    *,
    mesh: Optional[Mesh] = None,
    axis: str = SHARDS_AXIS,
) -> Callable[[Any], Any]:
    """Generic arrays->arrays over every shard, outputs stacked by shard.

    The TPU analog of the reference's *generic* service core — an
    ``ArraysToArraysService`` per node returning arbitrary arrays
    (reference: service.py:75-115, README.md:27-35) — for compute that is
    not a log-potential.  Returns a jitted ``fn(params) -> pytree`` whose
    leaves have a leading ``n_shards`` axis.
    """
    n_shards = _leading_dim(data)
    if mesh is None:
        placed = data

        def fn(params):
            return jax.vmap(lambda d: per_shard_fn(params, d))(placed)

        return jax.jit(fn)

    axis_size = mesh.shape[axis]
    if n_shards % axis_size != 0:
        raise ValueError(
            f"n_shards={n_shards} not divisible by mesh axis size {axis_size}"
        )
    placed = _shard_data_to_mesh(data, mesh, axis)
    data_specs = jax.tree_util.tree_map(lambda _: P(axis), placed)

    def fn(params, data_arg):
        def local(params, local_data):
            # Mark the replicated params device-varying BEFORE any user
            # code runs: per_shard_fn may call jax.grad internally, and a
            # pvary inserted inside the differentiated region transposes
            # to a psum over the axis — silently summing every shard's
            # gradient into each local update.  Varying params keep the
            # whole body axis-local, which is the semantics of one node
            # computing on its own private data.
            params = _mark_varying(params, axis)
            return jax.vmap(lambda d: per_shard_fn(params, d))(local_data)

        return shard_map(
            local,
            mesh=mesh,
            in_specs=(jax.tree_util.tree_map(lambda _: P(), params), data_specs),
            out_specs=P(axis),
        )(params, data_arg)

    # Data rides in as a jit ARGUMENT, not a closure constant — a
    # constant spanning non-addressable devices is an error on
    # multi-process meshes (same fix as FederatedLogp above).
    jitted = jax.jit(fn)
    return lambda params: jitted(params, placed)
