"""ZeRO-style gradient/optimizer-state sharding over the shards axis.

The reference's federated exchange always materializes the FULL
gradient on the driver (one dense grad array per input in every RPC
reply — reference: common.py:26-49, wrapper_ops.py:107-117).  That is
fine for a handful of regression coefficients; it wastes HBM and ICI
bandwidth once models carry high-dimensional parameters (GP inducing
points, neural likelihood weights).

TPU-native redesign, following the cross-replica weight-update sharding
recipe (Xu et al., arXiv:2004.13336, via PAPERS.md): inside the same
``shard_map`` that evaluates the federated logp, the backward's
cross-shard reduction runs as ``lax.psum_scatter`` instead of
``lax.psum`` — every device leaves the program holding only its
``1/axis_size`` slice of the summed gradient.  Updates run on slices,
and one ``all_gather`` per step rebuilds the replicated params for the
next evaluation.  Per step and per device this moves ``2 * dim / N``
floats over ICI (scatter + gather) versus ``2 * dim`` for
psum-everywhere, and divides gradient-exchange HBM residency by ``N``.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .._compat import shard_map
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import SHARDS_AXIS, mark_varying
from .sharded import _leading_dim, _shard_data_to_mesh

__all__ = ["ScatteredGrads", "ZeroShardedLogpGrad"]


class ScatteredGrads(NamedTuple):
    """Reduce-scattered gradient: per-device slices plus their layout."""

    logp: jax.Array  # scalar total logp, replicated
    grad_slices: jax.Array  # (padded_dim,) overall; device i holds slice i
    padded_dim: int
    dim: int


class ZeroShardedLogpGrad:
    """Federated logp whose gradient exchange is reduce-scattered.

    ``per_shard_logp(params, shard_data) -> scalar`` — the same contract
    as :class:`.sharded.FederatedLogp`, but gradients never materialize
    whole on any device:

    - :meth:`logp_and_scattered_grad`: one SPMD program computing the
      total logp (psum) and each device's 1/N slice of the summed
      gradient (psum_scatter of the flattened grad).
    - :meth:`sgd_steps`: a jitted scan of sharded gradient-ascent
      updates — the update math touches only local slices; one
      ``all_gather`` per step rebuilds the parameter vector.

    Numerically identical to the replicated path (tested against
    ``FederatedLogp.logp_and_grad``); the difference is where bytes
    live and what crosses ICI.
    """

    def __init__(
        self,
        per_shard_logp: Callable[[Any, Any], jax.Array],
        data: Any,
        example_params: Any,
        *,
        mesh: Mesh,
        axis: str = SHARDS_AXIS,
    ):
        self.axis = axis
        self.mesh = mesh
        self.n_shards = _leading_dim(data)
        axis_size = mesh.shape[axis]
        if self.n_shards % axis_size != 0:
            raise ValueError(
                f"n_shards={self.n_shards} not divisible by mesh axis "
                f"{axis!r} of size {axis_size}"
            )
        self.data = _shard_data_to_mesh(data, mesh, axis)
        self.axis_size = axis_size
        self._data_specs = jax.tree_util.tree_map(lambda _: P(axis), self.data)

        flat, unravel = ravel_pytree(example_params)
        self.dim = int(flat.shape[0])
        self.padded_dim = -(-self.dim // axis_size) * axis_size
        self.unravel = unravel
        dim = self.dim

        def flat_local_logp(vec, local_data):
            """Sum of this device's shard logps at params = unravel(vec)."""
            params = unravel(vec[:dim])
            lp = jax.vmap(lambda d: per_shard_logp(params, d))(local_data)
            return jnp.sum(lp)

        def local_body(vec, local_data):
            """(replicated padded vec, local shards) -> (logp, grad slice).

            Runs INSIDE shard_map.  ``mark_varying`` before the grad —
            a pvary inserted inside the differentiated region would
            transpose to a psum and double-count the cross-shard sum
            the psum_scatter below performs.
            """
            vec = mark_varying(vec, axis)
            lp_local, g_local = jax.value_and_grad(flat_local_logp)(
                vec, local_data
            )
            logp = lax.psum(lp_local, axis)
            # The cross-shard gradient reduction IS the scatter: device
            # i receives the i-th contiguous 1/N slice of sum_shards(g).
            g_slice = lax.psum_scatter(g_local, axis, tiled=True)
            return logp, g_slice

        self._local_body = local_body
        self._eval = jax.jit(
            shard_map(
                local_body,
                mesh=mesh,
                in_specs=(P(), self._data_specs),
                out_specs=(P(), P(axis)),
            )
        )
        self._sgd_cache: dict = {}

    # -- flat-vector plumbing ---------------------------------------------

    def flatten(self, params: Any) -> jax.Array:
        vec, _ = ravel_pytree(params)
        return jnp.pad(vec, (0, self.padded_dim - self.dim))

    # -- evaluation --------------------------------------------------------

    def logp_and_scattered_grad(self, params: Any) -> ScatteredGrads:
        logp, g = self._eval(self.flatten(params), self.data)
        return ScatteredGrads(logp, g, self.padded_dim, self.dim)

    def gather_grad(self, sg: ScatteredGrads) -> Any:
        """Materialize the full gradient pytree (diagnostic/interop path —
        defeats the sharding purpose if called every step)."""
        return self.unravel(jnp.reshape(sg.grad_slices, (-1,))[: self.dim])

    # -- sharded optimizer loop --------------------------------------------

    def sgd_steps(
        self,
        params: Any,
        *,
        learning_rate: float,
        num_steps: int,
    ) -> Tuple[Any, jax.Array]:
        """Gradient-ascent on the logp with sharded grads and updates.

        Eval, psum_scatter, slice update, and all_gather all compile
        into ONE program with the step loop as a ``lax.scan``.  Returns
        the final params pytree and the per-step logp trace.  The
        compiled program is cached per ``num_steps`` (the scan length
        is baked into the trace); ``learning_rate`` rides as a traced
        operand, so sweeping it does not recompile.
        """
        fn = self._sgd_cache.get(num_steps)
        if fn is None:
            fn = self._build_sgd(num_steps)
            self._sgd_cache[num_steps] = fn
        vec, logps = fn(
            self.flatten(params), jnp.float32(learning_rate), self.data
        )
        return self.unravel(vec[: self.dim]), logps

    def adam_steps(
        self,
        params: Any,
        *,
        learning_rate: float,
        num_steps: int,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
    ) -> Tuple[Any, jax.Array]:
        """Adam ascent with FULLY sharded optimizer state.

        The first/second-moment vectors never exist whole anywhere:
        each device carries only its 1/N slices, updated from its
        psum_scatter'd gradient slice — the optimizer-state half of the
        ZeRO recipe.  Returns ``(final_params, logp_trace)``.
        """
        fn = self._sgd_cache.get(("adam", num_steps, b1, b2, eps))
        if fn is None:
            fn = self._build_adam(num_steps, b1, b2, eps)
            self._sgd_cache[("adam", num_steps, b1, b2, eps)] = fn
        vec, logps = fn(
            self.flatten(params), jnp.float32(learning_rate), self.data
        )
        return self.unravel(vec[: self.dim]), logps

    def _build_loop(self, num_steps: int, init_opt_state, update_rule):
        """Shared sharded-optimizer scaffold.

        ``init_opt_state(slice_len, dtype) -> opt_state`` (per-device
        slices); ``update_rule(opt_state, g_slice, my_slice, lr) ->
        (new_opt_state, new_slice)`` runs purely on this device's 1/N
        slices — the optimizer never sees a full vector.  Step counts
        (e.g. Adam bias correction) live inside opt_state (optax keeps
        its own integer count there).
        """
        axis = self.axis
        local_body = self._local_body
        slice_len = self.padded_dim // self.axis_size

        def local(vec0, lr, local_data):
            def step(carry, _):
                vec, opt_state = carry
                logp, g_slice = local_body(vec, local_data)
                i = lax.axis_index(axis)
                my_slice = lax.dynamic_slice_in_dim(
                    vec, i * slice_len, slice_len
                )
                opt_state, new_slice = update_rule(
                    opt_state, g_slice, my_slice, lr
                )
                vec = lax.all_gather(
                    new_slice.astype(vec.dtype), axis, tiled=True
                )
                return (vec, opt_state), logp

            vec0 = mark_varying(vec0, axis)
            (vec, _), logps = lax.scan(
                step,
                (vec0, init_opt_state(slice_len, vec0.dtype)),
                None,
                length=num_steps,
            )
            return vec, logps

        # check_vma=False: the carried vec is rebuilt by all_gather each
        # step, so it is numerically replicated but *typed* varying —
        # the static replication check cannot see through that (same
        # situation as parallel/multichain.py).  Correctness of the
        # cross-shard reduction is carried by the explicit psum /
        # psum_scatter / all_gather collectives, and pinned by the
        # equality-with-replicated-path tests.
        return jax.jit(
            shard_map(
                local,
                mesh=self.mesh,
                in_specs=(P(), P(), self._data_specs),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

    def _build_sgd(self, num_steps: int):
        def update(state, g, my_slice, lr):
            return state, my_slice + lr * g

        return self._build_loop(num_steps, lambda n, dt: (), update)

    def _build_adam(self, num_steps: int, b1: float, b2: float, eps: float):
        try:
            import optax  # lazy, like samplers.find_map
        except ModuleNotFoundError as e:  # pragma: no cover - env-dependent
            raise ModuleNotFoundError(
                "adam_steps requires optax (pip install "
                "pytensor-federated-tpu[vi]); sgd_steps has no extra deps"
            ) from e

        # The library transform supplies the moment/bias-correction
        # math; its state is a plain per-slice pytree, so it shards the
        # same way the hand-rolled version did.
        tx = optax.scale_by_adam(b1=b1, b2=b2, eps=eps, mu_dtype=jnp.float32)

        def init(slice_len, dtype):
            return tx.init(jnp.zeros((slice_len,), jnp.float32))

        def update(state, g, my_slice, lr):
            u, state = tx.update(g.astype(jnp.float32), state)
            return state, my_slice + lr * u

        return self._build_loop(num_steps, init, update)
