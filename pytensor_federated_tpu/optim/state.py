"""Node-side optimizer-shard lifecycle: versioned checkpoints.

A sharded-optimizer node (ISSUE 16) owns ONE contiguous shard of the
flat parameter vector plus that shard's optimizer state.  Both live in
a :class:`ShardStore` — a directory of version-stamped ``.npz``
checkpoints, one file per shard geometry — with two hard rules:

- **Checkpoint BEFORE reply.**  ``make_update_compute`` persists the
  post-update shard before the reply frame leaves the node, so a
  replica killed at any instant leaves the store in one of exactly two
  states: the update never happened (driver retries cleanly) or it is
  durably applied (the retry's version mismatch tells the driver
  "already applied" and it refreshes the slice instead of re-stepping).
  There is no third state — that is the exactly-once story.
- **Version mismatches are LOUD.**  :class:`StaleShardError` is a
  :class:`~..service.npwire.WireError` subclass on purpose: every lane
  already treats WireError as the deterministic, non-retryable
  classification, and the message carries ``holds``/``expected`` so the
  driver can distinguish "already applied" (holds == expected + 1,
  recoverable by refresh) from genuine divergence (anything else,
  unrecoverable — surfaced, never papered over).

The store directory is deliberately SHAREABLE: any replica pointed at
the same root can restore any shard, which is what lets
:class:`~.sharded.ShardedOptimizer` re-bind a dead replica's shard onto
a live one (NodePool failover) without losing optimizer state.

Writes are atomic (``os.replace`` of a same-directory temp file) so a
crash mid-checkpoint leaves the previous version intact, never a torn
file.
"""

from __future__ import annotations

import io
import os
import re
import tempfile
import threading
from typing import Any, List, NamedTuple, Optional, Tuple

import numpy as np

from ..routing.partition import GradPartition, PartitionError
from ..service.npwire import WireError

__all__ = [
    "ShardState",
    "ShardStore",
    "StaleShardError",
    "parse_stale_error",
    "stale_message",
]

_STALE_RE = re.compile(
    r"StaleShardError: shard (\d+)/(\d+) holds version (\d+), "
    r"request expected (\d+)"
)


def stale_message(part: GradPartition, holds: int, expected: int) -> str:
    """The canonical (machine-parseable) stale-shard message.  It
    crosses the wire as in-band error TEXT (``pure_callback`` and the
    RPC error frame both erase exception types), so the format is the
    protocol: :func:`parse_stale_error` must keep matching it."""
    return (
        f"StaleShardError: shard {part.index}/{part.count} holds "
        f"version {holds}, request expected {expected} "
        f"(geometry offset={part.offset} length={part.length} "
        f"total={part.total})"
    )


def parse_stale_error(text: str) -> Optional[Tuple[int, int, int, int]]:
    """Extract ``(index, count, holds, expected)`` from an in-band
    error string, or ``None`` when it is not a stale-shard refusal."""
    m = _STALE_RE.search(text)
    if m is None:
        return None
    return tuple(int(g) for g in m.groups())  # type: ignore[return-value]


class StaleShardError(WireError):
    """A versioned request whose step-version stamp does not match the
    shard's checkpointed version.  ``holds == expected + 1`` means the
    update was durably applied but the reply was lost (recoverable:
    refresh the slice); anything else is divergence and must surface."""

    def __init__(self, part: GradPartition, holds: int, expected: int):
        super().__init__(stale_message(part, holds, expected))
        self.part = part
        self.holds = holds
        self.expected = expected


class ShardState(NamedTuple):
    """One shard's durable state: the monotonic step version, the
    owned parameter slice, and the optimizer-state leaves (tree
    structure is NOT stored — the node re-derives it from its own
    ``optimizer.init`` on a zeros slice, so a checkpoint written by one
    replica restores on any replica running the same optimizer)."""

    version: int
    params: np.ndarray
    opt_leaves: List[np.ndarray]


class ShardStore:
    """Version-stamped shard checkpoints under one directory.

    Keyed by the full shard geometry ``(count, total, index)`` — two
    different partition plans never collide, and a geometry
    disagreement on load is a loud :class:`PartitionError`, never a
    silently mis-sliced restore.  Thread-safe per process (one lock;
    checkpoints are small — O(model/N))."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, part: GradPartition) -> str:
        return os.path.join(
            self.root,
            f"shard_{part.count}x{part.total}_{part.index}.npz",
        )

    def save(
        self,
        part: GradPartition,
        version: int,
        params: np.ndarray,
        opt_leaves: List[Any],
    ) -> None:
        """Atomically persist one shard at ``version`` (temp file +
        ``os.replace`` in the same directory — a crash mid-write leaves
        the previous checkpoint intact)."""
        part.validate()
        params = np.asarray(params)
        if params.size != part.length:
            raise PartitionError(
                f"shard {part.index} params carry {params.size} elements "
                f"but the partition declares length {part.length}"
            )
        payload = {
            "version": np.asarray(int(version), np.uint64),
            "geometry": np.asarray(list(part), np.uint64),
            "params": params,
            "n_leaves": np.asarray(len(opt_leaves), np.uint64),
        }
        for i, leaf in enumerate(opt_leaves):
            payload[f"leaf_{i}"] = np.asarray(leaf)
        buf = io.BytesIO()
        np.savez(buf, **payload)
        data = buf.getvalue()
        path = self._path(part)
        with self._lock:
            fd, tmp = tempfile.mkstemp(
                dir=self.root, prefix=".tmp_shard_", suffix=".npz"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(data)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

    def load(self, part: GradPartition) -> Optional[ShardState]:
        """The shard's last durable state, or ``None`` when it was
        never checkpointed.  A geometry mismatch between the request
        partition and the stored stamp is loud — it means two
        partition plans collided on one store."""
        part.validate()
        path = self._path(part)
        with self._lock:
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except FileNotFoundError:
                return None
        try:
            with np.load(io.BytesIO(data)) as z:
                stored = tuple(int(v) for v in z["geometry"])
                if stored != tuple(part):
                    raise PartitionError(
                        f"checkpoint geometry {stored} does not match "
                        f"the requested shard {tuple(part)}"
                    )
                n = int(z["n_leaves"])
                return ShardState(
                    version=int(z["version"]),
                    params=np.asarray(z["params"]),
                    opt_leaves=[
                        np.asarray(z[f"leaf_{i}"]) for i in range(n)
                    ],
                )
        except PartitionError:
            raise
        except Exception as e:
            raise WireError(
                f"corrupt shard checkpoint {os.path.basename(path)}: {e}"
            ) from None

    def version(self, part: GradPartition) -> Optional[int]:
        state = self.load(part)
        return None if state is None else state.version

    def drop(self, part: GradPartition) -> None:
        """Forget one shard (tests / chaos teardown)."""
        try:
            os.unlink(self._path(part))
        except FileNotFoundError:
            pass
