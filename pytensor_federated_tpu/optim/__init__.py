"""ZeRO-style sharded optimizer over the replica pool (ISSUE 16).

Node-owned optimizer state, shard-local ``optax`` updates, versioned
checkpoints, and a lazy param-refresh lane — see :mod:`.sharded` for
the architecture and :mod:`.state` for the shard lifecycle.
"""

from .sharded import ShardedOptimizer, ShardResult, make_update_compute
from .state import (
    ShardState,
    ShardStore,
    StaleShardError,
    parse_stale_error,
    stale_message,
)

__all__ = [
    "ShardResult",
    "ShardState",
    "ShardStore",
    "ShardedOptimizer",
    "StaleShardError",
    "make_update_compute",
    "parse_stale_error",
    "stale_message",
]
