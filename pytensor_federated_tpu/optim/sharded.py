"""ZeRO-style sharded optimizer over the replica pool (ISSUE 16).

The driver-centric SVI loop (:class:`~..ppl.svi.StreamingSVI`) keeps
ALL optimizer state on the driver and ships the full gradient home
every step — ``O(model × n_mc × windows)`` reply bytes and
``O(model)`` gradient + ``2×O(model)`` adam state resident on the
driver.  This module inverts that, the DeepSpeed-ZeRO partitioning
applied to the pool wire:

- the flat parameter vector is split by
  :func:`~..routing.partition.plan_partitions` into one contiguous
  shard per OWNER replica;
- each step, the driver sends every owner the step inputs (params
  broadcast whole — they ride the PR-9 pin cache, so steady-state
  requests move almost no payload) stamped with the shard's expected
  step version (the VERSION wire block, flag 128 / field 21 / shm 32);
- the node computes the FULL gradient locally — the gradient never
  crosses the wire — slices its owned shard, applies ``optax`` on the
  slice, CHECKPOINTS the new shard state
  (:class:`~.state.ShardStore`, before the reply leaves), and returns
  only ``[loss, update_slice]`` at ``version + 1``;
- the driver applies each returned update slice to its parameter copy
  (`params[slice] += update` — the same elementwise add
  ``optax.apply_updates`` performs, so driver-centric and sharded
  trajectories are BIT-IDENTICAL on CPU for the same RNG stream,
  property-tested in tests/test_optim.py).

Exactly-once under failure: the checkpoint-before-reply rule means a
replica killed mid-update leaves either no trace (driver retries) or a
durably applied shard whose retry refusal (``holds == expected + 1``)
tells the driver to RECOVER the slice via the param-refresh lane (a
zero-array versioned request) instead of double-stepping.  Because
adam's step count IS the shard version, ``opt_steps == accepted``
holds per shard under chaos — the ``--lane zero`` invariant.

Ownership is SOFT: the checkpoint store is a shared directory, so when
a :class:`~..routing.pool.NodePool` is driving, a dead owner's shard
re-binds onto any live replica (which restores the shard from the
store) — failover without losing optimizer state.
"""

from __future__ import annotations

import contextvars
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import (
    Any,
    Callable,
    Dict,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
    Union,
)

import numpy as np

from ..faultinject import runtime as _fi
from ..routing.partition import (
    GradPartition,
    PartitionError,
    Reassembler,
    plan_partitions,
)
from ..service.npwire import WireError
from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics
from .state import ShardStore, StaleShardError, parse_stale_error

__all__ = [
    "ShardResult",
    "ShardedOptimizer",
    "make_update_compute",
]

SHARD_UPDATES = _metrics.counter(
    "pftpu_sharded_updates_total",
    "Sharded-optimizer per-shard step outcomes",
    ("outcome",),
)

GradFn = Callable[..., Tuple[Any, Any]]
ArraysFor = Union[
    Sequence[np.ndarray],
    Callable[[int, GradPartition], Sequence[np.ndarray]],
]


# ---------------------------------------------------------------------------
# node side: the versioned update compute
# ---------------------------------------------------------------------------


def _restore_opt_state(
    optimizer: Any, length: int, dtype: np.dtype, leaves: List[np.ndarray]
) -> Any:
    """Rebuild the optimizer-state pytree from checkpointed leaves.
    The tree STRUCTURE is re-derived from ``optimizer.init`` on a
    zeros slice (never stored), so any replica running the same
    optimizer restores any replica's checkpoint."""
    import jax.numpy as jnp
    from jax import tree_util

    template = optimizer.init(jnp.zeros((length,), dtype))
    t_leaves, treedef = tree_util.tree_flatten(template)
    if len(leaves) != len(t_leaves):
        raise WireError(
            f"shard checkpoint has {len(leaves)} optimizer-state leaves "
            f"but this optimizer expects {len(t_leaves)} — the store was "
            "written by a different optimizer"
        )
    return tree_util.tree_unflatten(
        treedef, [jnp.asarray(leaf) for leaf in leaves]
    )


def make_update_compute(
    grad_fn: GradFn,
    optimizer: Any,
    store: ShardStore,
    *,
    params_of: Callable[[Sequence[np.ndarray]], np.ndarray],
) -> Callable[..., list]:
    """Node-side compute for a sharded-optimizer OWNER replica.

    ``grad_fn(*arrays) -> (loss, flat_grad)`` computes the step loss
    and the FULL flat gradient (length = the partition's ``total``)
    from the request arrays — built from the same loss function the
    driver lane differentiates, so the two lanes cannot drift.
    ``params_of(arrays)`` extracts the full flat parameter vector from
    the request (used once, to initialize the shard at version 0).

    The returned compute REFUSES plain calls (a sharded-optimizer node
    only serves versioned requests) and carries the
    ``versioned_update(arrays, part, step_version)`` handler the
    tcp/shm servers dispatch versioned frames to:

    - **update** (arrays present): version-check against the shard's
      checkpoint (mismatch → :class:`~.state.StaleShardError`, in-band
      and machine-parseable), slice the local gradient, apply the
      optimizer on the slice, checkpoint at ``version + 1`` BEFORE
      replying ``[loss, update_slice]``;
    - **refresh** (zero arrays): return ``[param_slice]`` at the
      shard's checkpointed version — the lazy all-gather lane a driver
      uses to recover a slice whose update applied but whose reply was
      lost.  A shard OLDER than the requested version is refused
      (StaleShardError): the driver already saw newer state, so
      serving the old slice would silently rewind it.
    """
    import jax.numpy as jnp
    import optax
    from jax import tree_util

    def compute(*arrays: Any) -> list:
        raise RuntimeError(
            "sharded-optimizer node: plain (unversioned) requests are "
            "not served here — stamp a step version (evaluate_versioned)"
        )

    def versioned_update(
        arrays: Sequence[np.ndarray],
        part: Optional[Tuple[int, ...]],
        step_version: int,
    ) -> Tuple[List[np.ndarray], int]:
        if part is None:
            raise WireError(
                "versioned sharded-optimizer request without a "
                "partition block — the version stamps a SHARD"
            )
        p = GradPartition(*part).validate()

        if not arrays:  # -- refresh lane --------------------------------
            state = store.load(p)
            if state is None:
                raise WireError(
                    f"refresh of uninitialized shard {p.index}/{p.count} "
                    f"(geometry total={p.total}) — no checkpoint in the "
                    "store"
                )
            if state.version < step_version:
                raise StaleShardError(p, state.version, step_version)
            return [np.asarray(state.params)], state.version

        # -- update lane ---------------------------------------------
        state = store.load(p)
        if state is None:
            if step_version != 0:
                # A lost checkpoint under a non-zero expectation is
                # divergence, not init — holds=0 makes the driver's
                # classification refuse loudly.
                raise StaleShardError(p, 0, step_version)
            full = np.asarray(params_of(arrays)).ravel()
            if full.size != p.total:
                raise PartitionError(
                    f"request params carry {full.size} elements but the "
                    f"partition declares total {p.total}"
                )
            params_slice = full[p.offset : p.offset + p.length].copy()
            opt_state = optimizer.init(jnp.asarray(params_slice))
        else:
            if state.version != step_version:
                raise StaleShardError(p, state.version, step_version)
            params_slice = np.asarray(state.params)
            opt_state = _restore_opt_state(
                optimizer, p.length, params_slice.dtype, state.opt_leaves
            )

        loss, flat_grad = grad_fn(*arrays)
        flat_grad = np.asarray(flat_grad).ravel()
        if flat_grad.size != p.total:
            raise PartitionError(
                f"grad_fn produced {flat_grad.size} gradient elements "
                f"but the partition declares total {p.total}"
            )
        gslice = jnp.asarray(flat_grad[p.offset : p.offset + p.length])
        updates, new_opt_state = optimizer.update(gslice, opt_state)
        update_slice = np.asarray(updates)
        new_params = np.asarray(
            optax.apply_updates(jnp.asarray(params_slice), updates)
        )
        # Checkpoint BEFORE the reply leaves: the exactly-once story.
        store.save(
            p,
            step_version + 1,
            new_params,
            [np.asarray(leaf) for leaf in tree_util.tree_leaves(new_opt_state)],
        )
        return [np.asarray(loss), update_slice], step_version + 1

    compute.versioned_update = versioned_update  # type: ignore[attr-defined]
    return compute


# ---------------------------------------------------------------------------
# driver side
# ---------------------------------------------------------------------------


class ShardResult(NamedTuple):
    """One shard's outcome for one step.

    ``status``:

    - ``"applied"`` — the node stepped; ``update`` is the optimizer's
      update slice (ADD it to the owned parameter range).
    - ``"recovered"`` — the update had ALREADY applied node-side (a
      lost reply); ``params`` is the refreshed parameter slice
      (OVERWRITE the owned range).  Counts as an accepted step.
    - ``"stale"`` — the node refused without stepping (a bad stamp,
      e.g. chaos ``stale_param_version``); nothing to apply.
    - ``"failed"`` — transport/compute failure after the pool's
      failover budget; ``error`` carries the exception for the
      caller's classification.
    """

    index: int
    status: str
    version: int
    loss: Optional[float] = None
    update: Optional[np.ndarray] = None
    params: Optional[np.ndarray] = None
    error: Optional[BaseException] = None

    @property
    def accepted(self) -> bool:
        return self.status in ("applied", "recovered")


class ShardedOptimizer:
    """Driver-side coordinator of one sharded-optimizer group.

    ``clients``: pinned transport clients (tcp/shm), one OWNER per
    shard — or pass ``pool=`` (a :class:`~..routing.pool.NodePool` of
    tcp/shm replicas) with ``count=`` and shards bind to replicas
    lazily, re-binding on failure (the shared
    :class:`~.state.ShardStore` makes any replica able to restore any
    shard).  gRPC replicas have no versioned-update lane and are
    refused loudly at bind time.

    The driver here holds NO gradient and NO optimizer state — only
    the per-shard version vector and, transiently, one update slice
    per shard (``O(model/N)`` each; ``max_reply_elems`` records the
    high-water mark, asserted O(model/N) in tests).
    """

    def __init__(
        self,
        total: int,
        *,
        clients: Optional[Sequence[Any]] = None,
        pool: Optional[Any] = None,
        count: Optional[int] = None,
        failover_retries: int = 2,
    ) -> None:
        if (clients is None) == (pool is None):
            raise ValueError("pass exactly one of clients= or pool=")
        if clients is not None:
            count = len(clients)
        if not count or count < 1:
            raise ValueError("count must be >= 1 (pass count= with pool=)")
        self.total = int(total)
        self.count = int(count)
        self.parts: List[GradPartition] = plan_partitions(
            self.total, self.count
        )
        self._clients = list(clients) if clients is not None else None
        self._pool = pool
        self._owners: List[Optional[Any]] = [None] * self.count
        self.failover_retries = int(failover_retries)
        #: Per-shard step version — the driver's belief of each shard's
        #: checkpointed version; equals the shard's accepted-step count.
        self.versions: List[int] = [0] * self.count
        #: High-water mark of reply elements received for one shard —
        #: the driver-residency witness (never exceeds ceil(total/N)).
        self.max_reply_elems = 0
        self._hwm_lock = threading.Lock()
        self._executor: Optional[ThreadPoolExecutor] = None
        # Transport clients are lock-step (one frame in flight per
        # socket): two shards bound to the SAME replica must serialize
        # their calls or interleave frames on one connection.
        self._client_locks: Dict[int, threading.Lock] = {}
        self._locks_guard = threading.Lock()

    # -- shard → client binding ---------------------------------------

    @staticmethod
    def _require_versioned(client: Any, who: str) -> Any:
        if not hasattr(client, "evaluate_versioned"):
            raise TypeError(
                f"{who} has no versioned-update lane "
                "(evaluate_versioned) — sharded optimizers need tcp or "
                "shm replicas, not grpc"
            )
        return client

    def _bind(self, k: int, *, exclude: Sequence[str] = ()) -> Any:
        """The shard's current client; with a pool, (re)bind to an
        admitted replica — preferring replicas not already owning a
        shard — and validate the transport."""
        if self._clients is not None:
            return self._require_versioned(
                self._clients[k], f"shard {k}'s client"
            )
        owner = self._owners[k]
        if owner is not None and owner.breaker.available():
            if owner.address not in exclude:
                return self._require_versioned(
                    self._pool.client_for(owner),
                    f"replica {owner.address}",
                )
        taken = {
            r.address
            for j, r in enumerate(self._owners)
            if r is not None and j != k
        }
        picked = self._pool.pick(1, exclude=list(taken | set(exclude)))
        if not picked:  # every replica already owns a shard: share
            picked = self._pool.pick(1, exclude=list(exclude))
        if not picked:
            raise ConnectionError(
                f"no admitted replica available to own shard {k}"
            )
        self._owners[k] = picked[0]
        _flightrec.record(
            "optim.shard_bound", shard=k, replica=picked[0].address
        )
        return self._require_versioned(
            self._pool.client_for(picked[0]), f"replica {picked[0].address}"
        )

    def _owner_address(self, k: int) -> Optional[str]:
        owner = self._owners[k]
        return None if owner is None else owner.address

    def _record(self, k: int, ok: bool) -> None:
        if self._pool is not None and self._owners[k] is not None:
            self._pool.record_result(self._owners[k], ok)

    # -- the step -------------------------------------------------------

    def _client_lock(self, client: Any) -> threading.Lock:
        with self._locks_guard:
            lock = self._client_locks.get(id(client))
            if lock is None:
                lock = self._client_locks[id(client)] = threading.Lock()
            return lock

    def _refresh(self, k: int, client: Any, want: int) -> np.ndarray:
        """The param-refresh lane: a zero-array versioned request for
        shard ``k`` at version ``want``; returns the parameter slice."""
        if _fi.active_plan is not None:  # chaos seam: refresh lane
            _fi.refresh_filter("optim.refresh", peer=self._owner_address(k))
        with self._client_lock(client):
            outputs, rv = client.evaluate_versioned(
                partition=self.parts[k], version=want
            )
        if rv is None or rv < want or not outputs:
            raise WireError(
                f"shard {k} refresh returned version {rv} "
                f"(wanted >= {want}) with {len(outputs)} arrays"
            )
        slice_ = np.asarray(outputs[0]).ravel()
        if slice_.size != self.parts[k].length:
            raise PartitionError(
                f"shard {k} refresh carried {slice_.size} elements but "
                f"the partition declares length {self.parts[k].length}"
            )
        self.versions[k] = int(rv)
        return slice_

    def _step_shard(
        self, k: int, arrays: Sequence[np.ndarray]
    ) -> ShardResult:
        part = self.parts[k]
        want = self.versions[k]
        attempts = 0
        exclude: List[str] = []
        while True:
            try:
                client = self._bind(k, exclude=exclude)
            except ConnectionError as e:
                SHARD_UPDATES.labels(outcome="failed").inc()
                return ShardResult(k, "failed", want, error=e)
            stamp = want
            if _fi.active_plan is not None:  # chaos seam: version stamp
                stamp = _fi.version_filter(
                    "optim.update.version", want,
                    peer=self._owner_address(k),
                )
            try:
                with self._client_lock(client):
                    outputs, rv = client.evaluate_versioned(
                        *arrays, partition=part, version=stamp
                    )
            except (ConnectionError, OSError, TimeoutError) as e:
                # Transport failure: the node may or may not have
                # applied — the retry's version check disambiguates
                # (an applied update refuses holds == want + 1 below).
                self._record(k, ok=False)
                if (
                    self._pool is None
                    or attempts >= self.failover_retries
                    or not self._pool.allow_retry("shard_failover")
                ):
                    SHARD_UPDATES.labels(outcome="failed").inc()
                    return ShardResult(k, "failed", want, error=e)
                attempts += 1
                if self._owners[k] is not None:
                    exclude.append(self._owners[k].address)
                    self._owners[k] = None
                _flightrec.record("optim.shard_failover", shard=k)
                continue
            except RuntimeError as e:
                stale = parse_stale_error(str(e))
                if stale is None:
                    self._record(k, ok=True)  # the node answered
                    SHARD_UPDATES.labels(outcome="failed").inc()
                    return ShardResult(k, "failed", want, error=e)
                _idx, _cnt, holds, _expected = stale
                if holds == want + 1:
                    # Applied but the reply was lost (or a retry after
                    # a mid-reply death): recover the slice.
                    try:
                        slice_ = self._refresh(k, client, holds)
                    except (ConnectionError, OSError, TimeoutError) as re:
                        self._record(k, ok=False)
                        SHARD_UPDATES.labels(outcome="failed").inc()
                        return ShardResult(k, "failed", want, error=re)
                    self._record(k, ok=True)
                    # Adopt the node's version: without this the next
                    # step re-sends the stale stamp and "recovers"
                    # forever — the shard would never step again.
                    self.versions[k] = int(holds)
                    with self._hwm_lock:
                        self.max_reply_elems = max(
                            self.max_reply_elems, slice_.size
                        )
                    SHARD_UPDATES.labels(outcome="recovered").inc()
                    _flightrec.record(
                        "optim.shard_recovered", shard=k, version=holds
                    )
                    return ShardResult(
                        k, "recovered", holds, params=slice_
                    )
                if holds == want:
                    # The node did NOT step (a twisted/corrupt stamp —
                    # chaos stale_param_version): nothing to apply,
                    # nothing to count.
                    self._record(k, ok=True)
                    SHARD_UPDATES.labels(outcome="stale").inc()
                    return ShardResult(k, "stale", want, error=e)
                raise WireError(
                    f"shard {k} diverged: node holds version {holds}, "
                    f"driver believes {want} — refusing to continue "
                    "(a silent rewind or double-step would corrupt the "
                    "trajectory)"
                ) from e
            # -- success -------------------------------------------------
            self._record(k, ok=True)
            if rv != want + 1:
                raise WireError(
                    f"shard {k} update replied version {rv}, expected "
                    f"{want + 1}"
                )
            if len(outputs) != 2:
                raise WireError(
                    f"shard {k} update replied {len(outputs)} arrays, "
                    "expected [loss, update_slice]"
                )
            update = np.asarray(outputs[1]).ravel()
            if update.size != part.length:
                raise PartitionError(
                    f"shard {k} update slice carries {update.size} "
                    f"elements but the partition declares {part.length}"
                )
            self.versions[k] = int(rv)
            with self._hwm_lock:
                self.max_reply_elems = max(
                    self.max_reply_elems, update.size
                )
            SHARD_UPDATES.labels(outcome="applied").inc()
            return ShardResult(
                k,
                "applied",
                int(rv),
                loss=float(np.asarray(outputs[0])),
                update=update,
            )

    def step(self, arrays_for: ArraysFor) -> List[ShardResult]:
        """One sharded step: dispatch every owner's versioned update.

        ``arrays_for`` is either one shared request array list (every
        owner sees the same minibatch — the exact-equivalence mode) or
        a callable ``(shard_index, partition) -> arrays`` (disjoint
        per-owner minibatches — the bandwidth mode).  Returns one
        :class:`ShardResult` per shard; per-shard failures are
        returned, not raised (the caller owns classification), but
        version DIVERGENCE raises — that is never safe to continue
        past.

        Owners are dispatched CONCURRENTLY (each shard talks to its
        own replica connection; per-shard state — version, owner
        binding — is only ever touched by its own dispatch), so a
        step's wall clock is the slowest owner, not the sum.  The
        ambient deadline crosses the executor hop via the repo's
        ``copy_context`` convention."""

        def one(k: int) -> ShardResult:
            arrays = (
                arrays_for(k, self.parts[k])
                if callable(arrays_for)
                else arrays_for
            )
            return self._step_shard(k, list(arrays))

        if self.count == 1:
            return [one(0)]
        if self._executor is None:
            self._executor = ThreadPoolExecutor(
                max_workers=min(self.count, 16),
                thread_name_prefix="pftpu-sharded-step",
            )
        futures = [
            self._executor.submit(contextvars.copy_context().run, one, k)
            for k in range(self.count)
        ]
        # Collect in shard order; a divergence WireError from any
        # shard propagates after every in-flight dispatch settles
        # (never leaves a straggler racing the caller).
        results: List[Union[ShardResult, BaseException]] = []
        for f in futures:
            try:
                results.append(f.result())
            except BaseException as e:  # noqa: BLE001 - re-raised below
                results.append(e)
        for r in results:
            if isinstance(r, BaseException):
                raise r
        return results  # type: ignore[return-value]

    # -- applying results ------------------------------------------------

    def apply(
        self, flat_params: np.ndarray, results: Sequence[ShardResult]
    ) -> Tuple[np.ndarray, List[int]]:
        """Fold a step's shard results into the driver's flat parameter
        copy: ``applied`` slices ADD their update (the elementwise
        ``optax.apply_updates`` add), ``recovered`` slices OVERWRITE
        with the refreshed params.  Returns ``(new_flat, accepted shard
        indices)``; the input array is not mutated."""
        flat = np.array(flat_params, copy=True).ravel()
        if flat.size != self.total:
            raise PartitionError(
                f"flat params carry {flat.size} elements, expected "
                f"{self.total}"
            )
        accepted: List[int] = []
        for res in results:
            p = self.parts[res.index]
            if res.status == "applied":
                flat[p.offset : p.offset + p.length] += res.update
                accepted.append(res.index)
            elif res.status == "recovered":
                flat[p.offset : p.offset + p.length] = res.params
                accepted.append(res.index)
        return flat, accepted

    def flat_update(
        self, results: Sequence[ShardResult]
    ) -> Tuple[float, np.ndarray]:
        """The exact lane's assembly: every shard must have APPLIED
        (loud :class:`~..routing.partition.PartitionError` otherwise,
        via the Reassembler's completeness check); returns
        ``(mean_loss, full flat update vector)``."""
        applied = [r for r in results if r.status == "applied"]
        dtype = (
            applied[0].update.dtype if applied else np.dtype(np.float64)
        )
        asm = Reassembler(self.total, self.count, dtype)
        for res in applied:
            asm.add(self.parts[res.index], res.update)
        flat = asm.result()
        losses = [r.loss for r in applied if r.loss is not None]
        return float(np.mean(losses)), flat
