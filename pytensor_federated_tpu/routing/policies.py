"""Routing policies: which replica serves the next call.

A policy ranks the pool's *available* replicas (breaker-admitted,
not excluded by the caller); the pool applies it in
:meth:`~pytensor_federated_tpu.routing.pool.NodePool.pick`.  Policies
see a narrow read-only view of each replica:

- ``queue_depth()`` — the replica's ADVERTISED backlog from its last
  fresh GetLoad reply (server batcher queue depth, else in-flight RPC
  count, else ``n_clients``), or ``None`` when the load is unknown or
  stale (stale-load eviction, pool.py);
- ``ewma_latency_s`` — exponentially-weighted per-request latency
  observed by THIS driver's own calls (None until the first call);
- ``inflight`` — this driver's own in-flight calls to the replica
  (the local fallback signal when no load has been advertised).

Three built-ins:

- **round_robin** — cycle in registration order; the predictable
  baseline and the right choice for homogeneous replicas + uniform
  requests.
- **ewma** — lowest observed EWMA latency first; adapts to replicas
  that are alive-but-slow (which never trip a breaker).  Unmeasured
  replicas rank FIRST (optimistically) so new capacity gets probed.
- **p2c** (default) — power-of-two-choices over advertised queue
  depth: sample two random candidates, route to the less loaded one
  (ties: lower EWMA latency, then random).  The classic
  load-balancing result: two random choices get exponentially close
  to least-loaded routing without the herd behavior of deterministic
  least-loaded (every driver dog-piling the one idle replica between
  load refreshes).
"""

from __future__ import annotations

import random
import threading
from typing import TYPE_CHECKING, List, Optional, Sequence

if TYPE_CHECKING:  # import cycle: pool imports get_policy
    from .pool import Replica

__all__ = [
    "EwmaLatencyPolicy",
    "PowerOfTwoChoicesPolicy",
    "RoundRobinPolicy",
    "get_policy",
]


def _depth(replica: "Replica") -> float:
    """Advertised queue depth from a fresh load reply, else this
    driver's OWN in-flight count toward the replica — the local
    fallback signal for lanes that advertise liveness only (TCP) or
    whose load went stale.  The two scales differ (server-wide backlog
    vs one driver's outstanding calls), but both rank 'more loaded'
    upward, which is all power-of-two-choices needs."""
    d = replica.queue_depth()
    if d is not None:
        return float(d)
    return float(replica.inflight)


class RoundRobinPolicy:
    """Cycle through candidates in registration order."""

    name = "round_robin"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counter = 0

    def pick(self, candidates: Sequence, k: int = 1) -> List:
        if not candidates:
            return []
        with self._lock:
            start = self._counter
            self._counter += 1
        n = len(candidates)
        return [candidates[(start + i) % n] for i in range(min(k, n))]


class EwmaLatencyPolicy:
    """Lowest observed EWMA latency first; unmeasured replicas first
    of all (optimism: new/idle capacity must receive traffic to ever
    be measured)."""

    name = "ewma"

    def pick(self, candidates: Sequence, k: int = 1) -> List:
        ranked = sorted(
            candidates,
            key=lambda r: (
                r.ewma_latency_s is not None,  # unmeasured first
                r.ewma_latency_s or 0.0,
            ),
        )
        return list(ranked[:k])


class PowerOfTwoChoicesPolicy:
    """Two random candidates, route to the lower advertised queue
    depth (ties/unknown: lower EWMA, then the sampling order)."""

    name = "p2c"

    def __init__(self, rng: Optional[random.Random] = None) -> None:
        self._rng = rng or random.Random()

    def _better(self, a: "Replica", b: "Replica") -> "Replica":
        da, db = _depth(a), _depth(b)
        if da != db:
            return a if da < db else b
        ea, eb = a.ewma_latency_s, b.ewma_latency_s
        if ea is not None and eb is not None and ea != eb:
            return a if ea < eb else b
        return a

    def pick(self, candidates: Sequence, k: int = 1) -> List:
        pool = list(candidates)
        out: List = []
        while pool and len(out) < k:
            if len(pool) == 1:
                choice = pool[0]
            else:
                a, b = self._rng.sample(pool, 2)
                choice = self._better(a, b)
            out.append(choice)
            pool.remove(choice)
        return out


_POLICIES = {
    "round_robin": RoundRobinPolicy,
    "ewma": EwmaLatencyPolicy,
    "p2c": PowerOfTwoChoicesPolicy,
}


def get_policy(policy: object) -> object:
    """A policy instance from a name ("p2c" default, "round_robin",
    "ewma") or a pre-built object exposing ``pick(candidates, k)``."""
    if isinstance(policy, str):
        try:
            return _POLICIES[policy]()
        except KeyError:
            raise ValueError(
                f"unknown routing policy {policy!r}; "
                f"choose from {sorted(_POLICIES)}"
            ) from None
    if not hasattr(policy, "pick"):
        raise TypeError(
            f"policy must be a name or expose .pick(candidates, k); "
            f"got {type(policy).__name__}"
        )
    return policy
