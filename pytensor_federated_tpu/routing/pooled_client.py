"""`PooledArraysClient`: the transport-client surface over a replica pool.

The facade that makes a :class:`~.pool.NodePool` drop-in wherever a
pinned transport client went before — the same
``evaluate``/``evaluate_many`` (sync + async) surface as
:class:`~pytensor_federated_tpu.service.client.ArraysToArraysServiceClient`
and :class:`~pytensor_federated_tpu.service.tcp.TcpArraysClient`, with
three behaviors neither pinned client can express:

- **Routing**: every call picks its replica through the pool's policy
  (power-of-two-choices over advertised queue depth by default) and
  skips tripped breakers.
- **Hedged requests** (``hedge=True``, for idempotent computes): if
  the primary replica has not replied by the pool's observed
  latency-quantile deadline, the SAME request fires at a second
  replica; first reply wins, the loser is cancelled (gRPC lane — its
  connection is dropped so the lock-step stream cannot desynchronize)
  or abandoned (TCP lane — a sync socket call cannot be interrupted;
  its late reply is consumed and discarded on its own connection).
- **Mid-window failover**: ``evaluate_many`` spreads the request list
  over healthy replicas (shares weighted by observed per-request
  EWMA latency, so an alive-but-slow replica organically receives
  less work) and, when a replica dies mid-window, re-queues the
  UN-REPLIED tail of its pipelined window onto the survivors — the
  replies that already arrived are kept, nothing is double-assigned,
  and each shard still rides the PR-3 machinery (wire batch frames
  when advertised, in-flight byte caps, error drains) because the
  per-replica pass IS the existing client's
  ``evaluate_many_partial``.

Failure semantics mirror the pinned clients': transport trouble fails
over (and feeds the breaker); deterministic server errors — in-band
npwire error replies, ``RemoteComputeError``, non-retryable gRPC
status codes — raise immediately without burning a failover, because
the same inputs would fail identically on every replica.

Telemetry: calls run under ``pool.evaluate`` / ``pool.evaluate_many``
root spans with one ``pool.attempt`` / ``pool.window`` child per
replica attempt (attr ``replica``), so the trace of a failed-over or
hedged call shows every replica it touched; node-side span trees from
each attempt reunite under the same trace id as usual
(:mod:`~pytensor_federated_tpu.telemetry.reunion`).
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import math
import threading
import time
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..telemetry import flightrec as _flightrec
from ..telemetry import spans as _spans
from . import partition as _gradpart
from .pool import (
    NodePool,
    Replica,
    _POOL_FAILOVERS,
    _POOL_HEDGES,
)

__all__ = ["PooledArraysClient"]


class _LatencyRing:
    """Bounded ring of recent per-call latencies with an empirical
    quantile — the hedge-deadline estimator.  Tiny (128 floats) and
    lock-guarded; a sort per hedge decision is noise next to an RPC."""

    def __init__(self, capacity: int = 128) -> None:
        self._cap = capacity
        self._values: List[float] = []
        self._idx = 0
        self._lock = threading.Lock()

    def record(self, value: float) -> None:
        with self._lock:
            if len(self._values) < self._cap:
                self._values.append(value)
            else:
                self._values[self._idx] = value
                self._idx = (self._idx + 1) % self._cap

    def quantile(self, q: float, *, min_samples: int = 8) -> Optional[float]:
        with self._lock:
            if len(self._values) < min_samples:
                return None
            ordered = sorted(self._values)
        rank = min(len(ordered) - 1, int(math.ceil(q * len(ordered))) - 1)
        return ordered[max(rank, 0)]


@lru_cache(maxsize=1)
def _grpc_classifier() -> tuple:
    """Resolve (AioRpcError, _is_retryable) ONCE — the classifier runs
    per call result, and a per-call ``import grpc`` in that hot path
    is the PR-10-review function-level-import class (ISSUE-13
    satellite).  Lazy (not module-level) so importing routing/ never
    drags grpc in on pools that only run tcp/shm lanes."""
    try:
        import grpc

        from ..service.client import _is_retryable

        return grpc.aio.AioRpcError, _is_retryable
    except ImportError:
        return None, None


@lru_cache(maxsize=1)
def _deadline_exceeded() -> type:
    """Resolve DeadlineExceeded once (hot-path import hoist)."""
    from ..service.deadline import DeadlineExceeded

    return DeadlineExceeded


def _is_transport_error(exc: BaseException) -> bool:
    """Transport trouble (failover-worthy) vs deterministic failure.
    Matches the pinned clients' classification: ConnectionError/OSError
    always transport; AioRpcError by status code; RemoteComputeError
    and other RuntimeErrors are the request's own fault."""
    aio_error, is_retryable = _grpc_classifier()
    if aio_error is not None and isinstance(exc, aio_error):
        return is_retryable(exc)
    return isinstance(exc, (ConnectionError, OSError))


def _is_deadline(exc: BaseException) -> bool:
    """Whether the failure is the CALLER's spent deadline budget —
    which says nothing about the replica's health either way (the
    fail-fast guard can fire before a single byte is sent), so routing
    must book NEITHER a success nor a failure for it."""
    return isinstance(exc, _deadline_exceeded())


class PooledArraysClient:
    """Pool-routed evaluation client (module docstring for semantics).

    ``pool``: a pre-built :class:`NodePool`, or a sequence of
    ``(host, port)`` addresses — the latter constructs an owned pool
    (forwarding ``transport=``/``policy=``/etc. via ``pool_kwargs``)
    whose probe loop ``close()`` stops.

    ``hedge=True`` enables hedged single evaluations once enough
    latency samples exist; ``hedge_quantile`` sets the fire deadline
    (default p95 of this client's observed call latencies) and
    ``hedge_min_wait_s`` floors it.  Hedging re-executes the compute
    on a second replica — only enable it for idempotent computes
    (logp evaluations are; anything with server-side state is not).
    """

    def __init__(
        self,
        pool: object,
        *,
        hedge: bool = False,
        hedge_quantile: float = 0.95,
        hedge_min_wait_s: float = 0.001,
        **pool_kwargs: object,
    ) -> None:
        if isinstance(pool, NodePool):
            if pool_kwargs:
                raise ValueError(
                    "pool_kwargs only apply when constructing the pool "
                    "from addresses; pass them to NodePool instead"
                )
            self.pool = pool
            self._owns_pool = False
        else:
            self.pool = NodePool(pool, **pool_kwargs)
            self._owns_pool = True
        self.hedge = bool(hedge)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_wait_s = float(hedge_min_wait_s)
        self._latency = _LatencyRing()

    def close(self) -> None:
        """Stop probing / close clients on an OWNED pool (a shared
        pool outlives any one facade and is left untouched)."""
        if self._owns_pool:
            self.pool.close()

    # -- per-replica calls ------------------------------------------------

    async def _call_replica(
        self, replica: Replica, arrays: Sequence
    ) -> list:
        client = self.pool.client_for(replica)
        replica.inflight += 1  # the local load signal (policies.py)
        try:
            if replica.transport == "grpc":
                return await client.evaluate_async(*arrays)
            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()  # spans cross the worker
            return await loop.run_in_executor(
                self.pool.executor_for(replica),
                lambda: ctx.run(client.evaluate, *arrays),
            )
        finally:
            replica.inflight -= 1

    async def _window_replica(
        self, replica: Replica, reqs: Sequence, window: int, batch: object
    ) -> Tuple[list, Optional[BaseException], float]:
        """One partial pipelined pass on one replica ->
        ``(results_with_None_tail, transport_exc_or_None, wall_s)``.
        Deterministic server errors raise out of here."""
        client = self.pool.client_for(replica)
        t0 = time.perf_counter()
        replica.inflight += len(reqs)  # the local load signal
        try:
            with _spans.span(
                "pool.window", replica=replica.address, n=len(reqs)
            ):
                if replica.transport == "grpc":
                    partial, exc = (
                        await client.evaluate_many_partial_async(
                            reqs, window=window, batch=batch
                        )
                    )
                else:
                    loop = asyncio.get_running_loop()
                    ctx = contextvars.copy_context()
                    partial, exc = await loop.run_in_executor(
                        self.pool.executor_for(replica),
                        lambda: ctx.run(
                            client.evaluate_many_partial,
                            reqs,
                            window=window,
                            batch=batch,
                        ),
                    )
        finally:
            replica.inflight -= len(reqs)
        return partial, exc, time.perf_counter() - t0

    # -- single evaluation (+ hedging) ------------------------------------

    def _hedge_deadline_s(self) -> Optional[float]:
        if not self.hedge:
            return None
        q = self._latency.quantile(self.hedge_quantile)
        if q is None:
            return None
        return max(q, self.hedge_min_wait_s)

    async def _cancel_loser(self, task: asyncio.Task, replica: Replica) -> None:
        task.cancel()
        with contextlib.suppress(BaseException):
            await task
        # The loser's outcome is UNKNOWN (abandoned mid-flight): give
        # back any half-open probe token it held instead of recording a
        # verdict — leaving it claimed would park the breaker in
        # half-open forever when no probe loop runs.
        replica.breaker.release()
        if replica.transport == "grpc" and replica.client is not None:
            # A cancelled lock-step stream call may have written its
            # request without reading the reply — the connection is
            # desynchronized.  Drop it so the replica's next call
            # reconnects cleanly.  (TCP losers run to completion on
            # their own worker thread and stay correlated.)
            with contextlib.suppress(Exception):
                await replica.client._drop_privates()

    async def _attempt(
        self, replica: Replica, arrays: Sequence, exclude: Sequence
    ) -> Tuple[list, float, Replica]:
        """One (possibly hedged) attempt: returns
        ``(outputs, wall_s, serving_replica)``; transport errors and
        server errors raise to the failover loop."""
        t0 = time.perf_counter()
        deadline = self._hedge_deadline_s()
        with _spans.span("pool.attempt", replica=replica.address):
            if deadline is None:
                result = await self._call_replica(replica, arrays)
                return result, time.perf_counter() - t0, replica
            primary: asyncio.Task = asyncio.ensure_future(
                self._call_replica(replica, arrays)
            )
            done, _ = await asyncio.wait({primary}, timeout=deadline)
            if primary in done:
                return primary.result(), time.perf_counter() - t0, replica
            # A hedge re-executes the compute: it spends from the
            # pool's retry budget FIRST, so a sick pool stops hedging
            # before hedges become half its traffic (budget checked
            # before pick — a denied hedge must not burn a half-open
            # probe token).
            if not self.pool.allow_retry("hedge"):
                return await primary, time.perf_counter() - t0, replica
            hedged = self.pool.pick(
                1, exclude=set(exclude) | {replica.address}
            )
            if not hedged:
                # No replica to hedge onto (single-replica pool, or
                # everything else excluded/breaker-open): nothing
                # amplified, so give the token back — otherwise a
                # sustained slow patch drains the bucket with zero
                # hedges fired and later denies REAL failovers.
                self.pool.retry_budget.refund()
                return await primary, time.perf_counter() - t0, replica
            hedge_replica = hedged[0]
            _POOL_HEDGES.labels(outcome="fired").inc()
            _flightrec.record(
                "pool.hedge",
                primary=replica.address,
                hedge=hedge_replica.address,
                deadline_s=round(deadline, 6),
            )
            with _spans.span(
                "pool.attempt", replica=hedge_replica.address, hedge=True
            ):
                hedge_task: asyncio.Task = asyncio.ensure_future(
                    self._call_replica(hedge_replica, arrays)
                )
                tasks = {primary: replica, hedge_task: hedge_replica}
                first_exc: Optional[BaseException] = None
                while tasks:
                    done, _ = await asyncio.wait(
                        tasks, return_when=asyncio.FIRST_COMPLETED
                    )
                    for task in done:
                        task_replica = tasks.pop(task)
                        try:
                            result = task.result()
                        except BaseException as e:  # noqa: BLE001
                            # Only TRANSPORT trouble feeds the breaker:
                            # a deterministic server error is the
                            # request's own fault and would fail
                            # identically on a healthy replica (which
                            # DID serve it — a success for routing).
                            # A spent DEADLINE is neither: give back
                            # the probe token without an outcome.
                            if _is_deadline(e):
                                task_replica.breaker.release()
                            elif _is_transport_error(e):
                                self.pool.record_result(task_replica, False)
                            else:
                                self.pool.record_result(task_replica, True)
                            # Mark as already-recorded so the failover
                            # loop does not book a second breaker hit
                            # for the same failure when this re-raises.
                            e._pftpu_recorded = True  # type: ignore[attr-defined]
                            if not _is_transport_error(e) or not tasks:
                                for other, other_replica in tasks.items():
                                    await self._cancel_loser(
                                        other, other_replica
                                    )
                                raise
                            first_exc = first_exc or e
                            continue
                        for other, other_replica in list(tasks.items()):
                            tasks.pop(other)
                            await self._cancel_loser(other, other_replica)
                        _POOL_HEDGES.labels(
                            outcome=(
                                "won"
                                if task_replica is hedge_replica
                                else "lost"
                            )
                        ).inc()
                        return (
                            result,
                            time.perf_counter() - t0,
                            task_replica,
                        )
                raise first_exc  # both attempts failed on transport

    async def evaluate_async(self, *arrays: np.ndarray) -> List[np.ndarray]:
        """Evaluate one request through the pool with breaker-aware
        failover (and hedging when enabled)."""
        with _spans.span(
            "pool.evaluate", transport=self.pool.transport
        ) as root:
            exclude: set = set()
            last_exc: Optional[BaseException] = None
            charged = False
            while True:
                picked = self.pool.pick(1, exclude=exclude)
                if not picked:
                    if charged:
                        # The granted token bought a re-pick that
                        # found no replica: nothing amplified — give
                        # it back (the hedge no-replica posture).
                        self.pool.retry_budget.refund()
                    break
                charged = False
                replica = picked[0]
                try:
                    result, wall, served_by = await self._attempt(
                        replica, arrays, exclude
                    )
                except BaseException as e:  # noqa: BLE001
                    recorded = getattr(e, "_pftpu_recorded", False)
                    if _is_deadline(e):
                        # The CALLER's budget died — says nothing
                        # about the replica (the fail-fast guard can
                        # fire before a byte is sent): book neither
                        # outcome, just give back the breaker/probe
                        # token pick() acquired.
                        if not recorded:
                            replica.breaker.release()
                        root.set_attr("error", "deadline")
                        raise
                    if not _is_transport_error(e):
                        # Deterministic server failure: the request's
                        # own fault — no failover (it would fail
                        # identically everywhere), and the replica DID
                        # serve it, so routing books a SUCCESS (which
                        # also closes a half-open probe instead of
                        # leaking its token).
                        if not recorded:
                            self.pool.record_result(replica, True)
                        root.set_attr("error", "server")
                        raise
                    if not recorded:
                        self.pool.record_result(replica, False)
                    last_exc = e
                    exclude.add(replica.address)
                    _POOL_FAILOVERS.labels(
                        transport=self.pool.transport
                    ).inc()
                    _flightrec.record(
                        "pool.failover",
                        replica=replica.address,
                        error=f"{type(e).__name__}: {e}"[:200],
                    )
                    # Each failover re-pick is amplification and spends
                    # from the retry budget: exhausted = this call gets
                    # no further attempts (degrade to one-attempt-per-
                    # call instead of multiplying a sick pool's load).
                    if not self.pool.allow_retry("failover"):
                        root.set_attr("error", "transport")
                        raise
                    charged = True
                    continue
                self.pool.record_result(served_by, True, latency_s=wall)
                self._latency.record(wall)
                return result
            root.set_attr("error", "transport")
            raise last_exc if last_exc is not None else ConnectionError(
                f"no available replicas in pool "
                f"({len(self.pool)} registered)"
            )

    def evaluate(self, *arrays: np.ndarray) -> List[np.ndarray]:
        """Sync wrapper over :meth:`evaluate_async`."""
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(
            self.evaluate_async(*arrays)
        )

    __call__ = evaluate

    # -- pipelined batch with spread + mid-window failover ----------------

    # A replica only joins a spread window if it can serve at least one
    # request within ~this multiple of the window's makespan-balanced
    # wall; slower than that, its presence only ADDS tail latency (its
    # one-request shard outlives everyone else's whole shard).
    _STRAGGLER_SLACK = 1.5

    def _partition(
        self, pending: List[int], replicas: List[Replica], window: int
    ) -> List[Tuple[Replica, List[int]]]:
        """Contiguous shards of ``pending``, sized makespan-balanced by
        each replica's observed speed: replica ``i`` serves
        ``W / ewma_i`` requests where ``W = n / Σ(1/ewma)`` is the wall
        at which all shards finish together.  Unmeasured replicas get
        the mean measured weight so new capacity still receives work.
        A replica whose SINGLE-request cost exceeds the balanced wall
        (times a slack factor) sits the window out — an
        order-of-magnitude-degraded replica would otherwise stretch
        every window to its own latency for one request's worth of
        help.  Contiguity keeps each shard a well-formed pipelined
        window for batch-frame packing."""
        measured = [
            1.0 / r.ewma_latency_s
            for r in replicas
            if r.ewma_latency_s
        ]
        default_w = (sum(measured) / len(measured)) if measured else 1.0
        n = len(pending)

        def weights_of(group: Sequence[Replica]) -> List[float]:
            return [
                (1.0 / r.ewma_latency_s) if r.ewma_latency_s else default_w
                for r in group
            ]

        weights = weights_of(replicas)
        total_w = sum(weights) or float(len(replicas))
        balanced_wall = n / total_w  # seconds, in EWMA units
        kept = [
            r
            for r, w in zip(replicas, weights)
            if r.ewma_latency_s is None
            or r.ewma_latency_s <= balanced_wall * self._STRAGGLER_SLACK
        ]
        if kept:
            replicas = kept
            weights = weights_of(replicas)
            total_w = sum(weights) or float(len(replicas))
        # Floor + remainder-to-fastest: floor so a near-zero share
        # genuinely rounds to nothing, remainder biased to the fastest
        # replicas so the leftovers land where they finish soonest.
        sizes = [int(n * w / total_w) for w in weights]
        order = sorted(
            range(len(replicas)), key=lambda i: -weights[i]
        )
        i = 0
        while sum(sizes) < n:
            sizes[order[i % len(order)]] += 1
            i += 1
        shards: List[Tuple[Replica, List[int]]] = []
        start = 0
        for replica, size in zip(replicas, sizes):
            if size > 0:
                shards.append((replica, pending[start : start + size]))
                start += size
        return shards

    async def evaluate_many_async(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[List[np.ndarray]]:
        """Pipelined evaluation of MANY requests, spread over the
        pool's healthy replicas, with mid-window failover: a replica
        dying mid-pass costs only the un-replied tail of ITS shard,
        which re-queues onto the survivors.  Each per-replica shard
        runs the existing pipelined machinery (`evaluate_many`'s
        windowing, byte caps, and wire batch frames when the replica
        advertises them), so PR-3 semantics hold per shard."""
        requests = list(requests)
        n = len(requests)
        if n == 0:
            return []
        results: List[Optional[List[np.ndarray]]] = [None] * n
        with _spans.span(
            "pool.evaluate_many",
            transport=self.pool.transport,
            n=n,
            window=window,
        ) as root:
            pending = list(range(n))
            exclude: set = set()
            last_exc: Optional[BaseException] = None
            while pending:
                k = max(1, math.ceil(len(pending) / max(1, window)))
                replicas = self.pool.pick(k, exclude=exclude)
                if not replicas:
                    root.set_attr("error", "transport")
                    raise (
                        last_exc
                        if last_exc is not None
                        else ConnectionError(
                            f"no available replicas in pool "
                            f"({len(self.pool)} registered) with "
                            f"{len(pending)} requests un-replied"
                        )
                    )
                shards = self._partition(pending, replicas, window)
                # A replica picked (breaker-acquired) but then benched
                # by the partitioner — straggler rule, or a zero-sized
                # share — must give back its half-open probe token:
                # it never gets a call to resolve the probe.
                sharded = {id(r) for r, _ in shards}
                for replica in replicas:
                    if id(replica) not in sharded:
                        replica.breaker.release()
                outcomes = await asyncio.gather(
                    *(
                        self._window_replica(
                            replica,
                            [requests[i] for i in shard],
                            window,
                            batch,
                        )
                        for replica, shard in shards
                    ),
                    return_exceptions=True,
                )
                new_pending: List[int] = []
                server_exc: Optional[BaseException] = None
                budget_spent = False
                granted = 0
                for (replica, shard), out in zip(shards, outcomes):
                    if isinstance(out, BaseException):
                        # evaluate_many_partial returns transport
                        # trouble — an exception here is a
                        # deterministic server/decode error: the
                        # replica is healthy (it served the request),
                        # so routing books a SUCCESS — which also
                        # resolves a half-open probe instead of
                        # leaking its token.  A spent DEADLINE is
                        # neither outcome (the guard can fire before a
                        # byte is sent): release the token instead.
                        # Every sibling shard has settled (gather with
                        # return_exceptions), so raising is
                        # orphan-free.
                        if _is_deadline(out):
                            replica.breaker.release()
                        else:
                            self.pool.record_result(replica, True)
                        server_exc = server_exc or out
                        continue
                    partial, exc, wall = out
                    served = 0
                    for idx, res in zip(shard, partial):
                        if res is not None:
                            results[idx] = res
                            served += 1
                        else:
                            new_pending.append(idx)
                    if exc is None:
                        self.pool.record_result(
                            replica,
                            True,
                            latency_s=wall,
                            n_requests=max(1, len(shard)),
                        )
                    else:
                        last_exc = exc
                        self.pool.record_result(replica, False)
                        exclude.add(replica.address)
                        _POOL_FAILOVERS.labels(
                            transport=self.pool.transport
                        ).inc()
                        _flightrec.record(
                            "pool.failover",
                            replica=replica.address,
                            requeued=len(shard) - served,
                            error=f"{type(exc).__name__}: {exc}"[:200],
                        )
                        # Re-queuing a failed shard's tail is
                        # amplification: one budget spend per failed
                        # replica WITH a tail to re-queue (a replica
                        # that failed after serving its whole shard
                        # amplifies nothing); exhausted = the tail
                        # surfaces its transport error instead of
                        # another round.
                        if served < len(shard):
                            if self.pool.allow_retry("failover"):
                                granted += 1
                            else:
                                budget_spent = True
                if server_exc is not None:
                    if granted:
                        # The round aborts: tokens granted to sibling
                        # shards bought no re-queue — give them back
                        # (the hedge no-replica path's posture).
                        self.pool.retry_budget.refund(granted)
                    root.set_attr("error", "server")
                    raise server_exc
                if budget_spent and new_pending:
                    if granted:
                        self.pool.retry_budget.refund(granted)
                    root.set_attr("error", "transport")
                    raise (
                        last_exc
                        if last_exc is not None
                        else ConnectionError(
                            "retry budget exhausted with "
                            f"{len(new_pending)} requests un-replied"
                        )
                    )
                new_pending.sort()
                pending = new_pending
            return results  # type: ignore[return-value]

    def evaluate_many(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        batch: object = "auto",
    ) -> List[List[np.ndarray]]:
        """Sync wrapper over :meth:`evaluate_many_async`."""
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(
            self.evaluate_many_async(requests, window=window, batch=batch)
        )

    # -- reduce-scatter windows (ISSUE 13) --------------------------------

    async def _reduce_replica(
        self,
        replica: Replica,
        reqs: Sequence,
        window: int,
        slices: int,
        total: Optional[int],
    ) -> Tuple[Optional[np.ndarray], Optional[np.ndarray], List[int], float]:
        """One replica's reduce pass -> ``(head, flat, unserved_local
        _indices, wall_s)``.  tcp/shm lanes ride the wire reduce window
        (all-or-nothing per replica: a transport failure re-queues the
        whole shard); grpc replicas — which have no reduce wire — fall
        back to ``evaluate_many_partial_async`` plus a DRIVER-side
        reduction, keeping the answered items' partial sum and
        re-queuing only the holes (bytes are not saved on that lane,
        but a mixed pool stays correct).  Deterministic server errors
        raise out of here."""
        client = self.pool.client_for(replica)
        t0 = time.perf_counter()
        replica.inflight += len(reqs)
        try:
            with _spans.span(
                "pool.reduce_window",
                replica=replica.address,
                n=len(reqs),
            ):
                if replica.transport == "grpc":
                    partial, exc = (
                        await client.evaluate_many_partial_async(
                            reqs, window=window, batch="auto"
                        )
                    )
                    served = [
                        r for r in partial if r is not None
                    ]
                    holes = [
                        i for i, r in enumerate(partial) if r is None
                    ]
                    if exc is not None and not holes:
                        holes = list(range(len(reqs)))
                        served = []
                    head = flat = None
                    if served:
                        summed = _gradpart.reduce_replies(served)
                        head = np.asarray(summed[0])
                        flat = _gradpart.concat_tail(summed)
                        if total is not None and flat.size != int(total):
                            raise _gradpart.PartitionError(
                                f"grpc reduce tail size {flat.size} != "
                                f"declared total {total}"
                            )
                    return head, flat, holes, time.perf_counter() - t0
                loop = asyncio.get_running_loop()
                ctx = contextvars.copy_context()
                try:
                    head, flat = await loop.run_in_executor(
                        self.pool.executor_for(replica),
                        lambda: ctx.run(
                            client.evaluate_reduced,
                            reqs,
                            window=window,
                            slices=slices,
                            total=total,
                        ),
                    )
                except (ConnectionError, OSError):
                    # All-or-nothing wire window: the whole shard
                    # re-queues (holes = everything).
                    return (
                        None,
                        None,
                        list(range(len(reqs))),
                        time.perf_counter() - t0,
                    )
                return head, flat, [], time.perf_counter() - t0
        finally:
            replica.inflight -= len(reqs)

    async def evaluate_reduced_async(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        slices: int = 1,
        total: Optional[int] = None,
    ) -> List[np.ndarray]:
        """Reduce-scatter evaluation through the pool:
        ``[head_sum, flat_tail_sum]`` over ALL requests.

        Requests spread over healthy replicas exactly like
        :meth:`evaluate_many_async` (EWMA-weighted shards), but each
        replica answers its shard as ONE partition-indexed partial sum
        (wire reduce windows on tcp/shm; a driver-side reduction on
        grpc replicas, so MIXED pools stay correct), and the driver
        sums the partials — reply bytes scale with POOL WIDTH, not
        request count.  A replica failing mid-round re-queues only its
        un-reduced shard onto the survivors, charging the retry budget
        once per failed replica WITH a tail (the ``evaluate_many``
        refund posture); deterministic errors raise immediately —
        a partial sum is never silently returned."""
        requests = list(requests)
        if not requests:
            raise _gradpart.PartitionError(
                "cannot reduce an empty request list"
            )
        head: Optional[np.ndarray] = None
        flat: Optional[np.ndarray] = None
        with _spans.span(
            "pool.evaluate_reduced",
            transport=self.pool.transport,
            n=len(requests),
            slices=slices,
        ) as root:
            pending = list(range(len(requests)))
            exclude: set = set()
            last_exc: Optional[BaseException] = None
            while pending:
                k = max(1, math.ceil(len(pending) / max(1, window)))
                replicas = self.pool.pick(k, exclude=exclude)
                if not replicas:
                    root.set_attr("error", "transport")
                    raise (
                        last_exc
                        if last_exc is not None
                        else ConnectionError(
                            f"no available replicas in pool "
                            f"({len(self.pool)} registered) with "
                            f"{len(pending)} requests un-reduced"
                        )
                    )
                shards = self._partition(pending, replicas, window)
                sharded = {id(r) for r, _ in shards}
                for replica in replicas:
                    if id(replica) not in sharded:
                        replica.breaker.release()
                outcomes = await asyncio.gather(
                    *(
                        self._reduce_replica(
                            replica,
                            [requests[i] for i in shard],
                            window,
                            slices,
                            total,
                        )
                        for replica, shard in shards
                    ),
                    return_exceptions=True,
                )
                new_pending: List[int] = []
                budget_spent = False
                granted = 0
                server_exc: Optional[BaseException] = None
                for (replica, shard), out in zip(shards, outcomes):
                    if isinstance(out, BaseException):
                        # Deterministic server/geometry error: the
                        # replica DID serve (routing books a success);
                        # a spent deadline books neither.
                        if _is_deadline(out):
                            replica.breaker.release()
                        else:
                            self.pool.record_result(replica, True)
                        server_exc = server_exc or out
                        continue
                    r_head, r_flat, holes, wall = out
                    if r_head is not None:
                        assert r_flat is not None
                        if head is None:
                            head, flat = r_head, r_flat
                        elif (
                            r_head.shape != head.shape
                            or r_flat.size != flat.size
                        ):
                            server_exc = server_exc or (
                                _gradpart.PartitionError(
                                    "replicas disagree on reply "
                                    "geometry"
                                )
                            )
                            self.pool.record_result(replica, True)
                            continue
                        else:
                            head = head + r_head
                            flat = flat + r_flat
                    if not holes:
                        self.pool.record_result(
                            replica,
                            True,
                            latency_s=wall,
                            n_requests=max(1, len(shard)),
                        )
                        continue
                    # Transport failure with a tail to re-queue: one
                    # budget spend per failed replica (the
                    # evaluate_many posture — nothing charged for a
                    # replica that served its whole shard).
                    last_exc = last_exc or ConnectionError(
                        f"replica {replica.address} failed "
                        f"{len(holes)} reduce requests"
                    )
                    self.pool.record_result(replica, False)
                    exclude.add(replica.address)
                    _POOL_FAILOVERS.labels(
                        transport=self.pool.transport
                    ).inc()
                    _flightrec.record(
                        "pool.failover",
                        replica=replica.address,
                        requeued=len(holes),
                        error="reduce window transport failure",
                    )
                    new_pending.extend(shard[i] for i in holes)
                    if self.pool.allow_retry("failover"):
                        granted += 1
                    else:
                        budget_spent = True
                if server_exc is not None:
                    if granted:
                        self.pool.retry_budget.refund(granted)
                    root.set_attr("error", "server")
                    raise server_exc
                if budget_spent and new_pending:
                    if granted:
                        self.pool.retry_budget.refund(granted)
                    root.set_attr("error", "transport")
                    raise (
                        last_exc
                        if last_exc is not None
                        else ConnectionError(
                            "retry budget exhausted with "
                            f"{len(new_pending)} requests un-reduced"
                        )
                    )
                new_pending.sort()
                pending = new_pending
            assert head is not None and flat is not None
            return [head, flat]

    def evaluate_reduced(
        self,
        requests: Sequence[Sequence[np.ndarray]],
        *,
        window: int = 8,
        slices: int = 1,
        total: Optional[int] = None,
    ) -> List[np.ndarray]:
        """Sync wrapper over :meth:`evaluate_reduced_async`."""
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(
            self.evaluate_reduced_async(
                requests, window=window, slices=slices, total=total
            )
        )
