"""Replica-pool registry: health/load probing, breakers, routing state.

The reference design polls each node's ``GetLoad`` to decide where
work goes (reference: service.py:88-96, 240-263) — but its clients
stay pinned to whichever server they connected to, so one slow or
dead node stalls the whole graph.  :class:`NodePool` is the missing
registry between "arrays-in/arrays-out RPC" and multi-node
throughput: a set of interchangeable replicas serving the SAME
compute, each carrying

- a :class:`~.breaker.CircuitBreaker` (trip on consecutive failures,
  half-open probe, jittered exponential backoff),
- the last advertised load (the enriched npwire GetLoad reply — queue
  depth, batcher tallies, latency quantiles — or the reference's
  3-field protobuf reply; auto-detected per reply like
  ``get_load_async``), with STALE-LOAD EVICTION: a reply older than
  ``load_stale_s`` stops informing routing decisions,
- this driver's own observations (EWMA per-request latency, local
  in-flight count) as the fallback signal.

Probing lanes per transport:

- ``transport="grpc"`` — the existing ``GetLoad`` lane
  (:func:`~pytensor_federated_tpu.service.client.get_load_async`);
  npwire-JSON and reference-protobuf replies both parse.
- ``transport="tcp"`` — the ZERO-ITEM batch probe frame
  (:meth:`~pytensor_federated_tpu.service.tcp.TcpArraysClient._probe_batch`'s
  capability handshake) reused as the health check: a live node echoes
  an empty batch reply with the probe's uuid; anything else — refused
  connect, garbage, silence — is a failed probe.  The TCP protocol has
  no GetLoad, so liveness is all it advertises (load fields stay
  ``None`` and routing falls back to EWMA/in-flight).

``start()`` runs the probe sweep on a background daemon thread;
``probe_once()`` is the synchronous sweep (tests, on-demand recovery).
Probe failures feed the SAME breakers as call failures, so a dead
replica is quarantined even while no traffic flows.

Metric families (``pftpu_pool_*``, catalog: docs/observability.md) and
flight-recorder events (``pool.*``) are emitted here and by
:mod:`.pooled_client`; per-replica gauges are labeled by ``replica``
("host:port") so the exposition endpoint renders pool health directly
(``tools/metrics_dump.py --pool``).
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import uuid as uuid_mod
from functools import lru_cache
from typing import TYPE_CHECKING, Any, Dict, List, Optional, Sequence, Tuple

if TYPE_CHECKING:
    from concurrent.futures import ThreadPoolExecutor

from ..faultinject import runtime as _fi
from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics
from .breaker import CircuitBreaker
from .budget import RetryBudget
from .policies import get_policy

__all__ = ["NodePool", "Replica"]

HostPort = Tuple[str, int]

# -- pool metric families (catalog: docs/observability.md) ---------------

_POOL_REPLICAS = _metrics.gauge(
    "pftpu_pool_replicas",
    "Pool replicas by breaker state",
    ("state",),
)
_POOL_PICKS = _metrics.counter(
    "pftpu_pool_picks_total",
    "Replica picks, by routing policy",
    ("policy",),
)
_POOL_FAILOVERS = _metrics.counter(
    "pftpu_pool_failovers_total",
    "Mid-call failovers onto another replica",
    ("transport",),
)
_POOL_HEDGES = _metrics.counter(
    "pftpu_pool_hedges_total",
    "Hedged requests, by outcome (fired / won / lost)",
    ("outcome",),
)
_POOL_BREAKER_TRANSITIONS = _metrics.counter(
    "pftpu_pool_breaker_transitions_total",
    "Circuit-breaker state transitions, by destination state",
    ("to",),
)
_POOL_PROBE_S = _metrics.histogram(
    "pftpu_pool_probe_seconds", "Per-replica health/load probe latency"
)
_POOL_UP = _metrics.gauge(
    "pftpu_pool_replica_up",
    "1 while the replica's breaker admits traffic, else 0",
    ("replica",),
)
_POOL_QDEPTH = _metrics.gauge(
    "pftpu_pool_replica_queue_depth",
    "Last advertised queue depth (-1 = unknown or stale)",
    ("replica",),
)
_POOL_EWMA = _metrics.gauge(
    "pftpu_pool_replica_ewma_seconds",
    "EWMA per-request latency observed by this driver",
    ("replica",),
)

_EWMA_ALPHA = 0.3


@lru_cache(maxsize=1)
def _remote_compute_error() -> type:
    """Resolve RemoteComputeError once — ``is_transient`` runs per
    member failure, and a per-call import there is the
    PR-10-review function-level-import class (ISSUE-13 satellite).
    Lazy because routing/ must not import service/ at module level
    (service/tcp.py imports routing.partition — a module-level import
    here would cycle)."""
    from ..service.tcp import RemoteComputeError

    return RemoteComputeError


class Replica:
    """One pool member: address + breaker + routing signals.

    The lazily-created transport client and (sync lanes) its dedicated
    single worker thread hang off the replica so connection state keeps
    the thread/loop affinity the transports require (service/client.py
    connection cache; tcp.py's single-socket lock-step contract).
    ``transport`` is PER REPLICA (default: the pool's), so one pool can
    mix shm replicas (colocated, zero-copy) with grpc/tcp ones — the
    policies, breakers, and failover machinery are transport-blind.
    """

    def __init__(
        self,
        host: str,
        port: int,
        breaker: CircuitBreaker,
        transport: str = "grpc",
        client_kwargs: Optional[dict] = None,
    ) -> None:
        self.host = host
        self.port = int(port)
        self.breaker = breaker
        self.transport = transport
        self.client_kwargs = client_kwargs
        self.ewma_latency_s: Optional[float] = None
        self.load: Optional[dict] = None
        self.load_ts: Optional[float] = None
        self.inflight = 0
        self.client: Optional[Any] = None  # created by NodePool.client_for
        self._executor: Optional["ThreadPoolExecutor"] = None  # TCP worker
        self._lock = threading.Lock()
        self._load_stale_s = 10.0  # overwritten by the owning pool

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def queue_depth(self) -> Optional[float]:
        """Advertised backlog from the last FRESH load reply: server
        batcher queue depth, else in-flight RPCs, else ``n_clients``;
        ``None`` when no load is known or the last one went stale
        (stale-load eviction — routing must not keep trusting a
        snapshot of a node that stopped answering probes)."""
        with self._lock:
            if self.load is None or self.load_ts is None:
                return None
            if time.monotonic() - self.load_ts > self._load_stale_s:
                self.load = None  # evict: stale load misroutes
                return None
            load = self.load
        batch = load.get("batch")
        if isinstance(batch, dict) and "queue_depth" in batch:
            return float(batch["queue_depth"])
        rpc = load.get("rpc")
        if isinstance(rpc, dict) and rpc.get("inflight") is not None:
            return float(rpc["inflight"])
        n = load.get("n_clients")
        return None if n is None else float(n)

    def record_load(self, load: Optional[dict]) -> None:
        with self._lock:
            if load is None:
                self.load = None
                self.load_ts = None
            else:
                self.load = load
                self.load_ts = time.monotonic()

    def record_latency(self, per_request_s: float) -> None:
        with self._lock:
            prev = self.ewma_latency_s
            self.ewma_latency_s = (
                per_request_s
                if prev is None
                else _EWMA_ALPHA * per_request_s + (1 - _EWMA_ALPHA) * prev
            )
        _POOL_EWMA.labels(replica=self.address).set(self.ewma_latency_s)


def _tcp_probe(host: str, port: int, *, timeout: float) -> bool:
    """One-shot TCP liveness check: the zero-item batch probe frame
    over a fresh connection.  A batch-aware node echoes an empty batch
    reply carrying the probe's uuid (tcp.py `_probe_batch` — the same
    frame that negotiates the batch capability); a pre-batch node
    answers SOMETHING well-formed (zero-arrays reply or a decode-error
    frame), which still proves liveness.  Refused/closed/garbled/slow
    is a failed probe."""
    from ..service.npwire import decode_arrays_all, decode_batch, encode_batch, is_batch_frame

    uid = uuid_mod.uuid4().bytes
    frame = encode_batch([], uuid=uid)
    try:
        with socket.create_connection((host, port), timeout=timeout) as s:
            s.settimeout(timeout)
            s.sendall(struct.pack("<I", len(frame)) + frame)
            hdr = b""
            while len(hdr) < 4:
                b = s.recv(4 - len(hdr))
                if not b:
                    return False
                hdr += b
            (n,) = struct.unpack("<I", hdr)
            payload = b""
            while len(payload) < n:
                b = s.recv(n - len(payload))
                if not b:
                    return False
                payload += b
    except (OSError, ConnectionError):
        return False
    try:
        if is_batch_frame(payload):
            items, ruid, err, _tid, _sp = decode_batch(payload)
            return ruid == uid and err is None and not items
        # Pre-batch peer: any decodable npwire reply proves liveness.
        decode_arrays_all(payload)
        return True
    # A garbled reply is a FAILED PROBE — False is this lane's loud
    # in-band verdict (the breaker records it), not a swallowed error.
    except Exception:  # graftlint: disable=wire-loudness -- probe verdict lane
        return False


class NodePool:
    """Registry of interchangeable replicas with probing and routing.

    ``replicas``: a sequence of ``(host, port)``; more can be added or
    removed while the pool runs (:meth:`add_replica` /
    :meth:`remove_replica`).  ``policy``: "p2c" (default),
    "round_robin", "ewma", or any object with ``pick(candidates, k)``.
    ``transport``: "grpc" (GetLoad probe lane + async clients) or
    "tcp" (zero-item-frame probe lane + per-replica worker threads).
    ``client_kwargs`` forwards to the per-replica transport client
    constructor (e.g. ``codec=``, ``use_stream=`` on the gRPC lane).
    """

    def __init__(
        self,
        replicas: Sequence[HostPort] = (),
        *,
        transport: str = "grpc",
        policy: object = "p2c",
        client_kwargs: Optional[dict] = None,
        probe_interval_s: float = 1.0,
        probe_timeout_s: float = 2.0,
        load_stale_s: float = 10.0,
        breaker_kwargs: Optional[dict] = None,
        member_retries: int = 2,
        retry_budget: Optional[RetryBudget] = None,
    ) -> None:
        if transport not in ("grpc", "tcp", "shm", "ring"):
            raise ValueError(
                f"transport must be 'grpc', 'tcp', 'shm' or 'ring', "
                f"got {transport!r}"
            )
        self.transport = transport
        self.policy = get_policy(policy)
        self.policy_name = getattr(self.policy, "name", "custom")
        self.client_kwargs = dict(client_kwargs or {})
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.load_stale_s = float(load_stale_s)
        self.breaker_kwargs = dict(breaker_kwargs or {})
        # fanout_exec.run_members' retry policy when handed this pool:
        # how many times a TRANSIENT member failure is re-run before it
        # surfaces (the member's own pooled client fails over between
        # attempts, so a retry is a different replica, not an instant
        # replay against the dead one).
        self.member_retries = int(member_retries)
        # Retry budget (ISSUE 10): every amplifying recovery attempt —
        # hedges, failover re-picks, member re-runs — spends from this
        # token bucket via allow_retry(), so a sick pool degrades to
        # one attempt per call instead of multiplying its own load.
        # Always present by default; pass an explicit RetryBudget to
        # tune rate/burst (there is deliberately no "unlimited" knob:
        # unbounded amplification is the overload-collapse mode this
        # subsystem exists to remove).
        self.retry_budget = (
            retry_budget if retry_budget is not None else RetryBudget()
        )
        self._lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._probe_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        for host, port in replicas:
            self.add_replica(host, port)

    # -- registry ---------------------------------------------------------

    def _make_replica(
        self,
        host: str,
        port: int,
        transport: Optional[str] = None,
        client_kwargs: Optional[dict] = None,
    ) -> Replica:
        addr = f"{host}:{int(port)}"

        def on_transition(old: str, new: str, _addr: str = addr) -> None:
            _POOL_BREAKER_TRANSITIONS.labels(to=new).inc()
            _flightrec.record(f"pool.breaker_{new}", replica=_addr)
            self._refresh_state_gauges()

        replica = Replica(
            host,
            port,
            CircuitBreaker(on_transition=on_transition, **self.breaker_kwargs),
            transport or self.transport,
            client_kwargs,
        )
        replica._load_stale_s = self.load_stale_s
        return replica

    def add_replica(
        self,
        host: str,
        port: int,
        *,
        transport: Optional[str] = None,
        client_kwargs: Optional[dict] = None,
    ) -> Replica:
        """Register one replica; ``transport`` overrides the pool
        default for THIS replica (``"shm"`` mixes a colocated
        zero-copy node into a grpc/tcp pool).  ``client_kwargs``
        overrides the pool-level kwargs for this replica — a replica
        of a DIFFERENT transport never inherits the pool default's
        kwargs (they target another client class)."""
        if transport is not None and transport not in (
            "grpc", "tcp", "shm", "ring"
        ):
            raise ValueError(
                f"transport must be 'grpc', 'tcp', 'shm' or 'ring', "
                f"got {transport!r}"
            )
        replica = self._make_replica(host, port, transport, client_kwargs)
        with self._lock:
            existing = self._replicas.get(replica.address)
            if existing is not None:
                # Idempotent re-add is fine; a CONFLICTING override is
                # not — silently keeping the old transport would route
                # every call down a lane the caller believes replaced.
                if (
                    transport is not None
                    and existing.transport != transport
                ) or (
                    client_kwargs is not None
                    and existing.client_kwargs != client_kwargs
                ):
                    raise ValueError(
                        f"replica {replica.address} is already "
                        f"registered as transport="
                        f"{existing.transport!r}; remove_replica() "
                        "first to re-register with different settings"
                    )
                return existing
            self._replicas[replica.address] = replica
        _flightrec.record("pool.replica_added", replica=replica.address)
        self._refresh_state_gauges()
        return replica

    def remove_replica(self, host: str, port: int) -> None:
        addr = f"{host}:{int(port)}"
        with self._lock:
            replica = self._replicas.pop(addr, None)
        if replica is None:
            return
        _flightrec.record("pool.replica_removed", replica=addr)
        _POOL_UP.labels(replica=addr).set(0)
        if replica.client is not None:
            close = getattr(replica.client, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        if replica._executor is not None:
            replica._executor.shutdown(wait=False)
        self._refresh_state_gauges()

    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def replica_at(self, host: str, port: int) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(f"{host}:{int(port)}")

    def __len__(self) -> int:
        with self._lock:
            return len(self._replicas)

    # -- transport clients ------------------------------------------------

    def client_for(self, replica: Replica) -> Any:
        """The replica's lazily-created transport client (dispatched on
        the REPLICA's transport — mixed pools construct per kind).
        ``retries=0`` on purpose: the POOL owns retry/failover — an
        inner retry loop would replay against the very replica being
        failed away from."""
        if replica.client is None:
            # Per-replica kwargs win; pool-level kwargs apply only to
            # replicas of the pool's own transport (they target one
            # specific client class — a codec= meant for grpc must not
            # reach the shm constructor in a mixed pool).
            if replica.client_kwargs is not None:
                kwargs = dict(replica.client_kwargs)
            elif replica.transport == self.transport:
                kwargs = dict(self.client_kwargs)
            else:
                kwargs = {}
            if replica.transport == "grpc":
                from ..service.client import ArraysToArraysServiceClient

                replica.client = ArraysToArraysServiceClient(
                    replica.host,
                    replica.port,
                    retries=0,
                    **kwargs,
                )
            elif replica.transport == "shm":
                from ..service.shm import ShmArraysClient

                replica.client = ShmArraysClient(
                    replica.host,
                    replica.port,
                    retries=0,
                    **kwargs,
                )
            elif replica.transport == "ring":
                from ..service.ring import RingArraysClient

                replica.client = RingArraysClient(
                    replica.host,
                    replica.port,
                    retries=0,
                    **kwargs,
                )
            else:
                from ..service.tcp import TcpArraysClient

                replica.client = TcpArraysClient(
                    replica.host,
                    replica.port,
                    retries=0,
                    **kwargs,
                )
        return replica.client

    def executor_for(self, replica: Replica) -> "ThreadPoolExecutor":
        """Sync lanes (tcp/shm): the replica's single worker thread
        (the sync socket client is driven off the event loop via
        ``run_in_executor``; one dedicated thread preserves the
        lock-step single-caller contract)."""
        if replica._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            replica._executor = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f"pftpu-pool-{replica.address}",
            )
        return replica._executor

    # -- probing ----------------------------------------------------------

    async def _probe_replica_grpc(self, replica: Replica) -> bool:
        from ..service.client import get_load_async

        if _fi.active_plan is not None:  # chaos seam: probe lane
            # The async twin: a delay rule must not block the event
            # loop (graftlint async-blocking, the PR-5 bug class).
            if not await _fi.probe_filter_async(replica.address):
                replica.record_load(None)
                return False
        t0 = time.perf_counter()
        load = await get_load_async(
            replica.host, replica.port, timeout=self.probe_timeout_s
        )
        _POOL_PROBE_S.observe(time.perf_counter() - t0)
        replica.record_load(load)
        return load is not None

    async def probe_once_async(self) -> int:
        """One concurrent probe sweep, dispatched PER REPLICA (mixed
        pools probe each member over its own lane); returns the number
        of replicas that answered.  Success/failure feeds each
        replica's breaker exactly like call outcomes do."""
        import asyncio

        replicas = self.replicas
        loop = asyncio.get_running_loop()

        def one(r: Replica) -> bool:
            if _fi.active_plan is not None:  # chaos seam: probe lane
                if not _fi.probe_filter(r.address):
                    r.record_load(None)
                    return False
            t0 = time.perf_counter()
            # The zero-item batch probe frame: the TCP health check,
            # which the shm doorbell answers too (its npwire fallback
            # lane) — one probe shape for both sync transports.
            ok = _tcp_probe(
                r.host, r.port, timeout=self.probe_timeout_s
            )
            _POOL_PROBE_S.observe(time.perf_counter() - t0)
            # No load schema on the sync probe: liveness only.
            r.record_load({} if ok else None)
            return ok

        results = await asyncio.gather(
            *(
                self._probe_replica_grpc(r)
                if r.transport == "grpc"
                else loop.run_in_executor(None, one, r)
                for r in replicas
            )
        )
        up = 0
        for replica, ok in zip(replicas, results):
            if ok:
                up += 1
                # A probe success RESTORES a tripped/half-open breaker
                # (background probing is the recovery lane) but does
                # not touch a closed one: resetting the call-failure
                # count on every sweep would let a node whose event
                # loop answers probes while its compute path fails
                # hover forever below the trip threshold.
                if replica.breaker.state != "closed":
                    replica.breaker.record_success()
            else:
                _flightrec.record(
                    "pool.probe_failed", replica=replica.address
                )
                replica.breaker.record_failure()
        self._refresh_state_gauges()
        return up

    def probe_once(self) -> int:
        """Sync wrapper over :meth:`probe_once_async`."""
        from ..utils import get_event_loop

        return get_event_loop().run_until_complete(self.probe_once_async())

    def start(self) -> None:
        """Start the background probe loop (idempotent)."""
        with self._lock:
            if (
                self._probe_thread is not None
                and self._probe_thread.is_alive()
            ):
                return
            self._stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop,
                name="pftpu-pool-probe",
                daemon=True,
            )
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # a probe sweep must never kill the loop
                pass
            self._stop.wait(self.probe_interval_s)

    def stop(self) -> None:
        self._stop.set()
        thread = self._probe_thread
        if thread is not None:
            thread.join(timeout=self.probe_timeout_s + 5.0)
            self._probe_thread = None

    def close(self) -> None:
        """Stop probing and drop every replica (closes clients)."""
        self.stop()
        for replica in self.replicas:
            self.remove_replica(replica.host, replica.port)

    # -- routing ----------------------------------------------------------

    def available_replicas(
        self, exclude: Sequence = ()
    ) -> List[Replica]:
        excluded = {
            e if isinstance(e, str) else e.address for e in exclude
        }
        return [
            r
            for r in self.replicas
            if r.address not in excluded and r.breaker.available()
        ]

    def pick(
        self, k: int = 1, *, exclude: Sequence = ()
    ) -> List[Replica]:
        """Up to ``k`` distinct admitted replicas, policy-ranked.  Each
        returned replica passed ``breaker.acquire()`` — in half-open
        that claims the single probe token, so a recovering replica
        receives exactly one trial call."""
        candidates = self.available_replicas(exclude)
        chosen = []
        for replica in self.policy.pick(candidates, k):
            if replica.breaker.acquire():
                _POOL_PICKS.labels(policy=self.policy_name).inc()
                chosen.append(replica)
        return chosen

    def record_result(
        self,
        replica: Replica,
        ok: bool,
        *,
        latency_s: Optional[float] = None,
        n_requests: int = 1,
    ) -> None:
        """Feed one call outcome back into routing state: breaker,
        EWMA per-request latency, gauges."""
        if ok:
            replica.breaker.record_success()
            if latency_s is not None and n_requests > 0:
                replica.record_latency(latency_s / n_requests)
        else:
            replica.breaker.record_failure()
        self._refresh_state_gauges()

    def start_collector(self, **kwargs: Any) -> Any:
        """Start a fleet collector riding THIS pool's live replica
        registry (:class:`~..telemetry.collector.FleetCollector` with
        ``pool=self``, started): every sweep re-reads the registry, so
        replicas added, removed, or failed over mid-run are followed
        automatically; grpc replicas are scraped over the GetLoad
        ``b"telemetry"`` lane, other transports are reported
        ``unscraped`` unless an ``http_targets=`` exporter mapping is
        passed through.  ``interval_s`` defaults to this pool's probe
        cadence — the fleet view refreshes as often as the health
        view.  The caller owns the returned collector
        (``stop()``/context manager)."""
        from ..telemetry.collector import FleetCollector

        kwargs.setdefault("interval_s", self.probe_interval_s)
        return FleetCollector(pool=self, **kwargs).start()

    # -- recovery + introspection -----------------------------------------

    def recover(self) -> int:
        """On-demand recovery sweep (the elastic-sampling tier): probe
        every replica NOW, let the breakers quarantine the dead, and
        return how many replicas currently admit traffic.  Cheap and
        side-effect-bounded — safe to call from an exception path."""
        try:
            self.probe_once()
        except Exception:
            pass
        return len(self.available_replicas())

    # fanout_exec.run_members' retry policy hooks ------------------------

    def is_transient(self, exc: BaseException) -> bool:
        """Whether a member/call failure is worth retrying through the
        pool (transport trouble) vs deterministic (re-raising).  The
        same classification the transports use: RemoteComputeError and
        other RuntimeErrors are the request's own fault."""
        if isinstance(exc, _remote_compute_error()):
            return False
        from .pooled_client import _grpc_classifier

        aio_error, is_retryable = _grpc_classifier()
        if aio_error is not None and isinstance(exc, aio_error):
            return is_retryable(exc)
        return isinstance(exc, (ConnectionError, OSError, TimeoutError))

    def allow_retry(self, what: str = "retry") -> bool:
        """Charge one amplifying recovery attempt to the pool's retry
        budget (:mod:`.budget`).  ``False`` = exhausted: the caller
        must degrade to single-attempt behavior — skip the hedge, stop
        the failover loop, surface the member failure.  First attempts
        are never charged; only the MULTIPLIER is rationed."""
        return self.retry_budget.try_spend(what=what)

    def backoff_sleep(self, attempt: int) -> None:
        """Jittered exponential pause between member retries."""
        import random

        base = min(0.05 * (2.0 ** attempt), 0.5)
        time.sleep(base * (0.5 + random.random()))

    def _refresh_state_gauges(self) -> None:
        counts = {"closed": 0, "open": 0, "half_open": 0}
        for replica in self.replicas:
            state = replica.breaker.state
            counts[state] = counts.get(state, 0) + 1
            _POOL_UP.labels(replica=replica.address).set(
                1.0 if replica.breaker.available() else 0.0
            )
            depth = replica.queue_depth()
            _POOL_QDEPTH.labels(replica=replica.address).set(
                -1.0 if depth is None else depth
            )
        for state, n in counts.items():
            _POOL_REPLICAS.labels(state=state).set(n)

    def snapshot(self) -> dict:
        """JSON-friendly routing state (mirrors what the per-replica
        gauges expose; used by tests and ad-hoc debugging)."""
        now = time.monotonic()
        return {
            "transport": self.transport,
            "policy": self.policy_name,
            "retry_budget": self.retry_budget.snapshot(),
            "replicas": [
                {
                    "replica": r.address,
                    "state": r.breaker.state,
                    "up": r.breaker.available(),
                    "queue_depth": r.queue_depth(),
                    "ewma_latency_s": r.ewma_latency_s,
                    "inflight": r.inflight,
                    "load_age_s": (
                        None
                        if r.load_ts is None
                        else round(now - r.load_ts, 3)
                    ),
                }
                for r in self.replicas
            ],
        }
