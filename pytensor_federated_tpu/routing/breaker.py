"""Per-replica circuit breaker: closed → open → half-open → closed.

The failure-isolation primitive of the replica pool
(:mod:`.pool`).  A replica that keeps failing must stop receiving
traffic *before* every caller has personally timed out against it —
the classic circuit-breaker contract — and must win traffic back only
by proving itself on a single half-open probe, never by a thundering
herd of optimistic retries.

State machine (one lock, monotonic clock):

- **closed** — normal service.  ``consecutive_failures`` counts
  ``record_failure`` calls; reaching ``failure_threshold`` trips the
  breaker OPEN and arms a jittered backoff deadline.
- **open** — ``available()``/``acquire()`` refuse until the deadline.
  The backoff doubles on every re-trip up to ``max_backoff_s``; the
  deadline is jittered ±``jitter_frac`` so a pool of drivers that all
  tripped on the same dead node does not re-probe it in lockstep (the
  same de-sync argument as ``connect_balanced``'s sleep,
  service/client.py).
- **half-open** — after the deadline, exactly ONE caller wins
  ``acquire()`` (the probe); everyone else keeps being refused.  The
  probe's ``record_success`` closes the breaker and resets the
  backoff ladder; its ``record_failure`` re-opens with doubled
  backoff.

``available()`` is deliberately non-mutating so routing policies can
*rank* candidates without consuming the half-open probe token;
``acquire()`` is the mutating admission check the pool performs on the
one replica it actually picked.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

__all__ = ["CircuitBreaker"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """Thread-safe three-state breaker with jittered exponential backoff.

    ``on_transition(old_state, new_state)`` (optional) fires outside
    the hot path whenever the state changes — the pool uses it to emit
    ``pool.breaker_*`` flight-recorder events and the transition
    counter without this module depending on telemetry.
    """

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        backoff_s: float = 0.5,
        max_backoff_s: float = 30.0,
        jitter_frac: float = 0.2,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ) -> None:
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        if backoff_s <= 0 or max_backoff_s < backoff_s:
            raise ValueError(
                f"need 0 < backoff_s <= max_backoff_s, got "
                f"{backoff_s}/{max_backoff_s}"
            )
        self.failure_threshold = int(failure_threshold)
        self.base_backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter_frac = float(jitter_frac)
        self._clock = clock
        self._rng = rng or random.Random()
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._backoff_s = self.base_backoff_s
        self._open_until = 0.0
        self._probing = False  # half-open probe token held

    # -- introspection ----------------------------------------------------

    @property
    def state(self) -> str:
        """Current state; an expired OPEN deadline reads as half_open
        (the lazily-evaluated transition — there is no timer thread)."""
        with self._lock:
            return self._effective_state()

    def _effective_state(self) -> str:
        if self._state == OPEN and self._clock() >= self._open_until:
            return HALF_OPEN
        return self._state

    @property
    def consecutive_failures(self) -> int:
        return self._consecutive_failures

    @property
    def backoff_s(self) -> float:
        """The backoff the NEXT trip would arm (doubles per re-trip)."""
        return self._backoff_s

    def available(self) -> bool:
        """Non-mutating: would a call be admitted right now?  True in
        closed, and in half-open while the probe token is unclaimed."""
        with self._lock:
            eff = self._effective_state()
            if eff == CLOSED:
                return True
            if eff == HALF_OPEN:
                return not self._probing
            return False

    # -- admission + outcome ----------------------------------------------

    def acquire(self) -> bool:
        """Mutating admission: True admits the call.  In half-open this
        claims the single probe token — concurrent acquirers lose."""
        transition = None
        with self._lock:
            eff = self._effective_state()
            if eff == CLOSED:
                return True
            if eff == HALF_OPEN and not self._probing:
                if self._state == OPEN:
                    transition = (self._state, HALF_OPEN)
                    self._state = HALF_OPEN
                self._probing = True
                ok = True
            else:
                ok = False
        if transition is not None:
            self._notify(*transition)
        return ok

    def release(self) -> None:
        """Give back an acquired half-open probe token WITHOUT recording
        an outcome — for calls that were admitted but then abandoned
        (hedge loser, a spread window benching the replica).  Leaving
        the token claimed would park the breaker in half-open forever
        when no background probe loop runs."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        """A served call completed: close (from half-open), reset the
        failure count and the backoff ladder."""
        transition = None
        with self._lock:
            if self._state != CLOSED:
                transition = (self._state, CLOSED)
            self._state = CLOSED
            self._probing = False
            self._consecutive_failures = 0
            self._backoff_s = self.base_backoff_s
        if transition is not None:
            self._notify(*transition)

    def record_failure(self) -> None:
        """A call (or health probe) failed: count toward the trip
        threshold; in half-open, a failed probe re-opens immediately
        with doubled backoff."""
        transition = None
        with self._lock:
            self._consecutive_failures += 1
            eff = self._effective_state()
            if eff == CLOSED:
                if self._consecutive_failures >= self.failure_threshold:
                    transition = (self._state, OPEN)
                    self._trip_locked()
            else:
                # half-open probe failed, or extra failures landing
                # while open (stragglers from calls admitted earlier):
                # re-arm the deadline; only escalate the backoff for a
                # genuine failed PROBE, not for stragglers.
                escalate = eff == HALF_OPEN
                if self._state != OPEN:
                    transition = (self._state, OPEN)
                self._trip_locked(escalate=escalate)
        if transition is not None:
            self._notify(*transition)

    def _trip_locked(self, *, escalate: bool = False) -> None:
        if escalate:
            self._backoff_s = min(self._backoff_s * 2.0, self.max_backoff_s)
        jitter = 1.0 + self.jitter_frac * (2.0 * self._rng.random() - 1.0)
        self._state = OPEN
        self._probing = False
        self._open_until = self._clock() + self._backoff_s * jitter

    def _notify(self, old: str, new: str) -> None:
        if self._on_transition is not None:
            try:
                self._on_transition(old, new)
            except Exception:  # a metrics hook must never break routing
                pass
