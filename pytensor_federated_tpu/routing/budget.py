"""Per-pool retry budgets: failure amplification as a rationed resource.

Every recovery mechanism this package grew — transport retries, hedged
requests, mid-window failover, fanout member re-runs — MULTIPLIES load
exactly when the pool is least able to absorb it: a pool that is slow
because it is overloaded invites retries, which make it slower, which
invites more retries.  That feedback loop is the canonical overload
collapse (the Google SRE "retry storm"), and the fix is the same
everywhere: recovery attempts spend from a RATE-LIMITED budget, so a
healthy pool retries freely while a sick one organically degrades
toward one attempt per call instead of several.

:class:`RetryBudget` is a thread-safe token bucket over
``time.monotonic()``: ``burst`` tokens of headroom, refilled at
``rate_per_s``.  Spends are booked by
:meth:`~pytensor_federated_tpu.routing.pool.NodePool.allow_retry` —
the single choke point the hedging lane, the failover loops, and
``fanout_exec.run_members`` all charge — and a denial is LOUD:
``pftpu_retry_budget_spend_total{outcome="denied"}`` plus a
``budget.exhausted`` flight event, so an operator sees amplification
being refused, not just latency mysteriously rising.  Budgets
reconverge by construction: once load drops the bucket refills and
recovery behavior returns to normal (the chaos overload lane asserts
exactly that).

First attempts are NEVER charged — the budget rations the multiplier,
not the work.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..telemetry import flightrec as _flightrec
from ..telemetry import metrics as _metrics

__all__ = ["RetryBudget"]

_SPEND = _metrics.counter(
    "pftpu_retry_budget_spend_total",
    "Retry/hedge budget spend attempts, by kind and outcome",
    ("what", "outcome"),
)
_TOKENS = _metrics.gauge(
    "pftpu_retry_budget_tokens",
    "Tokens currently available in the retry budget, by budget name",
    ("name",),
)


class RetryBudget:
    """A token bucket rationing retry/hedge amplification.

    ``rate_per_s`` is the sustained amplification a pool tolerates
    (extra attempts per second, across all callers sharing the
    budget); ``burst`` the headroom for transient blips.  The defaults
    — 4/s sustained, 16 burst — absorb the occasional failover or
    hedge without ever letting a persistent failure multiply load by
    more than ``rate_per_s`` attempts a second.

    Thread-safe (callers include event loops, worker threads, and the
    fanout member pool); ``try_spend`` never blocks — a denied spend
    returns ``False`` and the caller degrades to its single-attempt
    behavior.
    """

    def __init__(
        self,
        rate_per_s: float = 4.0,
        burst: float = 16.0,
        *,
        name: str = "pool",
    ) -> None:
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst)
        self.name = name
        self._tokens = float(burst)
        self._t_last = time.monotonic()
        self._lock = threading.Lock()
        # Plain always-on tallies (the metrics are no-ops with
        # telemetry off; the chaos harness reconciles against these).
        self.n_granted = 0
        self.n_denied = 0

    def _refill(self, now: float) -> None:
        self._tokens = min(
            self.burst,
            self._tokens + (now - self._t_last) * self.rate_per_s,
        )
        self._t_last = now

    def try_spend(self, n: float = 1.0, *, what: str = "retry") -> bool:
        """Spend ``n`` tokens if available.  ``False`` = the budget is
        exhausted: the caller must NOT amplify (skip the hedge, stop
        the failover loop) — booked loudly in metrics and the flight
        recorder so refused amplification is a visible signal."""
        now = time.monotonic()
        with self._lock:
            self._refill(now)
            ok = self._tokens >= n
            if ok:
                self._tokens -= n
                self.n_granted += 1
            else:
                self.n_denied += 1
            tokens = self._tokens
        _SPEND.labels(
            what=what, outcome="granted" if ok else "denied"
        ).inc()
        _TOKENS.labels(name=self.name).set(tokens)
        if not ok:
            _flightrec.record(
                "budget.exhausted", budget=self.name, what=what,
                tokens=round(tokens, 3),
            )
        return ok

    def refund(self, n: float = 1.0) -> None:
        """Return tokens from a granted spend that never amplified —
        e.g. a hedge grant with no replica to hedge onto.  The
        granted/denied tallies stay as booked (the chaos harness
        bounds ATTEMPTS by grants, and a refunded grant attempted
        nothing, so the bound stays conservative)."""
        now = time.monotonic()
        with self._lock:
            self._refill(now)
            self._tokens = min(self.burst, self._tokens + n)
            tokens = self._tokens
        _TOKENS.labels(name=self.name).set(tokens)

    def tokens(self) -> float:
        """Current token count (refilled to now) — the reconvergence
        probe the chaos harness polls after load drops."""
        now = time.monotonic()
        with self._lock:
            self._refill(now)
            return self._tokens

    def snapshot(self) -> dict:
        return {
            "name": self.name,
            "rate_per_s": self.rate_per_s,
            "burst": self.burst,
            "tokens": round(self.tokens(), 3),
            "granted_total": self.n_granted,
            "denied_total": self.n_denied,
        }
