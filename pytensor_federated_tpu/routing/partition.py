"""Gradient partitioning: the shard math behind partition-indexed frames.

ISSUE 13's bandwidth story lives here.  Every remote logp+grad reply
used to ship the FULL gradient, so wire bytes per federated evaluation
scale as ``O(model_size × n_shards)``.  Two mechanisms cut that down,
both built on the partition-index wire block declared in
:mod:`..service.wire_registry` (``PARTITION_STRUCT``; npwire flag bit
64, shm doorbell flag bit 16, npproto extension field 20):

- **Sliced replies** ("scatter"): a request carrying a partition block
  asks the node to return only elements ``[offset, offset + length)``
  of its reply's flat gradient vector — the mechanism that lets a
  gradient larger than one reply frame stream home as several
  partition-indexed slices, reassembled here with loud errors on
  overlap, gaps, duplicates, or shape disagreement (never a silent
  partial sum).
- **Reduced windows** ("reduce"): a batch frame whose OUTER header
  carries a partition block asks the node to partially REDUCE the
  window — sum its items' replies elementwise — and return the sum as
  ``count`` partition-indexed slices.  A width-W pool answering
  n-shard windows this way returns ``W`` partial sums instead of ``n``
  full gradients, and mid-tier aggregator nodes (the tree lowering of
  ``fed_sum``) apply the same reduction over their children, giving
  O(log N) aggregation depth in pool width.

The reply contract both mechanisms share (the **head/tail rule**):
reply array 0 — the logp scalar of the ``[logp, *grads]`` node
contract — is the HEAD and is returned whole (summed under reduce);
reply arrays ``1..`` are the TAIL: raveled, concatenated in order into
one flat vector of ``total`` elements, and sliced.  All tail arrays
must share one dtype (mixed-precision tails would need a silent cast —
refused loudly instead), and the requester's ``total`` must equal the
node's actual flat size, making a driver/node shape disagreement a
wire error instead of a mis-assembled gradient.

Nothing here imports transports; the transports import this module
(the same direction as :mod:`..service.wire_registry`).
"""

from __future__ import annotations

import struct
import threading
from typing import (
    Callable,
    Dict,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from ..service.npwire import WireError
from ..service.wire_registry import PARTITION_STRUCT
from ..telemetry import metrics as _metrics

__all__ = [
    "GradPartition",
    "PartitionError",
    "Reassembler",
    "concat_tail",
    "make_aggregator_compute",
    "pack_partition",
    "plan_partitions",
    "reduce_replies",
    "shard_label",
    "slice_reply",
    "split_tail",
    "tail_layout",
    "unpack_partition",
]

#: Partition-indexed shard items served/consumed, by outcome — the
#: partition lane's goodput instrument (the fleet SLO engine clamps
#: per-shard error deltas at per-shard request deltas with these, the
#: ISSUE-13 satellite of the PR-11 underflow clamp).
PARTITION_SHARDS = _metrics.counter(
    "pftpu_partition_shards_total",
    "Partition-indexed shard items, by outcome (ok / error)",
    ("outcome",),
)

_PART_STRUCT = struct.Struct(PARTITION_STRUCT)
_U32_MAX = 0xFFFFFFFF
_U64_MAX = 0xFFFFFFFFFFFFFFFF


class PartitionError(WireError):
    """A partition block that cannot describe a valid shard, or a
    reassembly that would be silently wrong (overlap, gap, duplicate,
    shape/dtype disagreement).  A :class:`~..service.npwire.WireError`
    subclass on purpose: every lane already treats WireError as the
    loud, deterministic, close-the-connection classification."""


class GradPartition(NamedTuple):
    """One contiguous shard of a flat gradient vector.

    ``index``/``count`` place the shard among its siblings;
    ``offset``/``length`` are its element range; ``total`` the flat
    vector's full element count.  A plain tuple on purpose — the wire
    codecs accept it positionally (``PARTITION_FIELD_ORDER``)."""

    index: int
    count: int
    offset: int
    length: int
    total: int

    def validate(self) -> "GradPartition":
        if not 0 <= self.index < self.count:
            raise PartitionError(
                f"partition index {self.index} outside 0..{self.count - 1}"
            )
        if self.count > _U32_MAX or self.count < 1:
            raise PartitionError(f"bad partition count {self.count}")
        if min(self.offset, self.length, self.total) < 0 or max(
            self.offset, self.length, self.total
        ) > _U64_MAX:
            raise PartitionError(
                f"partition range out of u64 bounds: {self}"
            )
        if self.offset + self.length > self.total:
            raise PartitionError(
                f"partition slice [{self.offset}, "
                f"{self.offset + self.length}) overruns total {self.total}"
            )
        return self


def pack_partition(part: Sequence[int]) -> bytes:
    """The 32-byte wire form (``PARTITION_STRUCT``) of a partition."""
    p = GradPartition(*part).validate()
    return _PART_STRUCT.pack(*p)


def unpack_partition(buf: bytes, offset: int = 0) -> GradPartition:
    """Decode and validate one partition block at ``offset``."""
    try:
        fields = _PART_STRUCT.unpack_from(buf, offset)
    except struct.error as e:
        raise PartitionError(f"truncated partition block: {e}") from None
    return GradPartition(*fields).validate()


#: Wire size of one partition block.
PARTITION_BLOCK_SIZE = _PART_STRUCT.size


def plan_partitions(total: int, count: int) -> List[GradPartition]:
    """``count`` contiguous shards covering ``[0, total)`` exactly.

    Shards are balanced to within one element; the uneven tail goes to
    the LEADING shards (shard sizes are ``ceil`` then ``floor``), so
    ``plan_partitions(10, 4)`` is ``3+3+2+2``.  Deterministic — both
    ends of a wire derive the same plan from ``(total, count)``."""
    if count < 1:
        raise PartitionError(f"partition count must be >= 1, got {count}")
    if total < 0:
        raise PartitionError(f"negative total {total}")
    base, extra = divmod(total, count)
    out: List[GradPartition] = []
    offset = 0
    for i in range(count):
        length = base + (1 if i < extra else 0)
        out.append(GradPartition(i, count, offset, length, total))
        offset += length
    return out


# ---------------------------------------------------------------------------
# the head/tail reply rule
# ---------------------------------------------------------------------------


def tail_layout(
    arrays: Sequence[np.ndarray],
) -> Tuple[List[Tuple[Tuple[int, ...], int]], int, np.dtype]:
    """Shapes/sizes and flat total of a reply's TAIL (arrays 1..).

    Returns ``([(shape, size), ...], total, dtype)``; loud on an empty
    reply, a non-uniform tail dtype, or a non-inexact tail."""
    if not arrays:
        raise PartitionError(
            "partitioned reply rule needs at least a head array"
        )
    tail = [np.asarray(a) for a in arrays[1:]]
    dtypes = {a.dtype for a in tail}
    if len(dtypes) > 1:
        # Name WHICH tail slot carries which dtype (reply index 1..):
        # "got [...]" alone sends the node author diffing reply shapes
        # by hand; the offender list pins the mismatched output.
        per_slot = ", ".join(
            f"reply[{i + 1}]={a.dtype}" for i, a in enumerate(tail)
        )
        raise PartitionError(
            "partitioned tail arrays must share one dtype, got "
            f"{sorted(str(d) for d in dtypes)} ({per_slot})"
        )
    dtype = dtypes.pop() if dtypes else np.dtype(np.float64)
    layout = [(tuple(a.shape), int(a.size)) for a in tail]
    return layout, sum(s for _sh, s in layout), dtype


def concat_tail(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """The flat tail vector of a reply (arrays 1.. raveled + joined)."""
    tail = [np.ascontiguousarray(a).ravel() for a in arrays[1:]]
    if not tail:
        return np.zeros(0, np.float64)
    return np.concatenate(tail) if len(tail) > 1 else tail[0]


def split_tail(
    flat: np.ndarray, shapes: Sequence[Tuple[int, ...]]
) -> List[np.ndarray]:
    """Inverse of :func:`concat_tail`: carve the flat vector back into
    the tail arrays.  Loud when sizes disagree."""
    flat = np.asarray(flat).ravel()
    sizes = [int(np.prod(s, dtype=np.int64)) for s in shapes]
    if sum(sizes) != flat.size:
        raise PartitionError(
            f"flat vector has {flat.size} elements but shapes "
            f"{list(shapes)} need {sum(sizes)}"
        )
    out: List[np.ndarray] = []
    lo = 0
    for shape, size in zip(shapes, sizes):
        out.append(flat[lo : lo + size].reshape(shape))
        lo += size
    return out


def slice_reply(
    arrays: Sequence[np.ndarray], part: GradPartition
) -> List[np.ndarray]:
    """Server-side scatter: ``[head, tail-slice]`` for one partition.

    The head (array 0) rides whole; the tail is flat-concatenated and
    sliced to the partition's element range.  ``part.total`` must match
    the actual flat size — a driver/node shape disagreement fails here,
    loudly, before any bytes move."""
    part.validate()
    _layout, total, _dtype = tail_layout(arrays)
    if part.total != total:
        raise PartitionError(
            f"partition total {part.total} != reply tail size {total} "
            "(driver/node shape disagreement)"
        )
    flat = concat_tail(arrays)
    return [
        np.asarray(arrays[0]),
        flat[part.offset : part.offset + part.length],
    ]


def reduce_replies(
    replies: Sequence[Sequence[np.ndarray]],
) -> List[np.ndarray]:
    """Partial reduction of a window: elementwise sum of item replies.

    Every reply must agree in arity, shapes, and dtypes — a
    disagreement means the window mixed incompatible computes and a
    sum would be silently wrong, so it raises :class:`PartitionError`
    instead.  Returns ``[head_sum, *tail_sums]`` with the original
    array shapes (slicing to partitions is the caller's move)."""
    if not replies:
        raise PartitionError("cannot reduce an empty window")
    first = [np.asarray(a) for a in replies[0]]
    if not first:
        raise PartitionError("cannot reduce empty replies")
    acc = [a.copy() for a in first]
    for k, reply in enumerate(replies[1:], start=1):
        if len(reply) != len(acc):
            raise PartitionError(
                f"window item {k} replied {len(reply)} arrays, item 0 "
                f"replied {len(acc)} — refusing a ragged reduction"
            )
        for j, a in enumerate(reply):
            a = np.asarray(a)
            if a.shape != acc[j].shape or a.dtype != acc[j].dtype:
                raise PartitionError(
                    f"window item {k} array {j} is "
                    f"{a.dtype}{a.shape}, item 0's is "
                    f"{acc[j].dtype}{acc[j].shape} — refusing a "
                    "silently-casting reduction"
                )
            acc[j] += a
    return acc


# ---------------------------------------------------------------------------
# tree aggregation: the mid-tier node compute
# ---------------------------------------------------------------------------


def make_aggregator_compute(
    child_client: object, *, window: int = 8
) -> "Callable[..., List[np.ndarray]]":
    """The MID-TIER node of a tree aggregation: a compute for
    ``serve_tcp_once``/``serve_shm`` that forwards work to a child
    client (a pinned transport client or a whole
    :class:`~.pooled_client.PooledArraysClient` over the next tier
    down).

    Two lanes, matching the server dispatch:

    - plain/batched frames forward item-by-item
      (``child_client.evaluate``) — the aggregator is transparent for
      non-reduced traffic;
    - a REDUCE window hands the whole item list to the ``.reduce``
      attribute, which forwards it as ONE reduced child window
      (``child_client.evaluate_reduced``) and returns the summed
      ``[head, flat]`` — so a K-ary tree of aggregators reduces
      gradients with O(log N) depth in pool width, each tier's
      upstream link carrying ONE partial sum instead of its subtree's
      every reply (the ISSUE-13 fan-in story).

    Child failures surface as the child client's own loud
    classifications (transport errors re-queue in the child pool;
    deterministic errors ride in-band up the tree)."""

    def compute(*arrays: np.ndarray) -> List[np.ndarray]:
        return list(child_client.evaluate(*arrays))  # type: ignore[attr-defined]

    def reduce(windows: Sequence[Sequence[np.ndarray]]) -> List[np.ndarray]:
        return list(
            child_client.evaluate_reduced(  # type: ignore[attr-defined]
                windows, window=window
            )
        )

    compute.reduce = reduce  # type: ignore[attr-defined]
    return compute


# ---------------------------------------------------------------------------
# driver-side reassembly
# ---------------------------------------------------------------------------


class _BufferPool:
    """Reassembly buffers keyed by (total, dtype), reused across calls
    — the PR-9 pin-cache posture applied to the driver's gather side:
    a hot reduce loop reassembles into the same pages every step
    instead of allocating a fresh gradient-sized buffer per call.
    Bounded and lock-guarded; buffers are handed out exclusively and
    returned on the next request for the same key."""

    def __init__(self, max_entries: int = 8) -> None:
        self._max = max_entries
        self._lock = threading.Lock()
        self._free: Dict[Tuple[int, str], np.ndarray] = {}

    def take(self, total: int, dtype: np.dtype) -> np.ndarray:
        key = (int(total), np.dtype(dtype).str)
        with self._lock:
            buf = self._free.pop(key, None)
        if buf is None:
            buf = np.empty(total, dtype)
        return buf

    def give(self, buf: np.ndarray) -> None:
        key = (int(buf.size), buf.dtype.str)
        with self._lock:
            if len(self._free) < self._max:
                self._free[key] = buf


_REASSEMBLY_BUFFERS = _BufferPool()


def shard_label(part: GradPartition, iuid: Optional[str] = None) -> str:
    """The shard identity refusal messages carry (ISSUE 16 satellite):
    the full DECLARED geometry plus — when the transport has one — the
    reply item's wire identity, so a sharded-update refusal names WHICH
    replica's slice broke the reassembly, not just the failure class."""
    label = (
        f"shard {part.index}/{part.count} [declared offset={part.offset}"
        f" length={part.length} total={part.total}"
    )
    if iuid is not None:
        label += f" iuid={iuid}"
    return label + "]"


class Reassembler:
    """Collect partition-indexed slices back into one flat vector.

    The loud half of the scatter mechanism: every ``add`` validates the
    slice against the declared geometry and every anomaly — duplicate
    index, overlapping or out-of-bounds range, wrong slice length,
    disagreeing ``count``/``total``, dtype drift — raises
    :class:`PartitionError` immediately.  ``result()`` raises while any
    element of ``[0, total)`` is uncovered, so a dropped shard can
    never yield a silent partial gradient.

    ``reuse_buffers=True`` draws the flat buffer from a small process
    pool keyed by (total, dtype) and recycles it when the NEXT
    reassembly of the same geometry starts — callers that retain the
    result must copy (the fed executors do; ``result(copy=True)`` is
    the safe default)."""

    def __init__(
        self,
        total: int,
        count: int,
        dtype: np.dtype = np.dtype(np.float64),
        *,
        reuse_buffers: bool = True,
    ) -> None:
        if total < 0 or count < 1:
            raise PartitionError(
                f"bad reassembly geometry total={total} count={count}"
            )
        self.total = int(total)
        self.count = int(count)
        self.dtype = np.dtype(dtype)
        self._reuse = reuse_buffers
        self._buf = (
            _REASSEMBLY_BUFFERS.take(self.total, self.dtype)
            if reuse_buffers
            else np.empty(self.total, self.dtype)
        )
        self._seen: Dict[int, Tuple[int, int]] = {}
        self._iuids: Dict[int, Optional[str]] = {}
        self._covered = 0

    def add(
        self,
        part: GradPartition,
        flat: np.ndarray,
        *,
        iuid: Optional[str] = None,
    ) -> None:
        """Validate and place one slice.  ``iuid`` is the reply item's
        wire identity when the transport carries one — it rides into
        every refusal via :func:`shard_label` so the error names the
        offending replica's slice, not just the failure class."""
        try:
            self._add_checked(part, flat, iuid)
        except PartitionError:
            PARTITION_SHARDS.labels(outcome="error").inc()
            raise
        PARTITION_SHARDS.labels(outcome="ok").inc()

    def _add_checked(
        self,
        part: GradPartition,
        flat: np.ndarray,
        iuid: Optional[str] = None,
    ) -> None:
        part.validate()
        who = shard_label(part, iuid)
        if part.count != self.count or part.total != self.total:
            raise PartitionError(
                f"{who}: geometry ({part.count}, {part.total}) does not "
                f"match the reassembly ({self.count}, {self.total})"
            )
        if part.index in self._seen:
            first = self._iuids.get(part.index)
            raise PartitionError(
                f"duplicate {who}: index already covered "
                f"{self._seen[part.index]}"
                + (f" by iuid={first}" if first is not None else "")
            )
        flat = np.asarray(flat).ravel()
        if flat.size != part.length:
            raise PartitionError(
                f"{who} carries {flat.size} elements but declares "
                f"length {part.length}"
            )
        if flat.size and flat.dtype != self.dtype:
            raise PartitionError(
                f"{who} dtype {flat.dtype} != reassembly dtype "
                f"{self.dtype} — refusing a silent cast"
            )
        for idx, (lo, hi) in self._seen.items():
            if part.offset < hi and lo < part.offset + part.length:
                other = self._iuids.get(idx)
                raise PartitionError(
                    f"{who} range [{part.offset}, "
                    f"{part.offset + part.length}) overlaps shard "
                    f"{idx}'s [{lo}, {hi})"
                    + (f" (iuid={other})" if other is not None else "")
                )
        self._buf[part.offset : part.offset + part.length] = flat
        self._seen[part.index] = (
            part.offset,
            part.offset + part.length,
        )
        self._iuids[part.index] = iuid
        self._covered += part.length

    @property
    def missing(self) -> List[int]:
        """Shard indices not yet added (vs the declared count)."""
        return [i for i in range(self.count) if i not in self._seen]

    def result(self, *, copy: bool = True) -> np.ndarray:
        if self._covered != self.total or len(self._seen) != self.count:
            raise PartitionError(
                f"incomplete reassembly: {self._covered}/{self.total} "
                f"elements from {len(self._seen)}/{self.count} shards "
                f"(missing indices {self.missing}) — refusing a "
                "silent partial gradient"
            )
        out = self._buf.copy() if copy else self._buf
        if self._reuse and copy:
            _REASSEMBLY_BUFFERS.give(self._buf)
        return out
