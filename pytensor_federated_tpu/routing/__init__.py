"""Replica-pool routing for the host-federation lane.

The reference balances once at connect time (GetLoad poll + least-
loaded pick, reference: service.py:240-263) and then pins: every call
rides whichever server the client first connected to, so one slow or
dead node stalls the whole graph.  This subsystem sits ABOVE both
transports (gRPC `service.client` and TCP `service.tcp`) and routes
every call:

- :class:`NodePool` — the replica registry: static list plus late
  add/remove, background health/load probing over the existing
  GetLoad / zero-item-TCP-probe lanes, stale-load eviction, and one
  :class:`CircuitBreaker` per replica (half-open probing, jittered
  exponential backoff).
- :mod:`.policies` — pluggable pick policies: round-robin, EWMA
  latency, and power-of-two-choices over advertised queue depth
  (the default).
- :class:`PooledArraysClient` — the drop-in client facade: the same
  ``evaluate`` / ``evaluate_many`` surface as the pinned clients,
  plus hedged requests for idempotent computes and mid-window
  failover that re-queues the un-replied tail of a pipelined window
  onto a healthy replica.
- :class:`RetryBudget` — the per-pool token bucket every amplifying
  recovery path (retries, hedges, mid-window failover, fanout member
  re-runs) spends from, so a sick pool degrades to one attempt per
  call instead of multiplying its own load (:mod:`.budget`).
- :mod:`.partition` — the gradient-sharding lane (ISSUE 13):
  partition-index shard math, the head/tail slice rule, loud
  reassembly, window reduction, and the mid-tier aggregator compute
  behind ``PooledArraysClient.evaluate_reduced`` and the ``fed_sum``
  tree lowering.

Everything is observable: ``pftpu_pool_*`` metric families (catalog:
docs/observability.md), ``pool.*`` flight-recorder events, and
``pool.evaluate``/``pool.window`` spans that keep a failed-over
call's full replica itinerary in one trace.
"""

from .breaker import CircuitBreaker
from .budget import RetryBudget
from .partition import (
    GradPartition,
    PartitionError,
    Reassembler,
    make_aggregator_compute,
    plan_partitions,
)
from .policies import (
    EwmaLatencyPolicy,
    PowerOfTwoChoicesPolicy,
    RoundRobinPolicy,
    get_policy,
)
from .pool import NodePool, Replica
from .pooled_client import PooledArraysClient

__all__ = [
    "CircuitBreaker",
    "EwmaLatencyPolicy",
    "GradPartition",
    "NodePool",
    "PartitionError",
    "PooledArraysClient",
    "PowerOfTwoChoicesPolicy",
    "Reassembler",
    "Replica",
    "RetryBudget",
    "RoundRobinPolicy",
    "get_policy",
    "make_aggregator_compute",
    "plan_partitions",
]
