"""Pure scheduling core of the fused federated apply — no pytensor needed.

``ParallelFederatedOp.perform`` (fusion.py) must fan N member performs
out over pinned threads, slice the concatenated input/output-storage
lists per member, let every member settle, and surface the first
failure loudly.  Those are exactly the parts most likely to be wrong —
and pytensor cannot be installed in every environment this repo is
developed in — so they live here, importable and testable without
pytensor (VERDICT r2 item 5a); fusion.py keeps only the literal
pytensor API calls.

Contracts (mirroring the reference's ``ParallelAsyncOp.perform``,
reference: op_async.py:107-132):

- wall-clock = max member latency, not the sum (members run
  concurrently; they are host/network calls that release the GIL);
- member ``i`` runs on the SAME thread every evaluation (gRPC/asyncio
  client state caches per (token, pid, thread, loop) — a migrating
  member would re-dial its channels each call);
- on failure, every member still settles before the first exception
  (in member order) is raised — cancelling mid-flight would leave
  sibling storages half-set.
"""

from __future__ import annotations

import contextvars
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .telemetry import flightrec as _flightrec
from .telemetry import metrics as _metrics
from .telemetry import spans as _tspans

__all__ = [
    "CoalescingCaller",
    "MemberExecutorPool",
    "PartitionedCaller",
    "member_spans",
    "run_members",
]

# Fanout instrumentation (metric catalog: docs/observability.md).  The
# straggler gap — max minus min member latency within one fanout — is
# THE number that says how much of the "wall-clock = max member" budget
# is lost to imbalance (the per-stage accounting DrJAX-style MapReduce
# analyses center on).
_FANOUT_WIDTH = _metrics.histogram(
    "pftpu_fanout_width",
    "Members per fused fanout evaluation",
    buckets=_metrics.DEFAULT_COUNT_BUCKETS,
)
_MEMBER_S = _metrics.histogram(
    "pftpu_fanout_member_seconds", "Per-member latency within a fanout"
)
_STRAGGLER_S = _metrics.histogram(
    "pftpu_fanout_straggler_seconds",
    "Straggler gap per fanout: slowest member minus fastest",
)


def _shutdown_all(executors: List[ThreadPoolExecutor]) -> None:
    # Module-level (not a bound method) so weakref.finalize holds no
    # reference back to the pool it is finalizing.
    for ex in executors:
        ex.shutdown(wait=False)


class MemberExecutorPool:
    """One persistent single-thread executor per member, lazily created.

    Persistence pins member ``i`` to one thread for the life of the
    pool; ``weakref.finalize`` shuts the threads down when the pool is
    garbage-collected, so churn of compiled functions no longer leaks
    threads for the process lifetime (round-2 advisor finding on
    fusion.py).  ``shutdown()`` may also be called explicitly;
    idempotent either way.
    """

    def __init__(self, n_members: int, name: str = "pft-fused"):
        self._n = int(n_members)
        self._name = name
        self._lock = threading.Lock()
        self._executors: List[ThreadPoolExecutor] | None = None
        self._finalizer = None
        self._closed = False

    def _ensure(self) -> List[ThreadPoolExecutor]:
        if self._closed:
            # Without this, shutdown() before first use is a no-op and a
            # later submit would silently resurrect the pool (eager
            # ThreadPoolExecutors raised here; preserve that contract).
            raise RuntimeError("pool is shut down")
        execs = self._executors
        if execs is None:
            with self._lock:
                execs = self._executors
                if execs is None:
                    execs = [
                        ThreadPoolExecutor(
                            max_workers=1,
                            thread_name_prefix=f"{self._name}-{i}",
                        )
                        for i in range(self._n)
                    ]
                    self._executors = execs
                    self._finalizer = weakref.finalize(
                        self, _shutdown_all, execs
                    )
        return execs

    @property
    def size(self) -> int:
        return self._n

    def submit(self, i: int, fn: Callable, /, *args, **kwargs):
        return self._ensure()[i].submit(fn, *args, **kwargs)

    def shutdown(self) -> None:
        self._closed = True
        if self._finalizer is not None:
            self._finalizer()  # runs _shutdown_all at most once

    @property
    def alive(self) -> bool:
        return self._finalizer is not None and self._finalizer.alive


_COALESCED_CALLS = _metrics.histogram(
    "pftpu_fanout_coalesced_calls",
    "Member evaluations coalesced into one batched node call",
    buckets=_metrics.DEFAULT_COUNT_BUCKETS,
)


class CoalescingCaller:
    """Coalesce concurrent single evaluations into one batched call.

    The driver-side twin of the server's micro-batcher, for the fanout
    geometry: when several fanout members target the SAME node, each
    member thread's ``evaluate(*arrays)`` lands here, the first
    arrival becomes the window leader, and the whole group goes out as
    ONE ``evaluate_many`` — which the transport packs into one wire
    batch frame when the node advertises support (client.py / tcp.py),
    so W same-node members pay one round-trip instead of W.

    ``evaluate_many``: a callable taking a list of request tuples and
    returning one result per request, in order — e.g.
    ``lambda reqs: client.evaluate_many(reqs, window=w)`` for any of
    the transport clients or typed adapters.  ``width`` is the
    expected group size (the number of members sharing the node): the
    leader dispatches the moment the window is full, so a complete
    fanout pays ZERO added wait; ``max_wait_s`` bounds the wait when
    the group arrives ragged (a straggler past it simply leads the
    next window — correctness is unaffected, only coalescing width).

    Error semantics: the window is one transport call, so a failure
    raises in EVERY coalesced member (the per-member isolation lives
    server-side: a poisoned input fails only its own reply item, and
    ``evaluate_many`` surfaces the first error without retry).
    """

    def __init__(
        self,
        evaluate_many: Callable[[list], list],
        *,
        width: int,
        max_wait_s: float = 0.002,
    ):
        if width < 1:
            raise ValueError(f"width must be >= 1, got {width}")
        self._evaluate_many = evaluate_many
        self._width = int(width)
        self._max_wait_s = float(max_wait_s)
        self._cond = threading.Condition()
        self._pending: List[dict] = []  # {"args", "event", "result", "error"}
        # One window in flight at a time: a straggler that became the
        # NEXT window's leader must not drive ``evaluate_many``
        # concurrently with the previous leader — the transport
        # clients are single-connection lock-step objects, not
        # thread-safe (tcp.py), so overlapping windows would
        # interleave frames on one socket.
        self._dispatch_lock = threading.Lock()

    def evaluate(self, *arrays) -> list:
        slot = {
            "args": tuple(arrays),
            "event": threading.Event(),
            "result": None,
            "error": None,
        }
        with self._cond:
            self._pending.append(slot)
            leader = len(self._pending) == 1
            if not leader:
                self._cond.notify_all()
        if leader:
            self._lead()
        # Followers (and the leader, whose own slot _lead() filled)
        # wait for their slot to settle.
        slot["event"].wait()
        if slot["error"] is not None:
            raise slot["error"]
        return slot["result"]

    __call__ = evaluate

    def _lead(self) -> None:
        deadline = time.perf_counter() + self._max_wait_s
        with self._cond:
            while len(self._pending) < self._width:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            group, self._pending = self._pending, []
        # try/finally around EVERYTHING after the group pop: if the
        # events were not guaranteed to set, a leader failure (even a
        # BaseException like KeyboardInterrupt delivered to its
        # thread) would leave every follower blocked forever in
        # event.wait() — a silent wedge, the exact failure class this
        # codebase's watchdog exists to prevent.
        try:
            with self._dispatch_lock:
                _COALESCED_CALLS.observe(len(group))
                with _tspans.span(
                    "fanout.coalesced_call", width=len(group)
                ):
                    results = self._evaluate_many(
                        [s["args"] for s in group]
                    )
                    if len(results) != len(group):
                        raise RuntimeError(
                            f"evaluate_many returned {len(results)} "
                            f"results for {len(group)} coalesced requests"
                        )
                    for s, r in zip(group, results):
                        s["result"] = r
        except BaseException as e:
            for s in group:
                if s["result"] is None:
                    s["error"] = (
                        e
                        if isinstance(e, Exception)
                        else RuntimeError(
                            f"coalesced window leader aborted: {e!r}"
                        )
                    )
            if not isinstance(e, Exception):
                raise  # KeyboardInterrupt & co. still surface in the leader
        finally:
            for s in group:
                s["event"].set()


_PARTITIONED_SLICES = _metrics.histogram(
    "pftpu_fanout_partitioned_slices",
    "Partition-indexed slice fetches per oversized-reply evaluation",
    buckets=_metrics.DEFAULT_COUNT_BUCKETS,
)


class PartitionedCaller:
    """Fetch a member's oversized reply as partition-indexed slices.

    The fanout layer's half of ISSUE 13's "gradients larger than one
    reply frame": a member whose gradient exceeds what one reply frame
    should carry (transport frame caps, arena slot sizes) wraps its
    client here — ``evaluate(*arrays)`` issues ``count`` sliced
    requests (the head/tail rule, ``partition=`` on the pinned
    clients), reassembles them with the loud
    :class:`~.routing.partition.Reassembler` rules, and returns
    ``[head, *tail]`` with the original tail shapes restored (or
    ``[head, flat]`` when ``tail_shapes`` is not given).

    The node recomputes per slice — this trades compute for frame
    size, the right trade exactly when a reply cannot ride one frame;
    for per-item bandwidth reduction use the reduce windows
    (``evaluate_reduced``) instead.
    """

    def __init__(
        self,
        client: object,
        *,
        total: int,
        max_slice_elems: int,
        tail_shapes: Optional[Sequence[Tuple[int, ...]]] = None,
    ) -> None:
        from .routing import partition as _gradpart

        if max_slice_elems < 1:
            raise ValueError(
                f"max_slice_elems must be >= 1, got {max_slice_elems}"
            )
        self._gradpart = _gradpart
        self._client = client
        self.total = int(total)
        self.count = max(
            1, -(-self.total // int(max_slice_elems))
        )  # ceil
        self.tail_shapes = (
            None if tail_shapes is None else [tuple(s) for s in tail_shapes]
        )
        if self.tail_shapes is not None:
            declared = sum(
                int(np.prod(s, dtype=np.int64)) for s in self.tail_shapes
            )
            if declared != self.total:
                raise _gradpart.PartitionError(
                    f"tail_shapes cover {declared} elements, total "
                    f"declares {self.total}"
                )

    def evaluate(self, *arrays) -> list:
        gp = self._gradpart
        plan = gp.plan_partitions(self.total, self.count)
        _PARTITIONED_SLICES.observe(len(plan))
        head = None
        reassembler = None
        with _tspans.span(
            "fanout.partitioned_call", count=self.count, total=self.total
        ):
            for part in plan:
                reply = self._client.evaluate(*arrays, partition=part)
                if len(reply) != 2:
                    raise gp.PartitionError(
                        f"sliced reply must be [head, slice], got "
                        f"{len(reply)} arrays"
                    )
                head = reply[0]
                sl = np.asarray(reply[1])
                if reassembler is None:
                    reassembler = gp.Reassembler(
                        self.total,
                        self.count,
                        sl.dtype if sl.size else np.dtype(np.float64),
                    )
                reassembler.add(part, sl)
        assert reassembler is not None
        flat = reassembler.result()
        if self.tail_shapes is None:
            return [head, flat]
        return [head, *gp.split_tail(flat, self.tail_shapes)]

    __call__ = evaluate


def member_spans(counts: Sequence[int]) -> List[Tuple[int, int]]:
    """``[(lo, hi), ...]`` slices of a concatenated list per member."""
    spans = []
    lo = 0
    for c in counts:
        spans.append((lo, lo + c))
        lo += c
    return spans


def run_members(
    member_fns: Sequence[Callable[[list, list], None]],
    in_counts: Sequence[int],
    out_counts: Sequence[int],
    inputs: Sequence,
    output_storage: list,
    pool: MemberExecutorPool,
    node_pool=None,
) -> None:
    """Fan the members out; write results through ``output_storage``.

    ``member_fns[i](sub_inputs, sub_storage)`` receives member ``i``'s
    slice of ``inputs`` and the live (aliased, not copied) slice of
    ``output_storage`` — members write results into their own cells and
    never see a sibling's.  All members settle before the first failure
    (in member order) is raised.

    ``node_pool`` (a :class:`~pytensor_federated_tpu.routing.NodePool`,
    optional) routes member failures through the pool's retry/failover
    policy: a member raising a TRANSIENT error
    (``node_pool.is_transient`` — transport trouble, never a
    deterministic compute error) is re-run up to
    ``node_pool.member_retries`` times with the pool's jittered
    backoff between attempts.  Members built over that pool's
    :class:`~pytensor_federated_tpu.routing.PooledArraysClient` pick a
    DIFFERENT healthy replica on the re-run (the failed one's breaker
    just recorded the failure), so the retry is a failover, not an
    instant replay against the dead node.  Member storage writes are
    idempotent (each attempt overwrites the member's own cells), so a
    retried member cannot corrupt a sibling's slice.  Without a pool
    the round-1 contract stands: the first member error surfaces
    immediately after all members settle.
    """
    n = len(member_fns)
    if not (n == len(in_counts) == len(out_counts)):
        raise ValueError(
            f"member/count arity mismatch: {n} fns, "
            f"{len(in_counts)} in_counts, {len(out_counts)} out_counts"
        )
    if sum(in_counts) != len(inputs):
        raise ValueError(
            f"members consume {sum(in_counts)} inputs, got {len(inputs)}"
        )
    if sum(out_counts) != len(output_storage):
        raise ValueError(
            f"members produce {sum(out_counts)} outputs, storage has "
            f"{len(output_storage)}"
        )
    if pool.size < n:
        # An undersized pool would IndexError mid-submission, leaving
        # already-submitted members writing storage while the caller
        # handles the error — exactly the half-settled state the
        # settle-all contract forbids.  Validate up front instead.
        raise ValueError(
            f"pool has {pool.size} member executors but {n} members"
        )
    in_spans = member_spans(in_counts)
    out_spans = member_spans(out_counts)
    telemetry_on = _tspans.enabled()
    durations: List[float] = [0.0] * n if telemetry_on else []

    max_attempts = 1 + (
        max(0, int(node_pool.member_retries)) if node_pool is not None else 0
    )

    def call_member(idx: int, sub_inputs: list, sub_storage: list) -> None:
        """One member evaluation, re-run through the pool's retry
        policy on transient failures (no pool: exactly one attempt).
        Each re-run is amplification and spends from the pool's retry
        budget (``allow_retry``): a window that fans W members into a
        sick pool must degrade to W attempts, not W × retries."""
        for attempt in range(max_attempts):
            try:
                member_fns[idx](sub_inputs, sub_storage)
                return
            except Exception as e:
                if (
                    attempt + 1 >= max_attempts
                    or node_pool is None
                    or not node_pool.is_transient(e)
                    or not node_pool.allow_retry("member_retry")
                ):
                    raise
                _flightrec.record(
                    "fanout.member_retry",
                    idx=idx,
                    attempt=attempt + 1,
                    error=f"{type(e).__name__}: {e}"[:200],
                )
                node_pool.backoff_sleep(attempt)

    def make_run(idx: int):
        def run():
            ilo, ihi = in_spans[idx]
            olo, ohi = out_spans[idx]
            sub_storage = output_storage[olo:ohi]
            if telemetry_on:
                t0 = time.perf_counter()
            with _tspans.span("fanout.member", idx=idx):
                call_member(idx, list(inputs[ilo:ihi]), sub_storage)
            if telemetry_on:
                # Written pre-settle, read post-settle: the futures
                # barrier below orders the write before the read, so no
                # lock is needed despite the cross-thread handoff.
                durations[idx] = time.perf_counter() - t0
                _MEMBER_S.observe(durations[idx])
            # output_storage cells are single-element lists in the
            # pytensor calling convention; the slice above aliases those
            # inner lists, so member writes of sub_storage[j][0] are
            # already visible.  Guard against a member REBINDING a cell
            # (sub_storage[j] = [...]) instead of writing through it,
            # which the aliasing would silently drop:
            for j, cell in enumerate(sub_storage):
                if output_storage[olo + j] is not cell:
                    raise RuntimeError(
                        f"member {idx} rebound storage cell {j} instead "
                        "of writing cell[0]"
                    )

        return run

    with _tspans.span("fanout", width=n) as f_span:
        _FANOUT_WIDTH.observe(n)
        if telemetry_on:
            # ContextVars do NOT cross thread-pool boundaries on their
            # own; each member runs under a COPY of the caller's
            # context (one copy per member — a Context is not
            # re-entrant across concurrent threads), so member spans
            # parent under this fanout span and inherit its trace id.
            futures = [
                pool.submit(
                    i, contextvars.copy_context().run, make_run(i)
                )
                for i in range(n)
            ]
        else:
            futures = [pool.submit(i, make_run(i)) for i in range(n)]
        errs = [f.exception() for f in futures]
        if telemetry_on and n and not any(e is not None for e in errs):
            # Only clean fanouts rate the gap: a failed member's slot
            # never got its duration written, and max-minus-0.0 would
            # pollute exactly the imbalance histogram this feeds.
            gap = max(durations) - min(durations)
            _STRAGGLER_S.observe(gap)
            f_span.set_attr("straggler_gap_s", gap)
        for idx, e in enumerate(errs):
            if e is not None:
                # Black-box note BEFORE the raise: which member of how
                # wide a fanout failed, with siblings already settled
                # (flight-record taxonomy: fanout.member_error).
                _flightrec.record(
                    "fanout.member_error",
                    idx=idx,
                    width=n,
                    error=f"{type(e).__name__}: {e}"[:200],
                )
                raise e
