"""Deterministic fault injection for every I/O boundary the system owns.

The correctness tooling behind the robustness claims of the recovery
machinery (watchdog, circuit breakers, hedged requests, mid-window
failover, elastic restart): a seeded, serializable :class:`FaultPlan`
of faults — delay, drop, disconnect, mid-frame truncation/stall, byte
corruption, duplicated replies, compute errors, GetLoad garbage,
process kills — threaded through the TCP socket path, the npwire /
npproto codec seams, the gRPC stream lane, the server compute path, the
pool probe lane, and (via ``--fault-plan``) the C++ node.

Usage::

    from pytensor_federated_tpu import faultinject as fi

    plan = fi.FaultPlan(
        [fi.FaultRule("stall", point="tcp.send", nth=2, stall_s=3.0)],
        seed=7,
    )
    fi.install(plan)          # this process
    # ... or across a process boundary:
    env["PFTPU_FAULT_PLAN"] = plan.to_json()

``tools/chaos_run.py`` sweeps generated plans over a pooled driver and
asserts the system invariants (exactly-one-reply, watchdog-bounded,
breaker reconvergence, telemetry accounting); ``docs/robustness.md``
maps fault kind x layer x detection signal x recovery tier.

Importing this package activates ``$PFTPU_FAULT_PLAN`` when set (the
cross-process lane — subprocess nodes import the service stack, which
imports this).  With no plan installed every shim is one attribute
load (bench.py's ``faultinject_overhead`` gate).
"""

from .plan import FAULT_KINDS, FaultPlan, FaultRule
from .runtime import (
    FaultPlanError,
    decide,
    install,
    install_from_env,
    snapshot,
    uninstall,
)
from . import runtime

__all__ = [
    "FAULT_KINDS",
    "FaultPlan",
    "FaultPlanError",
    "FaultRule",
    "decide",
    "install",
    "install_from_env",
    "runtime",
    "snapshot",
    "uninstall",
]

# Cross-process activation: a subprocess node spawned with
# PFTPU_FAULT_PLAN set runs its half of the schedule the moment it
# imports the service stack.  Loudly — a chaos run whose plan failed to
# parse would otherwise "pass" by injecting nothing.
install_from_env()
