"""Deterministic, seed-driven fault schedules.

A :class:`FaultPlan` is a serializable list of :class:`FaultRule` s —
*what* chaos to inject (the fault kind), *where* (an injection-point
pattern plus an optional peer filter), and *when* (nth matching call,
every-k-th call, or seeded probability).  The plan is pure data: it
holds no sockets, no threads, and imports nothing heavier than the
stdlib, so the SAME plan object (or its JSON form) drives the driver
process, a subprocess node (``PFTPU_FAULT_PLAN``, see
:mod:`.runtime`), and — for the delay/disconnect/truncate subset — the
C++ node's ``--fault-plan`` flag via :meth:`FaultPlan.native_spec`.

Determinism contract: a plan is a pure function of its construction
arguments.  Probabilistic rules draw from a per-rule ``random.Random``
seeded from ``(plan seed, rule index)``, and nth/every rules count
*matching calls at their injection point*, so replaying the same
workload under the same plan replays the same faults.  (Across
concurrently-served connections the interleaving of matches is the
scheduler's, as in any real system — the *schedule* is deterministic,
the invariants chaos checks must hold under any interleaving.)

Fault kinds (the vocabulary every shim understands — see
:mod:`.runtime` for per-point applicability):

==================  =======================================================
kind                injected behavior
==================  =======================================================
delay               sleep ``delay_s`` then proceed (slow network / node)
drop                discard the frame and reset the connection (a lost
                    frame whose transport subsequently notices; a lost
                    frame over a *silently healthy* connection is
                    ``stall``)
disconnect          fail with ``ConnectionError`` before any bytes move
truncate_frame      emit/keep only the first ``cut_frac`` of the frame's
                    bytes, then reset — the mid-frame kill
corrupt_bytes       flip bytes in the frame's HEADER region (magic /
                    flags / uuid), guaranteeing a loud decode or
                    correlation failure rather than silent data damage
stall               transmit part of the frame, sleep ``stall_s`` (the
                    watchdog-visible wedge), then finish — bounded on
                    purpose so a chaos run always terminates
duplicate_reply     send the reply twice (desynchronizes a lock-step
                    stream; the uuid correlation must catch it)
compute_error       the node's compute raises (in-band error reply /
                    non-retryable status — the deterministic-failure
                    classification path)
slow_compute        the node's compute takes a SEEDED per-call delay,
                    drawn uniformly from ``[0, delay_s]`` by the
                    rule's own RNG — the degraded-replica model the
                    overload chaos lane stalls a pool with (every
                    call slower, none failing: deadlines and
                    admission control must do the shedding)
compute_wrong_shape the node's VECTORIZED batch compute returns the
                    wrong result count (the scalar-fallback isolation
                    path must absorb it)
getload_garbage     GetLoad answers undecodable bytes (the probe lane
                    must fail the probe, not balance toward zero load)
kill_process        ``SIGKILL`` the current process at the injection
                    point (mid-frame process death)
corrupt_descriptor  flip bytes inside a shm doorbell frame's descriptor
                    block (offset/len/generation/dtype bits) — the
                    arena reader must fail loudly, never read a wrong
                    or torn slot (shm lane only)
truncate_slot       scribble the arena slot's tail generation after the
                    payload write — the slot reads as a write that
                    never completed (shm lane only)
stale_generation    age the descriptor's generation so it no longer
                    matches the slot — the recycled-slot race, forced
                    (shm lane only)
drop_shard          remove one partition-indexed slice from a reduce
                    reply (the reassembler must refuse the incomplete
                    gradient loudly, never return a partial sum)
dup_shard           replace one reduce-reply slice with a copy of a
                    sibling (duplicate index + missing index — both
                    loud reassembly refusals)
corrupt_shard       flip bytes inside a reduce-reply slice's partition
                    block (geometry lies: overlap / out-of-bounds /
                    count drift — every shape a loud WireError)
torn_ring_word      leave a ring record's seqlock word mid-write (odd
                    sequence, never committed) — the consumer's bounded
                    wait must classify it as a loud transient timeout,
                    never spin forever or read the torn payload
                    (ring lane only)
ring_stall          delay the producer's futex wake after publishing a
                    ring record — NOT a loud fault: the parked waiter's
                    re-check / bounded park must still consume the
                    record (tests the lost-wake guard, ring lane only)
==================  =======================================================
"""

from __future__ import annotations

import fnmatch
import json
import random
import threading
import uuid as uuid_mod
from typing import Any, Dict, List, Optional, Sequence

__all__ = ["FAULT_KINDS", "FaultRule", "FaultPlan"]

FAULT_KINDS = frozenset(
    {
        "delay",
        "drop",
        "disconnect",
        "truncate_frame",
        "corrupt_bytes",
        "stall",
        "duplicate_reply",
        "compute_error",
        "slow_compute",
        "compute_wrong_shape",
        "getload_garbage",
        "kill_process",
        "corrupt_descriptor",
        "truncate_slot",
        "stale_generation",
        "drop_shard",
        "dup_shard",
        "corrupt_shard",
        "stale_param_version",
        "drop_param_refresh",
        "torn_ring_word",
        "ring_stall",
    }
)

#: Rules translatable to the C++ node's ``--fault-plan`` flag.
NATIVE_KINDS = frozenset({"delay", "disconnect", "truncate_frame"})


class FaultRule:
    """One fault: kind + match predicates + parameters + live counters.

    Predicates (all optional, AND-combined):

    - ``point``: fnmatch pattern over the injection-point name
      (``"tcp.send"``, ``"server.*"``; default ``"*"``).
    - ``peer``: substring of the peer address (``"127.0.0.1:9001"``)
      — pins a rule to one replica.
    - ``nth``: fire on exactly the nth matching call (1-based).
    - ``every``: fire on every ``every``-th matching call.
    - ``prob``: fire with this probability, drawn from the rule's own
      seeded RNG.

    Without nth/every/prob the rule fires on every match.  ``max_fires``
    bounds total fires (default 1 for ``nth`` rules, unbounded
    otherwise — pass explicitly to override).
    """

    __slots__ = (
        "kind", "point", "peer", "nth", "every", "prob", "max_fires",
        "delay_s", "stall_s", "cut_frac", "error", "index", "matches",
        "fires", "_rng",
    )

    def __init__(
        self,
        kind: str,
        *,
        point: str = "*",
        peer: Optional[str] = None,
        nth: Optional[int] = None,
        every: Optional[int] = None,
        prob: Optional[float] = None,
        max_fires: Optional[int] = None,
        delay_s: float = 0.05,
        stall_s: float = 2.0,
        cut_frac: float = 0.5,
        error: Optional[str] = None,
    ) -> None:
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; known: {sorted(FAULT_KINDS)}"
            )
        if nth is not None and nth < 1:
            raise ValueError(f"nth is 1-based, got {nth}")
        if every is not None and every < 1:
            raise ValueError(f"every must be >= 1, got {every}")
        if prob is not None and not 0.0 <= prob <= 1.0:
            raise ValueError(f"prob must be in [0, 1], got {prob}")
        if not 0.0 <= cut_frac <= 1.0:
            raise ValueError(f"cut_frac must be in [0, 1], got {cut_frac}")
        self.kind = kind
        self.point = point
        self.peer = peer
        self.nth = nth
        self.every = every
        self.prob = prob
        self.max_fires = (
            max_fires if max_fires is not None else (1 if nth else None)
        )
        self.delay_s = float(delay_s)
        self.stall_s = float(stall_s)
        self.cut_frac = float(cut_frac)
        self.error = error
        self.index = -1  # set by the owning plan
        self.matches = 0
        self.fires = 0
        self._rng: Optional[random.Random] = None

    def _bind(self, index: int, seed: int) -> None:
        self.index = index
        self._rng = random.Random(f"{seed}:{index}")

    def matches_site(self, point: str, peer: Optional[str]) -> bool:
        if not fnmatch.fnmatchcase(point, self.point):
            return False
        if self.peer is not None and (peer is None or self.peer not in peer):
            return False
        return True

    def should_fire(self, allow: bool = True) -> bool:
        """Consume one match (caller already checked the site) and
        decide whether this occurrence fires.  Counters advance even
        when a fire is vetoed by ``max_fires`` — or by ``allow=False``
        (an earlier rule already fired for this call: exactly one fault
        per call, so ``fires`` counts faults actually APPLIED) — so
        nth/every stay anchored to the workload, not to prior fires."""
        self.matches += 1
        if not allow:
            return False
        if self.max_fires is not None and self.fires >= self.max_fires:
            return False
        if self.nth is not None and self.matches != self.nth:
            return False
        if self.every is not None and self.matches % self.every != 0:
            return False
        if self.prob is not None:
            rng = self._rng or random.Random(self.index)
            if rng.random() >= self.prob:
                return False
        self.fires += 1
        return True

    def draw_delay_s(self) -> float:
        """``slow_compute``'s per-call delay: uniform over
        ``[0, delay_s]`` from the rule's seeded RNG, so the SAME plan
        replays the same latency profile while individual calls still
        vary (a constant-delay replica is `delay`; this models a
        degraded one)."""
        rng = self._rng or random.Random(self.index)
        return rng.random() * self.delay_s

    def cut_at(self, length: int) -> int:
        """Byte offset for truncate/stall splits: at least 1 byte in,
        at most length-1 (a zero-byte or full-length "truncation" would
        be a no-op or a disconnect, not a mid-frame event)."""
        if length <= 1:
            return length
        return min(max(int(length * self.cut_frac), 1), length - 1)

    # -- (de)serialization -------------------------------------------------

    _FIELDS = (
        "kind", "point", "peer", "nth", "every", "prob", "max_fires",
        "delay_s", "stall_s", "cut_frac", "error",
    )

    def to_dict(self) -> Dict[str, Any]:
        d = {}
        for f in self._FIELDS:
            v = getattr(self, f)
            if v is not None and not (f == "point" and v == "*"):
                d[f] = v
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultRule":
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            raise ValueError(f"unknown FaultRule fields: {sorted(unknown)}")
        if "kind" not in d:
            raise ValueError("FaultRule needs a 'kind'")
        kw = dict(d)
        kind = kw.pop("kind")
        return cls(kind, **kw)

    def snapshot(self) -> Dict[str, Any]:
        """Rule spec plus live counters (for incident bundles)."""
        d = self.to_dict()
        d["index"] = self.index
        d["matches"] = self.matches
        d["fires"] = self.fires
        if self.max_fires is not None:
            d["remaining"] = max(0, self.max_fires - self.fires)
        return d

    def __repr__(self) -> str:  # debugging / chaos_run logs
        return f"FaultRule({self.to_dict()!r})"


class FaultPlan:
    """A seeded, serializable schedule of faults.

    ``decide(point, peer)`` is the single runtime entry: it consumes
    one match on every rule whose predicates cover the site and returns
    the first rule that fires (or ``None``).  Thread-safe — injection
    points are hit from event loops, worker threads, and the pool's
    probe thread alike.
    """

    def __init__(
        self,
        rules: Sequence[FaultRule],
        *,
        seed: int = 0,
        plan_id: Optional[str] = None,
    ) -> None:
        self.seed = int(seed)
        self.plan_id = plan_id or f"plan-{self.seed}-{uuid_mod.uuid4().hex[:6]}"
        self.rules: List[FaultRule] = list(rules)
        self._lock = threading.Lock()
        for i, rule in enumerate(self.rules):
            rule._bind(i, self.seed)

    def decide(self, point: str, peer: Optional[str] = None) -> Optional[FaultRule]:
        """First rule that fires at this site, advancing every covering
        rule's match counter; ``None`` when nothing fires.  At most ONE
        rule fires per call — ``fires`` counts faults actually applied,
        which is what the chaos harness's telemetry-accounting
        invariant reconciles against ``fault.*`` events."""
        fired: Optional[FaultRule] = None
        with self._lock:
            for rule in self.rules:
                if not rule.matches_site(point, peer):
                    continue
                if rule.should_fire(allow=fired is None):
                    fired = rule
        return fired

    @property
    def total_fires(self) -> int:
        with self._lock:
            return sum(r.fires for r in self.rules)

    # -- (de)serialization -------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "plan_id": self.plan_id,
            "seed": self.seed,
            "rules": [r.to_dict() for r in self.rules],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultPlan":
        if not isinstance(d, dict) or "rules" not in d:
            raise ValueError("FaultPlan JSON needs a 'rules' list")
        return cls(
            [FaultRule.from_dict(r) for r in d["rules"]],
            seed=d.get("seed", 0),
            plan_id=d.get("plan_id"),
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """``PFTPU_FAULT_PLAN`` parser: inline JSON (leading ``{``) or a
        path to a JSON file."""
        spec = spec.strip()
        if spec.startswith("{"):
            return cls.from_json(spec)
        with open(spec, "r", encoding="utf-8") as fh:
            return cls.from_json(fh.read())

    def snapshot(self) -> Dict[str, Any]:
        """Plan id + per-rule spec/counters/remaining — what an incident
        bundle embeds so chaos-triggered bundles are self-describing."""
        with self._lock:
            return {
                "plan_id": self.plan_id,
                "seed": self.seed,
                "total_fires": sum(r.fires for r in self.rules),
                "rules": [r.snapshot() for r in self.rules],
            }

    def native_spec(self) -> str:
        """The delay/disconnect/truncate subset as the C++ node's
        compact ``--fault-plan`` string: comma-separated
        ``delay:<nth>:<ms>`` / ``disconnect:<nth>`` /
        ``truncate:<nth>:<frac_percent>`` entries (nth counts frames
        served by the node, process-wide).  Rules of other kinds — or
        without an ``nth`` anchor — are skipped: the native node only
        implements the cross-language minimum."""
        parts = []
        for rule in self.rules:
            if rule.kind not in NATIVE_KINDS or rule.nth is None:
                continue
            if rule.kind == "delay":
                parts.append(f"delay:{rule.nth}:{int(rule.delay_s * 1e3)}")
            elif rule.kind == "disconnect":
                parts.append(f"disconnect:{rule.nth}")
            else:
                parts.append(
                    f"truncate:{rule.nth}:{int(rule.cut_frac * 100)}"
                )
        return ",".join(parts)
