"""Fault-injection runtime: plan installation + the injection primitives.

One module-global ``active_plan`` is the whole disabled-path story:
every shim in the I/O stack guards itself with
``if _fi.active_plan is not None`` — a single attribute load — so a
process with no plan installed pays nothing measurable (bench.py's
``faultinject_overhead`` gate holds that line).  With a plan installed,
each injection point calls one of the primitives below; the primitive
asks the plan (:meth:`~.plan.FaultPlan.decide`) whether a rule fires
and applies the fault.

Every fired fault emits a ``fault.<kind>`` flight-recorder event
carrying the plan id, rule index, injection point, and (ambient) trace
id — so an incident bundle shows *what chaos did* right next to *how
the system reacted* (:mod:`..telemetry.flightrec`).

Cross-process activation: ``PFTPU_FAULT_PLAN=<path|inline-json>`` is
read once at import (the service stack imports this package), so a
subprocess node spawned with the variable set runs its half of the
schedule with zero code changes — how chaos reaches across real
process boundaries.  A malformed plan raises at import: a chaos run
whose plan silently failed to load would "pass" by testing nothing.

Applicability by injection point (the wired-in points; shims pass the
names, plans match them with fnmatch patterns):

========================  ==============================================
point                     primitive / applicable kinds
========================  ==============================================
``tcp.send``              :func:`send_frame_through` — all byte +
``tcp.server.send``       process kinds (mid-frame stall/truncate live
                          here: the frame is split at ``cut_frac``)
``tcp.recv``              :func:`filter_bytes` — delay, stall,
``tcp.server.recv``       truncate_frame, corrupt_bytes, drop,
``grpc.send``/``recv``    disconnect, kill_process
``npwire.encode/decode``  :func:`filter_bytes` (codec seams; also
``npwire.*_batch``        ``npproto.encode/decode``)
``grpc.server.reply``     interpreted in service/server.py (async lane:
                          delay, stall, drop→UNAVAILABLE abort,
                          duplicate_reply, truncate, corrupt, kill)
``server.compute``        :func:`compute_filter` (+ ``_async``) —
                          delay, slow_compute (seeded per-call delay),
                          stall, compute_error, kill_process
``server.compute_batch``  :func:`mangle_batch_result` —
                          compute_wrong_shape
``server.getload``        :func:`getload_filter` — getload_garbage,
                          delay
``shm.server.getload``    :func:`getload_filter` (shm doorbell LOAD
                          lane; garbage must fail the probe loudly)
``pool.probe``            :func:`probe_filter` — drop/disconnect (force
                          a failed probe), delay
``shm.send``/``recv``     :func:`send_frame_through` /
``shm.server.send``       :func:`filter_bytes` — the doorbell channel:
``shm.server.recv``       all byte + process kinds, plus
                          ``corrupt_descriptor`` via
                          :func:`corrupt_descriptor_bytes` at the
                          ``shm.descriptor`` point
``shm.arena.write``       :func:`arena_fault` — truncate_slot,
``shm.arena.reply``       stale_generation, delay, kill_process (the
                          arena-side kinds; :mod:`..service.shm`
                          applies the returned kind to the slot it
                          just wrote)
``shm.compute``           :func:`compute_filter` (same kinds as
                          ``server.compute``)
``partition.reply``       :func:`shard_filter` — drop_shard,
                          dup_shard, corrupt_shard (the reduce-reply
                          slice list, before it is framed; the
                          driver's reassembler must refuse every
                          shape loudly)
``ring.send``/``recv``    :func:`filter_bytes` — the ring record byte
``ring.server.send``      lanes (delay, stall, truncate_frame,
``ring.server.recv``      corrupt_bytes, drop, disconnect,
                          kill_process), plus ``corrupt_descriptor``
                          via :func:`corrupt_descriptor_bytes` at the
                          same points (frame header bytes inside the
                          record payload)
``ring.record``           :func:`ring_record_fault` — torn_ring_word,
                          stale_generation, delay, kill_process (the
                          seqlock-word kinds; :mod:`..service.ring`
                          applies the returned kind to the record it
                          just committed)
``ring.wake``             :func:`ring_wake_fault` — ring_stall, delay
                          (delays the producer's futex wake; the
                          parked consumer's lost-wake guard must
                          still make progress)
========================  ==============================================
"""

from __future__ import annotations

import os
import signal
import struct
import threading
import time
from typing import Callable, List, Optional

from ..telemetry import flightrec as _flightrec
from .plan import FaultPlan, FaultRule

__all__ = [
    "active_plan",
    "install",
    "uninstall",
    "install_from_env",
    "decide",
    "filter_bytes",
    "filter_bytes_async",
    "call_shimmed_async",
    "send_frame_through",
    "compute_filter",
    "compute_filter_async",
    "mangle_batch_result",
    "getload_filter",
    "getload_filter_async",
    "probe_filter",
    "probe_filter_async",
    "arena_fault",
    "corrupt_descriptor_bytes",
    "shard_filter",
    "version_filter",
    "refresh_filter",
    "ring_record_fault",
    "ring_wake_fault",
    "snapshot",
]

ENV_VAR = "PFTPU_FAULT_PLAN"

#: The installed plan, or ``None`` (the shipping default).  Shims read
#: this attribute directly as their fast-path guard.
active_plan: Optional[FaultPlan] = None

_lock = threading.Lock()

#: Header region a ``corrupt_bytes`` fault may touch: npwire
#: magic(4)+version(1)+flags(1)+uuid(16)+count(4) = 26 bytes.  Staying
#: inside it guarantees the damage is LOUD (bad magic / bad version /
#: uuid mismatch / insane count) — flipping array payload bytes would
#: be silent corruption the wire format carries no checksum against,
#: which is a different (known) property, not what chaos verifies.
_CORRUPT_REGION = 26


class FaultPlanError(RuntimeError):
    """A fault rule fired at a point that cannot express its kind —
    a plan authoring bug, surfaced loudly instead of skipped."""


def install(plan: FaultPlan) -> Optional[FaultPlan]:
    """Install ``plan`` process-wide; returns the previous plan."""
    global active_plan
    with _lock:
        prev = active_plan
        active_plan = plan
    _flightrec.record(
        "fault.plan_installed", plan=plan.plan_id, n_rules=len(plan.rules)
    )
    return prev


def uninstall() -> Optional[FaultPlan]:
    """Remove the installed plan (idempotent); returns it."""
    global active_plan
    with _lock:
        prev = active_plan
        active_plan = None
    if prev is not None:
        _flightrec.record(
            "fault.plan_uninstalled",
            plan=prev.plan_id,
            total_fires=prev.total_fires,
        )
    return prev


def install_from_env() -> Optional[FaultPlan]:
    """Install the plan named by ``$PFTPU_FAULT_PLAN`` (inline JSON or
    a file path); returns it, or ``None`` when the variable is unset.
    Called once at package import — the subprocess activation lane."""
    spec = os.environ.get(ENV_VAR)
    if not spec:
        return None
    plan = FaultPlan.from_spec(spec)
    install(plan)
    return plan


def snapshot() -> Optional[dict]:
    """The active plan's :meth:`~.plan.FaultPlan.snapshot`, or ``None``
    — what :func:`..telemetry.watchdog.write_incident_bundle` embeds."""
    plan = active_plan
    return plan.snapshot() if plan is not None else None


def decide(point: str, peer: Optional[str] = None) -> Optional[FaultRule]:
    """Ask the active plan whether a fault fires here; records the
    ``fault.<kind>`` flight event for a fired rule.  ``None`` when no
    plan is installed or nothing fires."""
    plan = active_plan
    if plan is None:
        return None
    rule = plan.decide(point, peer)
    if rule is not None:
        attrs = {"plan": plan.plan_id, "rule": rule.index, "point": point}
        if peer is not None:
            attrs["peer"] = peer
        _flightrec.record(f"fault.{rule.kind}", **attrs)
    return rule


def _corrupt(rule: FaultRule, buf: bytes) -> bytes:
    """Flip 1-3 header-region bytes, chosen by the rule's seeded RNG."""
    if not buf:
        return buf
    hi = min(len(buf), _CORRUPT_REGION)
    rng = rule._rng
    out = bytearray(buf)
    for _ in range(min(3, hi)):
        i = rng.randrange(hi) if rng is not None else 0
        out[i] ^= 0xFF
    return bytes(out)


def _kill_now(point: str) -> None:
    # SIGKILL, not sys.exit: the fault models abrupt process death —
    # no atexit hooks, no socket lingering, exactly like the real thing.
    os.kill(os.getpid(), signal.SIGKILL)


def apply_to_bytes(rule: FaultRule, buf: bytes, point: str) -> bytes:
    """Apply a byte-lane fault to an in-hand buffer (codec seams and
    recv paths, where "mid-frame" has no transport meaning): may sleep,
    mutate, raise :class:`ConnectionError`, or kill the process.

    SYNC callers only — the delay/stall kinds ``time.sleep``.  Async
    callers handle those kinds with ``await asyncio.sleep`` first
    (:func:`filter_bytes_async`, ``server._fi_reply_filter``) and
    delegate the rest to :func:`transform_bytes`, which never sleeps —
    the split keeps every blocking primitive off loop-reachable paths
    (graftflow ``async-blocking``)."""
    kind = rule.kind
    if kind == "delay":
        time.sleep(rule.delay_s)
        return buf
    if kind == "stall":
        time.sleep(rule.stall_s)
        return buf
    return transform_bytes(rule, buf, point)


def transform_bytes(rule: FaultRule, buf: bytes, point: str) -> bytes:
    """The sleep-free byte-lane kinds: mutate, raise, or kill — safe
    from any context, event loop included."""
    kind = rule.kind
    if kind in ("drop", "disconnect"):
        raise ConnectionError(f"faultinject[{kind}] at {point}")
    if kind == "truncate_frame":
        return buf[: rule.cut_at(len(buf))]
    if kind == "corrupt_bytes":
        return _corrupt(rule, buf)
    if kind == "kill_process":
        _kill_now(point)
    raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


def filter_bytes(point: str, buf: bytes, peer: Optional[str] = None) -> bytes:
    """The generic byte-lane shim (codec encode/decode seams and the
    sync TCP recv path).  Sleeps BLOCK the calling thread — async call
    sites must use :func:`filter_bytes_async` instead."""
    rule = decide(point, peer)
    if rule is None:
        return buf
    return apply_to_bytes(rule, buf, point)


async def filter_bytes_async(
    point: str, buf: bytes, peer: Optional[str] = None
) -> bytes:
    """Async twin of :func:`filter_bytes` for the grpc.aio lane:
    delay/stall are awaited so a chaos-slowed message behaves like a
    slow network, not a frozen driver — concurrent in-window RPCs and
    the hedge timer on the same event loop keep running."""
    rule = decide(point, peer)
    if rule is None:
        return buf
    if rule.kind in ("delay", "stall"):
        import asyncio

        await asyncio.sleep(
            rule.delay_s if rule.kind == "delay" else rule.stall_s
        )
        return buf
    return transform_bytes(rule, buf, point)


async def call_shimmed_async(fn, *args, inline: bool = True, **kwargs):
    """Call a sync function that HOLDS chaos seams (codec
    ``filter_bytes`` points, the vectorized ``mangle_batch_result``
    seam) from a coroutine without ever blocking the event loop.

    ``inline=True`` is the production fast path: a direct call, taken
    only while NO fault plan is active.  With a plan installed — or
    with ``inline=False`` (callers that always want the executor
    handoff, e.g. the non-inline batcher) — the call runs in the
    loop's default executor, so a sync shim's delay/stall sleeps a
    worker thread and a chaos-slowed frame behaves like a slow
    network, not a frozen driver.

    This exists because graftflow's transitive ``async-blocking`` rule
    found the PR-5 bug class again, three frames down: async handlers
    call the sync codecs inline, and the codecs hold ``filter_bytes``
    seams whose delay kinds ``time.sleep`` (tests:
    test_faultinject.py ``TestCallShimmedAsync``).

    The executor call carries the CALLER's contextvars
    (``copy_context``): the codecs read the ambient telemetry trace id
    (``spans.current_trace_id``), and a bare worker thread would
    silently encode ``trace_id=None`` exactly during chaos runs —
    the same convention as routing/pooled_client's executor hops."""
    if inline and active_plan is None:
        return fn(*args, **kwargs)
    import asyncio
    import contextvars
    from functools import partial

    loop = asyncio.get_running_loop()
    ctx = contextvars.copy_context()
    return await loop.run_in_executor(
        None, ctx.run, partial(fn, *args, **kwargs)
    )


def send_frame_through(
    point: str,
    sendall: Callable[[bytes], None],
    payload: bytes,
    peer: Optional[str] = None,
) -> None:
    """Send one u32-length-prefixed frame with injection — the TCP
    lane's send shim, where mid-frame faults are physically real:
    ``stall`` transmits the frame's first ``cut_frac`` bytes, sleeps,
    then finishes; ``truncate_frame`` transmits the head and resets the
    connection; ``duplicate_reply`` transmits the frame twice."""
    rule = decide(point, peer)
    prefix = struct.pack("<I", len(payload))
    if rule is None:
        sendall(prefix + payload)
        return
    kind = rule.kind
    if kind == "delay":
        time.sleep(rule.delay_s)
        sendall(prefix + payload)
    elif kind == "disconnect":
        raise ConnectionError(f"faultinject[disconnect] at {point}")
    elif kind == "drop":
        # The frame is discarded AND the connection resets: a lost
        # frame over a connection that stays silently healthy would
        # hang a lock-step peer forever — that failure mode is `stall`
        # (bounded, watchdog-visible) by design.
        raise ConnectionError(f"faultinject[drop] at {point}")
    elif kind == "truncate_frame":
        data = prefix + payload
        sendall(data[: 4 + rule.cut_at(len(payload))])
        raise ConnectionError(f"faultinject[truncate_frame] at {point}")
    elif kind == "stall":
        data = prefix + payload
        k = 4 + rule.cut_at(len(payload))
        sendall(data[:k])
        time.sleep(rule.stall_s)
        sendall(data[k:])
    elif kind == "corrupt_bytes":
        sendall(prefix + _corrupt(rule, payload))
    elif kind == "duplicate_reply":
        sendall(prefix + payload + prefix + payload)
    elif kind == "kill_process":
        _kill_now(point)
    else:
        raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


def compute_filter(point: str = "server.compute", peer: Optional[str] = None) -> None:
    """Node compute-path shim (sync lanes): ``compute_error`` raises —
    the caller's normal error handling turns it into an in-band error
    reply / status abort; delay/stall sleep (``slow_compute`` draws a
    seeded per-call delay — the degraded-replica model); kill kills."""
    rule = decide(point, peer)
    if rule is None:
        return
    kind = rule.kind
    if kind == "delay":
        time.sleep(rule.delay_s)
    elif kind == "slow_compute":
        time.sleep(rule.draw_delay_s())
    elif kind == "stall":
        time.sleep(rule.stall_s)
    elif kind == "compute_error":
        raise RuntimeError(
            rule.error or f"faultinject[compute_error] at {point}"
        )
    elif kind == "kill_process":
        _kill_now(point)
    else:
        raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


async def compute_filter_async(
    point: str = "server.compute", peer: Optional[str] = None
) -> None:
    """Async twin of :func:`compute_filter` for the grpc.aio server —
    sleeps are awaited so a stalled compute does not freeze the event
    loop (GetLoad and sibling streams keep serving, exactly like a real
    slow compute in the executor)."""
    rule = decide(point, peer)
    if rule is None:
        return
    kind = rule.kind
    if kind in ("delay", "stall", "slow_compute"):
        import asyncio

        await asyncio.sleep(
            rule.draw_delay_s()
            if kind == "slow_compute"
            else (rule.delay_s if kind == "delay" else rule.stall_s)
        )
    elif kind == "compute_error":
        raise RuntimeError(
            rule.error or f"faultinject[compute_error] at {point}"
        )
    elif kind == "kill_process":
        _kill_now(point)
    else:
        raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


def mangle_batch_result(point: str, outs: List[object]) -> List[object]:
    """The vectorized-compute seam: ``compute_wrong_shape`` drops one
    result so the batch returns K-1 outputs for K requests — the
    malformed-batch signature the scalar-fallback isolation path
    (service/batching.py) must absorb without corrupting any reply."""
    rule = decide(point)
    if rule is None:
        return outs
    if rule.kind == "compute_wrong_shape":
        return list(outs)[:-1]
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return outs
    raise FaultPlanError(f"fault kind {rule.kind!r} not applicable at {point}")


#: Valid-but-unknown-fields-only protobuf (field 31, len 3): the exact
#: shape proto3 leniency would decode to an all-zero — maximally
#: attractive — load if the GetLoad guard were missing; also not JSON.
GETLOAD_GARBAGE = b"\xfa\x01\x03xyz"


def getload_filter(point: str = "server.getload") -> Optional[bytes]:
    """GetLoad shim: returns replacement reply bytes for
    ``getload_garbage`` (``None`` = serve the real reply); ``delay``
    sleeps."""
    rule = decide(point)
    if rule is None:
        return None
    if rule.kind == "getload_garbage":
        return GETLOAD_GARBAGE
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return None
    raise FaultPlanError(f"fault kind {rule.kind!r} not applicable at {point}")


async def getload_filter_async(point: str = "server.getload") -> Optional[bytes]:
    """Async twin of :func:`getload_filter` for the grpc.aio GetLoad
    handler: a ``delay`` rule is awaited, so a chaos-slowed load reply
    behaves like a slow node — concurrent Evaluate streams on the same
    event loop keep serving (the PR-5 event-loop-blocking bug class,
    caught by the ``async-blocking`` graftlint rule)."""
    rule = decide(point)
    if rule is None:
        return None
    if rule.kind == "getload_garbage":
        return GETLOAD_GARBAGE
    if rule.kind == "delay":
        import asyncio

        await asyncio.sleep(rule.delay_s)
        return None
    raise FaultPlanError(f"fault kind {rule.kind!r} not applicable at {point}")


def arena_fault(point: str, peer: Optional[str] = None) -> Optional[str]:
    """SHM arena-side shim: returns the fired arena kind
    (``truncate_slot`` / ``stale_generation``) for the caller to apply
    to the slot it controls — the fault needs arena knowledge the
    runtime does not have, so :mod:`..service.shm` executes it at the
    write site.  ``delay`` sleeps here (sync lane); ``kill_process``
    kills; ``None`` = no fault."""
    rule = decide(point, peer)
    if rule is None:
        return None
    kind = rule.kind
    if kind in ("truncate_slot", "stale_generation"):
        return kind
    if kind == "delay":
        time.sleep(rule.delay_s)
        return None
    if kind in ("drop", "disconnect"):
        raise ConnectionError(f"faultinject[{kind}] at {point}")
    if kind == "kill_process":
        _kill_now(point)
    raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


def corrupt_descriptor_bytes(
    point: str, buf: bytes, desc_off: int, peer: Optional[str] = None
) -> bytes:
    """``corrupt_descriptor`` shim for shm doorbell frames: flips 1-3
    bytes INSIDE the descriptor block (``desc_off`` onward — offsets,
    lengths, generations, dtype bits), which is exactly the damage the
    arena reader's generation/bounds validation must classify as
    :class:`~..service.npwire.WireError`, never a torn or silently
    wrong array.  Frame-header kinds stay with :func:`filter_bytes`."""
    rule = decide(point, peer)
    if rule is None:
        return buf
    if rule.kind != "corrupt_descriptor":
        raise FaultPlanError(
            f"fault kind {rule.kind!r} not applicable at {point}"
        )
    if desc_off >= len(buf):
        return buf
    rng = rule._rng
    out = bytearray(buf)
    span = len(buf) - desc_off
    for _ in range(min(3, span)):
        i = desc_off + (rng.randrange(span) if rng is not None else 0)
        out[i] ^= 0xFF
    return bytes(out)


def ring_record_fault(point: str, peer: Optional[str] = None) -> Optional[str]:
    """Ring record-side shim (ISSUE 18): returns the fired seqlock-word
    kind (``torn_ring_word`` / ``stale_generation``) for
    :mod:`..service.ring` to apply to the record it just committed —
    the fault needs ring-geometry knowledge (slot position, sequence
    residue) the runtime does not have.  ``delay`` sleeps here (sync
    lane); ``kill_process`` kills; ``None`` = no fault."""
    rule = decide(point, peer)
    if rule is None:
        return None
    kind = rule.kind
    if kind in ("torn_ring_word", "stale_generation"):
        return kind
    if kind == "delay":
        time.sleep(rule.delay_s)
        return None
    if kind in ("drop", "disconnect"):
        raise ConnectionError(f"faultinject[{kind}] at {point}")
    if kind == "kill_process":
        _kill_now(point)
    raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


def ring_wake_fault(point: str, peer: Optional[str] = None) -> None:
    """``ring.wake`` shim (ISSUE 18): delays the producer's futex wake
    AFTER the record is published (``ring_stall`` sleeps ``stall_s``,
    ``delay`` sleeps ``delay_s``).  Deliberately NOT a loud fault: the
    record is committed, so the consumer's bounded park / lost-wake
    re-check must consume it regardless — chaos verifies liveness, not
    an error classification.  ``kill_process`` kills (wake never
    arrives: the peer-death path)."""
    rule = decide(point, peer)
    if rule is None:
        return
    kind = rule.kind
    if kind == "ring_stall":
        time.sleep(rule.stall_s)
        return
    if kind == "delay":
        time.sleep(rule.delay_s)
        return
    if kind == "kill_process":
        _kill_now(point)
    raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


def shard_filter(
    point: str,
    items: List[bytes],
    *,
    block_off: int = 0,
    block_len: int = 32,
    peer: Optional[str] = None,
) -> List[bytes]:
    """Partition reduce-reply shim (ISSUE 13): mangle the slice list a
    server is about to frame, so the DRIVER's reassembly loudness is
    what chaos verifies.  ``drop_shard`` removes a seeded slice (the
    reassembler's missing-index refusal); ``dup_shard`` replaces one
    slice with a copy of a sibling (duplicate + missing — both loud);
    ``corrupt_shard`` flips bytes inside one slice's partition/
    descriptor block — the ``block_len`` bytes at ``block_off``, NEVER
    payload bytes (payload damage would be silent; geometry damage is
    guaranteed loud: overlap/out-of-bounds/count drift).  ``delay``
    sleeps (sync server lanes only)."""
    rule = decide(point, peer)
    if rule is None or not items:
        return items
    kind = rule.kind
    rng = rule._rng
    idx = rng.randrange(len(items)) if rng is not None else 0
    if kind == "drop_shard":
        return [it for j, it in enumerate(items) if j != idx]
    if kind == "dup_shard":
        out = list(items)
        out[(idx + 1) % len(out)] = out[idx]
        return out
    if kind == "corrupt_shard":
        victim = bytearray(items[idx])
        span = min(block_len, len(victim) - block_off)
        if span > 0:
            for _ in range(min(3, span)):
                i = block_off + (
                    rng.randrange(span) if rng is not None else 0
                )
                victim[i] ^= 0xFF
        out = list(items)
        out[idx] = bytes(victim)
        return out
    if kind == "delay":
        time.sleep(rule.delay_s)
        return items
    raise FaultPlanError(f"fault kind {kind!r} not applicable at {point}")


def version_filter(
    point: str, version: int, peer: Optional[str] = None
) -> int:
    """Sharded-optimizer version-stamp shim (ISSUE 16): a
    ``stale_param_version`` rule TWISTS the step-version stamp the
    driver is about to put on an update request, so the node's version
    check refuses it — what chaos verifies is the driver's recovery
    classification (a refusal whose ``holds`` equals the driver's own
    version means NO step happened; the driver must not count it
    accepted).  ``delay`` sleeps (driver-side sync lane)."""
    rule = decide(point, peer)
    if rule is None:
        return version
    if rule.kind == "stale_param_version":
        # Twist DOWN when possible (the classic lost-driver-state
        # shape); a version-0 stamp twists up instead (u64 wire).
        return version - 1 if version > 0 else version + 1
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return version
    raise FaultPlanError(f"fault kind {rule.kind!r} not applicable at {point}")


def refresh_filter(point: str, peer: Optional[str] = None) -> None:
    """Param-refresh lane shim (ISSUE 16): a ``drop_param_refresh``
    rule raises :class:`ConnectionError` in place of the refresh call —
    a dropped refresh is a TRANSIENT transport failure (the shard stays
    at its old version; the next step's already-applied recovery
    retries the refresh), never a silent stale parameter read."""
    rule = decide(point, peer)
    if rule is None:
        return
    if rule.kind == "drop_param_refresh":
        raise ConnectionError(
            f"fault injection: dropped param refresh at {point}"
        )
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return
    raise FaultPlanError(f"fault kind {rule.kind!r} not applicable at {point}")


def probe_filter(peer: str, point: str = "pool.probe") -> bool:
    """Pool probe-lane shim: ``False`` forces the probe to be recorded
    as failed without dialing (``drop``/``disconnect``); ``delay``
    sleeps then proceeds; ``True`` = probe normally."""
    rule = decide(point, peer)
    if rule is None:
        return True
    if rule.kind in ("drop", "disconnect"):
        return False
    if rule.kind == "delay":
        time.sleep(rule.delay_s)
        return True
    raise FaultPlanError(f"fault kind {rule.kind!r} not applicable at {point}")


async def probe_filter_async(peer: str, point: str = "pool.probe") -> bool:
    """Async twin of :func:`probe_filter` for the gRPC probe lane:
    a ``delay`` rule is awaited so a chaos-slowed probe does not freeze
    the pool's event loop — sibling probes in the same ``gather`` and
    in-flight calls keep running (the PR-5 event-loop-blocking bug
    class, caught by the ``async-blocking`` graftlint rule)."""
    rule = decide(point, peer)
    if rule is None:
        return True
    if rule.kind in ("drop", "disconnect"):
        return False
    if rule.kind == "delay":
        import asyncio

        await asyncio.sleep(rule.delay_s)
        return True
    raise FaultPlanError(f"fault kind {rule.kind!r} not applicable at {point}")
