"""``fed.program``: trace once, lower per placement, fuse windows.

``program(fn, placement)`` returns a callable with ``fn``'s signature
that (1) traces ``fn`` — whose body uses :mod:`.primitives` — to a
jaxpr, (2) plans the window-fusion groups (:mod:`.batching`) and
builds one persistent placement EXECUTOR per ``fed_map``
equation/group, and (3) interprets the jaxpr, handing ``fed_map``
equations to their executors and executing everything else with its
normal JAX binding.  Because the interpreter runs under whatever trace
is ambient, the SAME program object works eagerly, under ``jax.jit``,
and under ``jax.grad``/``jax.vjp`` — the mesh lane differentiates
through ``shard_map``/psum, the pool lane through the custom-VJP
logp+grad contract, the mixed lane through both.

With ``placement=None`` the wrapper is the identity: the primitives'
dense semantics (vmap / sum / broadcast) execute directly, which is
also the fastest single-chip layout.

Trace + plan + executors are cached per argument-shape/dtype signature
(a hot pool program pays the interpreter walk and one cached callback
dispatch per call, not a retrace — bench_suite config 14 holds the IR
overhead under 10%), unless the trace lifted closure TRACERS from an
enclosing transformation — those are call-specific and must not leak
into a cache.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util
from jax.extend.core import Literal

from .batching import plan_windows
from .placements import MapSpec, Placement
from .primitives import (
    fed_broadcast,
    fed_broadcast_p,
    fed_map,
    fed_map_p,
    fed_sum,
    fed_sum_p,
    is_tracer as _is_tracer,
)

__all__ = ["FederatedLogpGrad", "canonical_round", "program"]


def canonical_round(
    per_shard_fn: Callable,
    data: Any,
    n_shards: int,
) -> Callable:
    """The canonical broadcast→map→sum round as a placement-free fed
    model: ``round(*params) = fed_sum(fed_map(per_shard_fn, (params
    broadcast to every shard, data)))``.

    ``per_shard_fn(*params, shard_data)`` is the per-shard term;
    ``data`` is the stacked shard pytree (a concrete pytree bakes into
    the trace, which pool lanes accept — the node's deployed copy of
    the function carries the same data).  Parameters reach the shards
    through ``fed_broadcast``, which makes them MAPPED operands: the
    shape every pool deployment must follow (closure capture of
    driver-varying values is refused at lowering), and the shape that
    keeps the PR-13 reduced-window lowering eligible.  This is the
    single implementation behind :class:`FederatedLogpGrad` and the
    ``ppl`` compiler's plate lowering (ISSUE 15), so the two front
    ends cannot drift."""
    n = int(n_shards)

    def round_model(*params: Any) -> Any:
        pb = fed_broadcast(tuple(params), n)
        lps = fed_map(
            lambda shard: per_shard_fn(*shard[0], shard[1]), (pb, data)
        )
        return fed_sum(lps)

    return round_model


def _plan_reduce(
    closed: Any,
    plan: Dict[int, List[int]],
    placement: Placement,
    baked: frozenset,
) -> Dict[int, int]:
    """Pair eligible ``fed_sum(fed_map(...))`` equations for the
    REDUCED window lowering (ISSUE 13) -> ``{map_eqn_idx:
    sum_eqn_idx}``.

    Eligibility (every check is a correctness gate, not a heuristic):

    - the placement opted in (``reduce=True``) and provides
      ``reduced_sum_executor``;
    - the ``fed_map`` fits the logp+grad wire contract (one scalar
      inexact output), ships no driver-varying closure values, and is
      not in a window-fusion group;
    - its single output feeds EXACTLY one equation — the ``fed_sum``
      — and is not itself a program output (anyone else reading the
      per-shard values needs them un-summed);
    - every INEXACT mapped operand is ``fed_broadcast``-derived or a
      trace-time-baked constant: the reduced gradient is ``Σ_s
      grad_s``, which is only a usable cotangent for consumers whose
      transpose SUMS over shards (broadcast) or who need no cotangent
      at all (baked consts, integers).  A per-shard program INPUT
      fails the gate and the pair falls back to the per-shard window
      — correct, just not reduced."""
    if not getattr(placement, "reduce", False) or not hasattr(
        placement, "reduced_sum_executor"
    ):
        return {}
    jaxpr = closed.jaxpr
    grouped: set = set()
    for group in plan.values():
        grouped.update(group)
    producer: Dict[Any, int] = {}
    consumers: Dict[Any, List[int]] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            producer[v] = i
        for v in eqn.invars:
            if not isinstance(v, Literal):
                consumers.setdefault(v, []).append(i)
    out_vars = {
        v for v in jaxpr.outvars if not isinstance(v, Literal)
    }
    broadcast_out = {
        v
        for eqn in jaxpr.eqns
        if eqn.primitive is fed_broadcast_p
        for v in eqn.outvars
    }
    baked_or_const = baked | set(jaxpr.constvars)
    pairs: Dict[int, int] = {}
    for j, sum_eqn in enumerate(jaxpr.eqns):
        if sum_eqn.primitive is not fed_sum_p:
            continue
        v = sum_eqn.invars[0]
        if isinstance(v, Literal) or v in out_vars:
            continue
        i = producer.get(v)
        if i is None or jaxpr.eqns[i].primitive is not fed_map_p:
            continue
        map_eqn = jaxpr.eqns[i]
        if len(map_eqn.outvars) != 1 or len(consumers.get(v, ())) != 1:
            continue
        if i in grouped or i in pairs:
            continue
        spec = MapSpec.from_eqn(map_eqn, baked)
        if not spec.grad_contract or spec.n_varying_consts:
            continue
        n_consts = map_eqn.params["n_consts"]
        eligible = True
        for xv in map_eqn.invars[n_consts:]:
            if isinstance(xv, Literal) or xv in baked_or_const:
                continue
            if xv in broadcast_out:
                continue
            if not jnp.issubdtype(xv.aval.dtype, jnp.inexact):
                continue
            eligible = False
            break
        if eligible:
            pairs[i] = j
    return pairs


def _build_executors(
    closed: Any, placement: Placement, plan: Dict[int, List[int]]
) -> Tuple[Dict[int, tuple], Dict[int, int]]:
    """One persistent executor per ``fed_map`` equation: fused groups
    share a group executor keyed at every member index; eligible
    ``fed_sum(fed_map)`` pairs lower to ONE reduced window
    (:func:`_plan_reduce`).  Outer constvars holding CONCRETE values
    are trace-time-baked constants (``MapSpec`` uses this to tell a
    baked function constant from driver-varying closure capture, which
    pool lanes must refuse)."""
    jaxpr = closed.jaxpr
    baked = frozenset(
        v
        for v, c in zip(jaxpr.constvars, closed.consts)
        if not _is_tracer(c)
    )
    reduce_pairs = _plan_reduce(closed, plan, placement, baked)
    executors: Dict[int, tuple] = {}
    done_groups: Dict[tuple, Any] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        if eqn.primitive is not fed_map_p:
            continue
        if i in reduce_pairs:
            executors[i] = (
                "reduced",
                placement.reduced_sum_executor(  # type: ignore[attr-defined]
                    MapSpec.from_eqn(eqn, baked)
                ),
                reduce_pairs[i],
            )
            continue
        group = plan.get(i)
        if group is None:
            executors[i] = ("single", placement.map_executor(
                MapSpec.from_eqn(eqn, baked)
            ))
            continue
        key = tuple(group)
        if key not in done_groups:
            done_groups[key] = placement.group_executor(
                [MapSpec.from_eqn(jaxpr.eqns[j], baked) for j in group]
            )
        executors[i] = ("group", key, done_groups[key])
    return executors, reduce_pairs


def program(
    fn: Callable,
    placement: Optional[Placement] = None,
    *,
    fuse: bool = True,
) -> Callable:
    """Placement-aware executable form of a ``fed``-primitive model.

    ``fn`` takes/returns pytrees of arrays; its body expresses the
    federated algebra with :func:`fed_map` / :func:`fed_sum` /
    :func:`fed_broadcast` / :func:`fed_mean`.  ``fuse=True`` coalesces
    independent ``fed_map`` calls into one window where the placement
    supports it (pool lanes).
    """
    if placement is None:
        return fn
    cache: dict = {}

    def wrapped(*args: Any) -> Any:
        flat, in_tree = tree_util.tree_flatten(args)
        flat = [jnp.asarray(x) for x in flat]
        key = (
            in_tree,
            tuple((jnp.shape(x), str(jnp.result_type(x))) for x in flat),
        )
        entry = cache.get(key)
        if entry is None:
            out_store: list = []

            def flat_fn(*leaves: Any) -> List[Any]:
                a = tree_util.tree_unflatten(in_tree, leaves)
                out_flat, out_tree = tree_util.tree_flatten(fn(*a))
                out_store.append(out_tree)
                return out_flat

            closed = jax.make_jaxpr(flat_fn)(*flat)
            plan = plan_windows(closed.jaxpr) if fuse else {}
            executors, reduce_pairs = _build_executors(
                closed, placement, plan
            )
            entry = (closed, out_store[0], plan, executors, reduce_pairs)
            if not any(_is_tracer(c) for c in closed.consts):
                cache[key] = entry
        closed, out_tree, plan, executors, reduce_pairs = entry
        outs = _interpret(closed, flat, plan, executors, reduce_pairs)
        return tree_util.tree_unflatten(out_tree, outs)

    wrapped.__name__ = getattr(fn, "__name__", "fed_program")
    return wrapped


def _interpret(
    closed: Any,
    args: List[Any],
    plan: Dict[int, List[int]],
    executors: Dict[int, tuple],
    reduce_pairs: Optional[Dict[int, int]] = None,
) -> list:
    jaxpr = closed.jaxpr
    env: dict = {}

    def read(v: Any) -> Any:
        return v.val if isinstance(v, Literal) else env[v]

    def write(vs: Any, vals: Any) -> None:
        for v, val in zip(vs, vals):
            env[v] = val

    write(jaxpr.constvars, closed.consts)
    write(jaxpr.invars, args)

    def ready(i: int) -> bool:
        return all(
            isinstance(v, Literal) or v in env
            for v in jaxpr.eqns[i].invars
        )

    def consts_xs(eqn: Any) -> Tuple[tuple, tuple]:
        invals = [read(v) for v in eqn.invars]
        n_consts = eqn.params["n_consts"]
        return tuple(invals[:n_consts]), tuple(invals[n_consts:])

    def run_eqn(eqn: Any, i: int) -> None:
        if eqn.primitive is fed_map_p:
            _, executor = executors[i]
            outs = executor(*consts_xs(eqn))
        else:
            invals = [read(v) for v in eqn.invars]
            subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
            outs = eqn.primitive.bind(*subfuns, *invals, **bind_params)
            if not eqn.primitive.multiple_results:
                outs = [outs]
        write(eqn.outvars, outs)

    reduce_pairs = reduce_pairs or {}
    remaining = set(range(len(jaxpr.eqns)))
    while remaining:
        progressed = False
        for i in sorted(remaining):
            if i not in remaining:
                continue
            if i in reduce_pairs:
                # A fed_sum(fed_map) pair lowered to one REDUCED
                # window: the executor's scalar IS the fed_sum's
                # output; the per-shard stack never materializes
                # (_plan_reduce guaranteed it has no other consumer).
                if not ready(i):
                    continue
                j = reduce_pairs[i]
                _, executor, _j = executors[i]
                outs = executor(*consts_xs(jaxpr.eqns[i]))
                write(jaxpr.eqns[j].outvars, outs)
                remaining -= {i, j}
                progressed = True
                continue
            group = plan.get(i)
            if group is not None:
                if not all(j in remaining and ready(j) for j in group):
                    continue
                _, _, executor = executors[i]
                group_outs = executor(
                    [consts_xs(jaxpr.eqns[j]) for j in group]
                )
                for j, outs in zip(group, group_outs):
                    write(jaxpr.eqns[j].outvars, outs)
                remaining -= set(group)
                progressed = True
                continue
            if not ready(i):
                continue
            run_eqn(jaxpr.eqns[i], i)
            remaining.discard(i)
            progressed = True
        if not progressed:  # pragma: no cover - grouping guarantees progress
            raise RuntimeError(
                "fed program scheduling wedged: remaining equations "
                f"{sorted(remaining)} have unmet inputs"
            )
    return [read(v) for v in jaxpr.outvars]


class FederatedLogpGrad:
    """One federated log-potential, every lane: the ``fed.program``
    evaluator the bridge routes through.

    ``per_shard_fn(*params, shard_data)`` is the per-shard
    log-potential; ``data`` is the stacked shard pytree.  The model it
    programs is the canonical broadcast→map→sum round::

        logp(params) = fed_sum(fed_map(f, (fed_broadcast(params), data)))

    Surfaces:

    - :meth:`logp` / :meth:`logp_and_grad` — JAX-side evaluation under
      the placement (``jax.grad`` works through all lanes).
    - ``__call__(*arrays) -> (logp, [grads])`` — the host
      ``LogpGradFn`` signature, directly usable as
      ``bridge.federated_potential``'s compute (perform path).
    - :attr:`jax_fn` — the ``(logp, grads)`` callable the bridge's
      ``jax_funcify`` dispatch inlines; ``federated_potential`` picks
      it up automatically, and ``bridge.core.fused_jax_callable``
      composes several of these into ONE fused program whose
      independent ``fed_map`` calls share a window.
    - :meth:`node_compute` — the matching node-side deployment
      (``service.run_node(ev.node_compute(), ...)``) for pool lanes.
    """

    def __init__(
        self,
        per_shard_fn: Callable,
        data: Any,
        *,
        placement: Optional[Placement] = None,
        fuse: bool = True,
    ) -> None:
        self.per_shard_fn = per_shard_fn
        self.data = data
        self.placement = placement
        leaves = tree_util.tree_leaves(data)
        dims = {jnp.shape(l)[0] for l in leaves}
        if len(dims) != 1:
            raise ValueError(
                f"data leaves must share a leading shard axis, got {dims}"
            )
        self.n_shards = int(dims.pop())
        self._data_treedef = tree_util.tree_structure(data)
        # The canonical round, in primitives (placement-free: `program`
        # owns the lowering) — the shared canonical_round shape.
        self._model = canonical_round(
            self.per_shard_fn, self.data, self.n_shards
        )
        self._program = program(
            self._model, placement=placement, fuse=fuse
        )

    def fed_model(self, *params: Any) -> Any:
        """The raw primitive-level model (no placement) — what
        ``fused_jax_callable`` composes across potentials so the fused
        program's batching pass sees every member's ``fed_map``."""
        return self._model(*params)

    def logp(self, *params: Any) -> jax.Array:
        return self._program(*params)

    def logp_and_grad(self, *params: Any) -> Tuple[Any, Any]:
        argnums = tuple(range(len(params)))
        return jax.value_and_grad(self._program, argnums=argnums)(*params)

    def jax_fn(self, *params: Any) -> Tuple[Any, List[Any]]:
        """``(logp, grads)`` for the bridge's ``jax_funcify`` lane."""
        logp, grads = self.logp_and_grad(*params)
        return logp, list(grads)

    def __call__(self, *arrays: Any) -> Tuple[Any, List[Any]]:
        """Host ``LogpGradFn``: numpy in, ``(logp, [grads])`` out."""
        logp, grads = self.logp_and_grad(
            *[jnp.asarray(a) for a in arrays]
        )
        return np.asarray(logp), [np.asarray(g) for g in grads]

    def node_compute(self, *, grads: bool = True) -> Callable[..., list]:
        """Node-side compute matching this evaluator's wire contract:
        requests carry ``(params leaves..., data leaves...)``."""
        from .placements import make_node_compute

        treedef = self._data_treedef
        n_data = treedef.num_leaves
        per_shard = self.per_shard_fn

        def flat(*arrays: Any) -> Any:
            params = arrays[: len(arrays) - n_data]
            dleaves = arrays[len(arrays) - n_data :]
            return per_shard(
                *params, tree_util.tree_unflatten(treedef, list(dleaves))
            )

        return make_node_compute(flat, grads=grads)
