"""Placement-aware lowering of the ``fed`` primitives.

A :class:`Placement` decides WHERE the shards of a ``fed_map`` live
and HOW the per-shard program executes there:

- :class:`MeshPlacement` — shards are positions along a named mesh
  axis; ``fed_map`` lowers to the existing ``shard_map`` + vmap
  machinery (``parallel/sharded.py``), with unmapped closure constants
  replicated and ``mark_varying``-ed before any user code runs (the
  CLAUDE.md pvary/psum invariant).
- :class:`PoolPlacement` — shards are requests over an RPC node pool;
  ``fed_map`` lowers to ONE pipelined ``evaluate_many`` window through
  ``jax.pure_callback``, differentiable via the reference's
  forward-supplied-gradient contract (nodes reply ``[logp, *grads]``;
  the custom VJP applies ``g · grads``).  A *group* of independent
  ``fed_map`` calls lowers to a single fused window — the
  AsyncFusionOptimizer rewrite (SURVEY L4) as a primitive-level pass.
- :class:`MixedPlacement` — splits the shard range: the leading shards
  ride a mesh, the trailing shards a pool, outputs concatenate (and
  gradients flow through both lanes).

Lowerings are built as PERSISTENT **executors**: ``map_executor(spec)``
/ ``group_executor(specs)`` construct the callback closures, custom
VJPs, and shard_map programs ONCE per traced program (``lowering.py``
caches them alongside the jaxpr), so repeated evaluations hit JAX's
dispatch caches instead of re-tracing — the primitive lane's overhead
budget (bench_suite config 14: IR must cost < 10% over the direct
fan-out).

The wire contract of a pool-placed ``fed_map``: each request carries
exactly the shard's MAPPED leaves, in ``tree_flatten`` order.  Closure
constants never leave the driver — driver state a node needs must
arrive via ``fed_broadcast`` (which makes it a mapped operand), and
the node's deployed compute must be the same per-shard function
(:func:`make_node_compute` builds it from the identical Python
callable, so driver and node cannot disagree).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .._compat import shard_map
from ..parallel.mesh import SHARDS_AXIS, mark_varying
from ..telemetry import flightrec as _flightrec
from ..telemetry import spans as _spans
from .primitives import _per_shard_fun, is_tracer as _is_tracer

__all__ = [
    "MapSpec",
    "MeshPlacement",
    "MixedPlacement",
    "Placement",
    "PoolPlacement",
    "make_node_compute",
]


@dataclasses.dataclass(frozen=True)
class MapSpec:
    """The static shape of one ``fed_map`` equation: everything an
    executor needs besides the runtime operand values."""

    jaxpr: Any
    n_consts: int
    n_shards: int
    x_avals: Tuple[Any, ...]  # stacked mapped operands (shape incl. shards)
    out_avals: Tuple[Any, ...]  # per-shard outputs
    # How many unmapped operands are DRIVER-VARYING — fed by program
    # inputs or upstream equations rather than concrete trace-time
    # constants.  A node cannot know such values, so pool lanes (which
    # ship only mapped leaves) must refuse them loudly; concrete baked
    # constants are fine — the node's deployed copy of the same
    # function carries them.
    n_varying_consts: int = 0

    @classmethod
    def from_eqn(
        cls, eqn: Any, baked_vars: frozenset = frozenset()
    ) -> "MapSpec":
        from jax.extend.core import Literal as _Literal

        jaxpr = eqn.params["jaxpr"]
        n_consts = eqn.params["n_consts"]
        varying = sum(
            1
            for v in eqn.invars[:n_consts]
            if not isinstance(v, _Literal) and v not in baked_vars
        )
        return cls(
            jaxpr=jaxpr,
            n_consts=n_consts,
            n_shards=eqn.params["n_shards"],
            x_avals=tuple(v.aval for v in eqn.invars[n_consts:]),
            out_avals=tuple(v.aval for v in jaxpr.outvars),
            n_varying_consts=varying,
        )

    @property
    def grad_contract(self) -> bool:
        """Whether this call fits the logp+grad wire contract: exactly
        one scalar inexact output per shard."""
        return (
            len(self.out_avals) == 1
            and tuple(self.out_avals[0].shape) == ()
            and jnp.issubdtype(self.out_avals[0].dtype, jnp.inexact)
        )

    def sliced(self, lo: int, hi: int) -> "MapSpec":
        return dataclasses.replace(
            self,
            n_shards=hi - lo,
            x_avals=tuple(
                jax.ShapeDtypeStruct(
                    (hi - lo,) + tuple(av.shape)[1:], av.dtype
                )
                for av in self.x_avals
            ),
        )


# An executor takes (consts, xs) value tuples and returns the stacked
# outputs; a group executor takes one (consts, xs) pair per member.
MapExecutor = Callable[[Tuple[Any, ...], Tuple[Any, ...]], List[Any]]


class Placement:
    """Where/how ``fed_map`` shards execute.  Subclasses implement
    :meth:`map_executor`; :meth:`group_executor` fuses a group of
    independent calls when the lane can (pool windows)."""

    def map_executor(self, spec: MapSpec) -> MapExecutor:
        raise NotImplementedError

    def fusion_key(self) -> tuple:
        """Equivalence key for cross-potential fusion
        (``bridge.core.fused_jax_callable``): two placements with the
        same key lower identically, so members built with distinct but
        equivalent placement OBJECTS still compose into one program."""
        return ("placement", id(self))

    def group_executor(self, specs: Sequence[MapSpec]) -> Callable:
        members = [self.map_executor(s) for s in specs]

        def run(args: Sequence[Tuple[tuple, tuple]]) -> List[List[Any]]:
            return [ex(c, x) for ex, (c, x) in zip(members, args)]

        return run

    # Convenience single-shot lowering (wrappers, tests): build an
    # executor and run it once.
    def lower_map(self, spec: MapSpec, consts: Any, xs: Any) -> List[Any]:
        return self.map_executor(spec)(tuple(consts), tuple(xs))


class MeshPlacement(Placement):
    """Shards along a named mesh axis: the current shard_map/psum
    lowering (``parallel/sharded.py``) behind the primitive IR.

    ``n_shards`` may exceed the axis size (each device vmaps its local
    block) but must divide evenly.  Closure constants are replicated
    (``P()``) and marked varying before the per-shard program runs, so
    a ``jax.grad`` inside the body cannot hit the implicit-pvary psum
    trap (CLAUDE.md design invariants).  The shard_map program is built
    once per executor and jitted, so repeat evaluations dispatch from
    cache.
    """

    def __init__(self, mesh: Any, axis: str = SHARDS_AXIS) -> None:
        if axis not in mesh.axis_names:
            raise ValueError(
                f"mesh has no axis {axis!r}: {mesh.axis_names}"
            )
        self.mesh = mesh
        self.axis = axis

    def fusion_key(self) -> tuple:
        return ("mesh", id(self.mesh), self.axis)

    def map_executor(self, spec: MapSpec) -> MapExecutor:
        axis, mesh = self.axis, self.mesh
        axis_size = mesh.shape[axis]
        if spec.n_shards % axis_size != 0:
            raise ValueError(
                f"n_shards={spec.n_shards} not divisible by mesh axis "
                f"{axis!r} of size {axis_size}"
            )
        fun = _per_shard_fun(spec.jaxpr)

        def local(consts: Any, local_xs: Any) -> Any:
            consts = mark_varying(consts, axis)
            return jax.vmap(lambda *s: tuple(fun(*consts, *s)))(*local_xs)

        sm = shard_map(
            local,
            mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=P(axis),
        )
        jitted = jax.jit(lambda consts, xs: tuple(sm(consts, xs)))
        return lambda consts, xs: list(jitted(tuple(consts), tuple(xs)))


class PoolPlacement(Placement):
    """Shards as requests over a replica pool (or any transport client
    with ``evaluate_many(requests, window=)`` — ``PooledArraysClient``,
    the gRPC/TCP clients, or their typed adapters).

    Differentiation uses the reference's logp+grad contract: for a
    ``fed_map`` whose per-shard program returns one scalar, the node
    replies ``[logp, *grads]`` (one grad per mapped leaf — deploy with
    :func:`make_node_compute`), and a ``jax.custom_vjp`` applies the
    forward-supplied gradients.  Non-scalar maps execute forward-only
    (``grads=False`` node deployments); differentiating through one
    raises like any ``pure_callback``.

    A group of independent ``fed_map`` calls lowers to ONE pipelined
    window: requests from every call ride a single ``evaluate_many``
    (span ``fed.window`` / flightrec ``fed.fused_window`` carry the
    evidence).  All calls in a fused window hit the same client, so the
    deployed node compute must serve every member's request shape —
    the reference's one-service-fn-per-node topology.
    """

    def __init__(
        self,
        client: Any,
        *,
        window: int = 8,
        logp_dtype: Any = None,
        reduce: bool = False,
        tag: str = "pool",
    ) -> None:
        """``reduce=True`` opts eligible ``fed_sum(fed_map(...))``
        pairs into the REDUCED window lowering (ISSUE 13): the whole
        window rides one reduce-scatter call
        (``client.evaluate_reduced`` — a pinned tcp/shm client, a
        :class:`~...routing.PooledArraysClient`, or a pool of mid-tier
        aggregator nodes for O(log N) tree aggregation), so reply
        bytes scale with pool width instead of shard count.
        Eligibility is gated at lowering time (lowering.py
        ``_plan_reduce``): the summed ``fed_map`` must fit the
        logp+grad contract and every inexact mapped operand must be
        broadcast-derived or a trace-time-baked constant — gradients
        w.r.t. per-shard PROGRAM INPUTS cannot survive a sum, so such
        programs fall back to the per-shard window silently-correctly
        rather than silently-wrongly.

        ``tag`` labels this placement's spans/flight events (the
        ``lane`` attribute of ``fed.window`` / ``fed.reduce_window``)
        so concurrent consumers — the SVI lanes tag theirs ``"svi"``
        (ISSUE 15) — stay attributable on the PR-11 telemetry plane."""
        self.client = client
        self.window = int(window)
        self.logp_dtype = logp_dtype
        self.reduce = bool(reduce)
        self.tag = str(tag)

    def fusion_key(self) -> tuple:
        return (
            "pool", id(self.client), self.window, self.logp_dtype,
            self.reduce, self.tag,
        )

    # -- host side ---------------------------------------------------------

    def _run_window(
        self, metas: Sequence[Tuple[int, int]], flat_np: Sequence[Any]
    ) -> List[list]:
        """One fused evaluate_many over every call's shards.  Returns
        the raw reply list per request, sliced per call."""
        requests: list = []
        slices = []
        i = 0
        for n_shards, arity in metas:
            xs = [np.asarray(x) for x in flat_np[i : i + arity]]
            i += arity
            lo = len(requests)
            for s in range(n_shards):
                requests.append(tuple(x[s] for x in xs))
            slices.append((lo, len(requests)))
        with _spans.span(
            "fed.window",
            lane=self.tag,
            calls=len(metas),
            requests=len(requests),
        ):
            _flightrec.record(
                "fed.fused_window",
                lane=self.tag,
                calls=len(metas),
                requests=len(requests),
                window=self.window,
            )
            replies = self.client.evaluate_many(
                requests, window=self.window
            )
        return [replies[lo:hi] for lo, hi in slices]

    # -- executors ---------------------------------------------------------

    def map_executor(self, spec: MapSpec) -> MapExecutor:
        group = self.group_executor([spec])

        def run(consts: Any, xs: Any) -> List[Any]:
            return group([(consts, xs)])[0]

        return run

    def group_executor(self, specs: Sequence[MapSpec]) -> Callable:
        specs = list(specs)
        for s in specs:
            if s.n_varying_consts:
                # Computing anyway would be SILENTLY wrong: the node
                # would use whatever it baked at deploy time and the
                # gradient of the dropped operand would be zero.
                raise ValueError(
                    f"a pool-placed fed_map closes over "
                    f"{s.n_varying_consts} driver-varying value(s); "
                    "pool placements ship only MAPPED operands, so "
                    "route driver state through fed_broadcast (making "
                    "it a mapped operand) instead of closure capture"
                )
        grad_idx = [i for i, s in enumerate(specs) if s.grad_contract]
        fwd_idx = [i for i, s in enumerate(specs) if not s.grad_contract]
        grad_exec = (
            self._grad_window_executor([specs[i] for i in grad_idx])
            if grad_idx
            else None
        )
        fwd_exec = (
            self._forward_group_executor([specs[i] for i in fwd_idx])
            if fwd_idx
            else None
        )

        def run(args: Sequence[Tuple[tuple, tuple]]) -> List[List[Any]]:
            results: dict = {}
            if grad_exec is not None:
                outs = grad_exec([args[i][1] for i in grad_idx])
                for i, o in zip(grad_idx, outs):
                    results[i] = o
            if fwd_exec is not None:
                outs = fwd_exec([args[i][1] for i in fwd_idx])
                for i, o in zip(fwd_idx, outs):
                    results[i] = o
            return [results[i] for i in range(len(specs))]

        return run

    def _grad_window_executor(self, specs: Sequence[MapSpec]) -> Callable:
        """Fused differentiable window, built ONCE: primal outputs are
        each call's stacked per-shard logps; the VJP applies the
        node-supplied per-shard gradients (mapped cotangent =
        ``g_s · grad_s``; an unmapped/broadcast operand's cotangent
        then falls out of the ``fed_broadcast`` transpose upstream)."""
        metas = [(s.n_shards, len(s.x_avals)) for s in specs]
        # Per MEMBER dtype: fused members need not share one (a bf16
        # and an f32 logp can ride the same window).
        logp_dts = [
            self.logp_dtype or s.out_avals[0].dtype for s in specs
        ]
        arity = [len(s.x_avals) for s in specs]

        logp_specs = tuple(
            jax.ShapeDtypeStruct((s.n_shards,), dt)
            for s, dt in zip(specs, logp_dts)
        )
        x_specs = [av for s in specs for av in s.x_avals]
        grad_specs = tuple(
            jax.ShapeDtypeStruct(tuple(av.shape), _grad_dtype(av.dtype))
            for av in x_specs
        )

        def host_logps(*arrays: Any) -> tuple:
            per_call = self._run_window(metas, arrays)
            return tuple(
                np.asarray([r[0] for r in replies], dtype=dt)
                for replies, dt in zip(per_call, logp_dts)
            )

        def host_logps_grads(*arrays: Any) -> tuple:
            per_call = self._run_window(metas, arrays)
            out = [
                np.asarray([r[0] for r in replies], dt)
                for replies, dt in zip(per_call, logp_dts)
            ]
            k = 0
            for (n_shards, n_in), replies in zip(metas, per_call):
                for j in range(n_in):
                    out.append(
                        np.stack(
                            [np.asarray(r[1 + j]) for r in replies]
                        ).astype(grad_specs[k].dtype)
                    )
                    k += 1
            return tuple(out)

        n_calls = len(specs)

        @jax.custom_vjp
        def window_call(*flat: Any) -> tuple:
            return jax.pure_callback(
                host_logps, logp_specs, *flat, vmap_method="sequential"
            )

        def fwd(*flat: Any) -> Tuple[tuple, tuple]:
            outs = jax.pure_callback(
                host_logps_grads,
                logp_specs + grad_specs,
                *flat,
                vmap_method="sequential",
            )
            return tuple(outs[:n_calls]), tuple(outs[n_calls:])

        def bwd(residual_grads: Any, cts: Any) -> tuple:
            flat_ct = []
            k = 0
            for ci in range(n_calls):
                g = cts[ci]  # (n_shards,) cotangent of the stacked logps
                for _ in range(arity[ci]):
                    grad = residual_grads[k]
                    av = x_specs[k]
                    k += 1
                    if not jnp.issubdtype(av.dtype, jnp.inexact):
                        flat_ct.append(
                            np.zeros(tuple(av.shape), jax.dtypes.float0)
                        )
                        continue
                    gb = g.reshape((-1,) + (1,) * (grad.ndim - 1))
                    flat_ct.append((gb * grad).astype(av.dtype))
            return tuple(flat_ct)

        window_call.defvjp(fwd, bwd)

        def run(xs_per_call: Sequence[tuple]) -> List[List[Any]]:
            flat = [x for xs in xs_per_call for x in xs]
            if not any(map(_is_tracer, flat)):
                # No ambient trace: skip the callback/custom-vjp
                # dispatch machinery entirely (measured ~0.5 ms/eval —
                # the bulk of the IR's fixed cost, bench_suite config
                # 14) and run the host window directly.  Transformed
                # calls (grad/jit/vmap) carry tracers and take the
                # callback path below.
                logps = host_logps(*flat)
            else:
                logps = window_call(*flat)
            return [[lp] for lp in logps]

        return run

    def reduced_sum_executor(self, spec: MapSpec) -> Callable:
        """One ``fed_sum(fed_map)`` pair as a REDUCED window (built
        once; lowering.py pairs the equations).

        Forward: the shard requests ride ONE
        ``client.evaluate_reduced`` — the node (or aggregator tree)
        sums the per-shard ``[logp, *grads]`` replies and returns
        ``[logp_sum, flat_grad_sum]``; the primal out is the summed
        scalar, so the ``fed_sum`` equation is absorbed.

        Backward: the cotangent of the summed logp is one scalar
        ``g``; the summed per-operand gradient is exactly
        ``Σ_s grad_s``, so the stacked operand's cotangent is ``g ·
        Σ_s grad_s`` placed at shard slot 0 with zeros elsewhere — the
        downstream ``fed_broadcast`` transpose SUMS over shards, which
        is why eligibility requires broadcast-derived (or baked-const)
        inexact operands: only a sum-transposed consumer makes the
        slot-0 placement exact."""
        n_shards = spec.n_shards
        x_avals = list(spec.x_avals)
        shard_shapes = [tuple(av.shape)[1:] for av in x_avals]
        shard_sizes = [
            int(np.prod(s, dtype=np.int64)) if s else 1
            for s in shard_shapes
        ]
        total = int(sum(shard_sizes))
        logp_dt = self.logp_dtype or spec.out_avals[0].dtype
        grad_dts = [_grad_dtype(av.dtype) for av in x_avals]
        client, window = self.client, self.window

        logp_spec = jax.ShapeDtypeStruct((), logp_dt)
        grad_specs = tuple(
            jax.ShapeDtypeStruct(shape, dt)
            for shape, dt in zip(shard_shapes, grad_dts)
        )

        def host_reduced(*arrays: Any) -> Tuple[Any, Any]:
            requests = [
                tuple(np.asarray(a)[s] for a in arrays)
                for s in range(n_shards)
            ]
            with _spans.span(
                "fed.reduce_window", lane=self.tag, requests=n_shards
            ):
                _flightrec.record(
                    "fed.reduce_window",
                    lane=self.tag,
                    requests=n_shards,
                    total=total,
                    window=window,
                )
                head, flat = client.evaluate_reduced(
                    requests, window=window, total=total
                )
            return np.asarray(head), np.asarray(flat)

        def host_logp(*arrays: Any) -> Any:
            head, _flat = host_reduced(*arrays)
            return np.asarray(head, logp_dt)

        def host_logp_grads(*arrays: Any) -> tuple:
            head, flat = host_reduced(*arrays)
            out = [np.asarray(head, logp_dt)]
            lo = 0
            for shape, size, dt in zip(
                shard_shapes, shard_sizes, grad_dts
            ):
                out.append(
                    np.asarray(flat[lo : lo + size], dt).reshape(shape)
                )
                lo += size
            return tuple(out)

        @jax.custom_vjp
        def reduced_call(*flat: Any) -> Any:
            return jax.pure_callback(
                host_logp, logp_spec, *flat, vmap_method="sequential"
            )

        def fwd(*flat: Any) -> Tuple[Any, tuple]:
            outs = jax.pure_callback(
                host_logp_grads,
                (logp_spec,) + grad_specs,
                *flat,
                vmap_method="sequential",
            )
            return outs[0], tuple(outs[1:])

        def bwd(residual_grads: Any, g: Any) -> tuple:
            cts = []
            for k, av in enumerate(x_avals):
                if not jnp.issubdtype(av.dtype, jnp.inexact):
                    cts.append(
                        np.zeros(tuple(av.shape), jax.dtypes.float0)
                    )
                    continue
                # Slot-0 placement: the consumer's transpose SUMS over
                # the shard axis (eligibility gate), so one slot
                # carrying g·Σgrad is exact.
                stacked = jnp.zeros(tuple(av.shape), av.dtype)
                cts.append(
                    stacked.at[0].set(
                        (g * residual_grads[k]).astype(av.dtype)
                    )
                )
            return tuple(cts)

        reduced_call.defvjp(fwd, bwd)

        def run(consts: Any, xs: Any) -> List[Any]:
            # Unmapped operands are dropped, exactly like the per-shard
            # pool window: n_varying_consts == 0 was checked at pairing
            # time, so every const here is a trace-time-baked constant
            # the node's deployed copy of the function already carries.
            del consts
            flat = list(xs)
            if not any(map(_is_tracer, flat)):
                return [host_logp(*flat)]  # eager fast path
            return [reduced_call(*flat)]

        return run

    def _forward_group_executor(self, specs: Sequence[MapSpec]) -> Callable:
        """Fused forward-only window (no grad contract): every member's
        shards ride one ``evaluate_many``; replies slice back per call.
        Differentiating through it raises like any ``pure_callback``."""
        metas = [(s.n_shards, len(s.x_avals)) for s in specs]
        out_specs_per_call = [
            tuple(
                jax.ShapeDtypeStruct(
                    (s.n_shards,) + tuple(av.shape), av.dtype
                )
                for av in s.out_avals
            )
            for s in specs
        ]
        flat_specs = tuple(
            sp for call in out_specs_per_call for sp in call
        )

        def host(*arrays: Any) -> tuple:
            per_call = self._run_window(metas, arrays)
            out = []
            for replies, call_specs in zip(per_call, out_specs_per_call):
                for k, sp in enumerate(call_specs):
                    out.append(
                        np.stack(
                            [np.asarray(r[k]) for r in replies]
                        ).astype(sp.dtype)
                    )
            return tuple(out)

        def run(xs_per_call: Sequence[tuple]) -> List[List[Any]]:
            flat = [x for xs in xs_per_call for x in xs]
            if not any(map(_is_tracer, flat)):
                outs = host(*flat)  # eager fast path (see grad twin)
            else:
                outs = jax.pure_callback(
                    host, flat_specs, *flat, vmap_method="sequential"
                )
            result, k = [], 0
            for call_specs in out_specs_per_call:
                result.append(list(outs[k : k + len(call_specs)]))
                k += len(call_specs)
            return result

        return run


def _grad_dtype(dt: Any) -> Any:
    return dt if jnp.issubdtype(dt, jnp.inexact) else jnp.float32


class MixedPlacement(Placement):
    """Shard range split across two lanes: the first ``n - pool_shards``
    shards execute on ``mesh``, the trailing ``pool_shards`` on
    ``pool``; stacked outputs concatenate in shard order, and gradients
    flow through both lanes (slice/concat transposes are exact)."""

    def __init__(
        self,
        mesh: MeshPlacement,
        pool: PoolPlacement,
        *,
        pool_shards: int,
    ) -> None:
        self.mesh = mesh
        self.pool = pool
        self.pool_shards = int(pool_shards)
        if self.pool_shards < 1:
            raise ValueError("pool_shards must be >= 1")

    def fusion_key(self) -> tuple:
        return (
            "mixed",
            self.mesh.fusion_key(),
            self.pool.fusion_key(),
            self.pool_shards,
        )

    def _cut(self, spec: MapSpec) -> int:
        k = self.pool_shards
        if not (0 < k < spec.n_shards):
            raise ValueError(
                f"pool_shards={k} must be in 1..{spec.n_shards - 1} "
                f"(got a {spec.n_shards}-shard fed_map)"
            )
        return spec.n_shards - k

    def map_executor(self, spec: MapSpec) -> MapExecutor:
        group = self.group_executor([spec])

        def run(consts: Any, xs: Any) -> List[Any]:
            return group([(consts, xs)])[0]

        return run

    def group_executor(self, specs: Sequence[MapSpec]) -> Callable:
        specs = list(specs)
        cuts = [self._cut(s) for s in specs]
        mesh_execs = [
            self.mesh.map_executor(s.sliced(0, cut))
            for s, cut in zip(specs, cuts)
        ]
        pool_group = self.pool.group_executor(
            [s.sliced(cut, s.n_shards) for s, cut in zip(specs, cuts)]
        )

        def run(args: Sequence[Tuple[tuple, tuple]]) -> List[List[Any]]:
            mesh_outs = [
                ex(c, tuple(x[:cut] for x in xs))
                for ex, cut, (c, xs) in zip(mesh_execs, cuts, args)
            ]
            pool_outs = pool_group(
                [
                    (c, tuple(x[cut:] for x in xs))
                    for cut, (c, xs) in zip(cuts, args)
                ]
            )
            return [
                [
                    jnp.concatenate([m, p], axis=0)
                    for m, p in zip(m_out, p_out)
                ]
                for m_out, p_out in zip(mesh_outs, pool_outs)
            ]

        return run


def make_node_compute(
    per_shard_fn: Callable[..., Any], *, grads: bool = True
) -> Callable[..., list]:
    """Node-side compute for a pool-placed ``fed_map``.

    ``per_shard_fn(*leaves)`` takes one shard's mapped leaves (the
    request arrays, ``tree_flatten`` order — broadcast driver state
    first if the program broadcasts it before the data).  With
    ``grads=True`` (the differentiable logp contract) it must return a
    scalar, and the node replies ``[logp, *grads]`` with one gradient
    per request array (zeros for integer leaves).  With ``grads=False``
    the reply is the flat output list.

    Built from the SAME Python callable the driver's ``fed_map`` maps,
    so the two sides cannot drift apart.
    """

    if grads:

        def compute(*arrays: Any) -> list:
            args = [jnp.asarray(a) for a in arrays]
            diff_idx = [
                i
                for i, a in enumerate(args)
                if jnp.issubdtype(a.dtype, jnp.inexact)
            ]

            def f(diff_args: Any) -> Any:
                full = list(args)
                for i, v in zip(diff_idx, diff_args):
                    full[i] = v
                return per_shard_fn(*full)

            val, dgrads = jax.value_and_grad(f)(
                [args[i] for i in diff_idx]
            )
            by_idx = dict(zip(diff_idx, dgrads))
            out = [np.asarray(val)]
            for i, a in enumerate(args):
                g = by_idx.get(i)
                out.append(
                    np.asarray(g)
                    if g is not None
                    else np.zeros(np.shape(a), np.float32)
                )
            return out

        return compute

    def compute_fwd(*arrays: Any) -> list:
        out = per_shard_fn(*[jnp.asarray(a) for a in arrays])
        import jax.tree_util as tu

        return [np.asarray(o) for o in tu.tree_leaves(out)]

    return compute_fwd
