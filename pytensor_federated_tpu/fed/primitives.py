"""Differentiable federated MapReduce as first-class JAX primitives.

DrJAX-style (PAPERS.md: "DrJAX: Scalable and Differentiable MapReduce
Primitives in JAX"): the federated algebra — *map* a function over
every shard's placed values, *sum* shard-placed values back to the
driver, *broadcast* driver state out to the shards — is expressed as
real ``jax.extend.core.Primitive``\\s with their own abstract-eval,
JVP, transpose, and batching rules, so ONE traced model differentiates
end to end and every placement (mesh devices, RPC node pools, a mix)
lowers the same IR instead of owning a parallel codepath.

The autodiff identities (the reason these are primitives, not helper
functions):

- ``fed_broadcast`` is linear; its transpose is ``fed_sum`` — the
  gradient of replicated driver state is the SUM of the shard
  cotangents (the psum that ``parallel.mesh.mark_varying`` exists to
  keep explicit).
- ``fed_sum`` is linear; its transpose is ``fed_broadcast``.
- ``fed_map`` transposes to a ``fed_map`` of the per-shard transposed
  function; cotangents of *unmapped* operands (closure constants —
  replicated values) are additionally ``fed_sum``-reduced, which is
  exactly the implicit-pvary-transposes-to-psum rule from CLAUDE.md,
  now a structural property of the IR instead of a trap.

Calling convention of ``fed_map_p``: the first ``n_consts`` operands
are UNMAPPED (closure constants lifted by tracing — identical on every
shard), the rest are MAPPED (leading ``n_shards`` axis).  ``jaxpr`` is
the per-shard program over ``(consts..., shard_leaves...)``.

With no placement active, the primitives carry dense semantics
(``vmap`` / ``sum(axis=0)`` / ``broadcast_to``) through impl, MLIR
lowering, and batching — so a ``fed`` program jits, vmaps, and grads
like plain JAX.  Placement-aware execution is :func:`..fed.program`
(lowering.py), which interprets the SAME jaxpr.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util
from jax.extend import core as jex_core
from jax.interpreters import ad, batching, mlir
from jax.interpreters import partial_eval as pe

__all__ = [
    "fed_broadcast",
    "fed_broadcast_p",
    "fed_map",
    "fed_map_p",
    "fed_mean",
    "fed_sum",
    "fed_sum_p",
]

# ShapedArray/Tracer moved around across jax versions; resolve once.
try:  # pragma: no cover - version layout
    from jax.core import ShapedArray, Tracer as _Tracer
except ImportError:  # pragma: no cover
    from jax._src.core import ShapedArray, Tracer as _Tracer


def is_tracer(x: Any) -> bool:
    """Whether ``x`` belongs to an ambient trace (vs a concrete value) —
    the eager-fast-path / cache-safety discriminator shared by the
    placement executors and ``fed.program``."""
    return isinstance(x, _Tracer)


def _leading_dim(leaves: Sequence[Any]) -> int:
    dims = {jnp.shape(l)[0] for l in leaves}
    if len(dims) != 1:
        raise ValueError(
            f"all mapped leaves must share a leading shard axis, got {dims}"
        )
    return int(dims.pop())


def _aval(x: Any) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))


def _shard_aval(x: Any) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(jnp.shape(x)[1:], jnp.result_type(x))


def _closed(jaxpr: Any) -> jex_core.ClosedJaxpr:
    return jex_core.ClosedJaxpr(jaxpr, ())


def _per_shard_fun(jaxpr: Any) -> Callable:
    """The per-shard function ``(consts..., shard_leaves...) -> outs``."""
    return jex_core.jaxpr_as_fun(_closed(jaxpr))


def _trace_flat(fn: Callable, avals: Sequence[Any]) -> Tuple[Any, List[Any]]:
    """``make_jaxpr`` + closure conversion: constants the trace lifts
    (including tracers from an enclosing trace) become leading invars,
    returned separately so the caller binds them as operands."""
    closed = jax.make_jaxpr(fn)(*avals)
    jaxpr = pe.convert_constvars_jaxpr(closed.jaxpr)
    return jaxpr, list(closed.consts)


# ---------------------------------------------------------------------------
# fed_map
# ---------------------------------------------------------------------------

fed_map_p = jex_core.Primitive("fed_map")
fed_map_p.multiple_results = True


def fed_map(fn: Callable[[Any], Any], data: Any) -> Any:
    """Apply ``fn`` to every shard of ``data``; outputs stacked along a
    leading shards axis.

    ``data`` is a pytree whose leaves carry a leading ``n_shards``
    axis; ``fn(shard_pytree) -> pytree``.  Values ``fn`` closes over
    become UNMAPPED operands of the primitive (replicated — their
    cotangents are ``fed_sum``-reduced by the transpose rule).  For
    placements that ship work over the wire (``PoolPlacement``), pass
    everything varying as *mapped* data via :func:`fed_broadcast`
    instead of closing over it — closure constants never leave the
    driver.
    """
    flat, in_tree = tree_util.tree_flatten(data)
    if not flat:
        raise ValueError("fed_map data pytree has no leaves")
    flat = [jnp.asarray(l) for l in flat]
    n_shards = _leading_dim(flat)
    out_store = []

    def per_shard(*shard_leaves: Any) -> List[Any]:
        shard = tree_util.tree_unflatten(in_tree, shard_leaves)
        out_flat, out_tree = tree_util.tree_flatten(fn(shard))
        out_store.append(out_tree)
        return out_flat

    jaxpr, consts = _trace_flat(per_shard, [_shard_aval(l) for l in flat])
    outs = fed_map_p.bind(
        *consts,
        *flat,
        jaxpr=jaxpr,
        n_consts=len(consts),
        n_shards=n_shards,
    )
    return tree_util.tree_unflatten(out_store[0], outs)


def _fed_map_dense(args: Sequence[Any], *, jaxpr: Any, n_consts: int, n_shards: int) -> List[Any]:
    fun = _per_shard_fun(jaxpr)
    in_axes = (None,) * n_consts + (0,) * (len(args) - n_consts)
    outs = jax.vmap(lambda *a: tuple(fun(*a)), in_axes=in_axes)(*args)
    return list(outs)


fed_map_p.def_impl(lambda *args, **params: _fed_map_dense(args, **params))
mlir.register_lowering(
    fed_map_p,
    mlir.lower_fun(
        lambda *args, **params: _fed_map_dense(args, **params),
        multiple_results=True,
    ),
)


@fed_map_p.def_abstract_eval
def _fed_map_abstract(*in_avals: Any, jaxpr: Any, n_consts: int, n_shards: int) -> List[Any]:
    return [
        ShapedArray((n_shards,) + tuple(v.aval.shape), v.aval.dtype)
        for v in jaxpr.outvars
    ]


def _inexact(x: Any) -> bool:
    return jnp.issubdtype(jnp.result_type(x), jnp.inexact)


def _zero_tangent_like(x: Any) -> Any:
    # Integer/bool primals take float0 tangents (the jax.jvp contract).
    return np.zeros(jnp.shape(x), jax.dtypes.float0)


def _fed_map_jvp(primals: Sequence[Any], tangents: Sequence[Any], *, jaxpr: Any, n_consts: int, n_shards: int) -> Tuple[List[Any], List[Any]]:
    """Primal bind plus a SEPARATE tangent ``fed_map`` bind.

    Two binds (rather than one jvp-of-fn bind returning both) keep
    linearize clean: the primal equation has all-known inputs, and the
    tangent equation is linear in the tangent operands, so the
    transpose rule below sees exactly the mixed known/undefined operand
    pattern it expects.  The tangent program re-derives the primal
    internally (``jax.jvp``); XLA CSE collapses the duplicate work when
    both binds land in one compiled program.
    """
    fun = _per_shard_fun(jaxpr)
    n_in = len(primals)
    # Only inexact operands with non-symbolic-zero tangents become
    # tangent operands; int/bool operands take float0 tangents which
    # cannot ride as primitive operands and are rebuilt inside.
    lin_idx = [
        i
        for i in range(n_in)
        if _inexact(primals[i]) and type(tangents[i]) is not ad.Zero
    ]

    primal_out = fed_map_p.bind(
        *primals, jaxpr=jaxpr, n_consts=n_consts, n_shards=n_shards
    )

    out_inexact = [_inexact_var(v) for v in jaxpr.outvars]
    if not lin_idx or not any(out_inexact):
        return primal_out, [
            _symbolic_zero_for(v, n_shards) for v in jaxpr.outvars
        ]

    lin_consts = [i for i in lin_idx if i < n_consts]
    lin_mapped = [i for i in lin_idx if i >= n_consts]
    # Argument order mirrors the bind below EXACTLY (unmapped operands
    # first): primal consts, tangent consts, mapped primals, mapped
    # tangents.
    def tangent_fn(*a: Any) -> List[Any]:
        pc = a[:n_consts]
        tc = dict(zip(lin_consts, a[n_consts : n_consts + len(lin_consts)]))
        off = n_consts + len(lin_consts)
        px = a[off : off + (n_in - n_consts)]
        tx = dict(zip(lin_mapped, a[off + (n_in - n_consts) :]))
        p = tuple(pc) + tuple(px)
        full_t = []
        for i in range(n_in):
            if i in tc:
                full_t.append(tc[i])
            elif i in tx:
                full_t.append(tx[i])
            elif _inexact(p[i]):
                full_t.append(
                    jnp.zeros(jnp.shape(p[i]), jnp.result_type(p[i]))
                )
            else:
                full_t.append(_zero_tangent_like(p[i]))
        _, t_out = jax.jvp(lambda *x: tuple(fun(*x)), p, tuple(full_t))
        return [o for o, ok in zip(t_out, out_inexact) if ok]

    avals = (
        [_aval(x) for x in primals[:n_consts]]
        + [_aval(tangents[i]) for i in lin_consts]
        + [_shard_aval(x) for x in primals[n_consts:]]
        + [_shard_aval(tangents[i]) for i in lin_mapped]
    )
    t_jaxpr, t_consts = _trace_flat(tangent_fn, avals)
    t_outs = fed_map_p.bind(
        *t_consts,
        *primals[:n_consts],
        *[tangents[i] for i in lin_consts],
        *primals[n_consts:],
        *[tangents[i] for i in lin_mapped],
        jaxpr=t_jaxpr,
        n_consts=len(t_consts) + n_consts + len(lin_consts),
        n_shards=n_shards,
    )
    t_iter = iter(t_outs)
    tangents_out = [
        next(t_iter) if ok else _symbolic_zero_for(v, n_shards)
        for v, ok in zip(jaxpr.outvars, out_inexact)
    ]
    return primal_out, tangents_out


def _inexact_var(v: Any) -> bool:
    return jnp.issubdtype(v.aval.dtype, jnp.inexact)


def _symbolic_zero_for(v: Any, n_shards: int) -> Any:
    aval = ShapedArray((n_shards,) + tuple(v.aval.shape), v.aval.dtype)
    try:
        return ad.Zero(aval.to_tangent_aval())
    except AttributeError:  # pragma: no cover - older jax spelling
        return ad.Zero(aval.at_least_vspace())


ad.primitive_jvps[fed_map_p] = _fed_map_jvp


def _fed_map_transpose(cts: Sequence[Any], *args: Any, jaxpr: Any, n_consts: int, n_shards: int) -> List[Any]:
    fun = _per_shard_fun(jaxpr)
    n_in = len(args)
    lin_idx = [i for i in range(n_in) if ad.is_undefined_primal(args[i])]
    nl_un = [i for i in range(n_consts) if i not in lin_idx]
    nl_mapped = [i for i in range(n_consts, n_in) if i not in lin_idx]
    used = [i for i, c in enumerate(cts) if type(c) is not ad.Zero]
    if not used:
        return [
            ad.Zero(args[i].aval) if i in lin_idx else None
            for i in range(n_in)
        ]

    def lin_shard_aval(i: int) -> jax.ShapeDtypeStruct:
        av = args[i].aval
        shape = tuple(av.shape) if i < n_consts else tuple(av.shape)[1:]
        return jax.ShapeDtypeStruct(shape, av.dtype)

    lin_avals = [lin_shard_aval(i) for i in lin_idx]

    def transposed_shard(*ops: Any) -> List[Any]:
        k1, k2 = len(nl_un), len(nl_mapped)
        vals = dict(zip(nl_un + nl_mapped, ops[: k1 + k2]))
        ct_shard = list(ops[k1 + k2 :])

        def lin(*lin_vals: Any) -> List[Any]:
            full = [None] * n_in
            for i, v in vals.items():
                full[i] = v
            for i, v in zip(lin_idx, lin_vals):
                full[i] = v
            outs = fun(*full)
            return [outs[i] for i in used]

        return jax.linear_transpose(lin, *lin_avals)(ct_shard)

    ct_vals = [cts[i] for i in used]
    avals = (
        [_aval(args[i]) for i in nl_un]
        + [_shard_aval(args[i]) for i in nl_mapped]
        + [_shard_aval(c) for c in ct_vals]
    )
    t_jaxpr, t_consts = _trace_flat(transposed_shard, avals)
    outs = fed_map_p.bind(
        *t_consts,
        *[args[i] for i in nl_un],
        *[args[i] for i in nl_mapped],
        *ct_vals,
        jaxpr=t_jaxpr,
        n_consts=len(t_consts) + len(nl_un),
        n_shards=n_shards,
    )
    result = [None] * n_in
    for k, i in enumerate(lin_idx):
        stacked = outs[k]
        result[i] = (
            fed_sum_p.bind(stacked) if i < n_consts else stacked
        )
    return result


ad.primitive_transposes[fed_map_p] = _fed_map_transpose


def _fed_map_batching(args: Sequence[Any], dims: Sequence[Any], *, jaxpr: Any, n_consts: int, n_shards: int) -> Tuple[List[Any], List[Any]]:
    fun = _per_shard_fun(jaxpr)
    new_args, inner_axes = [], []
    for i, (a, d) in enumerate(zip(args, dims)):
        if d is batching.not_mapped:
            new_args.append(a)
            inner_axes.append(None)
        elif i < n_consts:
            new_args.append(jnp.moveaxis(a, d, 0))
            inner_axes.append(0)
        else:
            # Mapped operand: shard axis must stay leading; batch rides
            # axis 1, so the per-shard view is batched at axis 0.
            new_args.append(jnp.moveaxis(a, d, 1))
            inner_axes.append(0)

    def batched_shard(*shard_args: Any) -> List[Any]:
        return tuple(
            jax.vmap(lambda *x: tuple(fun(*x)), in_axes=tuple(inner_axes))(
                *shard_args
            )
        )

    avals = [
        _aval(a) if i < n_consts else _shard_aval(a)
        for i, a in enumerate(new_args)
    ]
    b_jaxpr, b_consts = _trace_flat(batched_shard, avals)
    outs = fed_map_p.bind(
        *b_consts,
        *new_args[:n_consts],
        *new_args[n_consts:],
        jaxpr=b_jaxpr,
        n_consts=len(b_consts) + n_consts,
        n_shards=n_shards,
    )
    return outs, (1,) * len(outs)


batching.primitive_batchers[fed_map_p] = _fed_map_batching


# ---------------------------------------------------------------------------
# fed_sum
# ---------------------------------------------------------------------------

fed_sum_p = jex_core.Primitive("fed_sum")


def fed_sum(values: Any) -> Any:
    """Reduce shard-stacked values (leading shards axis) by summation —
    the driver's sum-of-potentials, transpose of :func:`fed_broadcast`."""
    return tree_util.tree_map(
        lambda l: fed_sum_p.bind(jnp.asarray(l)), values
    )


def _fed_sum_impl(x: Any) -> Any:
    return jnp.sum(x, axis=0)


fed_sum_p.def_impl(_fed_sum_impl)
mlir.register_lowering(
    fed_sum_p, mlir.lower_fun(_fed_sum_impl, multiple_results=False)
)


@fed_sum_p.def_abstract_eval
def _fed_sum_abstract(x: Any) -> Any:
    if not x.shape:
        raise ValueError("fed_sum operand must carry a leading shards axis")
    return ShapedArray(tuple(x.shape)[1:], x.dtype)


def _fed_sum_transpose(ct: Any, x: Any) -> List[Any]:
    if type(ct) is ad.Zero:
        return [ad.Zero(x.aval)]
    return [fed_broadcast_p.bind(ct, n_shards=int(x.aval.shape[0]))]


ad.deflinear2(fed_sum_p, _fed_sum_transpose)


def _fed_sum_batching(args: Sequence[Any], dims: Sequence[Any]) -> Tuple[Any, Any]:
    (x,), (d,) = args, dims
    out = fed_sum_p.bind(jnp.moveaxis(x, d, -1))
    return out, out.ndim - 1


batching.primitive_batchers[fed_sum_p] = _fed_sum_batching


# ---------------------------------------------------------------------------
# fed_broadcast
# ---------------------------------------------------------------------------

fed_broadcast_p = jex_core.Primitive("fed_broadcast")


def fed_broadcast(value: Any, n_shards: int) -> Any:
    """Replicate driver state to every shard (stacked along shards) —
    the placement move whose transpose is :func:`fed_sum`.  Pool
    placements ship ONLY mapped operands, so driver state a pool-placed
    ``fed_map`` needs must arrive through this, not through closure."""
    n = int(n_shards)
    if n < 1:
        raise ValueError(f"n_shards must be >= 1, got {n_shards}")
    return tree_util.tree_map(
        lambda l: fed_broadcast_p.bind(jnp.asarray(l), n_shards=n), value
    )


def _fed_broadcast_impl(x: Any, *, n_shards: int) -> Any:
    return jnp.broadcast_to(x, (n_shards,) + jnp.shape(x))


fed_broadcast_p.def_impl(_fed_broadcast_impl)
mlir.register_lowering(
    fed_broadcast_p,
    mlir.lower_fun(_fed_broadcast_impl, multiple_results=False),
)


@fed_broadcast_p.def_abstract_eval
def _fed_broadcast_abstract(x: Any, *, n_shards: int) -> Any:
    return ShapedArray((n_shards,) + tuple(x.shape), x.dtype)


def _fed_broadcast_transpose(ct: Any, x: Any, *, n_shards: int) -> List[Any]:
    if type(ct) is ad.Zero:
        return [ad.Zero(x.aval)]
    return [fed_sum_p.bind(ct)]


ad.deflinear2(fed_broadcast_p, _fed_broadcast_transpose)


def _fed_broadcast_batching(args: Sequence[Any], dims: Sequence[Any], *, n_shards: int) -> Tuple[Any, Any]:
    (x,), (d,) = args, dims
    out = fed_broadcast_p.bind(x, n_shards=n_shards)
    return out, d + 1


batching.primitive_batchers[fed_broadcast_p] = _fed_broadcast_batching


# ---------------------------------------------------------------------------
# fed_mean (composite)
# ---------------------------------------------------------------------------


def fed_mean(values: Any, weights: Optional[jax.Array] = None) -> Any:
    """(Weighted) mean across shards of shard-stacked values.

    ``weights`` must be a 1-D vector with EXACTLY one entry per shard:
    a wrong-length vector that happens to broadcast against trailing
    dimensions would silently weight the wrong axis, so the length is
    validated against the leading shard axis and raises ``ValueError``.
    """
    flat = tree_util.tree_leaves(values)
    if not flat:
        return values
    n = _leading_dim(flat)
    if weights is None:
        return tree_util.tree_map(
            lambda l: fed_sum_p.bind(jnp.asarray(l) / n), values
        )
    w = jnp.asarray(weights)
    if w.ndim != 1 or int(w.shape[0]) != n:
        raise ValueError(
            f"weights must be a length-{n} vector (one weight per "
            f"shard), got shape {tuple(w.shape)}"
        )
    w = w / jnp.sum(w)

    def wmean(l: Any) -> Any:
        l = jnp.asarray(l)
        wb = w.reshape((-1,) + (1,) * (l.ndim - 1))
        return fed_sum_p.bind(l * wb)

    return tree_util.tree_map(wmean, values)
