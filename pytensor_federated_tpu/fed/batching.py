"""Trace-time fusion pass: independent ``fed_map`` calls share a window.

The reference needs a global PyTensor graph rewrite
(``AsyncFusionOptimizer``, reference: op_async.py:216-234) to overlap
independent remote applies.  Here the model is ALREADY a jaxpr with
``fed_map`` equations in it, so the rewrite collapses to a planning
pass over equations: find groups of ``fed_map`` eqns with no
(transitive) data dependence between them, and hand each multi-member
group to the placement as ONE ``lower_map_group`` call — which the
pool lane turns into a single pipelined ``evaluate_many`` window
(placements.py).  The independence algorithm is the same one the
PyTensor rewriter uses (``bridge/grouping.group_independent`` — pure,
shared, tested without either framework).
"""

from __future__ import annotations

from typing import Any, Dict, List, Set

from jax.extend.core import Var

from ..bridge.grouping import group_independent
from .primitives import fed_map_p

__all__ = ["plan_windows"]


def plan_windows(jaxpr: Any) -> Dict[int, List[int]]:
    """Map each fused ``fed_map`` equation index to its group (a list
    of mutually independent eqn indices, topo order).  Only groups of
    two or more appear — singletons lower one call at a time.  Safety
    is inherited from ``group_independent``: dependence is a transitive
    closure over ALL equations, so members of one group can never reach
    each other through intermediate non-``fed_map`` equations."""
    eqns = list(jaxpr.eqns)
    producer: Dict[Var, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            producer[v] = i

    def parents(i: int) -> Set[int]:
        seen = set()
        for v in eqns[i].invars:
            if isinstance(v, Var) and v in producer:
                j = producer[v]
                if j not in seen:
                    seen.add(j)
                    yield j

    groups = group_independent(
        range(len(eqns)),
        parents=parents,
        is_candidate=lambda i: eqns[i].primitive is fed_map_p,
    )
    plan: Dict[int, List[int]] = {}
    for g in groups:
        if len(g) >= 2:
            for i in g:
                plan[i] = g
    return plan
