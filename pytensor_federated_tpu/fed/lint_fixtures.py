"""Pool-lane ``fed.program`` fixtures for the ``fed-placement`` lint.

The PR-6 contract (:mod:`.placements`): a pool-placed ``fed_map``
ships ONLY its mapped leaves — a closure that captures a
driver-varying value (a program input, or the output of an upstream
equation) would silently compute with whatever the node baked at
deploy time, so ``PoolPlacement.group_executor`` refuses it with a
``ValueError`` at runtime.  graftflow's ``fed-placement`` rule
(:mod:`..analysis.rules_fedflow`) moves that refusal to CI: it traces
every fixture registered here CPU-only, walks the jaxpr, and flags any
``fed_map`` equation whose closure captures a driver-varying operand —
with the operand's provenance chain in the finding.

Register a fixture for every fed program shape the repo ships on the
pool lane.  A fixture is the *placement-free* model (tracing needs no
transport); pool intent is what registration here asserts.  Keep the
example arguments tiny — the lint traces, it never executes shards.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence, Tuple

__all__ = ["LintFixture", "FIXTURES"]

FixtureProgram = Tuple[Callable[..., Any], Tuple[Any, ...]]


@dataclasses.dataclass(frozen=True)
class LintFixture:
    """One traceable pool-lane program: ``build()`` returns
    ``(fn, example_args)``; the lint calls ``jax.make_jaxpr(fn)(*args)``
    under the CPU backend."""

    name: str
    build: Callable[[], FixtureProgram]


def _canonical_round() -> FixtureProgram:
    """The FederatedLogpGrad model: broadcast -> map -> sum.  Params
    reach the shards through ``fed_broadcast`` (making them MAPPED
    operands), so the closure captures nothing driver-varying — the
    clean shape every pool deployment should follow."""
    import jax.numpy as jnp
    import numpy as np

    from .primitives import fed_broadcast, fed_map, fed_sum

    data = jnp.asarray(np.arange(12.0, dtype=np.float32).reshape(4, 3))

    def model(params: Any) -> Any:
        pb = fed_broadcast((params,), 4)
        lps = fed_map(
            lambda shard: jnp.sum(shard[0][0] * shard[1]), (pb, data)
        )
        return fed_sum(lps)

    return model, (jnp.ones((3,), jnp.float32),)


def _two_potential_window() -> FixtureProgram:
    """Two independent fed_maps (the fused-window shape,
    ``bridge.core.fused_jax_callable``): both members must stay free of
    driver-varying closure capture for the fused pool window to ship."""
    import jax.numpy as jnp
    import numpy as np

    from .primitives import fed_broadcast, fed_map, fed_sum

    data_a = jnp.asarray(np.ones((4, 3), np.float32))
    data_b = jnp.asarray(np.full((2, 5), 2.0, np.float32))

    def model(pa: Any, pb_: Any) -> Any:
        ba = fed_broadcast((pa,), 4)
        bb = fed_broadcast((pb_,), 2)
        la = fed_map(
            lambda shard: jnp.sum(shard[0][0] * shard[1]), (ba, data_a)
        )
        lb = fed_map(
            lambda shard: jnp.sum(shard[0][0][:5] + shard[1]), (bb, data_b)
        )
        return fed_sum(la) + fed_sum(lb)

    return model, (
        jnp.ones((3,), jnp.float32),
        jnp.ones((5,), jnp.float32),
    )


def _ppl_example() -> Any:
    """One tiny effectful model compiled placement-free: a global
    latent plus a plate-local latent over 4 shards — the exact
    program shape the ``ppl`` compiler (ISSUE 15) emits for pool
    lanes."""
    import jax.numpy as jnp
    import numpy as np

    from ..ppl import compile as ppl_compile
    from ..ppl import plate, sample, subsample
    from ..ppl.distributions import Normal

    data = jnp.asarray(np.arange(12.0, dtype=np.float32).reshape(4, 3))

    def model(x: Any) -> None:
        w = sample("w", Normal(0.0, 1.0))
        with plate("shards", 4) as sh:
            b = sample("b", Normal(0.0, 1.0))
            xs = subsample(x, sh)
            sample("obs", Normal(w + b[:, None], 1.0), obs=xs)

    return ppl_compile(model, (data,))


def _ppl_plate_round() -> FixtureProgram:
    """The ppl full-data lowering: ``prior + fed_sum(fed_map(per_shard,
    shard_ids))`` — parameters broadcast whole (mapped operands), the
    shard id rides as an integer data leaf, data bakes into the
    per-shard closure as a trace-time constant.  Nothing
    driver-varying may hide in the closure or the pool lane refuses
    the program the compiler emitted."""
    import jax.numpy as jnp

    from jax import tree_util

    compiled = _ppl_example()
    leaves = tree_util.tree_leaves(compiled.init_params())
    return compiled.fed_model, tuple(jnp.asarray(l) for l in leaves)


def _ppl_subsample_round() -> FixtureProgram:
    """The ppl minibatch/streaming lowering: the same round mapped
    over an index BATCH (a program input) with the unbiased
    ``size/batch`` scaling — the shape every streaming-SVI step ships
    to the pool through the gateway."""
    import jax.numpy as jnp

    from jax import tree_util

    compiled = _ppl_example()
    leaves = tree_util.tree_leaves(compiled.init_params())
    idx = jnp.asarray([0, 2], jnp.int32)
    return compiled.fed_batch_model(2), tuple(
        jnp.asarray(l) for l in leaves
    ) + (idx,)


def _zero_owner_update() -> FixtureProgram:
    """The sharded-optimizer OWNER round (ISSUE 16): the mean-field
    neg-ELBO an owner replica differentiates per versioned update —
    the minibatch lowering under MC draws, parameters arriving as
    request arrays (mu, log_sd), the RNG key and index batch as data
    leaves.  The update compute executes this node-LOCALLY, but the
    inner ``logp_indices`` round is the same pool-lane program shape
    as ``ppl-subsample-round``; registering the full estimator keeps
    the owner's closure honest too (a driver-varying capture here
    would bake stale state into every owner)."""
    import jax
    import jax.numpy as jnp

    from jax.flatten_util import ravel_pytree

    from ..ppl.svi import make_meanfield_neg_elbo

    compiled = _ppl_example()
    flat0, unravel = ravel_pytree(compiled.init_params())
    dim = int(flat0.shape[0])
    neg_elbo = make_meanfield_neg_elbo(compiled, unravel, dim, 2)

    def owner_round(
        mu: Any, log_sd: Any, key: Any, idx: Any
    ) -> Any:
        return neg_elbo((mu, log_sd), key, idx)

    key = jax.random.PRNGKey(0)
    idx = jnp.asarray([0, 2], jnp.int32)
    return owner_round, (
        jnp.zeros((dim,), flat0.dtype),
        jnp.full((dim,), -2.0, flat0.dtype),
        key,
        idx,
    )


def _linalg_block_row_reduce() -> FixtureProgram:
    """The blocked-linalg quadratic-form round (ISSUE 19): ``x^T A x``
    with ``A`` sharded by block-rows through ``canonical_round`` — the
    vector broadcasts (mapped operand), the row panels bake as
    trace-time constants, the per-shard term is scalar.  Exactly the
    shape that keeps the PR-13 reduce-window lowering eligible under
    ``PoolPlacement(reduce=True)``; a driver-varying capture creeping
    into the panels would both trip the pool refusal and kill the
    reduce eligibility."""
    import jax.numpy as jnp
    import numpy as np

    from ..linalg.ops import quadratic_per_shard
    from .lowering import canonical_round

    panels = jnp.asarray(
        np.arange(24.0, dtype=np.float32).reshape(4, 2, 3)
    )
    x_rows = jnp.asarray(
        np.arange(8.0, dtype=np.float32).reshape(4, 2)
    )
    model = canonical_round(quadratic_per_shard(), (panels, x_rows), 4)
    return model, (jnp.ones((3,), jnp.float32),)


FIXTURES: Sequence[LintFixture] = (
    LintFixture(name="canonical-round", build=_canonical_round),
    LintFixture(name="two-potential-window", build=_two_potential_window),
    LintFixture(name="ppl-plate-round", build=_ppl_plate_round),
    LintFixture(name="ppl-subsample-round", build=_ppl_subsample_round),
    LintFixture(name="zero-owner-update", build=_zero_owner_update),
    LintFixture(
        name="linalg-block-row-reduce", build=_linalg_block_row_reduce
    ),
)
