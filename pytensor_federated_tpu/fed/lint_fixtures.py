"""Pool-lane ``fed.program`` fixtures for the ``fed-placement`` lint.

The PR-6 contract (:mod:`.placements`): a pool-placed ``fed_map``
ships ONLY its mapped leaves — a closure that captures a
driver-varying value (a program input, or the output of an upstream
equation) would silently compute with whatever the node baked at
deploy time, so ``PoolPlacement.group_executor`` refuses it with a
``ValueError`` at runtime.  graftflow's ``fed-placement`` rule
(:mod:`..analysis.rules_fedflow`) moves that refusal to CI: it traces
every fixture registered here CPU-only, walks the jaxpr, and flags any
``fed_map`` equation whose closure captures a driver-varying operand —
with the operand's provenance chain in the finding.

Register a fixture for every fed program shape the repo ships on the
pool lane.  A fixture is the *placement-free* model (tracing needs no
transport); pool intent is what registration here asserts.  Keep the
example arguments tiny — the lint traces, it never executes shards.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence, Tuple

__all__ = ["LintFixture", "FIXTURES"]

FixtureProgram = Tuple[Callable[..., Any], Tuple[Any, ...]]


@dataclasses.dataclass(frozen=True)
class LintFixture:
    """One traceable pool-lane program: ``build()`` returns
    ``(fn, example_args)``; the lint calls ``jax.make_jaxpr(fn)(*args)``
    under the CPU backend."""

    name: str
    build: Callable[[], FixtureProgram]


def _canonical_round() -> FixtureProgram:
    """The FederatedLogpGrad model: broadcast -> map -> sum.  Params
    reach the shards through ``fed_broadcast`` (making them MAPPED
    operands), so the closure captures nothing driver-varying — the
    clean shape every pool deployment should follow."""
    import jax.numpy as jnp
    import numpy as np

    from .primitives import fed_broadcast, fed_map, fed_sum

    data = jnp.asarray(np.arange(12.0, dtype=np.float32).reshape(4, 3))

    def model(params: Any) -> Any:
        pb = fed_broadcast((params,), 4)
        lps = fed_map(
            lambda shard: jnp.sum(shard[0][0] * shard[1]), (pb, data)
        )
        return fed_sum(lps)

    return model, (jnp.ones((3,), jnp.float32),)


def _two_potential_window() -> FixtureProgram:
    """Two independent fed_maps (the fused-window shape,
    ``bridge.core.fused_jax_callable``): both members must stay free of
    driver-varying closure capture for the fused pool window to ship."""
    import jax.numpy as jnp
    import numpy as np

    from .primitives import fed_broadcast, fed_map, fed_sum

    data_a = jnp.asarray(np.ones((4, 3), np.float32))
    data_b = jnp.asarray(np.full((2, 5), 2.0, np.float32))

    def model(pa: Any, pb_: Any) -> Any:
        ba = fed_broadcast((pa,), 4)
        bb = fed_broadcast((pb_,), 2)
        la = fed_map(
            lambda shard: jnp.sum(shard[0][0] * shard[1]), (ba, data_a)
        )
        lb = fed_map(
            lambda shard: jnp.sum(shard[0][0][:5] + shard[1]), (bb, data_b)
        )
        return fed_sum(la) + fed_sum(lb)

    return model, (
        jnp.ones((3,), jnp.float32),
        jnp.ones((5,), jnp.float32),
    )


FIXTURES: Sequence[LintFixture] = (
    LintFixture(name="canonical-round", build=_canonical_round),
    LintFixture(name="two-potential-window", build=_two_potential_window),
)
