"""``fed`` — differentiable federated MapReduce with placement-aware
lowering: ONE IR for the device and host lanes.

The DrJAX-style unification (ROADMAP open item 1): ``fed_map`` /
``fed_sum`` / ``fed_broadcast`` are real JAX primitives
(:mod:`.primitives`) whose JVP/transpose rules encode the federated
autodiff identities, so one traced model runs and ``jax.grad``\\s end
to end whether its shards live on mesh devices
(:class:`MeshPlacement`), RPC node pools (:class:`PoolPlacement`), or
a mix (:class:`MixedPlacement`) — and the AsyncFusionOptimizer-style
rewrite is a primitive-level batching pass (:mod:`.batching`) instead
of graph surgery.

Quick shape::

    from pytensor_federated_tpu import fed

    def model(params):
        pb = fed.fed_broadcast(params, n_shards)
        lps = fed.fed_map(lambda s: shard_logp(s[0], s[1]), (pb, data))
        return fed.fed_sum(lps)

    run = fed.program(model, fed.MeshPlacement(mesh))   # or Pool/Mixed
    value, grads = jax.value_and_grad(run)(params)
"""

from .batching import plan_windows
from .lowering import FederatedLogpGrad, program
from .placements import (
    MapSpec,
    MeshPlacement,
    MixedPlacement,
    Placement,
    PoolPlacement,
    make_node_compute,
)
from .primitives import (
    fed_broadcast,
    fed_broadcast_p,
    fed_map,
    fed_map_p,
    fed_mean,
    fed_sum,
    fed_sum_p,
)

__all__ = [
    "FederatedLogpGrad",
    "MapSpec",
    "MeshPlacement",
    "MixedPlacement",
    "Placement",
    "PoolPlacement",
    "fed_broadcast",
    "fed_broadcast_p",
    "fed_map",
    "fed_map_p",
    "fed_mean",
    "fed_sum",
    "fed_sum_p",
    "make_node_compute",
    "plan_windows",
    "program",
]
