"""Typed compute-function contracts.

TPU-native re-design of the reference's modeling-signature layer
(reference: pytensor_federated/signatures.py:8-33).  The reference defines
three ``Callable`` aliases over NumPy arrays; here the same contracts are
expressed over JAX arrays, plus an explicit *shape signature* type
(:class:`ArraysSpec`) built from :class:`jax.ShapeDtypeStruct`.  Shape
signatures are first-class because XLA compiles one executable per static
signature — the TPU analog of the reference's wire-format schema
(reference: protobufs/service.proto:6-19).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array
ArrayLike = Any  # anything jnp.asarray accepts

#: Arrays in -> arrays out.  The universal compute contract
#: (reference: signatures.py:8-14 ``ComputeFunc``).  Must be JAX-traceable
#: for the on-device path; host/blackbox functions enter through
#: :mod:`pytensor_federated_tpu.ops.blackbox` instead.
ComputeFn = Callable[..., Sequence[Array]]

#: Model parameters -> scalar log-potential
#: (reference: signatures.py:17-23 ``LogpFunc``).
LogpFn = Callable[..., Array]

#: Model parameters -> (scalar log-potential, gradients w.r.t. every input)
#: (reference: signatures.py:26-33 ``LogpGradFunc``).
LogpGradFn = Callable[..., Tuple[Array, Tuple[Array, ...]]]

#: A static arrays signature: one ShapeDtypeStruct per array.
ArraysSpec = Tuple[jax.ShapeDtypeStruct, ...]


def spec_of(*arrays: ArrayLike) -> ArraysSpec:
    """Return the static signature of concrete arrays."""
    out = []
    for a in arrays:
        a = jnp.asarray(a)
        out.append(jax.ShapeDtypeStruct(a.shape, a.dtype))
    return tuple(out)


def scalar_spec(dtype=jnp.float32) -> jax.ShapeDtypeStruct:
    """Signature of a 0-d scalar (the logp output)."""
    return jax.ShapeDtypeStruct((), jnp.dtype(dtype))


def check_scalar(x: Array, what: str = "logp") -> Array:
    """Trace-time check that ``x`` is a scalar.

    Mirrors the reference's runtime validation that a log-potential is
    scalar (reference: common.py:18-22), but runs at trace time: XLA shapes
    are static, so an ill-typed model fails at compile, not mid-sampling.
    """
    if jnp.shape(x) != ():
        raise ValueError(f"{what} must be scalar, got shape {jnp.shape(x)}")
    return x
