"""loop-affinity: grpc.aio channels flow through the connection cache.

A ``grpc.aio`` channel is bound to the event loop it was created on;
driving it from another loop errors or hangs, and one thread legally
runs several loops over its lifetime (CLAUDE.md design invariants).
The service layer therefore keys every cached connection on
``(client token, pid, thread, loop)`` and purges closed-loop entries
(:mod:`..service.client`, ``ClientPrivates``).  A channel created
anywhere else and stored on a long-lived object silently resurrects
the bug the cache exists to kill.

Allowed channel-creation sites:

1. the cache constructor itself — ``ClientPrivates.connect`` in
   ``service/client.py``;
2. a scoped ``async with grpc.aio.*_channel(...) as ch:`` — the
   channel provably dies on the loop that made it.

Everything else is a finding, wherever it appears in the package.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from .core import Finding, SourceFile, rule

_RULE = "loop-affinity"

_CACHE_FILE = "pytensor_federated_tpu/service/client.py"
_CACHE_SITE = ("ClientPrivates", "connect")


def _is_channel_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    try:
        dotted = ast.unparse(node.func)
    except Exception:  # pragma: no cover
        return False
    return dotted.endswith(
        ("aio.insecure_channel", "aio.secure_channel")
    ) or dotted in ("insecure_channel", "secure_channel")


def _scoped_channel_calls(tree: ast.Module) -> set:
    """ids of channel calls appearing as an ``async with`` context
    expression (directly, or behind an immediate Await — not expected
    for channel constructors but harmless to accept)."""
    scoped = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.AsyncWith, ast.With)):
            # Plain `with` is also accepted: grpc sync channels are
            # loop-free and a scoped aio channel in a `with` would
            # fail at runtime long before loop affinity matters.
            for item in node.items:
                expr = item.context_expr
                if isinstance(expr, ast.Await):
                    expr = expr.value
                if _is_channel_call(expr):
                    scoped.add(id(expr))
    return scoped


def _enclosing_stack(
    tree: ast.Module,
) -> List[Tuple[ast.AST, Optional[str], Optional[str]]]:
    """Flatten (node, enclosing-class, enclosing-function) for calls."""
    out: List[Tuple[ast.AST, Optional[str], Optional[str]]] = []

    def visit(node: ast.AST, cls: Optional[str], fn: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name, fn)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, cls, child.name)
            else:
                if isinstance(child, ast.Call):
                    out.append((child, cls, fn))
                visit(child, cls, fn)

    visit(tree, None, None)
    return out


@rule(
    _RULE,
    "grpc.aio channels must come from the (token,pid,thread,loop)-keyed "
    "connection cache or a scoped `async with`, never be stored directly",
)
def check_loop_affinity(src: SourceFile) -> Iterator[Finding]:
    if not src.is_python:
        return
    scoped = _scoped_channel_calls(src.tree)
    for call, cls, fn in _enclosing_stack(src.tree):
        if not _is_channel_call(call):
            continue
        if id(call) in scoped:
            continue
        if src.rel == _CACHE_FILE and (cls, fn) == _CACHE_SITE:
            continue
        yield src.finding(
            _RULE,
            call.lineno,
            "grpc.aio channel created outside the connection cache "
            f"(in {cls or '<module>'}.{fn or '<module>'}) — channels are "
            "bound to their creation loop; go through "
            "service.client.ClientPrivates (the (token,pid,thread,loop)-"
            "keyed cache) or use a scoped `async with`",
        )
